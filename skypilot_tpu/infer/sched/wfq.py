"""Weighted fair queueing (deficit round robin) over per-tenant queues.

The multi-tenant isolation policy: one tenant's burst must never 429
(or starve) everyone else. Requests queue per tenant; service rotates
deficit-round-robin — each visit replenishes the tenant's deficit by
``quantum_tokens x weight`` and the head request is served once the
deficit covers its token cost (``base.request_cost``: prompt + already
generated output). Over time each backlogged tenant receives service
proportional to its weight, measured in TOKENS, not requests — a
tenant of few huge prompts cannot crowd out a tenant of many small
ones.

Deficit discipline (the carryover bounds the tests pin):

- replenish is capped at ``quantum x weight + head_cost``, so an
  unlucky tenant accumulates just enough to afford its head and a
  quantum of change — never unbounded credit;
- a tenant whose queue empties is GC'd (queue, deficit, rotation
  slot — cumulative stats survive for metrics): idle time earns
  nothing, the classic DRR anti-hoarding rule.

Admission quotas: the global ``max_queue_requests`` /
``max_queue_tokens`` bounds are split by weight across the tenants
currently holding queued work (plus the applicant), so shedding
answers 429 to the tenant that outran ITS share — the victim of an
aggressor's burst is never the one shed. Because every tenant is
guaranteed at least one queue slot (a newcomer must be admittable),
the quota sum can exceed the configured bound; a HARD ceiling of 2x
each bound caps the total — per-tenant fairness below it, finite
memory above it even against a client minting fresh tenant ids. The Retry-After estimate is
tenant-scoped: the tenant's own backlog over its weight share of the
engine's recent decode throughput.

Page-pressure preemption evicts the most-over-share tenant's youngest
slot, and the prefill chunk budget rotates across the prefilling
slots' tenants — fairness applies at every decision point, not just
the queue.
"""
from __future__ import annotations

import collections
import math
from typing import Any, Deque, Dict, List, Optional

from skypilot_tpu.infer.sched import base


class WFQScheduler(base.Scheduler):
    name = 'wfq'

    # Guarded by the owning engine's _lock, like the base class
    # (methods are '# holds: _lock'; the scheduler has no lock).
    _GUARDED_BY = {
        '_queues': '_lock',
        '_order': '_lock',
        '_deficit': '_lock',
        '_cursor': '_lock',
        '_fresh': '_lock',
        '_prr': '_lock',
    }

    def __init__(self, config: Optional[base.SchedulerConfig] = None
                 ) -> None:
        super().__init__(config)
        # tenant -> FIFO of its queued requests. _order is the DRR
        # rotation (insertion order, stable); _cursor points at the
        # tenant currently being served; _fresh marks whether that
        # tenant still owes itself this visit's replenish.
        self._queues: Dict[str, Deque[Any]] = {}
        self._order: List[str] = []
        self._deficit: Dict[str, float] = {}
        self._cursor = 0
        self._fresh = True
        self._prr = 0   # prefill-chunk rotation over tenants

    # ---- queue -----------------------------------------------------------
    def enqueue(self, req) -> None:  # holds: _lock
        self._tstats(req.tenant).admitted += 1
        self._tenant_queue(req.tenant).append(req)

    def requeue(self, req) -> None:  # holds: _lock
        # Preempted: front of ITS tenant's queue (the deficit already
        # paid for it once; DRR will charge the recompute again, which
        # is honest — the recompute is real work).
        self._tenant_queue(req.tenant).appendleft(req)

    def _tenant_queue(self, tenant: str) -> Deque[Any]:  # holds: _lock
        q = self._queues.get(tenant)
        if q is None:
            q = self._queues[tenant] = collections.deque()
            self._deficit.setdefault(tenant, 0.0)
            self._order.append(tenant)
        return q

    def _gc_tenant(self, tenant: str) -> None:  # holds: _lock
        """Empty-tenant GC: reclaim scheduling state (queue, deficit,
        rotation slot). Cumulative _stats survive — observability
        outlives the burst (bounded by the base class's stats cap)."""
        del self._queues[tenant]
        self._deficit.pop(tenant, None)
        i = self._order.index(tenant)
        del self._order[i]
        if i < self._cursor:
            self._cursor -= 1   # same tenant at the cursor: keep its
            #                     in-progress visit (_fresh untouched)
        elif i == self._cursor:
            # The cursor now points at the NEXT tenant: it is owed a
            # fresh replenish. (An i > cursor removal changes nothing
            # for the tenant in service — resetting _fresh there would
            # hand it a spurious extra quantum per unrelated GC.)
            self._fresh = True
        if self._cursor >= len(self._order):
            self._cursor = 0
            self._fresh = True

    def pending(self) -> int:  # holds: _lock
        return sum(len(q) for q in self._queues.values())

    def _queued_tenants(self):  # holds: _lock
        return set(self._order)

    def queued_requests(self) -> List[Any]:  # holds: _lock
        return [r for t in self._order for r in self._queues[t]]

    def sweep(self, now: float) -> List[tuple]:  # holds: _lock
        out = []
        for t in list(self._order):
            q = self._queues[t]
            keep = []
            for r in q:
                if r.cancelled:
                    out.append((r, 'cancelled'))
                elif r.deadline is not None and now > r.deadline:
                    out.append((r, 'deadline'))
                else:
                    keep.append(r)
            if not keep:
                self._gc_tenant(t)
            elif len(keep) != len(q):
                self._queues[t] = collections.deque(keep)
        self._count_swept(out)
        return out

    # ---- admission quotas ------------------------------------------------
    def _share(self, tenant: str) -> float:  # holds: _lock
        """This tenant's weight share over the tenants that currently
        hold queued work (plus itself) — the divisor adapts to who is
        actually contending, so a lone tenant gets the whole bound."""
        active = set(self._order) | {tenant}
        total = sum(self.weight(t) for t in active)
        return self.weight(tenant) / total if total else 1.0

    def admit(self, req, drain_tps: float = 0.0) -> None:  # holds: _lock
        t = req.tenant
        share = self._share(t)
        q = self._queues.get(t)
        cap = self.cfg.max_queue_requests
        if cap is not None:
            allowed = max(1, math.ceil(cap * share))
            if q is not None and len(q) >= allowed:
                self._shed(
                    req, f'tenant {t!r} queue full ({len(q)} waiting '
                         f'>= quota {allowed} of '
                         f'max_queue_requests={cap})', drain_tps)
            if self.pending() >= 2 * cap:
                # Hard global ceiling. Per-tenant quotas adapt to the
                # contending set (each tenant gets at least one slot),
                # so a client minting fresh tenant ids per request
                # could otherwise queue ~cap·H(n) work — unbounded.
                # 2x the configured bound keeps quota fairness in the
                # normal regime and memory finite in the adversarial
                # one.
                self._shed(
                    req, f'engine queue full ({self.pending()} '
                         f'waiting >= hard ceiling '
                         f'{2 * cap} = 2 x max_queue_requests={cap})',
                    drain_tps)
        tcap = self.cfg.max_queue_tokens
        if tcap is not None:
            cost = base.request_cost(req)
            if cost > tcap:
                # Outgrows even the GLOBAL bound: no amount of
                # queue-draining ever admits it.
                self._shed(req, f'request ({cost} tokens) exceeds '
                                f'max_queue_tokens={tcap}', drain_tps)
            queued = (sum(base.request_cost(r) for r in q)
                      if q else 0)
            allowed_tok = math.ceil(tcap * share)
            if queued and queued + cost > allowed_tok:
                self._shed(
                    req, f'tenant {t!r} queue full ({queued} queued '
                         f'tokens + {cost} > quota {allowed_tok} of '
                         f'max_queue_tokens={tcap})', drain_tps)
            if self.queued_tokens() + cost > 2 * tcap:
                # Same hard ceiling, token-denominated.
                self._shed(
                    req, f'engine queue full ({self.queued_tokens()} '
                         f'queued tokens + {cost} > hard ceiling '
                         f'{2 * tcap} = 2 x max_queue_tokens={tcap})',
                    drain_tps)

    def retry_after(self, tenant: str,  # holds: _lock
                    drain_tps: float) -> float:
        """Tenant-scoped drain estimate: its own backlog over its
        weight share of the engine's decode throughput."""
        q = self._queues.get(tenant)
        backlog = sum(base.request_cost(r) for r in q) if q else 0
        eff = drain_tps * self._share(tenant)
        if eff <= 0.0 or backlog <= 0:
            return 1.0
        return min(60.0, max(1.0, backlog / eff))

    # ---- DRR service -----------------------------------------------------
    def pop_next(self):  # holds: _lock
        if not self._order:
            return None
        quantum = max(1, self.cfg.quantum_tokens)
        # Worst-case rotations until SOME head is affordable:
        # ceil(max_head / (quantum * min_weight)) — deficits grow by
        # quantum*w per visit, capped at quantum*w + head (always
        # reachable). The bound makes the loop provably finite; the
        # tail return is a belt-and-braces fallback.
        max_head = max(base.request_cost(q[0])
                       for q in self._queues.values())
        min_w = min(self.weight(t) for t in self._order)
        rounds = int(max_head / (quantum * max(min_w, 1e-9))) + 2
        for _ in range(rounds * len(self._order)):
            t = self._order[self._cursor]
            q = self._queues[t]
            w = self.weight(t)
            head = base.request_cost(q[0])
            if self._fresh:
                # Carryover bound: never more than one quantum of
                # change beyond the head's own cost.
                self._deficit[t] = min(self._deficit[t] + quantum * w,
                                       quantum * w + head)
                self._fresh = False
            if self._deficit[t] >= head:
                req = q.popleft()
                self._deficit[t] -= head
                if not q:
                    self._gc_tenant(t)
                # else: stay on this tenant (classic DRR serves while
                # the deficit lasts); the next pop re-checks
                # affordability without replenishing.
                return req
            self._cursor = (self._cursor + 1) % len(self._order)
            self._fresh = True
        # Unreachable given the bound; serve strict FIFO as a failsafe
        # rather than wedging the step loop.
        for t in self._order:
            req = self._queues[t].popleft()
            if not self._queues[t]:
                self._gc_tenant(t)
            return req
        return None

    # ---- speculation budget ---------------------------------------------
    def spec_budget(self, req, spec_k: int) -> int:  # holds: _lock
        """Weight-share draft width under contention: speculation
        burns extra pages and verify lanes for latency, and when other
        tenants hold queued work that headroom belongs to the rotation
        — so a tenant drafts ``spec_k`` scaled by its weight share of
        the contending set (the uncontended engine always drafts full
        width). Floor 1, not 0: with many contenders the truncated
        share would zero EVERYONE's width and turn speculation off
        fleet-wide — a tenant at its fair share keeps at least one
        draft lane; the scaling only narrows wide drafting, it never
        disables it."""
        if not (set(self._order) - {req.tenant}):
            return spec_k
        # The same weight-share-of-the-contending-set rule admission
        # quotas use (_share) — one definition of "fair share".
        return min(spec_k,
                   max(1, int(spec_k * self._share(req.tenant))))

    # ---- step work selection --------------------------------------------
    def next_prefill_slot(self, candidates: List[int],  # holds: _lock
                          slots: List[Any]) -> int:
        """Rotate the chunk budget across the prefilling slots'
        tenants (FIFO within a tenant: lowest slot), so one tenant's
        burst of long prompts cannot monopolize prefill bandwidth."""
        tenants = sorted({slots[s].tenant for s in candidates})
        t = tenants[self._prr % len(tenants)]
        self._prr += 1
        return min(s for s in candidates if slots[s].tenant == t)

    def pick_victim(self, victims: List[int],  # holds: _lock
                    slots: List[Any]) -> int:
        """Evict the most-over-share tenant's youngest slot: service
        held in slots (token cost) per unit weight decides WHO pays
        for page pressure; recency decides WHICH of their slots
        (cheapest recompute), matching the fcfs rule within a
        tenant."""
        service: Dict[str, int] = {}
        for r in slots:
            if r is not None:
                service[r.tenant] = (service.get(r.tenant, 0)
                                     + base.request_cost(r))
        tenant = max({slots[s].tenant for s in victims},
                     key=lambda t: (service.get(t, 0) / self.weight(t),
                                    t))
        cands = [s for s in victims if slots[s].tenant == tenant]
        return max(cands, key=lambda s: slots[s].submitted_at)
