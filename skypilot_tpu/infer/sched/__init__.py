"""Pluggable engine scheduler (docs/serving.md "Engine scheduler").

``make(name, config)`` builds the policy the engine step loop drives:
``fcfs`` (default, bit-identical to the historical inline behavior),
``deadline`` (EDF over per-request wall-clock budgets), ``wfq``
(deficit-round-robin weighted fair queueing over per-tenant queues).
"""
from __future__ import annotations

from typing import Dict, Optional, Type

from skypilot_tpu.infer.sched.base import (AdmissionError,
                                           DEFAULT_TENANT,
                                           FCFSScheduler, Scheduler,
                                           SchedulerConfig,
                                           aggregate_stats,
                                           request_cost)
from skypilot_tpu.infer.sched.deadline import DeadlineScheduler
from skypilot_tpu.infer.sched.wfq import WFQScheduler

POLICIES: Dict[str, Type[Scheduler]] = {
    'fcfs': FCFSScheduler,
    'deadline': DeadlineScheduler,
    'wfq': WFQScheduler,
}


def make(name: str,
         config: Optional[SchedulerConfig] = None) -> Scheduler:
    try:
        cls = POLICIES[name]
    except KeyError:
        raise ValueError(
            f'unknown scheduler policy {name!r} '
            f'(have: {", ".join(sorted(POLICIES))})') from None
    return cls(config)


__all__ = [
    'AdmissionError', 'DEFAULT_TENANT', 'DeadlineScheduler',
    'FCFSScheduler', 'POLICIES', 'Scheduler', 'SchedulerConfig',
    'WFQScheduler', 'aggregate_stats', 'make', 'request_cost',
]
