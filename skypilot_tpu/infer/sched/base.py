"""Scheduler interface + FCFS policy (the engine's default).

The engine step loop's admission/ordering decisions — who gets
admitted, which queued request takes a freed slot, which prefilling
slot gets the next chunk, who is preempted under page pressure — used
to live inline in ``InferenceEngine``. This package factors them into
a narrow :class:`Scheduler` interface the engine calls at exactly
those decision points, so policies are swappable without touching the
device path (docs/serving.md "Engine scheduler"):

- ``fcfs`` (this module): bit-identical to the historical inline
  behavior — FIFO queue, round-robin chunking, youngest-victim
  preemption, global admission bounds.
- ``deadline`` (sched/deadline.py): earliest-deadline-first over the
  per-request wall-clock budgets (utils/common.DEADLINE_HEADER).
- ``wfq`` (sched/wfq.py): deficit-round-robin weighted fair queueing
  over per-tenant queues with token-cost accounting and per-tenant
  admission quotas.

Concurrency contract: a scheduler owns NO lock of its own — every
mutable field is guarded by the owning engine's ``_lock`` (the engine
calls in from ``submit()`` HTTP threads and the engine thread, always
under that lock). Methods are annotated ``# holds: _lock`` so
SKY-LOCK (docs/static-analysis.md) enforces the contract on the
declared ``_GUARDED_BY`` fields.

Tenant accounting: every request carries a ``tenant`` (the
``X-SkyTpu-Tenant`` header end to end; ``'default'`` otherwise). The
base class keeps per-tenant cumulative counters and recent windows
(queue wait, TTFT) for all policies — fairness must be observable
before it is enforceable. ``aggregate_stats`` turns one or many
scheduler snapshots into the per-tenant metric dict surfaced by
``engine.metrics()['tenants']`` (and merged across tiers by
``EnginePool``); its keys are cataloged in docs/observability.md and
gated by SKY-REGISTRY.
"""
from __future__ import annotations

import collections
import dataclasses
from typing import Any, Deque, Dict, Iterable, List, Mapping, Optional

DEFAULT_TENANT = 'default'

# Recent-window sizes (per tenant): bounded so a long-lived replica's
# /metrics stays O(1) in memory and percentiles reflect current
# behavior, mirroring the engine's own TTFT window.
_WINDOW = 512


class AdmissionError(ValueError):
    """The scheduler refused new work — the tenant's (or the global)
    queue bound is hit: the caller sheds (HTTP 429 + Retry-After at
    the server) instead of queueing unboundedly. ``retry_after_s`` is
    the scheduler's queue-drain estimate (tokens ahead / recent decode
    throughput), not a constant. A ``ValueError`` subclass so the
    multihost lockstep tick's uniform-rejection rule applies unchanged
    on every host."""

    def __init__(self, msg: str, retry_after_s: float = 1.0) -> None:
        super().__init__(msg)
        self.retry_after_s = retry_after_s


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    """Policy knobs, carried over from ``EngineConfig``."""
    # Global queue bounds (fcfs/deadline shed against these directly;
    # wfq derives per-tenant quotas from them). None = unbounded.
    max_queue_requests: Optional[int] = None
    max_queue_tokens: Optional[int] = None
    # tenant -> relative weight (wfq); unknown tenants weigh 1.0.
    tenant_weights: Optional[Mapping[str, float]] = None
    # DRR replenish per rotation visit, in tokens (wfq). Also the
    # fairness granularity: one visit serves ~quantum/cost consecutive
    # requests before the rotation moves on, so a quantum much larger
    # than the typical request cost lets a bursty tenant monopolize
    # whole rounds (64 ≈ a page of tokens keeps interleave tight).
    quantum_tokens: int = 64


def request_cost(req) -> int:
    """Token cost of a queued request: what its (re-)prefill must
    cover — prompt plus already-generated output (resume tokens at
    submit; everything streamed so far for a preempted requeue). The
    same accounting the historical ``max_queue_tokens`` bound used."""
    return len(req.prompt_tokens) + len(req.output_tokens)


def _pct(sorted_vals: List[float], p: float) -> Optional[float]:
    if not sorted_vals:
        return None
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(len(sorted_vals) * p))]


class _TenantStats:
    """Cumulative per-tenant counters + recent windows. Survives the
    wfq empty-tenant GC (scheduling state is reclaimed; observability
    is not)."""

    __slots__ = ('admitted', 'shed', 'cancelled', 'expired',
                 'abandoned', 'decode_tokens', 'queue_waits', 'ttfts')

    def __init__(self) -> None:
        self.admitted = 0
        self.shed = 0
        self.cancelled = 0
        self.expired = 0
        self.abandoned = 0
        self.decode_tokens = 0
        self.queue_waits: Deque[float] = collections.deque(
            maxlen=_WINDOW)
        self.ttfts: Deque[float] = collections.deque(maxlen=_WINDOW)


class Scheduler:
    """FCFS policy and the interface every policy implements.

    The engine calls in at five decision points, always under its
    ``_lock``: ``admit``+``enqueue`` (submission), ``pop_next`` (slot
    refill), ``next_prefill_slot`` (chunk budget), ``pick_victim``
    (page-pressure preemption), ``sweep`` (deadline/cancel GC over the
    queue). Accounting hooks (``note_*``) feed the per-tenant metrics;
    ``snapshot`` exports them.
    """

    name = 'fcfs'

    # Guarded by the OWNING ENGINE's _lock (SKY-LOCK): the scheduler
    # has no lock of its own; every caller is an engine method that
    # already holds the engine lock, hence the '# holds: _lock'
    # annotations below.
    _GUARDED_BY = {
        '_queue': '_lock',      # submit() threads vs the step loop
        '_stats': '_lock',      # note_* (engine thread) vs metrics
        '_weights': '_lock',
        '_rr': '_lock',         # chunk round-robin cursor
    }

    def __init__(self, config: Optional[SchedulerConfig] = None) -> None:
        self.cfg = config or SchedulerConfig()
        self._queue: List[Any] = []
        self._stats: Dict[str, _TenantStats] = {}
        self._weights: Dict[str, float] = {
            str(k): float(v)
            for k, v in (self.cfg.tenant_weights or {}).items()}
        self._rr = 0   # round-robin cursor over prefilling slots

    # ---- weights ---------------------------------------------------------
    def weight(self, tenant: str) -> float:  # holds: _lock
        return self._weights.get(tenant, 1.0)

    def set_tenant_weights(self, weights: Mapping[str, float]  # holds: _lock
                           ) -> None:
        """Replace the weight map mid-flight (a runtime knob): queued
        work keeps its position; future scheduling decisions use the
        new weights."""
        self._weights = {str(k): float(v)
                         for k, v in (weights or {}).items()}

    # Distinct-tenant stats are bounded: tenant ids are
    # client-controlled (X-SkyTpu-Tenant), so an id-minting client
    # must not grow this map — or every /metrics snapshot — without
    # bound. At the cap, the oldest-created entries without queued
    # work are evicted (their windows/counters reset if they return).
    max_tenant_stats = 1024

    def _tstats(self, tenant: str) -> _TenantStats:  # holds: _lock
        st = self._stats.get(tenant)
        if st is None:
            if len(self._stats) >= self.max_tenant_stats:
                live = self._queued_tenants()
                for old in list(self._stats):
                    if old not in live:
                        del self._stats[old]
                        if len(self._stats) < self.max_tenant_stats:
                            break
            st = self._stats[tenant] = _TenantStats()
        return st

    def _queued_tenants(self):  # holds: _lock
        return {r.tenant for r in self._queue}

    # ---- admission -------------------------------------------------------
    def admit(self, req, drain_tps: float = 0.0) -> None:  # holds: _lock
        """Bounds check WITHOUT enqueueing (the engine enqueues on
        success). Raises :class:`AdmissionError` carrying the
        queue-drain Retry-After estimate. ``drain_tps`` is the
        engine's recent decode throughput (tokens/s)."""
        cap = self.cfg.max_queue_requests
        if cap is not None and self.pending() >= cap:
            self._shed(req, f'engine queue full ({self.pending()} '
                            f'waiting >= max_queue_requests={cap})',
                       drain_tps)
        tcap = self.cfg.max_queue_tokens
        if tcap is not None:
            queued = self.queued_tokens()
            total = request_cost(req)
            if queued + total > tcap:
                self._shed(req, f'engine queue full ({queued} queued '
                                f'tokens + {total} > '
                                f'max_queue_tokens={tcap})', drain_tps)

    def _shed(self, req, msg: str, drain_tps: float) -> None:  # holds: _lock
        self._tstats(req.tenant).shed += 1
        raise AdmissionError(
            msg, retry_after_s=self.retry_after(req.tenant, drain_tps))

    def retry_after(self, tenant: str,  # holds: _lock
                    drain_tps: float) -> float:
        """Queue-drain estimate: queued tokens ahead of this tenant
        over the recent decode throughput, clamped to [1, 60] s. 1.0
        when the engine has no throughput history yet."""
        backlog = self.queued_tokens()
        if drain_tps <= 0.0 or backlog <= 0:
            return 1.0
        return min(60.0, max(1.0, backlog / drain_tps))

    # ---- queue -----------------------------------------------------------
    def enqueue(self, req) -> None:  # holds: _lock
        self._tstats(req.tenant).admitted += 1
        self._queue.append(req)

    def requeue(self, req) -> None:  # holds: _lock
        """A preempted request resumes at the FRONT: it already holds
        streamed output and its pages were just reclaimed for someone
        else — making it wait again would double-charge it."""
        self._queue.insert(0, req)

    def pop_next(self):  # holds: _lock
        """Next request for a freed slot, or None."""
        return self._queue.pop(0) if self._queue else None

    def pending(self) -> int:  # holds: _lock
        return len(self._queue)

    def queued_tokens(self) -> int:  # holds: _lock
        return sum(request_cost(r) for r in self.queued_requests())

    def queued_requests(self) -> List[Any]:  # holds: _lock
        """Snapshot of the queue in service order (for sweeps, metrics
        and scheduler migration — never mutate the returned list)."""
        return list(self._queue)

    def sweep(self, now: float) -> List[tuple]:  # holds: _lock
        """Drop queued requests whose client is gone ('cancelled') or
        whose deadline passed ('deadline'); returns ``(request,
        reason)`` pairs for the engine to finish/notify. Policy queue
        state stays consistent (wfq GCs tenants emptied here)."""
        out = []
        keep = []
        for r in self._queue:
            if r.cancelled:
                out.append((r, 'cancelled'))
            elif r.deadline is not None and now > r.deadline:
                out.append((r, 'deadline'))
            else:
                keep.append(r)
        self._queue[:] = keep
        self._count_swept(out)
        return out

    def _count_swept(self, out: List[tuple]) -> None:  # holds: _lock
        for r, reason in out:
            st = self._tstats(r.tenant)
            if reason == 'cancelled':
                st.abandoned += 1   # never reached a slot
            else:
                st.expired += 1

    # ---- speculation budget ---------------------------------------------
    def spec_budget(self, req, spec_k: int) -> int:  # holds: _lock
        """Draft width allowed for ``req`` THIS step (speculative
        decoding, docs/serving.md). Speculation spends extra page and
        verify-lane budget chasing latency, so the scheduler — the
        owner of contention policy — gets the last word on how wide a
        request may draft. fcfs/deadline grant the global
        ``EngineConfig.spec_k`` unconditionally; wfq caps an
        over-share tenant's width under contention (its override)."""
        del req
        return spec_k

    # ---- step work selection --------------------------------------------
    def next_prefill_slot(self, candidates: List[int],  # holds: _lock
                          slots: List[Any]) -> int:
        """Which prefilling slot gets the next chunk. ``candidates``
        is sorted ascending. FCFS keeps the historical round-robin
        cursor arithmetic verbatim (the fcfs bit-identity gate)."""
        del slots
        self._rr = (self._rr + 1) % len(candidates)
        return candidates[self._rr]

    def pick_victim(self, victims: List[int],  # holds: _lock
                    slots: List[Any]) -> int:
        """Which active slot to preempt under page pressure. FCFS:
        the youngest (latest-submitted) — the historical rule."""
        return max(victims, key=lambda s: slots[s].submitted_at)

    # ---- accounting hooks (engine thread) --------------------------------
    def note_queue_wait(self, req, wait_s: float) -> None:  # holds: _lock
        self._tstats(req.tenant).queue_waits.append(wait_s)

    def note_first_token(self, req, ttft_s: float) -> None:  # holds: _lock
        self._tstats(req.tenant).ttfts.append(ttft_s)

    def note_tokens(self, req, n: int = 1) -> None:  # holds: _lock
        self._tstats(req.tenant).decode_tokens += n

    def note_outcome(self, req, reason: str) -> None:  # holds: _lock
        """An ACTIVE slot was torn down early ('cancelled' /
        'deadline') — the queued-side outcomes are counted by
        ``sweep`` itself."""
        st = self._tstats(req.tenant)
        if reason == 'cancelled':
            st.cancelled += 1
        elif reason == 'deadline':
            st.expired += 1

    # ---- metrics ---------------------------------------------------------
    def snapshot(self) -> Dict[str, Dict[str, Any]]:  # holds: _lock
        """Raw per-tenant export (counters + copied windows), merged
        by ``aggregate_stats``. Taken under the engine lock so
        EnginePool's cross-tier merge never iterates a live deque the
        engine thread is appending to."""
        depth: Dict[str, int] = {}
        tokens: Dict[str, int] = {}
        for r in self.queued_requests():
            depth[r.tenant] = depth.get(r.tenant, 0) + 1
            tokens[r.tenant] = (tokens.get(r.tenant, 0)
                                + request_cost(r))
        out: Dict[str, Dict[str, Any]] = {}
        for t in set(self._stats) | set(depth):
            st = self._stats.get(t)
            out[t] = {
                'queue_depth': depth.get(t, 0),
                'queued_tokens': tokens.get(t, 0),
                'weight': self.weight(t),
                'queue_waits': list(st.queue_waits) if st else [],
                'ttfts': list(st.ttfts) if st else [],
                'decode_tokens': st.decode_tokens if st else 0,
                'shed': st.shed if st else 0,
                'cancelled': st.cancelled if st else 0,
                'expired': st.expired if st else 0,
                'abandoned': st.abandoned if st else 0,
            }
        return out


def _merge_snapshots(snapshots: Iterable[Dict[str, Dict[str, Any]]]
                     ) -> Dict[str, Dict[str, Any]]:
    """Sum counters and concatenate raw windows across tiers. Kept
    OUT of ``aggregate_stats`` on purpose: that function is a
    SKY-REGISTRY metric surface, and these accumulator keys are
    internal, not emitted metric names."""
    merged: Dict[str, Dict[str, Any]] = {}
    for snap in snapshots:
        for t, s in snap.items():
            m = merged.get(t)
            if m is None:
                m = merged[t] = {k: (list(v) if isinstance(v, list)
                                     else v) for k, v in s.items()}
                continue
            for k, v in s.items():
                if isinstance(v, list):
                    m[k] = m[k] + v
                elif k != 'weight':     # weights agree across tiers
                    m[k] = m[k] + v
    return merged


def aggregate_stats(snapshots: Iterable[Dict[str, Dict[str, Any]]],
                    decode_time_s: float = 0.0) -> Dict[str, Dict]:
    """Merge scheduler ``snapshot()``s (one per engine tier) into the
    per-tenant metric dict surfaced as ``metrics()['tenants']``.
    ``decode_time_s`` is the engines' combined decode wall-clock — the
    denominator that makes ``tokens_per_sec`` honest across
    interleaved tiers (the EnginePool rule). The dict keys below are
    cataloged in docs/observability.md (SKY-REGISTRY)."""
    out: Dict[str, Dict] = {}
    for t, m in _merge_snapshots(snapshots).items():
        waits = sorted(m['queue_waits'])
        ttfts = sorted(m['ttfts'])
        w50, w99 = _pct(waits, 0.50), _pct(waits, 0.99)
        out[t] = {
            'queue_depth': m['queue_depth'],
            'queued_tokens': m['queued_tokens'],
            'weight': m['weight'],
            'queue_wait_p50_ms': (round(w50 * 1e3, 3)
                                  if w50 is not None else None),
            'queue_wait_p99_ms': (round(w99 * 1e3, 3)
                                  if w99 is not None else None),
            'ttft_p50_s': _pct(ttfts, 0.50),
            'ttft_p99_s': _pct(ttfts, 0.99),
            'decode_tokens': m['decode_tokens'],
            'tokens_per_sec': (m['decode_tokens'] / decode_time_s
                               if decode_time_s else 0.0),
            'requests_shed': m['shed'],
            'requests_cancelled': m['cancelled'],
            'requests_expired': m['expired'],
            'requests_abandoned': m['abandoned'],
        }
    return out


class FCFSScheduler(Scheduler):
    """The default policy — the base class IS fcfs; this subclass only
    pins the registry name."""
    name = 'fcfs'
