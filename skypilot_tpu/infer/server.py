"""HTTP inference server — the workload `sky-tpu serve` replicas run.

Endpoints (shape follows the reference's vLLM-serving examples,
reference llm/vllm/serve.yaml):

- ``GET  /health``     → 200 once the engine is warm (readiness probe).
- ``POST /generate``   → {"prompt": str | "tokens": [int], and optional
  "max_new_tokens", "temperature"} → completion JSON.
- ``GET  /metrics``    → engine metrics (TTFT p50, decode throughput).

A background thread drives ``engine.step()`` continuously; HTTP handlers
only enqueue requests and wait — many concurrent requests batch onto the
same decode steps (continuous batching).

Without a real checkpoint the server runs randomly-initialized weights
sized by ``--model`` (tiny/350m/8b) — enough for serving-layer load tests
and TTFT benchmarking; ``--checkpoint`` loads Orbax weights from
``train/checkpoint.py``.

Run: ``python -m skypilot_tpu.infer.server --port $SKYPILOT_SERVE_PORT``
"""
from __future__ import annotations

import argparse
import asyncio
import contextlib
import json
import logging
import os
import threading
import time
from typing import List, Optional

import aiohttp
import jax
from aiohttp import web

from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.models import llama
from skypilot_tpu.observability import prometheus as prom_lib
from skypilot_tpu.utils import common as common_lib
from skypilot_tpu.utils import failpoints

logger = logging.getLogger(__name__)

MODELS = {
    'tiny': llama.LlamaConfig.tiny,
    '350m': llama.LlamaConfig.bench_350m,
    '1b': llama.LlamaConfig.bench_1b,
    '8b': llama.LlamaConfig.llama3_8b,
}


class Tokenizer:
    """Text<->token codec for /generate.

    ``tokenizer.json`` (HuggingFace `tokenizers` fast format — ships
    with the baked-in transformers dependency) or a sentencepiece
    ``.model``; byte-level fallback otherwise, so `tokens`-only callers
    and tests need no vocab file. The reference's serving examples all
    run real tokenizers (reference llm/vllm) — the byte fallback is NOT
    the benchmark path (round-3 verdict, missing #4).
    """

    def __init__(self, path: str = None, vocab_limit: int = 0) -> None:
        self.kind = 'bytes'
        self._tok = None
        if path:
            if path.endswith('.json'):
                try:
                    from tokenizers import Tokenizer as HFTokenizer
                except ImportError:
                    raise SystemExit(
                        "the 'tokenizers' package is not installed in "
                        'this image; install it (it ships with '
                        'transformers) or serve with token ids only')
                self._tok = HFTokenizer.from_file(path)
                self.kind = 'hf'
                size = self._tok.get_vocab_size()
            else:
                try:
                    import sentencepiece as spm
                except ImportError:
                    raise SystemExit(
                        'sentencepiece not installed; use a '
                        'tokenizer.json (tokenizers format) instead')
                self._tok = spm.SentencePieceProcessor(model_file=path)
                self.kind = 'spm'
                size = self._tok.vocab_size()
            if vocab_limit and size > vocab_limit:
                raise SystemExit(
                    f'tokenizer vocab ({size}) exceeds the model vocab '
                    f'({vocab_limit}); ids would be out of range')

    def encode(self, text: str) -> List[int]:
        if self.kind == 'hf':
            return list(self._tok.encode(text).ids)
        if self.kind == 'spm':
            return list(self._tok.encode(text))
        return list(text.encode('utf-8'))

    def decode(self, tokens: List[int]) -> str:
        if self.kind == 'hf':
            return self._tok.decode(tokens)
        if self.kind == 'spm':
            # A model vocab larger than the spm vocab can sample ids the
            # tokenizer has no piece for; spm raises where the HF path
            # silently skips — filter to match.
            size = self._tok.vocab_size()
            return self._tok.decode([t for t in tokens if 0 <= t < size])
        try:
            return bytes(t for t in tokens if 0 <= t < 256).decode(
                'utf-8', errors='replace')
        except ValueError:
            return ''


def synthesize_wordlevel_tokenizer(vocab_size: int, path: str) -> str:
    """Write a derived HF-`tokenizers` WordLevel tokenizer.json of the
    requested vocab size and return ``path``.

    For vocab-size workload benchmarks (the 128k-vocab serving lane):
    what matters to TTFT/decode cost is the model's vocab dimension and
    the token-id distribution width, not linguistic quality — so a 24 MB
    trained BPE file has no business living in the repo (VERDICT r5
    weak #5). The derived vocab is the 256 byte tokens plus synthetic
    words, whitespace-pretokenized; deterministic, so repeated bench
    runs encode identically.
    """
    import json as json_lib
    vocab = {}
    # Byte tokens first: arbitrary prompt text keeps nonzero coverage.
    for b in range(min(256, vocab_size)):
        vocab[f'<0x{b:02X}>'] = b
    i = len(vocab)
    while i < vocab_size:
        vocab[f'w{i:07d}'] = i
        i += 1
    tok = {
        'version': '1.0',
        'truncation': None,
        'padding': None,
        'added_tokens': [],
        'normalizer': None,
        'pre_tokenizer': {'type': 'Whitespace'},
        'post_processor': None,
        'decoder': None,
        'model': {
            'type': 'WordLevel',
            'vocab': vocab,
            'unk_token': '<0x00>',
        },
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f'{path}.tmp.{os.getpid()}'
    with open(tmp, 'w', encoding='utf-8') as f:
        json_lib.dump(tok, f)
    os.replace(tmp, path)
    return path


def parse_tenant_weights(spec: Optional[str]) -> Optional[dict]:
    """``'tenantA=4,tenantB=1'`` → ``{'tenantA': 4.0, 'tenantB':
    1.0}`` (None/empty → None). Loud on malformed entries — a silently
    dropped weight is an unfair scheduler nobody can debug."""
    if not spec:
        return None
    out = {}
    for part in spec.split(','):
        part = part.strip()
        if not part:
            continue
        name, sep, val = part.partition('=')
        try:
            weight = float(val)
        except ValueError:
            weight = -1.0
        if not sep or not name.strip() or weight <= 0:
            raise SystemExit(
                f'bad --tenant-weights entry {part!r}: expected '
                f'name=positive_number')
        out[name.strip()] = weight
    return out or None


def setup_compile_cache(cache_dir: str) -> bool:
    """Point XLA's persistent compilation cache at ``cache_dir`` so a
    relaunched replica deserializes its warm-path programs instead of
    recompiling them — the dominant term of a scale-to-zero cold start
    after weights (docs/cost.md "Scale to zero"). The threshold tuning
    makes the very first boot populate the cache even for small
    programs, so the SECOND boot is the fast one.

    Degradation, not failure: on the ``infer.server.compile_cache_miss``
    failpoint or any real setup error (read-only dir, an XLA build
    without the flag) the server warms with a cold compile — slower
    first tokens, never a crash."""
    try:
        failpoints.hit('infer.server.compile_cache_miss')
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update('jax_compilation_cache_dir', cache_dir)
        # Cache everything: the default min-compile-time gate would
        # skip exactly the small warm-path programs a cold start
        # replays.
        jax.config.update('jax_persistent_cache_min_compile_time_secs',
                          0.0)
        jax.config.update('jax_persistent_cache_min_entry_size_bytes',
                          -1)
        logger.info('persistent compile cache at %s', cache_dir)
        return True
    except failpoints.FailpointError as e:
        logger.warning('compile cache miss injected (%s): serving '
                       'with a cold compile', e)
        return False
    except Exception as e:  # noqa: BLE001 — cache is an optimization
        logger.warning('compile cache setup failed (%s: %s): serving '
                       'with a cold compile', type(e).__name__, e)
        return False


class IncrementalDecoder:
    """Streaming detokenizer with an O(window) cost per flush.

    The cumulative approach (decode ALL tokens so far, emit the suffix)
    was multibyte-correct but O(n²) over a stream: a 1k-token response
    re-decoded ~500k token positions. This keeps the correctness and
    drops the cost: decode a window starting at the last CLEAN commit
    point (a flush whose text did not end in a dangling U+FFFD) and
    emit only the stable part.

    Stability rule: a truncated multibyte sequence at the end of the
    byte stream collapses to exactly ONE trailing U+FFFD under
    ``errors='replace'`` — so only the window's final U+FFFD can still
    transform once more tokens arrive; everything before it is
    permanent. Holding back just that one character keeps the
    concatenated stream identical to the one-shot decode for the byte
    fallback, clean text and garbage soup alike.

    Window restarts keep ``_CONTEXT`` tokens of overlap: real
    tokenizers (HF/sentencepiece) are NOT concatenative across a cut —
    the joining space between tokens n-1 and n only renders when both
    are decoded together — so each new window re-decodes a small
    already-emitted suffix purely as context (the vLLM
    detokenize-incrementally trick). ``_MAX_WINDOW`` bounds the window
    (and so the per-flush cost) against a pathological never-clean
    stream.
    """

    _CONTEXT = 4       # overlap tokens kept when the window restarts
    _MAX_WINDOW = 64   # tokens; forces a boundary on pathological input

    def __init__(self, tokenizer: 'Tokenizer') -> None:
        self._tok = tokenizer
        self._prefix = 0    # token index where the decode window starts
        self._emitted = 0   # chars of decode(window) already emitted

    def feed(self, tokens: List[int], n: Optional[int] = None) -> str:
        """New text for ``tokens[:n]`` (the output list so far; ``n``
        defaults to all of it, and passing the LIVE list plus an
        explicit ``n`` avoids copying the cumulative prefix on every
        flush); may be '' while a possibly-split multibyte character is
        pending."""
        if n is None:
            n = len(tokens)
        window = self._tok.decode(tokens[self._prefix:n])
        if (not window.endswith('\ufffd')
                or n - self._prefix >= self._MAX_WINDOW):
            # Clean end (or a pathological never-clean stream hitting
            # the cost bound): emit the rest, restart the window with
            # _CONTEXT tokens of overlap marked as already emitted.
            delta = window[self._emitted:]
            self._prefix = max(0, n - self._CONTEXT)
            self._emitted = len(
                self._tok.decode(tokens[self._prefix:n]))
            return delta
        # Hold back ONLY the final replacement char — the sole char
        # that can still become a real character; the rest is stable.
        stable = len(window) - 1
        delta = window[self._emitted:stable]
        self._emitted = max(self._emitted, stable)
        return delta

    def flush(self, tokens: List[int], n: Optional[int] = None) -> str:
        """Stream end: surface anything still held back."""
        if n is None:
            n = len(tokens)
        window = self._tok.decode(tokens[self._prefix:n])
        delta = window[self._emitted:]
        self._prefix = n
        self._emitted = 0
        return delta


class _TokenWaiter:
    """asyncio bridge for engine token events.

    The engine's consumer thread fires ``Request`` listeners on every
    appended token and on finish; this relays them onto the handler's
    event loop so ``h_generate`` awaits tokens instead of sleep-polling
    ``output_tokens`` at a 2–5 ms cadence (which cost a poll interval
    of added latency per flush and woke the loop ~400x/s per request).
    The timeout passed to :meth:`wait` is only a safety net — it lets
    the handler notice a dead engine, not deliver tokens.
    """

    def __init__(self, req) -> None:
        self._req = req
        self._ev = asyncio.Event()
        loop = asyncio.get_running_loop()

        def _on_progress() -> None:
            try:
                loop.call_soon_threadsafe(self._ev.set)
            except RuntimeError:   # loop already closed mid-shutdown
                pass

        self._cb = _on_progress
        req.add_listener(self._cb)
        if req.output_tokens or req.done:
            self._ev.set()   # progress predating the registration

    async def wait(self, timeout: float) -> None:
        with contextlib.suppress(asyncio.TimeoutError):
            await asyncio.wait_for(self._ev.wait(), timeout)
        self._ev.clear()

    def close(self) -> None:
        self._req.remove_listener(self._cb)


class InferenceServer:
    # Concurrency contract (SKY-LOCK): the drain/admission state below
    # is asyncio-confined — only /drain, /generate and /metrics
    # handlers (and their sync helpers, which the interprocedural pass
    # proves are only reached from coroutines) touch it. The ENGINE
    # thread must never write these: it reports through
    # engine.metrics() under the engine lock instead. `ready`/`dead`
    # stay unregistered on purpose — they are GIL-atomic one-way flags
    # the engine thread flips exactly once.
    _GUARDED_BY = {
        '_active': 'event-loop',
        '_requests_shed': 'event-loop',
        'draining': 'event-loop',
        '_drain_started': 'event-loop',
        'drain_duration_s': 'event-loop',
    }

    def __init__(self, engine: engine_lib.InferenceEngine,
                 tokenizer: Tokenizer = None, driver=None,
                 boot_t0: Optional[float] = None,
                 role: str = 'mixed',
                 kv_pull_timeout_s: float = 10.0,
                 kv_export_max_pages: int = 64) -> None:
        self.engine = engine
        self.tokenizer = tokenizer or Tokenizer()
        # Disaggregation role (docs/serving.md "Disaggregated
        # prefill/decode"): advertised via /metrics so the LB routes
        # by it. The server itself never refuses work by role — the
        # LB steers; a mis-routed request still computes correctly.
        if role not in ('mixed', 'prefill', 'decode'):
            raise ValueError(f'role must be mixed|prefill|decode, '
                             f'got {role!r}')
        self.role = role
        # KV streaming knobs: donor-pull budget (fetch + attach), and
        # the largest prefix one export ships (pages beyond the cap
        # are recomputed locally — bounds donor readback and blob
        # size).
        self.kv_pull_timeout_s = kv_pull_timeout_s
        self.kv_export_max_pages = kv_export_max_pages
        # Cold-start stopwatch origin: process start (main() stamps
        # it) — the compile stamp reports total time-to-serviceable,
        # not just the warm loop.
        self.boot_t0 = boot_t0 if boot_t0 is not None else time.time()
        # Multi-host replica: submissions go through the lockstep
        # broadcast driver (infer/multihost.py) instead of the local
        # engine queue.
        self.driver = driver
        self.ready = False
        self.dead: str = ''
        # Graceful drain (docs/robustness.md "Zero-downtime serving"):
        # once draining, /generate refuses new work (503), /health
        # reports 'draining' so the serve layer pulls this replica from
        # the ready set, and /drain long-polls until the last in-flight
        # request finishes — event-driven, no poll loop anywhere.
        self.draining = False
        self._drain_started: Optional[float] = None
        self.drain_duration_s: Optional[float] = None
        self._active = 0            # in-flight /generate handlers
        self._drained_ev = asyncio.Event()
        self._requests_shed = 0     # 429s answered (admission control)
        self._stop = threading.Event()
        self._woken = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name='engine-loop')

    def _loop(self) -> None:
        try:
            # Warm the decode program once so /health flips only when
            # real traffic would not hit a multi-second compile.
            t0 = time.time()
            if self.driver is not None:
                # Lockstep mode: this thread runs the tick loop; the
                # warm request is submitted from a side thread because
                # driver.submit blocks until a tick admits it.
                def _warm():
                    reqs = [self.driver.submit([1], max_new_tokens=2)]
                    if hasattr(self.engine, 'engines'):
                        tiers = self.engine.engines
                        for prev in tiers[:-1]:
                            reqs.append(self.driver.submit(
                                [1] * prev.ecfg.max_seq_len,
                                max_new_tokens=2))
                    for r in reqs:
                        r.wait_done()   # token events, not sleep-polls
                    logger.info('engine warm in %.1fs',
                                time.time() - t0)
                    self.engine.note_lifecycle_event(
                        'coldstart.compiled',
                        warm_s=round(time.time() - t0, 3),
                        total_s=round(time.time() - self.boot_t0, 3))
                    self.ready = True
                threading.Thread(target=_warm, daemon=True).start()
                self.driver.run()
                return
            warm_reqs = [self.engine.submit([1], max_new_tokens=2)]
            if hasattr(self.engine, 'engines'):
                # Pool: compile every tier before declaring ready (a
                # long prompt must not eat a multi-second first-compile
                # mid-traffic).
                tiers = self.engine.engines
                for prev, eng in zip(tiers, tiers[1:]):
                    # A prompt just past the previous tier's cap is
                    # guaranteed to route to THIS tier.
                    n = prev.ecfg.max_seq_len
                    warm_reqs.append(self.engine.submit(
                        [1] * n, max_new_tokens=2))
            while not all(w.done for w in warm_reqs):
                self.engine.step()
            logger.info('engine warm in %.1fs', time.time() - t0)
            # Cold-start timeline (docs/cost.md "Scale to zero"):
            # weights_loaded was stamped by main(); this is the
            # compile→serviceable edge the wake path waits on.
            self.engine.note_lifecycle_event(
                'coldstart.compiled',
                warm_s=round(time.time() - t0, 3),
                total_s=round(time.time() - self.boot_t0, 3))
            self.ready = True
            while not self._stop.is_set():
                if self.engine.step() == 0:
                    # Idle: block until a submit wakes us (the timeout
                    # is a safety net, not a poll cadence — h_generate
                    # sets the event on every submission).
                    self._woken.wait(timeout=0.1)
                    self._woken.clear()
        except Exception as e:  # noqa: BLE001 — a dead loop must unready
            logger.exception('engine loop died')
            # /health flips to 503 so the serve layer replaces this
            # replica instead of routing into a wedged engine.
            self.dead = f'{type(e).__name__}: {e}'
            self.ready = False

    async def h_health(self, _req: web.Request) -> web.Response:
        if self.dead:
            return web.json_response(
                {'status': 'dead', 'error': self.dead}, status=503)
        if self.engine.integrity_suspect():
            # The on-device SDC sentinel tripped: this replica's
            # device produces garbage. Mirrors the draining contract
            # (503 pulls it from the ready set) — the golden-probe
            # plane quarantines and replaces it
            # (docs/robustness.md "Data integrity").
            return web.json_response({'status': 'corrupt'}, status=503)
        if self.draining:
            # 503 on purpose: the replica manager's readiness probe
            # fails, so the LB pulls this replica from the ready set
            # while the in-flight tail finishes.
            return web.json_response(
                {'status': 'draining', 'inflight': self._active},
                status=503)
        if not self.ready:
            return web.json_response({'status': 'warming'}, status=503)
        return web.json_response({'status': 'ok'})

    async def h_metrics(self, req: web.Request) -> web.Response:
        m = self.engine.metrics()
        m['draining'] = self.draining
        m['server_inflight'] = self._active
        m['requests_shed'] = self._requests_shed
        m['role'] = self.role
        if self.drain_duration_s is not None:
            m['drain_duration_s'] = round(self.drain_duration_s, 4)
        if self.engine.kv_index_armed():
            # Radix summary for the LB's fleet prefix index
            # (docs/serving.md "Disaggregated prefill/decode"):
            # `?prefix_gen=N` is the caller's last-seen generation, so
            # steady-state ticks carry a tiny journal delta instead of
            # the full hash list. Rendering rides the same sync-tick
            # fetch — no extra endpoint, no extra poll.
            try:
                since_gen = int(req.query.get('prefix_gen', -1))
            except ValueError:
                since_gen = -1
            m['kv_prefix_index'] = self.engine.kv_index_snapshot(
                since_gen)
        # `?format=prometheus` wraps the same gauges in text
        # exposition (docs/observability.md "Prometheus exposition");
        # JSON stays the default — the LB sync tick and the bench
        # parse it.
        if req.query.get('format') == 'prometheus':
            return web.Response(text=prom_lib.render_replica(m),
                                content_type='text/plain',
                                charset='utf-8')
        return web.json_response(m)

    async def h_stepline(self, _req: web.Request) -> web.Response:
        """Flight-recorder snapshot (docs/observability.md "Flight
        recorder"): the step ring + request timeline as JSON.
        ``sky-tpu profile <replica-url>`` fetches this and renders it
        as a Perfetto trace. The engine lock is held only for the
        ring's pointer copy; the O(ring) dict rendering AND the
        multi-MB json.dumps both run off the event loop — a 1 Hz
        profile poll must not inject stalls into in-flight token
        streams."""
        def _render() -> str:
            return json.dumps(self.engine.stepline_snapshot())
        body = await asyncio.to_thread(_render)
        return web.Response(text=body,
                            content_type='application/json')

    # -- KV prefix streaming (disaggregated prefill/decode) ----------------
    async def h_kv_export(self, request: web.Request) -> web.Response:
        """Ship this replica's cached KV pages for a prompt prefix in
        the int8 on-wire page format (infer/kv_wire.py): the donor half
        of a fleet-routed prefix transfer. The readback itself runs on
        the engine thread between steps (request_kv_export), so an
        export never races a decode dispatch; the handler only waits.

        Responses: 200 + octet-stream blob, 404 when nothing is cached
        for the prompt (a clean miss — the puller just recomputes), 409
        when the prefix cache is off, 503 on an engine-side error or a
        wait past the transfer budget. Every non-200 degrades the
        puller to plain recompute — never a client-visible error.
        """
        if not self.engine.kv_index_armed():
            return web.json_response(
                {'error': 'prefix cache disabled'}, status=409)
        try:
            body = await request.json()
            tokens = [int(t) for t in body['tokens']]
        except (ValueError, UnicodeDecodeError, KeyError, TypeError):
            # Narrow on purpose (SKY-EXCEPT): resets/cancellations
            # during the body read must propagate.
            return web.json_response(
                {'error': 'need {"tokens": [int, ...]}'}, status=400)
        cap = self.kv_export_max_pages * (self.engine.kv_page_size()
                                          or 1)
        job = self.engine.request_kv_export(tokens[:cap])
        self._woken.set()
        done = await asyncio.to_thread(job.wait, self.kv_pull_timeout_s)
        if not done or job.error is not None:
            return web.json_response(
                {'error': 'export failed' if done else 'export timed '
                 'out'}, status=503)
        if job.result is None:
            return web.json_response(
                {'error': 'no cached prefix'}, status=404)
        blob = job.result
        # Chaos seam (docs/robustness.md site catalog): `error` mode
        # flips payload bytes IN FLIGHT — the importer's per-page CRC
        # must catch it and the puller must degrade to recompute, which
        # is exactly what tests/chaos/test_disagg_chaos.py gates.
        try:
            failpoints.hit('infer.server.kv_export_corrupt')
        except failpoints.FailpointError:
            blob = blob[:-1] + bytes([blob[-1] ^ 0xFF])
        return web.Response(body=blob,
                            content_type='application/octet-stream')

    async def _pull_kv(self, donor_url: str, tokens: List[int]) -> None:
        """Pull the donor's cached prefix and attach it locally before
        prefilling (the decode half of a fleet-routed transfer).
        Best-effort end to end: ANY failure — donor unreachable, donor
        evicted the prefix, stalled link past the budget, CRC mismatch,
        local page-pool dry — lands on plain recompute; the request
        never sees an error. A donor 404 is a clean stale-index miss,
        not a transfer failure."""
        url = donor_url.rstrip('/') + '/kv/export'
        t0 = time.monotonic()
        try:
            timeout = aiohttp.ClientTimeout(total=self.kv_pull_timeout_s)
            async with aiohttp.ClientSession(timeout=timeout) as sess:
                async with sess.post(url,
                                     json={'tokens': tokens}) as resp:
                    if resp.status == 404:
                        return
                    if resp.status != 200:
                        self.engine.note_kv_transfer_failure()
                        return
                    blob = await resp.read()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            self.engine.note_kv_transfer_failure()
            return
        # Attach on the engine thread (request_kv_import): the fetch
        # wall time rides along so kv_transfer_p99_s covers the whole
        # pull, not just the attach.
        job = self.engine.request_kv_import(
            blob, fetch_s=time.monotonic() - t0)
        self._woken.set()
        done = await asyncio.to_thread(job.wait, self.kv_pull_timeout_s)
        if not done:
            # Import errors (CRC, geometry, pool dry) are already
            # counted by the engine; only a wait past the budget is
            # ours to count.
            self.engine.note_kv_transfer_failure()

    # -- graceful drain ----------------------------------------------------
    def _enter_drain(self) -> None:
        if self.draining:
            return
        self.draining = True
        self._drain_started = time.time()
        logger.info('drain: stopped admitting (%d in flight)',
                    self._active)
        if self._active == 0:
            self._mark_drained()

    def _mark_drained(self) -> None:
        if self.drain_duration_s is None:
            self.drain_duration_s = time.time() - (self._drain_started
                                                   or time.time())
        self._drained_ev.set()

    async def h_drain(self, request: web.Request) -> web.Response:
        """Flip to draining and LONG-POLL until every in-flight request
        finished (or ``deadline_s`` lapsed): the caller (the serve
        replica manager, before terminating the slice) makes exactly
        one blocking call — the response arrives the moment the last
        stream ends, event-driven on both sides."""
        try:
            body = await request.json()
        except (ValueError, UnicodeDecodeError):
            # Bare/garbled POST = default deadline. Narrow on purpose
            # (SKY-EXCEPT): a connection reset or cancellation during
            # the body read must propagate, not be mistaken for an
            # empty drain request.
            body = {}
        try:
            deadline_s = float(body.get('deadline_s', 30.0))
        except (TypeError, ValueError):
            deadline_s = 30.0
        self._enter_drain()
        # Chaos seam: `hang` parks the drain past the manager's HTTP
        # timeout — teardown must proceed anyway (a wedged drain must
        # never block replacement forever).
        await failpoints.hit_async('infer.server.drain_hang')
        if not self._drained_ev.is_set():
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(self._drained_ev.wait(),
                                       max(0.0, deadline_s))
        drained = self._drained_ev.is_set()
        return web.json_response({
            'status': 'drained' if drained else 'draining',
            'inflight': self._active,
            'drain_duration_s': self.drain_duration_s,
        })

    def _cancel_request(self, req) -> None:
        """Client went away: free the engine slot now (queued → dropped
        before admission, decoding → slot freed, clean pages donated to
        the prefix cache) instead of generating to nobody. Lockstep
        replicas skip it (request state must stay host-identical)."""
        if self.driver is None and hasattr(self.engine, 'cancel'):
            self.engine.cancel(req)

    async def h_generate(self, request: web.Request) -> web.Response:
        # In-flight accounting starts BEFORE the first await: a request
        # suspended in body-parse or engine submit must hold the drain
        # open, or /drain could report 'drained' (and teardown proceed)
        # while this handler goes on to admit work — the exact
        # truncation the drain contract forbids.
        self._active += 1
        try:
            return await self._admit_generate(request)
        finally:
            self._active -= 1
            if self.draining and self._active == 0:
                self._mark_drained()

    async def _admit_generate(self, request: web.Request) -> web.Response:
        if self.engine.integrity_suspect():
            # The SDC sentinel tripped: this device emits garbage —
            # shed EVERYTHING with the quarantined marker. The LB
            # treats it like a drain 503 (release, never a breaker
            # failure) and retries elsewhere; Retry-After covers the
            # window until the control plane replaces us.
            return web.json_response(
                {'error': 'replica corrupt', 'quarantined': True},
                status=503, headers={'Retry-After': '1'})
        if self.draining:
            # Admission stops the moment drain begins; the LB routes
            # around us (it pulls the replica once health flips, and
            # retries a 503 on another replica meanwhile).
            return web.json_response(
                {'error': 'replica draining', 'draining': True},
                status=503, headers={'Retry-After': '1'})
        try:
            body = await request.json()
        except (ValueError, UnicodeDecodeError):
            # Narrow on purpose (SKY-EXCEPT): only a genuinely
            # malformed body earns a 400. A client that vanished
            # mid-upload raises a reset/cancellation that must
            # propagate — writing 400 to the dead socket would count
            # a disconnect as a caller error.
            return web.json_response({'error': 'malformed JSON'},
                                     status=400)
        if 'tokens' in body:
            tokens = [int(t) for t in body['tokens']]
        elif 'prompt' in body:
            tokens = self.tokenizer.encode(str(body['prompt']))
        else:
            return web.json_response(
                {'error': 'need "tokens" or "prompt"'}, status=400)
        resume = body.get('resume_from')
        if resume is not None:
            # Mid-stream failover continuation (the serve LB re-issues
            # a died stream with the tokens it already delivered): the
            # engine prefills prompt+resume — a near-pure prefix-cache
            # hit under cache_aware routing — and only NEW tokens are
            # ever emitted below.
            try:
                resume = [int(t) for t in resume]
            except (TypeError, ValueError):
                return web.json_response(
                    {'error': '"resume_from" must be a token id list'},
                    status=400)
        deadline = None
        hdr = request.headers.get(common_lib.DEADLINE_HEADER)
        if hdr and self.driver is None:
            # Wall-clock budget from the LB. Lockstep replicas ignore
            # it (host clocks differ; see engine.set_wallclock_cancel).
            try:
                budget_s = float(hdr)
            except ValueError:
                return web.json_response(
                    {'error': f'bad {common_lib.DEADLINE_HEADER} '
                              f'header: {hdr!r}'}, status=400)
            if budget_s <= 0:
                return web.json_response(
                    {'error': 'deadline already exceeded'}, status=504)
            deadline = time.time() + budget_s
        # Multi-tenant identity: the X-SkyTpu-Tenant header (forwarded
        # by the serve LB) wins; a 'tenant' body field is the
        # header-less fallback. The scheduler uses it for fair
        # queueing/quotas; metrics break down by it.
        tenant = (request.headers.get(common_lib.TENANT_HEADER)
                  or str(body.get('tenant') or '') or 'default')
        if len(tenant) > 128:
            return web.json_response(
                {'error': 'tenant id too long (>128 chars)'},
                status=400)
        if self.engine.integrity_suspect():
            # Sentinel may have tripped while we were parsing the
            # body — re-check at the admission edge, like drain.
            return web.json_response(
                {'error': 'replica corrupt', 'quarantined': True},
                status=503, headers={'Retry-After': '1'})
        if self.draining:
            # Drain may have begun while we were parsing the body —
            # re-check at the admission edge (the in-flight counter is
            # already held, so the drain cannot have completed).
            return web.json_response(
                {'error': 'replica draining', 'draining': True},
                status=503, headers={'Retry-After': '1'})
        donor = request.headers.get(common_lib.KV_DONOR_HEADER)
        if (donor and self.driver is None
                and self.engine.kv_index_armed()):
            # Fleet-routed miss-with-remote-hit: the LB saw a longer
            # cached prefix on `donor` than here. Pull those pages
            # before submit so the prefill below starts from the
            # transferred boundary (a near-pure prefix-cache hit);
            # every failure path inside degrades to plain recompute.
            # Lockstep replicas skip it (per-host page pools would
            # diverge).
            await self._pull_kv(donor, tokens)
        try:
            # Admission span parented to the LB's lb.proxy hop (the
            # traceparent header it forwards); decode time is the
            # request's own life, not admission — so the span covers
            # submit only. No-op without SKY_TPU_TRACE.
            from skypilot_tpu.observability import trace as trace_lib
            with trace_lib.context_from(
                    request.headers.get(trace_lib.HEADER)), \
                    trace_lib.span('infer.submit', hop='infer',
                                   prompt_tokens=len(tokens)):
                if self.driver is not None:
                    # Blocks until the next lockstep tick admits it on
                    # every host — off the event loop.
                    req = await asyncio.to_thread(
                        self.driver.submit, tokens,
                        body.get('max_new_tokens'),
                        float(body.get('temperature', 0.0)),
                        resume)
                else:
                    req = self.engine.submit(
                        tokens,
                        max_new_tokens=body.get('max_new_tokens'),
                        temperature=float(body.get('temperature', 0.0)),
                        resume_tokens=resume,
                        deadline=deadline,
                        tenant=tenant,
                        # Per-request speculation opt-out ("spec":
                        # false) — the spec-off baseline lane of
                        # bench_ttft --sweep speculative; outputs are
                        # bit-identical either way.
                        spec=bool(body.get('spec', True)))
        except engine_lib.AdmissionError as e:
            # Bounded admission: shed with 429 + Retry-After instead of
            # queueing unboundedly (the LB tries other replicas first).
            self._requests_shed += 1
            return web.json_response(
                {'error': str(e)}, status=429,
                headers={'Retry-After':
                         str(max(1, int(round(e.retry_after_s))))})
        except ValueError as e:
            return web.json_response({'error': str(e)}, status=400)
        self._woken.set()
        return await self._answer_generate(request, body, req)

    async def _answer_generate(self, request: web.Request, body: dict,
                               req) -> web.Response:
        if body.get('stream'):
            # Token streaming (what a production LLM endpoint serves):
            # one JSON line per token batch, flushed as the engine emits
            # them — the first byte leaves at the FIRST token, so
            # LB-measured TTFT is true time-to-first-token, not
            # time-to-full-completion.
            if self.dead:
                # Before prepare(): once 200 headers are out, a dead
                # engine would masquerade as a valid TTFT sample to the
                # LB (which excludes 5xx from the distribution).
                return web.json_response(
                    {'error': f'engine died: {self.dead}'}, status=500)
            resp = web.StreamResponse()
            resp.content_type = 'application/jsonlines'
            await resp.prepare(request)
            # A resumed stream (mid-stream failover) never re-emits the
            # tokens the LB already delivered: emission starts at the
            # resume boundary, and the decoder is primed with the
            # resumed prefix (delta discarded — the pre-failover leg
            # already streamed that text) so windows stay token-exact.
            sent = req.resumed_from
            # Incremental detokenization (O(window) per flush, not a
            # cumulative re-decode) + event-driven flushes: each line
            # leaves the moment the engine's consume appends tokens.
            decoder = IncrementalDecoder(self.tokenizer)
            if sent:
                decoder.feed(req.output_tokens, sent)
            waiter = _TokenWaiter(req)
            try:
                while True:
                    if self.dead:
                        await resp.write(json.dumps(
                            {'error':
                             f'engine died: {self.dead}'}).encode()
                            + b'\n')
                        break
                    done = req.done       # read BEFORE the token count:
                    n = len(req.output_tokens)   # done ⇒ n is final
                    if n > sent:
                        chunk = req.output_tokens[sent:n]
                        delta = decoder.feed(req.output_tokens, n)
                        await resp.write(json.dumps(
                            {'tokens': chunk,
                             'text': delta}).encode()
                            + b'\n')
                        sent = n
                    if done and sent == len(req.output_tokens):
                        tail = decoder.flush(req.output_tokens, sent)
                        if tail:
                            await resp.write(json.dumps(
                                {'tokens': [],
                                 'text': tail}).encode() + b'\n')
                        await resp.write(json.dumps(
                            {'done': True, 'request_id': req.request_id,
                             'finish_reason': req.finish_reason,
                             'ttft_s': req.ttft,
                             # TTFT's scheduling share (submit → first
                             # chunk dispatch): lets the bench
                             # attribute queueing apart from prefill.
                             'queue_wait_s': req.queue_wait,
                             # Prompt tokens served from the shared-
                             # prefix KV cache (prefill skipped).
                             'cached_tokens': req.cached_tokens,
                             # Mean tokens landed per verify step for
                             # THIS request (speculative decoding);
                             # None when it never rode a verify step.
                             'accepted_len_mean': (round(
                                 req.spec_emitted / req.spec_steps, 3)
                                 if req.spec_steps else None)
                             }).encode() + b'\n')
                        break
                    await waiter.wait(1.0)
            except ConnectionResetError:
                # Client vanished mid-stream (aiohttp raises on the
                # write): free the engine slot now — its clean pages
                # donate to the prefix cache — instead of decoding to
                # nobody. Return the broken response quietly; there is
                # nobody left to answer.
                self._cancel_request(req)
                return resp
            except asyncio.CancelledError:
                self._cancel_request(req)
                raise
            finally:
                waiter.close()
            await resp.write_eof()
            return resp
        waiter = _TokenWaiter(req)
        try:
            while not req.done:
                if self.dead:
                    return web.json_response(
                        {'error': f'engine died: {self.dead}'},
                        status=500)
                tr = request.transport
                if tr is None or tr.is_closing():
                    # Non-streaming caller went away: nothing will ever
                    # read the answer — cancel (frees the slot/pages).
                    # Checked on each token event (≤1s safety net), not
                    # on a poll cadence.
                    self._cancel_request(req)
                    return web.Response(status=499)
                await waiter.wait(1.0)
        except asyncio.CancelledError:
            self._cancel_request(req)
            raise
        finally:
            waiter.close()
        if (req.finish_reason == 'deadline'
                and len(req.output_tokens) <= req.resumed_from):
            # Expired before producing anything: a real timeout, not a
            # truncated-but-usable completion.
            return web.json_response(
                {'error': 'deadline exceeded before first token',
                 'finish_reason': 'deadline'}, status=504)
        return web.json_response({
            'request_id': req.request_id,
            'tokens': req.output_tokens,
            'text': self.tokenizer.decode(req.output_tokens),
            'finish_reason': req.finish_reason,
            'ttft_s': req.ttft,
            'queue_wait_s': req.queue_wait,
            'cached_tokens': req.cached_tokens,
            'accepted_len_mean': (round(
                req.spec_emitted / req.spec_steps, 3)
                if req.spec_steps else None),
        })

    def make_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get('/health', self.h_health)
        app.router.add_get('/metrics', self.h_metrics)
        app.router.add_get('/debug/stepline', self.h_stepline)
        app.router.add_post('/generate', self.h_generate)
        app.router.add_post('/kv/export', self.h_kv_export)
        app.router.add_post('/drain', self.h_drain)
        return app

    def run(self, host: str, port: int) -> None:
        self._thread.start()
        web.run_app(self.make_app(), host=host, port=port,
                    print=lambda *_: None)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--host', default='0.0.0.0')
    parser.add_argument('--port', type=int, required=True)
    parser.add_argument('--model', default='tiny', choices=sorted(MODELS))
    parser.add_argument('--checkpoint', default=None,
                        help='Orbax checkpoint dir (train/checkpoint.py)')
    parser.add_argument('--slots', type=int, default=8)
    parser.add_argument('--max-seq-len', type=int, default=1024)
    parser.add_argument('--long-slots', type=int, default=0,
                        help='Add a second engine pool with this many '
                             'slots at --long-seq-len: long prompts '
                             'route there, so HBM is '
                             'slots*max_seq + long_slots*long_seq '
                             'instead of every slot paying the '
                             'longest length (two-tier KV).')
    parser.add_argument('--long-seq-len', type=int, default=8192)
    parser.add_argument('--paged', action='store_true',
                        help='Paged KV cache (block tables over a '
                             'shared page pool): HBM ∝ tokens-in-'
                             'flight, one engine serves mixed 2k/16k '
                             'prompts — supersedes --long-slots '
                             '(infer/paged_cache.py).')
    parser.add_argument('--page-size', type=int, default=64)
    parser.add_argument('--n-pages', type=int, default=None,
                        help='Page-pool size (default: dense-equivalent '
                             'slots*max_seq/page; lower it to cap KV '
                             'HBM at expected tokens-in-flight)')
    parser.add_argument('--kv-dtype', default='bfloat16',
                        choices=['bfloat16', 'int8'],
                        help='KV page value dtype (requires --paged '
                             'for int8): int8 pages carry per-row '
                             'absmax scales (quant-on-write, dequant-'
                             'in-kernel) — half the KV bytes per '
                             'token, ~2x resident pages per HBM '
                             'budget. Greedy output is gated at a '
                             'pinned tolerance vs bf16, not '
                             'bit-identical.')
    parser.add_argument('--fused-prefill', action='store_true',
                        help='Fused mixed steps (docs/serving.md): '
                             'while slots decode, one prefill chunk '
                             'rides the decode dispatch as a single '
                             'device program instead of a standalone '
                             'prefill dispatch stalling the decode '
                             'batch — long prompts stop showing up '
                             'as victim ITL spikes. Greedy outputs '
                             'are bit-identical fused on/off.')
    parser.add_argument('--prefix-cache', action='store_true',
                        help='Shared-prefix KV reuse over the paged '
                             'pool (requires --paged): repeated prompt '
                             'prefixes attach cached pages instead of '
                             're-prefilling (infer/prefix_cache.py); '
                             '/metrics gains prefix_* counters and '
                             'responses a cached_tokens field.')
    parser.add_argument('--tp', type=int, default=1,
                        help='Tensor-parallel degree over local devices '
                             '(8B-class models need tp>=4 on v5e in '
                             'bf16, or --quantize on one chip)')
    parser.add_argument('--quantize', action='store_true',
                        help='int8 weight-only quantization '
                             '(ops/quant.py): 8B fits one v5e chip')
    parser.add_argument('--tokenizer', default=None,
                        help='tokenizer.json (tokenizers format) or '
                             'sentencepiece .model for /generate text')
    parser.add_argument('--max-queue-requests', type=int, default=None,
                        help='Admission control: refuse new work (HTTP '
                             '429 + Retry-After) once this many '
                             'requests wait in the engine queue, '
                             'instead of queueing unboundedly '
                             '(docs/robustness.md "Zero-downtime '
                             'serving"). Default: unbounded.')
    parser.add_argument('--max-queue-tokens', type=int, default=None,
                        help='Companion cap on total queued '
                             'prompt+resume tokens (sheds few-but-'
                             'huge prompts the request cap misses).')
    parser.add_argument('--scheduler', default='fcfs',
                        choices=['fcfs', 'deadline', 'wfq'],
                        help='Step-loop scheduling policy '
                             '(docs/serving.md "Engine scheduler"): '
                             'fcfs (default), deadline (EDF over '
                             'X-SkyTpu-Deadline-S budgets), wfq '
                             '(per-tenant weighted fair queueing over '
                             'X-SkyTpu-Tenant with quota shedding).')
    parser.add_argument('--tenant-weights', default=None,
                        help="wfq weights as 'tenantA=4,tenantB=1' "
                             '(unlisted tenants weigh 1.0).')
    parser.add_argument('--spec-k', type=int, default=0,
                        help='Self-speculative decoding draft width '
                             '(docs/serving.md "Speculative '
                             'decoding"): a prompt-lookup drafter '
                             'proposes up to this many tokens per '
                             'greedy slot and one fused verify step '
                             'scores them all — accepted runs emit '
                             'up to spec_k+1 tokens per engine step '
                             'with BIT-IDENTICAL greedy output. 0 = '
                             'off (default; multi-host lockstep '
                             'replicas always run 0).')
    parser.add_argument('--spec-ngram', type=int, default=3,
                        help='Longest trailing n-gram the drafter '
                             'matches (falls back to shorter grams).')
    parser.add_argument('--no-stepline', action='store_true',
                        help='Disable the engine flight recorder '
                             '(docs/observability.md "Flight '
                             'recorder"). On by default: a fixed-size '
                             'ring of per-step records + request '
                             'timelines at GET /debug/stepline, '
                             'snapshotted into the span store on '
                             'anomalies (TTFT-SLO breach, preemption, '
                             'cache_full, admission shed).')
    parser.add_argument('--stepline-cap', type=int, default=None,
                        help='Flight-recorder ring capacity in step '
                             'records (default: SKY_TPU_STEPLINE_CAP '
                             'or 1024).')
    parser.add_argument('--ttft-slo-s', type=float, default=None,
                        help='TTFT SLO in seconds: a first token '
                             'slower than this triggers a flight-'
                             'recorder anomaly dump (read later with '
                             '`sky-tpu profile`). Default: no SLO '
                             'trigger.')
    parser.add_argument('--compile-cache-dir', default=None,
                        help='Persistent XLA compilation cache dir '
                             '(docs/cost.md "Scale to zero"): a '
                             'relaunched replica deserializes its '
                             'warm-path programs instead of '
                             'recompiling, cutting cold-start '
                             'time-to-ready. Survives restarts; share '
                             'it across replicas of one service.')
    parser.add_argument('--no-sdc-sentinel', action='store_true',
                        help='Disable the on-device SDC sentinel '
                             '(docs/robustness.md "Data integrity"). '
                             'On by default: an isfinite reduction '
                             'over each step\'s logits rides the '
                             'existing readback; a NaN/inf hit marks '
                             'the replica corrupt (503 /health) until '
                             'it is replaced. Greedy outputs are '
                             'bit-identical either way.')
    parser.add_argument('--role', default='mixed',
                        choices=['mixed', 'prefill', 'decode'],
                        help='Disaggregation role (docs/serving.md '
                             '"Disaggregated prefill/decode"): '
                             'advertised via /metrics so the serve LB '
                             'routes first-chunk work to prefill '
                             'replicas and steers decode replicas to '
                             'pull cached KV prefixes from donors. '
                             'mixed (default) behaves exactly as '
                             'before.')
    parser.add_argument('--kv-pull-timeout-s', type=float, default=10.0,
                        help='Budget for one donor KV pull (fetch + '
                             'attach) and for serving one /kv/export; '
                             'past it the request falls back to plain '
                             'recompute.')
    parser.add_argument('--kv-export-max-pages', type=int, default=64,
                        help='Largest cached prefix one /kv/export '
                             'ships, in KV pages — bounds donor '
                             'readback time and blob size; tokens '
                             'past the cap are recomputed by the '
                             'puller.')
    parser.add_argument('--pipeline-depth', type=int, default=1,
                        help='Dispatch-ahead decode depth: decode N+1 '
                             'is dispatched before step N is read '
                             'back, overlapping host bookkeeping with '
                             'device compute (docs/serving.md). 0 = '
                             'synchronous loop; multi-host lockstep '
                             'replicas always run 0.')
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    boot_t0 = time.time()
    if args.compile_cache_dir:
        setup_compile_cache(args.compile_cache_dir)
    if args.paged and args.long_slots > 0:
        # Usage error: fail in milliseconds, not after minutes of
        # checkpoint loading and KV allocation.
        raise SystemExit('--paged already serves mixed lengths from '
                         'one pool; drop --long-slots')
    if args.prefix_cache and not args.paged:
        raise SystemExit('--prefix-cache requires --paged (sharing is '
                         'at page granularity)')
    if args.kv_dtype != 'bfloat16' and not args.paged:
        raise SystemExit('--kv-dtype int8 requires --paged '
                         '(quantization is at page granularity)')

    # Multi-host replica: the agent runs this same command on EVERY host
    # of the slice with the jax.distributed env injected
    # (runtime/distributed_env.py). Host 0 serves HTTP; followers run
    # the lockstep tick loop.
    from skypilot_tpu.infer import multihost
    world = multihost.maybe_initialize_distributed()

    config = MODELS[args.model]()
    if world > 1 and args.tp == 1:
        # A multi-host replica exists to shard the model; default the
        # tp axis to the whole slice.
        args.tp = len(jax.devices())
        logger.info('multi-host replica: defaulting --tp to %d '
                    '(all devices of the slice)', args.tp)
    if args.checkpoint:
        from skypilot_tpu.train import checkpoint as ckpt_lib
        mgr = ckpt_lib.CheckpointManager(args.checkpoint)
        if args.quantize and args.tp == 1:
            # bf16-whole-on-device would OOM the very chip the int8
            # form is meant to fit: restore into host RAM; the shared
            # extraction + quantize below move it to the device
            # leaf-by-leaf.
            abstract = jax.eval_shape(
                lambda: llama.init_params(config, jax.random.PRNGKey(0)))
            try:
                restored = mgr.restore_to_host(abstract)
            except Exception as first_err:  # noqa: BLE001 — train-state
                # checkpoints nest params under 'params'.
                try:
                    restored = mgr.restore_to_host({'params': abstract})
                except Exception as second_err:
                    raise second_err from first_err
        elif args.tp > 1:
            # Restore DIRECTLY sharded: an 8B-class model cannot first
            # materialize on one chip (engine.init_params_sharded has
            # the same rule for random weights). The target carries
            # per-leaf NamedShardings; orbax places each shard on its
            # device.
            from skypilot_tpu.parallel import sharding as sharding_lib
            mesh = engine_lib.tp_mesh(args.tp)
            abstract = jax.eval_shape(
                lambda: llama.init_params(config, jax.random.PRNGKey(0)))
            shardings = sharding_lib.param_shardings(mesh, abstract)
            target = jax.tree_util.tree_map(
                lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                  sharding=s),
                abstract, shardings)
            try:
                restored = mgr.restore(target=target)
            except Exception as first_err:  # noqa: BLE001 — may be a
                # tree-structure mismatch: full-train-state checkpoints
                # nest params under 'params'. Retry with that shape;
                # chain the ORIGINAL error so a missing/corrupt
                # checkpoint isn't masked by the retry's mismatch.
                logger.warning('sharded params-shaped restore failed '
                               '(%s); retrying with train-state shape',
                               first_err)
                try:
                    restored = mgr.restore(target={'params': target})
                except Exception as second_err:
                    raise second_err from first_err
        else:
            restored = mgr.restore()
        # Accept either a bare params pytree or a full train state.
        params = restored.get('params', restored) if isinstance(
            restored, dict) else restored.params
        if args.quantize and args.tp == 1:
            from skypilot_tpu.ops import quant as quant_lib
            params = quant_lib.quantize_params_transfer(params)
    elif args.quantize:
        # Direct int8 init, sharded when tp>1: neither a model's bf16
        # form nor (for 70B-class) a single int8 leaf may materialize
        # whole on one chip (ops/quant.py init_params_quantized).
        from skypilot_tpu.ops import quant as quant_lib
        logger.warning('no --checkpoint: serving random int8 weights '
                       '(%s, tp=%d)', args.model, args.tp)
        params = quant_lib.init_params_quantized(
            config, jax.random.PRNGKey(0), tp=args.tp)
    elif args.tp > 1:
        logger.warning('no --checkpoint: serving random weights (%s), '
                       'initialized sharded over tp=%d', args.model,
                       args.tp)
        params = engine_lib.init_params_sharded(config, args.tp)
    else:
        logger.warning('no --checkpoint: serving random weights (%s)',
                       args.model)
        params = llama.init_params(config, jax.random.PRNGKey(0))
    tenant_weights = parse_tenant_weights(args.tenant_weights)
    t_weights = time.time()
    engine = engine_lib.InferenceEngine(
        config, params,
        engine_lib.EngineConfig(
            n_slots=args.slots,
            max_seq_len=min(args.max_seq_len, config.max_seq_len),
            tp=args.tp, quantize=args.quantize,
            paged=args.paged, page_size=args.page_size,
            n_pages=args.n_pages, prefix_cache=args.prefix_cache,
            kv_dtype=args.kv_dtype,
            fused_prefill=args.fused_prefill,
            pipeline_depth=args.pipeline_depth,
            spec_k=args.spec_k, spec_ngram=args.spec_ngram,
            max_queue_requests=args.max_queue_requests,
            max_queue_tokens=args.max_queue_tokens,
            scheduler=args.scheduler,
            tenant_weights=tenant_weights,
            stepline=not args.no_stepline,
            stepline_cap=args.stepline_cap,
            ttft_slo_s=args.ttft_slo_s,
            sdc_sentinel=not args.no_sdc_sentinel))
    if args.long_slots > 0:
        short_cap = min(args.max_seq_len, config.max_seq_len)
        long_cap = min(args.long_seq_len, config.max_seq_len)
        if long_cap <= short_cap:
            raise SystemExit(
                f'--long-seq-len ({args.long_seq_len}, clamped to '
                f'{long_cap} by the model) must exceed --max-seq-len '
                f'({short_cap}); equal or inverted tiers would break '
                f'routing')
        # Two-tier KV (EnginePool): same params object — the weights
        # are shared; only the KV caches differ.
        long_engine = engine_lib.InferenceEngine(
            config, engine.params,
            engine_lib.EngineConfig(
                n_slots=args.long_slots,
                max_seq_len=long_cap,
                tp=args.tp, quantize=False,   # params already int8
                fused_prefill=args.fused_prefill,
                pipeline_depth=args.pipeline_depth,
                spec_k=args.spec_k, spec_ngram=args.spec_ngram,
                max_queue_requests=args.max_queue_requests,
                max_queue_tokens=args.max_queue_tokens,
                scheduler=args.scheduler,
                tenant_weights=tenant_weights,
                stepline=not args.no_stepline,
                stepline_cap=args.stepline_cap,
                ttft_slo_s=args.ttft_slo_s,
                sdc_sentinel=not args.no_sdc_sentinel),
            seed=1)
        engine = engine_lib.EnginePool([engine, long_engine])
    # Cold-start timeline stamp #1 (t_weights covers checkpoint
    # restore/random init; the KV allocation above rides in the gap
    # before the compile stamp).
    engine.note_lifecycle_event('coldstart.weights_loaded',
                                load_s=round(t_weights - boot_t0, 3))
    driver = None
    if world > 1:
        driver = multihost.MultihostEngineDriver(engine)
        if jax.process_index() > 0:
            logger.info('follower host %d/%d: entering lockstep loop',
                        jax.process_index(), world)
            driver.run()
            return
    tokenizer = Tokenizer(args.tokenizer,
                          vocab_limit=config.vocab_size)
    InferenceServer(engine, tokenizer, driver=driver,
                    boot_t0=boot_t0, role=args.role,
                    kv_pull_timeout_s=args.kv_pull_timeout_s,
                    kv_export_max_pages=args.kv_export_max_pages,
                    ).run(args.host, args.port)


if __name__ == '__main__':
    main()
