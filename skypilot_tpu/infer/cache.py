"""Slotted KV cache: the static-shape heart of continuous batching.

Layout (all shapes static, per XLA's compilation model):

    k, v: [n_layers, n_slots, max_seq_len, n_kv_heads, head_dim]
    lengths: [n_slots] int32   — tokens currently cached per slot

One running sequence owns one slot; finishing frees the slot for the next
request with **no recompilation** — insertion is `dynamic_update_slice`
at a traced slot index, appending during decode is a vmapped
`dynamic_update_slice` at per-slot positions (XLA lowers both to
scatters). seq-len axis placed before heads so a slot's cache lines are
contiguous per position — the decode gather walks positions linearly.
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    k: jnp.ndarray        # [L, slots, S, kv_heads, head_dim]
    v: jnp.ndarray        # [L, slots, S, kv_heads, head_dim]
    lengths: jnp.ndarray  # [slots] int32

    @property
    def n_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_seq_len(self) -> int:
        return self.k.shape[2]


def init_cache(n_layers: int, n_slots: int, max_seq_len: int,
               n_kv_heads: int, head_dim: int,
               dtype=jnp.bfloat16) -> KVCache:
    shape = (n_layers, n_slots, max_seq_len, n_kv_heads, head_dim)
    return KVCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        lengths=jnp.zeros((n_slots,), jnp.int32))


def append_token(cache_k_layer: jnp.ndarray, cache_v_layer: jnp.ndarray,
                 k_new: jnp.ndarray, v_new: jnp.ndarray,
                 positions: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Append one token's K/V at per-slot ``positions``.

    cache_*_layer: [slots, S, kv, hd]; k_new/v_new: [slots, kv, hd];
    positions: [slots] int32 (the write offset = current length).
    """
    def upd(cache_slot, new, pos):
        return jax.lax.dynamic_update_slice(
            cache_slot, new[None].astype(cache_slot.dtype), (pos, 0, 0))
    k = jax.vmap(upd)(cache_k_layer, k_new, positions)
    v = jax.vmap(upd)(cache_v_layer, v_new, positions)
    return k, v


def append_run(cache_k_layer: jnp.ndarray, cache_v_layer: jnp.ndarray,
               k_new: jnp.ndarray, v_new: jnp.ndarray,
               positions: jnp.ndarray
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Append a RUN of R tokens' K/V per slot at positions
    ``positions[slot] + i`` (the speculative-verify write: the input
    token plus up to R-1 padded draft candidates land in one step).

    cache_*_layer: [slots, S, kv, hd]; k_new/v_new: [slots, R, kv, hd];
    positions: [slots] int32 run starts. Positions past the cache end
    (a draft run padded beyond a near-full slot, or an inactive slot's
    garbage lane) are clamped and REWRITE THE VALUE ALREADY THERE — a
    run-shaped ``dynamic_update_slice`` would instead clamp the start
    and shift the whole run over live positions. The per-position
    writes are sequential (chained functional updates), so a guarded
    rewrite always reads the latest value.
    """
    slots = cache_k_layer.shape[0]
    S = cache_k_layer.shape[1]
    R = k_new.shape[1]
    rows = jnp.arange(slots)
    for i in range(R):
        pos = jnp.minimum(positions + i, S - 1)
        valid = (positions + i) < S                     # [slots]
        old_k = cache_k_layer[rows, pos]                # [slots, kv, hd]
        old_v = cache_v_layer[rows, pos]
        ki = jnp.where(valid[:, None, None],
                       k_new[:, i].astype(cache_k_layer.dtype), old_k)
        vi = jnp.where(valid[:, None, None],
                       v_new[:, i].astype(cache_v_layer.dtype), old_v)
        cache_k_layer = cache_k_layer.at[rows, pos].set(ki)
        cache_v_layer = cache_v_layer.at[rows, pos].set(vi)
    return cache_k_layer, cache_v_layer


def free_slot(cache: KVCache, slot: int) -> KVCache:
    """Mark a slot reusable. K/V bytes are left in place — lengths=0
    makes them unreachable, so no memset traffic on the hot path."""
    return KVCache(k=cache.k, v=cache.v,
                   lengths=cache.lengths.at[slot].set(0))
