"""Slotted KV cache: the static-shape heart of continuous batching.

Layout (all shapes static, per XLA's compilation model):

    k, v: [n_layers, n_slots, max_seq_len, n_kv_heads, head_dim]
    lengths: [n_slots] int32   — tokens currently cached per slot

One running sequence owns one slot; finishing frees the slot for the next
request with **no recompilation** — insertion is `dynamic_update_slice`
at a traced slot index, appending during decode is a vmapped
`dynamic_update_slice` at per-slot positions (XLA lowers both to
scatters). seq-len axis placed before heads so a slot's cache lines are
contiguous per position — the decode gather walks positions linearly.
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    k: jnp.ndarray        # [L, slots, S, kv_heads, head_dim]
    v: jnp.ndarray        # [L, slots, S, kv_heads, head_dim]
    lengths: jnp.ndarray  # [slots] int32

    @property
    def n_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_seq_len(self) -> int:
        return self.k.shape[2]


def init_cache(n_layers: int, n_slots: int, max_seq_len: int,
               n_kv_heads: int, head_dim: int,
               dtype=jnp.bfloat16) -> KVCache:
    shape = (n_layers, n_slots, max_seq_len, n_kv_heads, head_dim)
    return KVCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        lengths=jnp.zeros((n_slots,), jnp.int32))


def append_token(cache_k_layer: jnp.ndarray, cache_v_layer: jnp.ndarray,
                 k_new: jnp.ndarray, v_new: jnp.ndarray,
                 positions: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Append one token's K/V at per-slot ``positions``.

    cache_*_layer: [slots, S, kv, hd]; k_new/v_new: [slots, kv, hd];
    positions: [slots] int32 (the write offset = current length).
    """
    def upd(cache_slot, new, pos):
        return jax.lax.dynamic_update_slice(
            cache_slot, new[None].astype(cache_slot.dtype), (pos, 0, 0))
    k = jax.vmap(upd)(cache_k_layer, k_new, positions)
    v = jax.vmap(upd)(cache_v_layer, v_new, positions)
    return k, v


def free_slot(cache: KVCache, slot: int) -> KVCache:
    """Mark a slot reusable. K/V bytes are left in place — lengths=0
    makes them unreachable, so no memset traffic on the hot path."""
    return KVCache(k=cache.k, v=cache.v,
                   lengths=cache.lengths.at[slot].set(0))
