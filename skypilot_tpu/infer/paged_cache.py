"""Paged KV cache: block tables over a shared page pool.

The round-5 refinement named in engine.py's round-4 docstring: the dense
slot cache prices every slot at max_seq_len, so 16 slots at 16k cost
16x16k of KV HBM even when most requests are 2k. Here the cache is a
pool of fixed-size pages shared by all slots; a slot owns
ceil(len/page) pages, HBM scales with tokens-in-flight, and one engine
serves mixed 2k/16k prompts (subsuming the round-4 two-tier EnginePool).

Device state (static shapes, XLA-friendly):

    k_pages, v_pages: [n_layers, n_kv_heads, n_pages, page, head_dim]
    lengths:          [n_slots] int32

Host state: the **allocator** (free-page stack + per-slot block table).
Page assignment is control flow, not compute — it changes a few ints
per step — so it lives on the host and the current block table rides
into each compiled step as a tiny [slots, max_pages] int32 argument
(the kernels read it via scalar prefetch; see ops/paged_attention.py).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedKVCache:
    k_pages: jnp.ndarray   # [L, hkv, P, page, hd] (bf16, or int8 quantized)
    v_pages: jnp.ndarray   # [L, hkv, P, page, hd]
    lengths: jnp.ndarray   # [slots] int32
    # int8 KV ("kv_dtype=int8"): per-page, per-head absmax scales — one
    # fp32 scale per cached token row of each page, pool-aligned with
    # the pages themselves so a page id addresses its values AND its
    # scales. None on the bf16 flavor (pytree-wise None is an empty
    # subtree, so bf16 caches flatten exactly as before).
    k_scales: Optional[jnp.ndarray] = None   # [L, hkv, P, page] f32
    v_scales: Optional[jnp.ndarray] = None   # [L, hkv, P, page] f32

    @property
    def n_pages(self) -> int:
        return self.k_pages.shape[2]

    @property
    def page_size(self) -> int:
        return self.k_pages.shape[3]


def init_paged_cache(n_layers: int, n_slots: int, n_pages: int,
                     page_size: int, n_kv_heads: int, head_dim: int,
                     dtype=jnp.bfloat16) -> PagedKVCache:
    shape = (n_layers, n_kv_heads, n_pages, page_size, head_dim)
    dtype = jnp.dtype(dtype)
    if dtype == jnp.int8:
        # Quantized pages halve the KV bytes per token (int8 values +
        # a 4-byte row scale vs 2-byte bf16 x head_dim), so the same
        # HBM budget holds ~2x the resident pages — which multiplies
        # the prefix cache (PR 4) and shrinks preemption pressure.
        return PagedKVCache(
            k_pages=jnp.zeros(shape, jnp.int8),
            v_pages=jnp.zeros(shape, jnp.int8),
            lengths=jnp.zeros((n_slots,), jnp.int32),
            k_scales=jnp.zeros(shape[:-1], jnp.float32),
            v_scales=jnp.zeros(shape[:-1], jnp.float32))
    return PagedKVCache(
        k_pages=jnp.zeros(shape, dtype),
        v_pages=jnp.zeros(shape, dtype),
        lengths=jnp.zeros((n_slots,), jnp.int32))


class PageAllocator:
    """Host-side free-page stack + per-slot block tables.

    Never touches the device: ``table()`` snapshots the current
    [slots, max_pages] int32 block table for the next compiled call.
    Freed pages go back on the stack; their bytes stay in HBM untouched
    (a slot's length makes stale pages unreachable, same zero-memset
    rule as the dense cache's free_slot).

    Pages are REFCOUNTED so the prefix cache (infer/prefix_cache.py)
    can share one physical page between several slots' block-table rows
    plus the radix tree itself: ``extend`` hands out fresh pages at
    refcount 1, ``attach`` maps already-cached pages into a slot
    (refcount++), and a page returns to the free stack only when its
    LAST reference drops. Engines without the prefix cache never see a
    refcount above 1 and behave exactly as before.
    """

    # Concurrency contract (SKY-LOCK, docs/static-analysis.md):
    # 'owner' = confinement. The allocator has no lock of its own —
    # every mutation happens on the engine thread (or under the
    # engine's _lock via metrics()), and that only stays true if
    # external code goes through the accessor methods instead of
    # reaching into the free stack / block tables / refcounts.
    _GUARDED_BY = {
        '_free': 'owner',
        '_owned': 'owner',
        '_table': 'owner',
        '_ref': 'owner',
    }

    def __init__(self, n_pages: int, page_size: int, n_slots: int,
                 max_pages_per_slot: int) -> None:
        self.page_size = page_size
        self.n_pages = n_pages
        self.max_pages_per_slot = max_pages_per_slot
        # Page 0 is the GARBAGE SINK, never allocated: the decode step
        # is one static program over every slot, so inactive slots
        # still scatter a garbage K/V row at table[slot,0] — with the
        # table zeroed that is page 0, which must therefore belong to
        # nobody (in the dense cache the garbage landed in the inactive
        # slot's own region; pages share, so the sink makes it safe).
        self._free: List[int] = list(range(n_pages - 1, 0, -1))
        self._owned: List[List[int]] = [[] for _ in range(n_slots)]
        self._table = np.zeros((n_slots, max_pages_per_slot), np.int32)
        self._ref = np.zeros((n_pages,), np.int32)
        # Bumped on every table mutation (pages assigned or returned):
        # the engine keys its device-resident block-table copy on this,
        # re-uploading only when the table actually changed instead of
        # jnp.asarray(table) once per decoded token.
        self.version = 0

    # -- queries -----------------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_of(self, slot: int) -> int:
        return len(self._owned[slot])

    def owned_pages(self, slot: int) -> List[int]:
        """The slot's page ids in block-table order (a copy)."""
        return list(self._owned[slot])

    def page_at(self, slot: int, idx: int) -> int:
        """One page id, no list copy (per-token hot-path accessor)."""
        return self._owned[slot][idx]

    def refcount(self, pid: int) -> int:
        return int(self._ref[pid])

    def pages_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def table(self) -> np.ndarray:
        """Current block table (copy — compiled calls must not see later
        mutations through a shared buffer)."""
        return self._table.copy()

    # -- allocation --------------------------------------------------------
    def extend(self, slot: int, upto_tokens: int) -> bool:
        """Grow `slot` to cover `upto_tokens` positions. All-or-nothing:
        returns False (allocating nothing) when the pool can't cover it
        — the engine then defers the chunk or preempts."""
        need = self.pages_needed(upto_tokens) - len(self._owned[slot])
        if need <= 0:
            return True
        if need > len(self._free):
            return False
        if self.pages_needed(upto_tokens) > self.max_pages_per_slot:
            return False
        for _ in range(need):
            pid = self._free.pop()
            self._ref[pid] = 1
            self._table[slot, len(self._owned[slot])] = pid
            self._owned[slot].append(pid)
        self.version += 1
        return True

    def alloc_pages(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` fresh pages at refcount 1 WITHOUT binding them to
        a slot — for KV-import (fleet prefix streaming): pulled pages
        land in the radix tree directly, owned by the tree's reference
        alone until some slot attaches them. All-or-nothing; returns
        None when the pool can't cover it (the import degrades to
        recompute). The caller must hand every returned page to the
        tree (or decref it) — these pages have no slot to free them."""
        if n > len(self._free):
            return None
        out: List[int] = []
        for _ in range(n):
            pid = self._free.pop()
            self._ref[pid] = 1
            out.append(pid)
        return out

    # -- reference counting (prefix sharing) -------------------------------
    def incref(self, pid: int) -> None:
        self._ref[pid] += 1

    def decref(self, pid: int) -> None:
        """Drop one reference; the page returns to the free stack when
        the last reference goes (never the sink page)."""
        assert self._ref[pid] > 0, f'double-free of page {pid}'
        self._ref[pid] -= 1
        if self._ref[pid] == 0 and pid != 0:
            self._free.append(pid)

    def attach(self, slot: int, pids: List[int]) -> None:
        """Map already-resident (cached) pages as the PREFIX of an empty
        slot's block table, taking one reference on each. The pages'
        bytes are untouched — this is the whole prefix-cache win: the
        slot starts life with its shared prefix already in HBM."""
        assert not self._owned[slot], 'attach on a non-empty slot'
        assert len(pids) <= self.max_pages_per_slot
        for i, pid in enumerate(pids):
            self.incref(pid)
            self._table[slot, i] = pid
        self._owned[slot] = list(pids)
        if pids:
            self.version += 1

    def clear_slot(self, slot: int) -> None:
        """Reset a slot's table WITHOUT touching refcounts — for callers
        (PrefixCache.donate) that have already disposed of every
        reference the slot held."""
        if self._owned[slot]:
            self.version += 1
        self._owned[slot] = []
        self._table[slot, :] = 0

    def cow(self, slot: int, page_idx: int) -> Optional[tuple]:
        """Copy-on-write the slot's page at ``page_idx``: swap in a
        fresh private page and drop the slot's reference on the shared
        one. Returns (src_pid, dst_pid) for the engine's device-side
        page copy, or None when the pool has no free page (the caller
        evicts/preempts and retries). No-op (returns None) when the
        page is not shared."""
        pid = self._owned[slot][page_idx]
        if self._ref[pid] <= 1:
            return None
        if not self._free:
            return None
        dst = self._free.pop()
        self._ref[dst] = 1
        self.decref(pid)
        self._owned[slot][page_idx] = dst
        self._table[slot, page_idx] = dst
        self.version += 1
        return pid, dst

    def shrink(self, slot: int, upto_tokens: int) -> int:
        """Trim the slot's TAIL pages down to what covers
        ``upto_tokens`` positions — the speculative-decoding rollback:
        pages extended for draft positions the verify step rejected go
        straight back to the pool (refcount-dropped, so a page somehow
        still shared merely loses this slot's reference) instead of
        riding the slot as dead weight until finish. Returns the
        number of pages released."""
        keep = max(self.pages_needed(max(upto_tokens, 0)), 0)
        dropped = 0
        while len(self._owned[slot]) > keep:
            pid = self._owned[slot].pop()
            self._table[slot, len(self._owned[slot])] = 0
            self.decref(pid)
            dropped += 1
        if dropped:
            self.version += 1
        return dropped

    def free(self, slot: int) -> None:
        """Drop the slot's reference on all of its pages (pages shared
        with the prefix tree or other slots survive; exclusive pages
        return to the pool)."""
        if self._owned[slot]:
            self.version += 1
        for pid in reversed(self._owned[slot]):
            self.decref(pid)
        self._owned[slot] = []
        self._table[slot, :] = 0

    def used_tokens_capacity(self) -> int:
        """Tokens coverable by currently-owned pages (observability)."""
        return sum(len(o) for o in self._owned) * self.page_size


def free_slot(cache: PagedKVCache, slot: int) -> PagedKVCache:
    """Device half of freeing: zero the slot's length (the allocator's
    ``free`` is the host half)."""
    return PagedKVCache(k_pages=cache.k_pages, v_pages=cache.v_pages,
                        lengths=cache.lengths.at[slot].set(0),
                        k_scales=cache.k_scales,
                        v_scales=cache.v_scales)


def copy_page(cache: PagedKVCache, src: jnp.ndarray,
              dst: jnp.ndarray) -> PagedKVCache:
    """Device half of copy-on-write: duplicate physical page ``src``
    into ``dst`` across all layers/heads (the allocator's ``cow`` is
    the host half). src/dst are traced scalars, so one compiled program
    covers every CoW. On the int8 flavor the page's row scales copy
    with it — a page id is only meaningful as a (values, scales) pair."""
    def dup(arr):
        row = jax.lax.dynamic_index_in_dim(arr, src, axis=2,
                                           keepdims=True)
        return jax.lax.dynamic_update_index_in_dim(arr, row, dst,
                                                   axis=2)
    return PagedKVCache(
        k_pages=dup(cache.k_pages),
        v_pages=dup(cache.v_pages),
        lengths=cache.lengths,
        k_scales=(dup(cache.k_scales)
                  if cache.k_scales is not None else None),
        v_scales=(dup(cache.v_scales)
                  if cache.v_scales is not None else None))
