"""Paged KV cache: block tables over a shared page pool.

The round-5 refinement named in engine.py's round-4 docstring: the dense
slot cache prices every slot at max_seq_len, so 16 slots at 16k cost
16x16k of KV HBM even when most requests are 2k. Here the cache is a
pool of fixed-size pages shared by all slots; a slot owns
ceil(len/page) pages, HBM scales with tokens-in-flight, and one engine
serves mixed 2k/16k prompts (subsuming the round-4 two-tier EnginePool).

Device state (static shapes, XLA-friendly):

    k_pages, v_pages: [n_layers, n_kv_heads, n_pages, page, head_dim]
    lengths:          [n_slots] int32

Host state: the **allocator** (free-page stack + per-slot block table).
Page assignment is control flow, not compute — it changes a few ints
per step — so it lives on the host and the current block table rides
into each compiled step as a tiny [slots, max_pages] int32 argument
(the kernels read it via scalar prefetch; see ops/paged_attention.py).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedKVCache:
    k_pages: jnp.ndarray   # [L, hkv, P, page, hd]
    v_pages: jnp.ndarray   # [L, hkv, P, page, hd]
    lengths: jnp.ndarray   # [slots] int32

    @property
    def n_pages(self) -> int:
        return self.k_pages.shape[2]

    @property
    def page_size(self) -> int:
        return self.k_pages.shape[3]


def init_paged_cache(n_layers: int, n_slots: int, n_pages: int,
                     page_size: int, n_kv_heads: int, head_dim: int,
                     dtype=jnp.bfloat16) -> PagedKVCache:
    shape = (n_layers, n_kv_heads, n_pages, page_size, head_dim)
    return PagedKVCache(
        k_pages=jnp.zeros(shape, dtype),
        v_pages=jnp.zeros(shape, dtype),
        lengths=jnp.zeros((n_slots,), jnp.int32))


class PageAllocator:
    """Host-side free-page stack + per-slot block tables.

    Never touches the device: ``table()`` snapshots the current
    [slots, max_pages] int32 block table for the next compiled call.
    Freed pages go back on the stack; their bytes stay in HBM untouched
    (a slot's length makes stale pages unreachable, same zero-memset
    rule as the dense cache's free_slot).
    """

    def __init__(self, n_pages: int, page_size: int, n_slots: int,
                 max_pages_per_slot: int) -> None:
        self.page_size = page_size
        self.n_pages = n_pages
        self.max_pages_per_slot = max_pages_per_slot
        # Page 0 is the GARBAGE SINK, never allocated: the decode step
        # is one static program over every slot, so inactive slots
        # still scatter a garbage K/V row at table[slot,0] — with the
        # table zeroed that is page 0, which must therefore belong to
        # nobody (in the dense cache the garbage landed in the inactive
        # slot's own region; pages share, so the sink makes it safe).
        self._free: List[int] = list(range(n_pages - 1, 0, -1))
        self._owned: List[List[int]] = [[] for _ in range(n_slots)]
        self._table = np.zeros((n_slots, max_pages_per_slot), np.int32)
        # Bumped on every table mutation (pages assigned or returned):
        # the engine keys its device-resident block-table copy on this,
        # re-uploading only when the table actually changed instead of
        # jnp.asarray(table) once per decoded token.
        self.version = 0

    # -- queries -----------------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_of(self, slot: int) -> int:
        return len(self._owned[slot])

    def pages_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def table(self) -> np.ndarray:
        """Current block table (copy — compiled calls must not see later
        mutations through a shared buffer)."""
        return self._table.copy()

    # -- allocation --------------------------------------------------------
    def extend(self, slot: int, upto_tokens: int) -> bool:
        """Grow `slot` to cover `upto_tokens` positions. All-or-nothing:
        returns False (allocating nothing) when the pool can't cover it
        — the engine then defers the chunk or preempts."""
        need = self.pages_needed(upto_tokens) - len(self._owned[slot])
        if need <= 0:
            return True
        if need > len(self._free):
            return False
        if self.pages_needed(upto_tokens) > self.max_pages_per_slot:
            return False
        for _ in range(need):
            pid = self._free.pop()
            self._table[slot, len(self._owned[slot])] = pid
            self._owned[slot].append(pid)
        self.version += 1
        return True

    def free(self, slot: int) -> None:
        """Return all of `slot`'s pages to the pool."""
        if self._owned[slot]:
            self.version += 1
        self._free.extend(reversed(self._owned[slot]))
        self._owned[slot] = []
        self._table[slot, :] = 0

    def used_tokens_capacity(self) -> int:
        """Tokens coverable by currently-owned pages (observability)."""
        return sum(len(o) for o in self._owned) * self.page_size


def free_slot(cache: PagedKVCache, slot: int) -> PagedKVCache:
    """Device half of freeing: zero the slot's length (the allocator's
    ``free`` is the host half)."""
    return PagedKVCache(k_pages=cache.k_pages, v_pages=cache.v_pages,
                        lengths=cache.lengths.at[slot].set(0))
