"""Multi-host tensor-parallel serving driver (JetStream-style lockstep).

The reference reaches multi-GPU/多-node serving by delegating to
vLLM/TGI (reference llm/vllm example YAMLs). TPU-native equivalent: a
serve replica that IS a multi-host slice. The agent gang-fans the same
``infer.server`` command to every host with the ``jax.distributed`` env
injected (runtime/distributed_env.py); host 0 serves HTTP, and every
host runs an IDENTICAL engine in lockstep:

- Request submissions are broadcast host0 → all as two fixed-shape
  collectives (length, then padded payload bytes) via
  ``jax.experimental.multihost_utils``.
- Every host then performs the same ``engine.step()``. All host-side
  decisions (slot assignment, chunk scheduling, sampling keys) are
  deterministic functions of the submission order, and the device work
  is one SPMD program over the global ``tp`` mesh — the hosts cannot
  diverge.

Shutdown: a ``stop`` flag rides the same broadcast, so followers exit
cleanly when host 0 does.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)

# Watchdog deadline for time spent BLOCKED INSIDE the submission
# broadcast. A dead peer leaves the survivors stuck in
# broadcast_one_to_all forever — the watchdog kills THIS host so the
# failure becomes observable: host 0's death takes the HTTP server
# down (readiness probe red -> replica manager relaunches the slice);
# a follower's death fails its agent rank.
#
# Deliberately NOT a whole-tick deadline: ``engine.step`` time is
# excluded, so a legitimately slow step (a first-prefill-bucket compile
# can run minutes on a big model) never trips the watchdog on any host
# — every host runs the identical step, so while rank 0 compiles, the
# followers are compiling too, not waiting.
#
# A peer dying mid-step inside a DEVICE collective is invisible to the
# broadcast deadline; it normally surfaces as the distributed runtime's
# own error (run() turns that into the same exit code). The HARD
# deadline below is the backstop for the case where that detection
# never fires: whole-tick time (step included), sized far above any
# legitimate compile so it can only mean a wedged slice.
TICK_DEADLINE_ENV = 'SKY_TPU_LOCKSTEP_TICK_DEADLINE_S'
DEFAULT_TICK_DEADLINE_S = 900.0
HARD_DEADLINE_ENV = 'SKY_TPU_LOCKSTEP_HARD_DEADLINE_S'
DEFAULT_HARD_DEADLINE_S = 7200.0
WATCHDOG_EXIT_CODE = 42


def _broadcast_bytes(data: Optional[bytes]) -> bytes:
    """host0 → all. ``data`` is ignored on followers (pass None)."""
    import jax
    from jax.experimental import multihost_utils
    del jax
    n_local = len(data) if data else 0
    n = int(multihost_utils.broadcast_one_to_all(
        np.array([n_local], np.int32))[0])
    if n == 0:
        return b''
    buf = np.zeros((n,), np.uint8)
    if data:
        buf[:] = np.frombuffer(data, np.uint8)
    return bytes(np.asarray(multihost_utils.broadcast_one_to_all(buf)))


class MultihostEngineDriver:
    """Lockstep wrapper around an ``InferenceEngine`` replicated on
    every host of the slice."""

    # Concurrency contract (SKY-LOCK): `_pending` is the only state
    # shared between HTTP handler threads (submit) and the rank-0 tick
    # loop — every touch is under `_lock`. `_stop`/`_collective_since`
    # /`_last_tick` are GIL-atomic scalar flags (single writer,
    # watchdog reader) and stay unregistered by design.
    _GUARDED_BY = {
        '_pending': '_lock',
    }

    def __init__(self, engine) -> None:
        import jax
        self.engine = engine
        # Lockstep REQUIRES the synchronous step loop: every host must
        # observe identical request state after each tick, but the
        # overlapped pipeline leaves host state stale-by-one behind an
        # in-flight dispatch — pin depth 0 until the tick protocol
        # carries the in-flight window in the broadcast.
        if hasattr(engine, 'set_pipeline_depth'):
            engine.set_pipeline_depth(0)
        if hasattr(engine, 'set_wallclock_cancel'):
            # Deadline/disconnect sweeps read the LOCAL wall clock;
            # lockstep hosts must never diverge on request state, so
            # they are disabled (same rule as pipeline depth 0).
            engine.set_wallclock_cancel(False)
        if hasattr(engine, 'pin_spec_off'):
            # Speculative drafting reads host-LOCAL state (each host's
            # prompt-lookup index) — until the tick spec carries the
            # draft tokens in the broadcast, hosts could propose
            # different drafts and diverge. Pinned OFF, and the pin is
            # sticky: a later set_spec_k(k>0) raises instead of
            # silently forking the replicas.
            engine.pin_spec_off()
        self.rank = jax.process_index()
        self.world = jax.process_count()
        self._pending: List[Dict[str, Any]] = []   # rank0 only
        self._lock = threading.Lock()
        # Set on submit so rank 0's idle loop wakes immediately instead
        # of sleeping out its nap (event-driven, not a poll cadence).
        self._work = threading.Event()
        self._stop = False
        self._tick_deadline = float(os.environ.get(
            TICK_DEADLINE_ENV, DEFAULT_TICK_DEADLINE_S))
        self._hard_deadline = float(os.environ.get(
            HARD_DEADLINE_ENV, DEFAULT_HARD_DEADLINE_S))
        # Set while the main loop is blocked inside the submission
        # broadcast (a float write is atomic under the GIL; the side
        # thread only reads it). None = not in the collective.
        self._collective_since: Optional[float] = None
        # Last completed tick (step included) — feeds only the HARD
        # backstop deadline, never the broadcast deadline.
        self._last_tick = time.monotonic()
        self._watchdog_started = False

    def _die(self, stalled: float, *,
             reason: str = 'stuck in the submission collective',
             deadline: Optional[float] = None) -> None:
        """Watchdog kill — isolated so tests can observe instead of
        dying. os._exit (not sys.exit): the main thread is wedged in a
        native collective and will never unwind a SystemExit."""
        logger.error(
            'lockstep watchdog: host %d/%d %s %.0fs (> %.0fs) — a peer '
            'host is gone; exiting so the replica manager can relaunch '
            'the slice', self.rank, self.world, reason, stalled,
            deadline if deadline is not None else self._tick_deadline)
        os._exit(WATCHDOG_EXIT_CODE)

    def _start_watchdog(self) -> None:
        """VERDICT r4 weak #3: without this, a dead follower leaves
        host 0 blocked inside broadcast_one_to_all forever — the
        replica hangs silently instead of failing its probe. The
        watchdog turns the silent hang into a process death the serve
        replica manager (or the agent's job status) can see and
        recover.

        The heartbeat runs on this side thread and monitors only
        time-in-collective — it is independent of ``engine.step``, so a
        slow step (compile) on a healthy slice never kills replicas
        (peer-slow), while a peer death (broadcast never completes:
        peer-dead) still does."""
        # The two deadlines are independent knobs: zeroing the
        # broadcast deadline (long-compile operators) must not also
        # kill the hard backstop.
        if self._watchdog_started or (self._tick_deadline <= 0 and
                                      self._hard_deadline <= 0):
            return
        self._watchdog_started = True
        shortest = min(d for d in (self._tick_deadline,
                                   self._hard_deadline) if d > 0)
        interval = min(5.0, max(0.05, shortest / 4))

        def loop() -> None:
            while not self._stop:
                now = time.monotonic()
                since = self._collective_since
                if (self._tick_deadline > 0 and since is not None and
                        now - since > self._tick_deadline):
                    self._die(now - since)
                # Hard backstop: a peer death inside engine.step's
                # device collectives that the distributed runtime
                # never surfaces. Whole-tick timed, so the bound must
                # dwarf any legitimate compile.
                if (self._hard_deadline > 0 and
                        now - self._last_tick > self._hard_deadline):
                    self._die(now - self._last_tick,
                              reason='whole tick wedged (step included)',
                              deadline=self._hard_deadline)
                time.sleep(interval)

        threading.Thread(target=loop, daemon=True,
                         name='lockstep-watchdog').start()

    # ---- rank-0 API (called from HTTP handler threads) ------------------
    def submit(self, prompt_tokens, max_new_tokens=None,
               temperature: float = 0.0, resume_tokens=None):
        """Queue a submission for the next tick; block until every host
        has admitted it, then return this host's Request object.
        ``resume_tokens`` (mid-stream failover continuation) is part of
        the broadcast spec, so every host pre-seeds identically;
        wall-clock deadlines are NOT supported on the lockstep path
        (hosts' clocks differ — see set_wallclock_cancel)."""
        assert self.rank == 0, 'only host 0 accepts requests'
        entry = {
            'spec': {'prompt_tokens': list(map(int, prompt_tokens)),
                     'max_new_tokens': max_new_tokens,
                     'temperature': float(temperature),
                     'resume_tokens': (list(map(int, resume_tokens))
                                       if resume_tokens else None)},
            'event': threading.Event(),
            'request': None,
            'error': None,
        }
        with self._lock:
            self._pending.append(entry)
        self._work.set()
        entry['event'].wait()
        if entry['error'] is not None:
            raise entry['error']
        return entry['request']

    def stop(self) -> None:
        self._stop = True
        self._work.set()   # wake the idle loop to broadcast the stop

    # ---- the lockstep loop (every host) ---------------------------------
    def tick(self) -> bool:
        """One broadcast + one engine step on every host. Returns False
        when the replica is shutting down."""
        batch: List[Dict[str, Any]] = []
        payload = None
        if self.rank == 0:
            with self._lock:
                batch, self._pending = self._pending, []
            payload = json.dumps({
                'reqs': [e['spec'] for e in batch],
                'stop': self._stop,
            }).encode()
        self._collective_since = time.monotonic()
        try:
            data = _broadcast_bytes(payload)
        finally:
            self._collective_since = None
        msg = json.loads(data) if data else {'reqs': [], 'stop': False}
        for i, spec in enumerate(msg['reqs']):
            try:
                req = self.engine.submit(
                    spec['prompt_tokens'],
                    max_new_tokens=spec['max_new_tokens'],
                    temperature=spec['temperature'],
                    resume_tokens=spec.get('resume_tokens'))
            except ValueError as e:
                # Every host rejects identically (same validation on the
                # same spec) — lockstep is preserved.
                req, err = None, e
            else:
                err = None
            if self.rank == 0:
                batch[i]['request'] = req
                batch[i]['error'] = err
                batch[i]['event'].set()
        if msg.get('stop'):
            return False
        self.engine.step()
        if self.world > 1 and hasattr(self.engine, 'output_digest'):
            # Desync detection (docs/robustness.md "Data integrity"):
            # every host's request state is supposed to be a pure
            # function of the broadcast order — all-gather a digest of
            # it each tick and fail the slice LOUDLY on any mismatch.
            # A diverged host is SDC at slice scope; streaming its
            # tokens is the one outcome this check forbids. The raise
            # rides run()'s catch-everything → os._exit(42) → the
            # replica manager relaunches the slice (slice-level
            # quarantine).
            self._collective_since = time.monotonic()
            try:
                digests = self._gather_digests(
                    int(self.engine.output_digest()))
            finally:
                self._collective_since = None
            self._check_digests(digests)
        self._last_tick = time.monotonic()
        return True

    def _gather_digests(self, digest: int) -> List[int]:
        """All-gather this host's output digest (one uint32 per host —
        a fixed-shape collective, same transport rules as the
        submission broadcast)."""
        from jax.experimental import multihost_utils
        out = multihost_utils.process_allgather(
            np.array([digest], np.uint32))
        return [int(x) for x in np.asarray(out).ravel()]

    def _check_digests(self, digests: List[int]) -> None:
        """Raise on any cross-host divergence. Isolated from the
        gather so tests can drive the verdict with synthetic digest
        sets (no multiprocess runtime needed)."""
        if len(set(digests)) > 1:
            raise RuntimeError(
                f'lockstep desync: host {self.rank}/{self.world} '
                f'sees per-host output digests {digests} — a host '
                f'diverged (slice-scope SDC); failing the slice '
                f'instead of streaming diverged tokens')

    def run(self, idle_sleep: float = 0.05) -> None:
        """Follower loop (and usable as rank-0's loop body driver): tick
        until stopped; wait only when the engine is idle AND nothing is
        queued (followers block inside the broadcast instead). The idle
        wait is EVENT-DRIVEN: ``submit`` sets ``_work``, so a new
        request triggers the next broadcast immediately —
        ``idle_sleep`` is just the re-check cadence for the stop flag,
        not a submission-poll interval. Runs under the tick watchdog; a
        collective error (the distributed runtime noticed a dead peer
        before the watchdog did) exits nonzero the same way."""
        self._last_tick = time.monotonic()   # arm the hard backstop
        self._start_watchdog()
        try:
            while self.tick():
                if self.rank == 0 and self.engine.idle():
                    with self._lock:
                        quiet = not self._pending
                    if quiet and not self._stop:
                        self._work.wait(idle_sleep)
                        self._work.clear()
        except Exception:  # noqa: BLE001 — any lockstep error is fatal
            logger.exception(
                'lockstep host %d/%d: collective failed — exiting for '
                'replica recovery', self.rank, self.world)
            os._exit(WATCHDOG_EXIT_CODE)
        finally:
            self._stop = True


# ---------------------------------------------------------------------------
# Capability probe: XLA-CPU multiprocess support
# ---------------------------------------------------------------------------
# The smallest program that exercises what the 2-process e2e tests
# need: a jitted computation whose input is sharded across BOTH
# processes. XLA CPU builds without cross-process collective support
# fail it with "Multiprocess computations aren't implemented".
_MULTIPROC_PROBE = """
import numpy as np
import jax
import jax.numpy as jnp
from skypilot_tpu.infer import multihost
assert multihost.maybe_initialize_distributed() == 2
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(np.array(jax.devices()), ('x',))
x = jax.device_put(jnp.arange(4, dtype=jnp.float32),
                   NamedSharding(mesh, P('x')))
y = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x)
assert float(np.asarray(jax.device_get(y))) == 6.0
print('MULTIPROC_OK', flush=True)
"""

_multiproc_supported: Optional[bool] = None


def xla_cpu_multiprocess_supported(timeout_s: float = 300.0) -> bool:
    """Whether this jax/XLA build can run a computation spanning two
    CPU processes (cached per process).

    Some XLA-CPU builds ship without cross-process collectives and die
    with "Multiprocess computations aren't implemented" — an
    environment limit, not a product regression. The multihost e2e
    tests probe this first so tier-1 CI reflects real breakage only.
    The probe spawns two 1-device CPU processes over a loopback
    coordinator and runs one cross-process reduction.
    """
    global _multiproc_supported
    if _multiproc_supported is not None:
        return _multiproc_supported
    import subprocess
    import sys

    from skypilot_tpu.utils import common
    port = common.free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            'JAX_PLATFORMS': 'cpu',
            'JAX_PLATFORM_NAME': 'cpu',
            'XLA_FLAGS': '--xla_force_host_platform_device_count=1',
            'JAX_COORDINATOR_ADDRESS': f'127.0.0.1:{port}',
            'JAX_NUM_PROCESSES': '2',
            'JAX_PROCESS_ID': str(rank),
        })
        env.pop('PALLAS_AXON_POOL_IPS', None)
        procs.append(subprocess.Popen(
            [sys.executable, '-c', _MULTIPROC_PROBE], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    ok = True
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            p.kill()
            out = ''
        if p.returncode != 0 or (p is procs[0]
                                 and 'MULTIPROC_OK' not in out):
            ok = False
    if not ok:
        logger.warning('XLA CPU multiprocess probe failed: 2-process '
                       'computations unsupported in this environment')
    _multiproc_supported = ok
    return ok


def maybe_initialize_distributed() -> int:
    """``jax.distributed.initialize`` from the env the provisioner
    injected (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID, runtime/distributed_env.py). Args are passed
    explicitly — argless initialize() only works with jax's cluster
    auto-detectors (TPU pod metadata, SLURM), not plain env vars.
    Returns the process count (1 = single-host: nothing initialized)."""
    import os

    import jax
    if int(os.environ.get('JAX_NUM_PROCESSES', '1')) <= 1:
        return 1
    jax.distributed.initialize(
        coordinator_address=os.environ['JAX_COORDINATOR_ADDRESS'],
        num_processes=int(os.environ['JAX_NUM_PROCESSES']),
        process_id=int(os.environ['JAX_PROCESS_ID']))
    return jax.process_count()
