"""Shared-prefix KV cache: radix-tree page reuse over the paged pool.

Most production traffic shares long common prefixes — system prompts,
few-shot templates, multi-turn history. Without sharing, every request
re-prefills its whole prompt into private pages; with it, the repeated
prefill becomes a host-side tree walk (vLLM's prefix caching, SGLang's
RadixAttention — convergent design, re-derived here over this repo's
``PageAllocator``).

Structure: a radix tree at PAGE granularity. Each node is exactly one
full page of tokens; its edge key is that page's token block (the
``page_size``-tuple of token ids), so a node is reachable only through
the exact chain of blocks that precede it. That chaining is what makes
reuse SOUND: K/V at position p depends on every token <= p (causal
attention through all layers), so a cached page may only be reused when
the *entire* prefix matches — which the walk enforces structurally, and
exact tuple keys (not hashes) make collision-proof.

Ownership protocol (refcounts live in ``PageAllocator``):

- The tree holds ONE reference on every cached page; each slot whose
  block table maps the page holds one more. A page is *evictable* only
  at refcount 1 (tree-only) — pages under active slots are pinned.
- ``match`` returns the longest cached page-aligned prefix, capped at
  the last full page strictly BEFORE the prompt end: at least one
  token is always left to prefill (its logits seed the first sampled
  token), so the slot's frontier page is always private and decode
  never writes a shared page. The engine still guards the invariant
  with copy-on-write (``PageAllocator.cow`` + ``copy_page``) in case a
  future matching change shares the frontier.
- ``donate`` (called by the engine on finish AND preempt) walks the
  request's token sequence and hands the slot's full clean pages to the
  tree instead of freeing them: new blocks transfer the slot's
  reference to the tree; already-cached blocks just drop the slot's
  reference (duplicates deallocate); the partial last page is freed.
- ``evict`` reclaims leaf pages in LRU order, only under page pressure
  (the engine calls it when ``extend`` fails, before considering
  preemption). Leaves-first keeps every surviving node reachable.
"""
from __future__ import annotations

import collections
import dataclasses
import heapq
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from skypilot_tpu.infer import paged_cache as paged_cache_lib
from skypilot_tpu.utils import prefix_hash


@dataclasses.dataclass
class _Node:
    block: Optional[Tuple[int, ...]]        # None only for the root
    page_id: int                            # physical page (tree ref)
    parent: Optional['_Node']
    last_access: int
    # Chained prefix digest (utils/prefix_hash.py): commits to the
    # whole root->node token path, so the fleet index can advertise
    # "this replica holds this prefix" in 8 bytes. 0 only at the root.
    chain: int = 0
    children: Dict[Tuple[int, ...], '_Node'] = dataclasses.field(
        default_factory=dict)


class PrefixCache:
    """Radix tree of per-page token blocks -> physical page ids."""

    # Concurrency contract (SKY-LOCK): the tree is confined to the
    # engine thread under the ENGINE's lock discipline — external code
    # (EnginePool, the server) must go through match/donate/evict/
    # stats, never the node structures (a reach-in would race the
    # step loop's donations and corrupt refcount bookkeeping).
    _GUARDED_BY = {
        '_root': 'owner',
        '_clock': 'owner',
        '_by_hash': 'owner',
        '_journal': 'owner',
        'index_gen': 'owner',
    }

    def __init__(self,
                 allocator: paged_cache_lib.PageAllocator,
                 index_cap: int = 4096) -> None:
        self.allocator = allocator
        self.page = allocator.page_size
        self._root = _Node(block=None, page_id=-1, parent=None,
                           last_access=0, chain=0)
        self._clock = 0
        self.hits = 0
        self.misses = 0
        self.tokens_saved = 0
        self.evictions = 0
        self.cached_pages = 0
        # Fleet prefix index (docs/serving.md "Disaggregated
        # prefill/decode"): a bounded mirror of the tree keyed on chain
        # digests, maintained incrementally so the LB's sync-tick fetch
        # ships DELTAS, not the whole set. Insertion is parent-first
        # (donate walks root-down) and a child is only indexed while
        # its parent is, so the advertised set stays prefix-closed —
        # the LB's longest-match walk can stop at the first miss.
        self.index_cap = index_cap
        self._by_hash: Dict[int, _Node] = {}
        self.index_gen = 0
        self._journal: Deque[Tuple[int, str, int]] = collections.deque(
            maxlen=1024)

    # -- fleet index bookkeeping -------------------------------------------
    def _index_add(self, node: _Node) -> None:
        if len(self._by_hash) >= self.index_cap:
            return
        parent = node.parent
        if parent is not self._root and parent.chain not in self._by_hash:
            return          # keep the advertised set prefix-closed
        if node.chain in self._by_hash:
            return          # 64-bit collision: first writer wins
        self._by_hash[node.chain] = node
        self.index_gen += 1
        self._journal.append((self.index_gen, '+', node.chain))

    def _index_del(self, node: _Node) -> None:
        if self._by_hash.get(node.chain) is not node:
            return
        del self._by_hash[node.chain]
        self.index_gen += 1
        self._journal.append((self.index_gen, '-', node.chain))

    def publishable(self) -> tuple:
        """Immutable copy of the index state — ``(gen, crc, page,
        journal, hashes)`` — for the engine's cross-thread publication:
        the tree is engine-thread-confined, so the engine snapshots
        this at step boundaries and the HTTP thread builds wire
        summaries from the copy (utils.prefix_hash.build_snapshot)."""
        return (self.index_gen, prefix_hash.fold_crc(self._by_hash),
                self.page, tuple(self._journal),
                frozenset(self._by_hash))

    def index_snapshot(self, since_gen: int) -> Dict[str, object]:
        """The on-wire radix summary for the LB's sync tick: delta
        against ``since_gen`` when the journal covers it, full list
        otherwise; ``crc`` is the XOR fold of the whole advertised set
        (the LB verifies its delta-maintained mirror against it and
        forces a full resync on mismatch)."""
        gen, crc, page, journal, hashes = self.publishable()
        return prefix_hash.build_snapshot(gen, crc, page, journal,
                                          hashes, since_gen)

    # -- lookup ------------------------------------------------------------
    def match(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached page-aligned prefix of ``tokens``.

        Returns (page_ids, n_tokens). Capped at the last full page
        strictly before the end of ``tokens`` so the caller always
        prefills >= 1 token (see module docstring). Touches the LRU
        clock along the matched path. The caller must ``attach`` the
        pages in the same engine step (nothing else runs between —
        evictions happen only on the engine thread)."""
        self._clock += 1
        limit = (len(tokens) - 1) // self.page
        node = self._root
        pages: List[int] = []
        for i in range(limit):
            child = node.children.get(
                tuple(tokens[i * self.page:(i + 1) * self.page]))
            if child is None:
                break
            child.last_access = self._clock
            pages.append(child.page_id)
            node = child
        matched = len(pages) * self.page
        if matched:
            self.hits += 1
            self.tokens_saved += matched
        else:
            self.misses += 1
        return pages, matched

    def peek(self, tokens: Sequence[int],
             whole: bool = False) -> Tuple[List[int], int]:
        """``match`` without the side effects: no hit/miss accounting,
        no LRU touch. The KV-export path uses it — a donor serving a
        remote pull must not skew its own cache statistics, and export
        never takes references (the pages are only READ, on the engine
        thread, with no eviction point between lookup and readback).

        ``whole=True`` drops the strictly-before-end cap and matches
        every full page — the import diff uses it (a transferred blob
        covers exactly full pages; the leave-one-token rule applies to
        the PROMPT the puller will prefill, not to the blob)."""
        limit = (len(tokens) // self.page if whole
                 else (len(tokens) - 1) // self.page)
        node = self._root
        pages: List[int] = []
        for i in range(limit):
            child = node.children.get(
                tuple(tokens[i * self.page:(i + 1) * self.page]))
            if child is None:
                break
            pages.append(child.page_id)
            node = child
        return pages, len(pages) * self.page

    # -- donation ----------------------------------------------------------
    def donate(self, tokens: Sequence[int], slot: int) -> int:
        """Release ``slot``'s pages into the tree: full pages covered by
        ``tokens`` (the exact sequence whose K/V the pages hold) are
        cached; everything else (the partial last page) is freed. Also
        clears the slot's block table — this REPLACES
        ``allocator.free(slot)`` on the finish/preempt paths. Returns
        the number of newly cached pages."""
        al = self.allocator
        owned = al.owned_pages(slot)
        self._clock += 1
        full = min(len(tokens) // self.page, len(owned))
        node = self._root
        added = 0
        for i in range(full):
            blk = tuple(tokens[i * self.page:(i + 1) * self.page])
            child = node.children.get(blk)
            if child is None:
                # Tree takes over the slot's reference — no decref.
                child = _Node(block=blk, page_id=owned[i], parent=node,
                              last_access=self._clock,
                              chain=prefix_hash.block_hash(node.chain,
                                                           blk))
                node.children[blk] = child
                self.cached_pages += 1
                self._index_add(child)
                added += 1
            else:
                # Block already cached (possibly by this very page, if
                # it was attached at match time): drop the slot's ref;
                # a privately-computed duplicate deallocates here.
                child.last_access = self._clock
                al.decref(owned[i])
            node = child
        for pid in owned[full:]:
            al.decref(pid)
        al.clear_slot(slot)
        return added

    def insert_remote(self, tokens: Sequence[int],
                      page_ids: Sequence[Optional[int]]) -> int:
        """Graft IMPORTED pages (a fleet KV transfer) into the tree.

        ``page_ids`` has one entry per full page of ``tokens``; a None
        entry means that block was already cached locally when the
        caller diffed (the walk just descends through it). Fresh pages
        must come from ``PageAllocator.alloc_pages`` — the tree takes
        over their single reference. A non-None page for a block that
        turns out cached is a duplicate and is released; the EXISTING
        page always wins (slots may already attach it, and overwriting
        it with transferred bytes would change their stream mid-flight).
        Returns the number of pages grafted."""
        al = self.allocator
        self._clock += 1
        node = self._root
        added = 0
        for i, pid in enumerate(page_ids):
            blk = tuple(tokens[i * self.page:(i + 1) * self.page])
            child = node.children.get(blk)
            if child is None:
                if pid is None:     # caller's diff went stale — stop
                    break
                child = _Node(block=blk, page_id=pid, parent=node,
                              last_access=self._clock,
                              chain=prefix_hash.block_hash(node.chain,
                                                           blk))
                node.children[blk] = child
                self.cached_pages += 1
                self._index_add(child)
                added += 1
            else:
                child.last_access = self._clock
                if pid is not None:
                    al.decref(pid)
            node = child
        return added

    # -- eviction ----------------------------------------------------------
    def evict(self, n_pages: int) -> int:
        """Reclaim up to ``n_pages`` cached pages, LRU leaf first.

        Only refcount-1 pages (tree-only — no slot maps them) are
        candidates; an attached page pins itself AND its ancestors
        (ancestors are never leaves while it exists). Called by the
        engine strictly under page pressure. Returns pages freed.

        One tree walk total, not one per freed page: the walk seeds a
        min-heap of evictable leaves; evicting a node may turn its
        parent into a leaf, which is pushed then. Multi-page
        shortfalls (a whole prefill chunk) stay O(tree + k log k)."""
        freed = 0
        heap = []
        stack = [self._root]
        while stack:
            node = stack.pop()
            if (node is not self._root and not node.children
                    and self.allocator.refcount(node.page_id) == 1):
                heap.append((node.last_access, id(node), node))
            stack.extend(node.children.values())
        heapq.heapify(heap)
        while freed < n_pages and heap:
            _, _, victim = heapq.heappop(heap)
            if (victim.children or victim.parent is None
                    or victim.parent.children.get(victim.block)
                    is not victim
                    or self.allocator.refcount(victim.page_id) != 1):
                continue   # stale heap entry
            parent = victim.parent
            del parent.children[victim.block]
            self._index_del(victim)
            self.allocator.decref(victim.page_id)
            self.cached_pages -= 1
            self.evictions += 1
            freed += 1
            if (parent is not self._root and not parent.children
                    and self.allocator.refcount(parent.page_id) == 1):
                heapq.heappush(heap,
                               (parent.last_access, id(parent), parent))
        return freed

    # -- observability -----------------------------------------------------
    @property
    def indexed_pages(self) -> int:
        return len(self._by_hash)

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stats(self) -> Dict[str, object]:
        return {
            'prefix_hit_rate': round(self.hit_rate(), 4),
            'prefix_tokens_saved': self.tokens_saved,
            'prefix_cached_pages': self.cached_pages,
            'prefix_evictions': self.evictions,
            # Raw counters so consumers (bench_ttft's shared-prefix
            # sweep) can compute WINDOWED hit rates from deltas — the
            # rate above is cumulative since engine start.
            'prefix_hits': self.hits,
            'prefix_misses': self.misses,
            # Fleet-index advertisement size (<= index_cap; lags
            # cached_pages when the cap bites).
            'prefix_indexed_pages': self.indexed_pages,
        }
