"""Shared-prefix KV cache: radix-tree page reuse over the paged pool.

Most production traffic shares long common prefixes — system prompts,
few-shot templates, multi-turn history. Without sharing, every request
re-prefills its whole prompt into private pages; with it, the repeated
prefill becomes a host-side tree walk (vLLM's prefix caching, SGLang's
RadixAttention — convergent design, re-derived here over this repo's
``PageAllocator``).

Structure: a radix tree at PAGE granularity. Each node is exactly one
full page of tokens; its edge key is that page's token block (the
``page_size``-tuple of token ids), so a node is reachable only through
the exact chain of blocks that precede it. That chaining is what makes
reuse SOUND: K/V at position p depends on every token <= p (causal
attention through all layers), so a cached page may only be reused when
the *entire* prefix matches — which the walk enforces structurally, and
exact tuple keys (not hashes) make collision-proof.

Ownership protocol (refcounts live in ``PageAllocator``):

- The tree holds ONE reference on every cached page; each slot whose
  block table maps the page holds one more. A page is *evictable* only
  at refcount 1 (tree-only) — pages under active slots are pinned.
- ``match`` returns the longest cached page-aligned prefix, capped at
  the last full page strictly BEFORE the prompt end: at least one
  token is always left to prefill (its logits seed the first sampled
  token), so the slot's frontier page is always private and decode
  never writes a shared page. The engine still guards the invariant
  with copy-on-write (``PageAllocator.cow`` + ``copy_page``) in case a
  future matching change shares the frontier.
- ``donate`` (called by the engine on finish AND preempt) walks the
  request's token sequence and hands the slot's full clean pages to the
  tree instead of freeing them: new blocks transfer the slot's
  reference to the tree; already-cached blocks just drop the slot's
  reference (duplicates deallocate); the partial last page is freed.
- ``evict`` reclaims leaf pages in LRU order, only under page pressure
  (the engine calls it when ``extend`` fails, before considering
  preemption). Leaves-first keeps every surviving node reachable.
"""
from __future__ import annotations

import dataclasses
import heapq
from typing import Dict, List, Optional, Sequence, Tuple

from skypilot_tpu.infer import paged_cache as paged_cache_lib


@dataclasses.dataclass
class _Node:
    block: Optional[Tuple[int, ...]]        # None only for the root
    page_id: int                            # physical page (tree ref)
    parent: Optional['_Node']
    last_access: int
    children: Dict[Tuple[int, ...], '_Node'] = dataclasses.field(
        default_factory=dict)


class PrefixCache:
    """Radix tree of per-page token blocks -> physical page ids."""

    # Concurrency contract (SKY-LOCK): the tree is confined to the
    # engine thread under the ENGINE's lock discipline — external code
    # (EnginePool, the server) must go through match/donate/evict/
    # stats, never the node structures (a reach-in would race the
    # step loop's donations and corrupt refcount bookkeeping).
    _GUARDED_BY = {
        '_root': 'owner',
        '_clock': 'owner',
    }

    def __init__(self,
                 allocator: paged_cache_lib.PageAllocator) -> None:
        self.allocator = allocator
        self.page = allocator.page_size
        self._root = _Node(block=None, page_id=-1, parent=None,
                           last_access=0)
        self._clock = 0
        self.hits = 0
        self.misses = 0
        self.tokens_saved = 0
        self.evictions = 0
        self.cached_pages = 0

    # -- lookup ------------------------------------------------------------
    def match(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached page-aligned prefix of ``tokens``.

        Returns (page_ids, n_tokens). Capped at the last full page
        strictly before the end of ``tokens`` so the caller always
        prefills >= 1 token (see module docstring). Touches the LRU
        clock along the matched path. The caller must ``attach`` the
        pages in the same engine step (nothing else runs between —
        evictions happen only on the engine thread)."""
        self._clock += 1
        limit = (len(tokens) - 1) // self.page
        node = self._root
        pages: List[int] = []
        for i in range(limit):
            child = node.children.get(
                tuple(tokens[i * self.page:(i + 1) * self.page]))
            if child is None:
                break
            child.last_access = self._clock
            pages.append(child.page_id)
            node = child
        matched = len(pages) * self.page
        if matched:
            self.hits += 1
            self.tokens_saved += matched
        else:
            self.misses += 1
        return pages, matched

    # -- donation ----------------------------------------------------------
    def donate(self, tokens: Sequence[int], slot: int) -> int:
        """Release ``slot``'s pages into the tree: full pages covered by
        ``tokens`` (the exact sequence whose K/V the pages hold) are
        cached; everything else (the partial last page) is freed. Also
        clears the slot's block table — this REPLACES
        ``allocator.free(slot)`` on the finish/preempt paths. Returns
        the number of newly cached pages."""
        al = self.allocator
        owned = al.owned_pages(slot)
        self._clock += 1
        full = min(len(tokens) // self.page, len(owned))
        node = self._root
        added = 0
        for i in range(full):
            blk = tuple(tokens[i * self.page:(i + 1) * self.page])
            child = node.children.get(blk)
            if child is None:
                # Tree takes over the slot's reference — no decref.
                child = _Node(block=blk, page_id=owned[i], parent=node,
                              last_access=self._clock)
                node.children[blk] = child
                self.cached_pages += 1
                added += 1
            else:
                # Block already cached (possibly by this very page, if
                # it was attached at match time): drop the slot's ref;
                # a privately-computed duplicate deallocates here.
                child.last_access = self._clock
                al.decref(owned[i])
            node = child
        for pid in owned[full:]:
            al.decref(pid)
        al.clear_slot(slot)
        return added

    # -- eviction ----------------------------------------------------------
    def evict(self, n_pages: int) -> int:
        """Reclaim up to ``n_pages`` cached pages, LRU leaf first.

        Only refcount-1 pages (tree-only — no slot maps them) are
        candidates; an attached page pins itself AND its ancestors
        (ancestors are never leaves while it exists). Called by the
        engine strictly under page pressure. Returns pages freed.

        One tree walk total, not one per freed page: the walk seeds a
        min-heap of evictable leaves; evicting a node may turn its
        parent into a leaf, which is pushed then. Multi-page
        shortfalls (a whole prefill chunk) stay O(tree + k log k)."""
        freed = 0
        heap = []
        stack = [self._root]
        while stack:
            node = stack.pop()
            if (node is not self._root and not node.children
                    and self.allocator.refcount(node.page_id) == 1):
                heap.append((node.last_access, id(node), node))
            stack.extend(node.children.values())
        heapq.heapify(heap)
        while freed < n_pages and heap:
            _, _, victim = heapq.heappop(heap)
            if (victim.children or victim.parent is None
                    or victim.parent.children.get(victim.block)
                    is not victim
                    or self.allocator.refcount(victim.page_id) != 1):
                continue   # stale heap entry
            parent = victim.parent
            del parent.children[victim.block]
            self.allocator.decref(victim.page_id)
            self.cached_pages -= 1
            self.evictions += 1
            freed += 1
            if (parent is not self._root and not parent.children
                    and self.allocator.refcount(parent.page_id) == 1):
                heapq.heappush(heap,
                               (parent.last_access, id(parent), parent))
        return freed

    # -- observability -----------------------------------------------------
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stats(self) -> Dict[str, object]:
        return {
            'prefix_hit_rate': round(self.hit_rate(), 4),
            'prefix_tokens_saved': self.tokens_saved,
            'prefix_cached_pages': self.cached_pages,
            'prefix_evictions': self.evictions,
            # Raw counters so consumers (bench_ttft's shared-prefix
            # sweep) can compute WINDOWED hit rates from deltas — the
            # rate above is cumulative since engine start.
            'prefix_hits': self.hits,
            'prefix_misses': self.misses,
        }
