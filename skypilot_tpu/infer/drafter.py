"""Draft-free speculative drafting: n-gram / prompt-lookup.

The host side of self-speculative decoding (docs/serving.md
"Speculative decoding"): given a request's context (prompt + generated
suffix), propose up to ``k`` candidate continuation tokens by finding
the most recent earlier occurrence of the context's trailing n-gram
and replaying what followed it — "prompt lookup decoding" (the
ANPL/transformers trick; vLLM's ``ngram`` speculator is the same
idea). No draft model, no device work: drafting is a dict lookup, and
the fused ``verify`` program (infer/model.py) checks all candidates in
ONE device step, so a wrong draft costs one wasted verify lane, never
a wrong token.

Why it works on serving traffic: templated/JSON output, quoting the
prompt (RAG, summarization, code edits), and the repetition loops
greedy decoding falls into all make the trailing n-gram's continuation
an excellent predictor of the model's own next tokens.

The drafter is stateless; per-request incremental state (how much of
the context is already indexed) lives in a caller-owned ``memo`` dict
(the engine hangs it off the ``Request``), so a request keeps its
index across slot moves and preemptions and each new token costs O(1)
amortized indexing, not an O(context) rescan per step.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


def cached_context(prompt: Sequence[int], output: Sequence[int],
                   memo: Dict) -> List[int]:
    """Memo-cached ``prompt + output`` list, extended incrementally as
    the output grows — so the per-step drafting cost stays O(new
    tokens), never an O(context) list rebuild per step (a request's
    prompt is immutable and its output only appends)."""
    ctx = memo.get('ctx')
    if ctx is None or len(ctx) < len(prompt):
        ctx = memo['ctx'] = list(prompt)
    have = len(ctx) - len(prompt)
    if have < len(output):
        ctx.extend(output[have:])
    return ctx


class PromptLookupDrafter:
    """Longest-suffix n-gram matcher over a token sequence.

    ``propose(context, k, memo)`` returns up to ``k`` draft tokens: the
    tokens that followed the most recent PRIOR occurrence of the
    context's trailing ``n``-gram, trying ``max_ngram`` down to
    ``min_ngram`` (longer matches are stronger evidence). Returns
    ``[]`` when no trailing n-gram has occurred before — speculation
    is opportunistic; the engine just decodes normally that step.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1,
                 max_index_per_call: int = 1024) -> None:
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                f'need 1 <= min_ngram <= max_ngram, got '
                f'[{min_ngram}, {max_ngram}]')
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        # Per-call indexing budget: the FIRST propose() for a long
        # prompt would otherwise index the whole thing inline in the
        # engine step loop, stalling token emission for every
        # co-batched slot. Capped, the index catches up over the next
        # few steps instead (proposals just see a partial index
        # meanwhile — speculation is opportunistic, and the schedule
        # is a pure function of the call sequence, so drafts stay
        # deterministic).
        self.max_index_per_call = max(max_ngram + 1,
                                      int(max_index_per_call))

    def _index(self, context: Sequence[int],
               memo: Dict) -> Dict[Tuple[int, ...], int]:
        """Incrementally extend the memo's n-gram index, at most
        ``max_index_per_call`` new positions per call.

        ``index[(gram...)] = j`` maps each n-gram (every n in
        [min_ngram, max_ngram]) to the LATEST start position j with at
        least one following token (j + n <= len - 1) — i.e. every
        occurrence except a bare trailing one, which has no
        continuation to propose. Appending one token adds at most
        ``max_ngram`` entries, so a streaming request pays O(1)
        amortized per generated token."""
        index = memo.setdefault('index', {})
        done = memo.get('indexed', 0)
        limit = min(len(context), done + self.max_index_per_call)
        # Grams indexed so far END before the old frontier: an
        # occurrence starting at j is indexable once position j + n
        # exists. Walk only the new start positions up to the budget.
        for n in range(self.min_ngram, self.max_ngram + 1):
            lo = max(0, done - n)          # starts not yet indexed
            for j in range(lo, limit - n):
                index[tuple(context[j:j + n])] = j
        memo['indexed'] = limit
        return index

    def propose(self, context: Sequence[int], k: int,
                memo: Optional[Dict] = None) -> List[int]:
        """Up to ``k`` draft tokens continuing ``context``, or []."""
        if k <= 0 or len(context) < self.min_ngram + 1:
            return []
        if memo is None:
            memo = {}
        if memo.get('indexed', 0) > len(context):
            # Context shrank (a fresh request reusing a stale memo):
            # rebuild rather than serve ghosts.
            memo.clear()
        index = self._index(context, memo)
        for n in range(min(self.max_ngram, len(context) - 1),
                       self.min_ngram - 1, -1):
            tail = tuple(context[-n:])
            j = index.get(tail)
            if j is None or j == len(context) - n:
                continue          # only the tail itself occurs
            # Copy what followed the match. When the copy source runs
            # off the end of the context it continues INTO the draft
            # being built (conceptually reading the sequence
            # context+draft) — so a repetition loop of period p drafts
            # the full k tokens of its cycle instead of stopping at
            # the frontier after p-ish tokens. Greedy decoding falls
            # into exactly such loops, and they are the drafter's
            # richest vein.
            src = j + n
            draft: List[int] = []
            for m in range(k):
                idx = src + m
                draft.append(int(context[idx]) if idx < len(context)
                             else draft[idx - len(context)])
            return draft
        return []
