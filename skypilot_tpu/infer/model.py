"""Prefill + decode paths over ``models/llama.py`` parameters.

Same weights, two execution shapes:

- **prefill_chunk**: the prompt in bounded chunks with cache context
  (MXU-bound; interleaves with decode so long prompts never
  head-of-line block active slots).
- **decode**: ONE token for every slot in one fused step
  (HBM-bandwidth-bound: the work is streaming the KV cache through the
  chip once). Attention is computed dense over the static cache with a
  length mask — at seq=1 there is nothing for a flash kernel to tile, so
  the einsum form is the fast form.

Both are pure functions jitted by the engine with buffer donation on the
cache (XLA updates it in place).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from skypilot_tpu.infer import cache as cache_lib
from skypilot_tpu.infer import paged_cache as paged_cache_lib
from skypilot_tpu.models import llama
from skypilot_tpu.ops import norms
from skypilot_tpu.ops import paged_attention as paged_attn
from skypilot_tpu.ops import quant as quant_lib
from skypilot_tpu.ops import rope as rope_lib


def prefill_chunk(config: llama.LlamaConfig, params: llama.Params,
                  kv: cache_lib.KVCache, slot: jnp.ndarray,
                  tokens: jnp.ndarray, offset: jnp.ndarray,
                  true_len: jnp.ndarray
                  ) -> Tuple[cache_lib.KVCache, jnp.ndarray]:
    """Process ONE chunk of a prompt with cache context (chunked /
    incremental prefill — the fix for prefill head-of-line blocking:
    long prompts no longer monopolize the device between decode steps).

    tokens: [C] int32, a chunk padded to the chunk bucket; offset =
    tokens of this slot already in the cache; true_len = valid tokens in
    this chunk. K/V of the chunk are written into ``slot`` at
    [offset, offset+C) (write-then-attend, like decode), the chunk's
    queries attend to the slot's cached prefix plus the chunk itself
    (causal), and lengths[slot] advances to offset+true_len. Returns
    (cache', last_logits [vocab]) — logits at local position
    true_len-1, meaningful on the final chunk.

    The pad tail writes garbage at [offset+true_len, offset+C), beyond
    the slot's frontier: unreadable (every mask stops at the frontier)
    and overwritten by the next chunk/decode write before the frontier
    reaches it.
    """
    C = tokens.shape[0]
    x = quant_lib.qembed(params['embed'], tokens)[None]   # [1, C, d]
    cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                         config.max_seq_len,
                                         config.rope_theta)
    positions = offset + jnp.arange(C, dtype=jnp.int32)   # [C]
    S = kv.max_seq_len
    # [C, S]: causal over cache prefix + chunk (key_pos <= query_pos).
    mask = jnp.arange(S)[None, :] <= positions[:, None]

    def body(carry, xs):
        layer, k_layer, v_layer = xs
        h, k_new, v_new = _chunk_layer(config, carry, layer, cos, sin,
                                       k_layer, v_layer, slot,
                                       positions, mask)
        return h, (k_new, v_new)

    x, (k_upd, v_upd) = jax.lax.scan(
        body, x, (params['layers'], kv.k, kv.v))
    x = norms.rms_norm(x, params['final_norm'], config.norm_eps)
    last = jax.lax.dynamic_index_in_dim(x[0], true_len - 1, axis=0,
                                        keepdims=False)
    logits = quant_lib.qdot(last,
                            params['lm_head']).astype(jnp.float32)
    lengths = kv.lengths.at[slot].set(
        (offset + true_len).astype(jnp.int32))
    return cache_lib.KVCache(k=k_upd, v=v_upd, lengths=lengths), logits


def _chunk_layer(config, x, layer, cos, sin, k_cache, v_cache, slot,
                 positions, mask):
    """One layer of chunked prefill. k_cache/v_cache: [slots, S, kv, hd]
    (this layer); x: [1, C, d]."""
    _, C, d = x.shape
    hq, hkv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    group = hq // hkv

    h = norms.rms_norm(x, layer['attn_norm'], config.norm_eps)
    q = quant_lib.qdot(h, layer['wq']).reshape(1, C, hq, hd)
    k = quant_lib.qdot(h, layer['wk']).reshape(1, C, hkv, hd)
    v = quant_lib.qdot(h, layer['wv']).reshape(1, C, hkv, hd)
    q = rope_lib.apply_rope(q, cos, sin, positions[None])
    k = rope_lib.apply_rope(k, cos, sin, positions[None])

    # Write the chunk's K/V into the slot FIRST, then attend over the
    # cache — the chunk sees itself through the causal mask.
    k_cache = jax.lax.dynamic_update_slice(
        k_cache, k.astype(k_cache.dtype), (slot, positions[0], 0, 0))
    v_cache = jax.lax.dynamic_update_slice(
        v_cache, v.astype(v_cache.dtype), (slot, positions[0], 0, 0))

    kc = jax.lax.dynamic_index_in_dim(k_cache, slot, axis=0,
                                      keepdims=False)  # [S, kv, hd]
    vc = jax.lax.dynamic_index_in_dim(v_cache, slot, axis=0,
                                      keepdims=False)
    qg = q[0].reshape(C, hkv, group, hd).astype(jnp.float32)
    scores = jnp.einsum('ckgd,skd->ckgs', qg,
                        kc.astype(jnp.float32)) * (hd ** -0.5)
    scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    att = jnp.einsum('ckgs,skd->ckgd', probs, vc.astype(jnp.float32))
    att = att.reshape(1, C, hq * hd).astype(x.dtype)
    x = x + quant_lib.qdot(att, layer['wo'])
    x = llama.mlp_block(config, x, layer)
    return x, k_cache, v_cache


def paged_prefill_chunk(config: llama.LlamaConfig, params: llama.Params,
                        pkv: paged_cache_lib.PagedKVCache,
                        slot: jnp.ndarray, table_row: jnp.ndarray,
                        tokens: jnp.ndarray, offset: jnp.ndarray,
                        true_len: jnp.ndarray
                        ) -> Tuple[paged_cache_lib.PagedKVCache,
                                   jnp.ndarray]:
    """prefill_chunk over the paged cache: same contract, but the
    chunk's K/V land in the slot's PAGES (block table row) and the
    chunk attends through the tiled ``paged_prefill_attention`` kernel
    — O(C * len) bandwidth instead of the dense path's O(C * S) fp32
    einsum over the whole static cache (VERDICT r4 weak #1).

    The engine guarantees: chunk size C is a multiple of the page
    size, offset is PAGE-aligned (not necessarily C-aligned — a
    prefix-cache match starts prefill at an arbitrary page boundary),
    and `table_row` already covers positions [0, offset + C). Kernel
    work must not assume offset % C == 0.
    """
    C = tokens.shape[0]
    x = quant_lib.qembed(params['embed'], tokens)[None]   # [1, C, d]
    cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                         config.max_seq_len,
                                         config.rope_theta)
    positions = offset + jnp.arange(C, dtype=jnp.int32)

    def body(carry, xs):
        layer, k_layer, v_layer, ks, vs = _unpack_layer_xs(xs)
        h, k_new, v_new, ks, vs = _paged_chunk_layer(
            config, carry, layer, cos, sin, k_layer, v_layer,
            table_row, positions, offset, true_len, ks, vs)
        return h, _pack_layer_ys(k_new, v_new, ks, vs)

    x, ys = jax.lax.scan(body, x, _layer_xs(params, pkv))
    k_upd, v_upd, ks_upd, vs_upd = _unpack_layer_upd(pkv, ys)
    x = norms.rms_norm(x, params['final_norm'], config.norm_eps)
    last = jax.lax.dynamic_index_in_dim(x[0], true_len - 1, axis=0,
                                        keepdims=False)
    logits = quant_lib.qdot(last,
                            params['lm_head']).astype(jnp.float32)
    lengths = pkv.lengths.at[slot].set(
        (offset + true_len).astype(jnp.int32))
    return paged_cache_lib.PagedKVCache(
        k_pages=k_upd, v_pages=v_upd, lengths=lengths,
        k_scales=ks_upd, v_scales=vs_upd), logits


def _layer_xs(params, pkv):
    """Per-layer scan operands: pages, plus the scale pages on the
    int8 flavor (lax.scan cannot carry None leaves in xs)."""
    if pkv.k_scales is not None:
        return (params['layers'], pkv.k_pages, pkv.v_pages,
                pkv.k_scales, pkv.v_scales)
    return (params['layers'], pkv.k_pages, pkv.v_pages)


def _unpack_layer_xs(xs):
    if len(xs) == 5:
        return xs
    layer, kp, vp = xs
    return layer, kp, vp, None, None


def _pack_layer_ys(k_new, v_new, ks, vs):
    if ks is not None:
        return (k_new, v_new, ks, vs)
    return (k_new, v_new)


def _unpack_layer_upd(pkv, ys):
    if pkv.k_scales is not None:
        return ys
    k_upd, v_upd = ys
    return k_upd, v_upd, None, None


def _paged_chunk_layer(config, x, layer, cos, sin, k_pages, v_pages,
                       table_row, positions, offset, true_len,
                       k_scales=None, v_scales=None):
    """One layer of paged chunked prefill. k_pages/v_pages:
    [hkv, P, page, hd] (this layer); x: [1, C, d]."""
    _, C, d = x.shape
    hq, hkv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    group = hq // hkv

    h = norms.rms_norm(x, layer['attn_norm'], config.norm_eps)
    q = quant_lib.qdot(h, layer['wq']).reshape(1, C, hq, hd)
    k = quant_lib.qdot(h, layer['wk']).reshape(1, C, hkv, hd)
    v = quant_lib.qdot(h, layer['wv']).reshape(1, C, hkv, hd)
    q = rope_lib.apply_rope(q, cos, sin, positions[None])
    k = rope_lib.apply_rope(k, cos, sin, positions[None])

    # Write-then-attend, page edition (quant-on-write on int8 pages:
    # the chunk's own self-attention reads its rows back dequantized,
    # exactly what every later decode step will see).
    if k_scales is not None:
        k_pages, v_pages, k_scales, v_scales = (
            paged_attn.write_chunk_pages(k_pages, v_pages, k[0], v[0],
                                         table_row, offset,
                                         k_scales, v_scales))
    else:
        k_pages, v_pages = paged_attn.write_chunk_pages(
            k_pages, v_pages, k[0], v[0], table_row, offset)
    qg = q[0].reshape(C, hkv, group, hd)
    att = paged_attn.paged_prefill_attention(
        qg, k_pages, v_pages, table_row, offset, true_len,
        k_scales=k_scales, v_scales=v_scales)
    att = att.reshape(1, C, hq * hd).astype(x.dtype)
    x = x + quant_lib.qdot(att, layer['wo'])
    x = llama.mlp_block(config, x, layer)
    return x, k_pages, v_pages, k_scales, v_scales


def paged_decode_step(config: llama.LlamaConfig, params: llama.Params,
                      pkv: paged_cache_lib.PagedKVCache,
                      block_tables: jnp.ndarray, tokens: jnp.ndarray,
                      active: Optional[jnp.ndarray] = None
                      ) -> Tuple[jnp.ndarray,
                                 paged_cache_lib.PagedKVCache]:
    """decode_step over the paged cache: one token for every slot, HBM
    traffic ∝ sum(ceil(len_i/page)) pages via the scalar-prefetch decode
    kernel (dead page steps skip their DMA; ops/paged_attention.py).

    The engine guarantees every active slot's table covers position
    lengths[slot] (the incoming token's write target).
    """
    positions = pkv.lengths
    x = quant_lib.qembed(params['embed'], tokens)[:, None]
    cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                         config.max_seq_len,
                                         config.rope_theta)

    def body(carry, xs):
        layer, k_layer, v_layer, ks, vs = _unpack_layer_xs(xs)
        h, k_new, v_new, ks, vs = _paged_decode_layer(
            config, carry, layer, cos, sin, k_layer, v_layer,
            block_tables, positions, ks, vs)
        return h, _pack_layer_ys(k_new, v_new, ks, vs)

    x, ys = jax.lax.scan(body, x, _layer_xs(params, pkv))
    k_upd, v_upd, ks_upd, vs_upd = _unpack_layer_upd(pkv, ys)
    x = norms.rms_norm(x, params['final_norm'], config.norm_eps)
    logits = quant_lib.qdot(x[:, 0],
                            params['lm_head']).astype(jnp.float32)
    bump = (jnp.ones_like(pkv.lengths) if active is None
            else active.astype(pkv.lengths.dtype))
    new_cache = paged_cache_lib.PagedKVCache(
        k_pages=k_upd, v_pages=v_upd, lengths=pkv.lengths + bump,
        k_scales=ks_upd, v_scales=vs_upd)
    return logits, new_cache


def _paged_decode_layer(config, x, layer, cos, sin, k_pages, v_pages,
                        block_tables, positions,
                        k_scales=None, v_scales=None):
    slots, _, d = x.shape
    hq, hkv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    group = hq // hkv

    h = norms.rms_norm(x, layer['attn_norm'], config.norm_eps)
    q = quant_lib.qdot(h, layer['wq']).reshape(slots, 1, hq, hd)
    k = quant_lib.qdot(h, layer['wk']).reshape(slots, 1, hkv, hd)
    v = quant_lib.qdot(h, layer['wv']).reshape(slots, 1, hkv, hd)
    q = rope_lib.apply_rope(q, cos, sin, positions[:, None])
    k = rope_lib.apply_rope(k, cos, sin, positions[:, None])

    # Write the new K/V into the slot's current page, then attend over
    # positions <= length (the new token sees itself).
    if k_scales is not None:
        k_pages, v_pages, k_scales, v_scales = (
            paged_attn.append_token_pages(
                k_pages, v_pages, k[:, 0], v[:, 0], block_tables,
                positions, k_scales, v_scales))
    else:
        k_pages, v_pages = paged_attn.append_token_pages(
            k_pages, v_pages, k[:, 0], v[:, 0], block_tables,
            positions)
    qg = q[:, 0].reshape(slots, hkv, group, hd)
    att = paged_attn.paged_decode_attention(
        qg, k_pages, v_pages, block_tables, positions + 1,
        k_scales=k_scales, v_scales=v_scales)
    att = att.reshape(slots, 1, hq * hd).astype(x.dtype)
    x = x + quant_lib.qdot(att, layer['wo'])
    x = llama.mlp_block(config, x, layer)
    return x, k_pages, v_pages, k_scales, v_scales


def verify_step(config: llama.LlamaConfig, params: llama.Params,
                kv: cache_lib.KVCache, tokens: jnp.ndarray
                ) -> Tuple[jnp.ndarray, cache_lib.KVCache]:
    """Speculative verify over the dense cache: R = spec_k+1 tokens
    for EVERY slot in one fused step.

    tokens: [slots, R] int32 — column 0 the slot's last sampled token,
    columns 1..R-1 the (padded) draft candidates. K/V for all R
    positions are written at lengths[slot]..lengths[slot]+R-1
    (write-then-attend; ``cache_lib.append_run`` guards positions past
    the cache end), each query attends causally through the cache plus
    the run prefix up to itself, and the logits at every position come
    back — the engine's acceptance rule (sampling.speculative_accept)
    turns them into 1..R emitted tokens. ``lengths`` is NOT advanced
    here: only the engine knows the accepted length (it bumps by
    accepted+1 in its jitted wrapper).

    Returns (logits [slots, R, vocab] fp32, cache with K/V written,
    lengths unchanged).
    """
    slots, R = tokens.shape
    positions = kv.lengths[:, None] + jnp.arange(
        R, dtype=jnp.int32)[None, :]                  # [slots, R]
    x = quant_lib.qembed(params['embed'], tokens)     # [slots, R, d]
    cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                         config.max_seq_len,
                                         config.rope_theta)
    S = kv.max_seq_len
    # [slots, R, S]: query i sees cached positions <= lengths + i
    # (itself included — its K/V is written before the attend).
    mask = jnp.arange(S)[None, None, :] <= positions[:, :, None]

    def body(carry, xs):
        layer, k_layer, v_layer = xs
        h, k_new, v_new = _verify_layer(config, carry, layer, cos, sin,
                                        k_layer, v_layer, positions,
                                        mask)
        return h, (k_new, v_new)

    x, (k_upd, v_upd) = jax.lax.scan(
        body, x, (params['layers'], kv.k, kv.v))
    x = norms.rms_norm(x, params['final_norm'], config.norm_eps)
    logits = quant_lib.qdot(x, params['lm_head']).astype(jnp.float32)
    return logits, cache_lib.KVCache(k=k_upd, v=v_upd,
                                     lengths=kv.lengths)


def _verify_layer(config, x, layer, cos, sin, k_cache, v_cache,
                  positions, mask):
    """One layer of the dense verify step. x: [slots, R, d];
    positions: [slots, R]; mask: [slots, R, S]."""
    slots, R, d = x.shape
    hq, hkv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    group = hq // hkv

    h = norms.rms_norm(x, layer['attn_norm'], config.norm_eps)
    q = quant_lib.qdot(h, layer['wq']).reshape(slots, R, hq, hd)
    k = quant_lib.qdot(h, layer['wk']).reshape(slots, R, hkv, hd)
    v = quant_lib.qdot(h, layer['wv']).reshape(slots, R, hkv, hd)
    q = rope_lib.apply_rope(q, cos, sin, positions)
    k = rope_lib.apply_rope(k, cos, sin, positions)

    k_cache, v_cache = cache_lib.append_run(
        k_cache, v_cache, k, v, positions[:, 0])

    qg = q.reshape(slots, R, hkv, group, hd).astype(jnp.float32)
    kc = k_cache.astype(jnp.float32)             # [slots, S, kv, hd]
    vc = v_cache.astype(jnp.float32)
    scores = jnp.einsum('brkgd,bskd->brkgs', qg, kc) * (hd ** -0.5)
    scores = jnp.where(mask[:, :, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    att = jnp.einsum('brkgs,bskd->brkgd', probs, vc)
    att = att.reshape(slots, R, hq * hd).astype(x.dtype)
    x = x + quant_lib.qdot(att, layer['wo'])
    x = llama.mlp_block(config, x, layer)
    return x, k_cache, v_cache


def paged_verify_step(config: llama.LlamaConfig, params: llama.Params,
                      pkv: paged_cache_lib.PagedKVCache,
                      block_tables: jnp.ndarray, tokens: jnp.ndarray
                      ) -> Tuple[jnp.ndarray,
                                 paged_cache_lib.PagedKVCache]:
    """verify_step over the paged cache: the run's K/V land in the
    slot's pages (positions past the block-table coverage redirect to
    the sink page) and all R queries stream each owned page ONCE via
    the verify kernel — the bandwidth bill of a single decode step for
    up to R tokens of progress. ``lengths`` is not advanced (the
    engine bumps by accepted+1)."""
    slots, R = tokens.shape
    positions = pkv.lengths[:, None] + jnp.arange(
        R, dtype=jnp.int32)[None, :]                  # [slots, R]
    x = quant_lib.qembed(params['embed'], tokens)     # [slots, R, d]
    cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                         config.max_seq_len,
                                         config.rope_theta)

    def body(carry, xs):
        layer, k_layer, v_layer, ks, vs = _unpack_layer_xs(xs)
        h, k_new, v_new, ks, vs = _paged_verify_layer(
            config, carry, layer, cos, sin, k_layer, v_layer,
            block_tables, positions, pkv.lengths, ks, vs)
        return h, _pack_layer_ys(k_new, v_new, ks, vs)

    x, ys = jax.lax.scan(body, x, _layer_xs(params, pkv))
    k_upd, v_upd, ks_upd, vs_upd = _unpack_layer_upd(pkv, ys)
    x = norms.rms_norm(x, params['final_norm'], config.norm_eps)
    logits = quant_lib.qdot(x, params['lm_head']).astype(jnp.float32)
    return logits, paged_cache_lib.PagedKVCache(
        k_pages=k_upd, v_pages=v_upd, lengths=pkv.lengths,
        k_scales=ks_upd, v_scales=vs_upd)


def _paged_verify_layer(config, x, layer, cos, sin, k_pages, v_pages,
                        block_tables, positions, lengths,
                        k_scales=None, v_scales=None):
    slots, R, d = x.shape
    hq, hkv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    group = hq // hkv

    h = norms.rms_norm(x, layer['attn_norm'], config.norm_eps)
    q = quant_lib.qdot(h, layer['wq']).reshape(slots, R, hq, hd)
    k = quant_lib.qdot(h, layer['wk']).reshape(slots, R, hkv, hd)
    v = quant_lib.qdot(h, layer['wv']).reshape(slots, R, hkv, hd)
    q = rope_lib.apply_rope(q, cos, sin, positions)
    k = rope_lib.apply_rope(k, cos, sin, positions)

    # Write-then-attend, run edition (sink-redirected past coverage).
    if k_scales is not None:
        k_pages, v_pages, k_scales, v_scales = (
            paged_attn.append_run_pages(k_pages, v_pages, k, v,
                                        block_tables, lengths,
                                        k_scales, v_scales))
    else:
        k_pages, v_pages = paged_attn.append_run_pages(
            k_pages, v_pages, k, v, block_tables, lengths)
    qg = q.reshape(slots, R, hkv, group, hd)
    att = paged_attn.paged_verify_attention(
        qg, k_pages, v_pages, block_tables, lengths,
        k_scales=k_scales, v_scales=v_scales)
    att = att.reshape(slots, R, hq * hd).astype(x.dtype)
    x = x + quant_lib.qdot(att, layer['wo'])
    x = llama.mlp_block(config, x, layer)
    return x, k_pages, v_pages, k_scales, v_scales


def decode_step(config: llama.LlamaConfig, params: llama.Params,
                kv: cache_lib.KVCache, tokens: jnp.ndarray,
                active: Optional[jnp.ndarray] = None
                ) -> Tuple[jnp.ndarray, cache_lib.KVCache]:
    """One decode token for every slot.

    tokens: [slots] int32 (last sampled token per slot). Returns
    (logits [slots, vocab] fp32, cache with K/V appended and lengths
    advanced). Inactive slots (``active`` False — free, or mid-way
    through a chunked prefill) compute garbage that the engine ignores
    and their lengths DON'T advance; their garbage K/V write lands at
    the slot frontier, which the next real write covers. Uniform work
    keeps the step a single static program.
    """
    positions = kv.lengths                       # write offset = length
    x = quant_lib.qembed(params['embed'],
                         tokens)[:, None]        # [slots, 1, d]
    cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                         config.max_seq_len,
                                         config.rope_theta)
    S = kv.max_seq_len
    # mask [slots, S]: attend to cached positions 0..len-1 plus the new
    # token at position len.
    mask = jnp.arange(S)[None, :] <= positions[:, None]

    def body(carry, xs):
        layer, k_layer, v_layer = xs
        h, k_new, v_new = _decode_layer(config, carry, layer, cos, sin,
                                        k_layer, v_layer, positions, mask)
        return h, (k_new, v_new)

    x, (k_upd, v_upd) = jax.lax.scan(
        body, x, (params['layers'], kv.k, kv.v))
    x = norms.rms_norm(x, params['final_norm'], config.norm_eps)
    logits = quant_lib.qdot(x[:, 0],
                            params['lm_head']).astype(jnp.float32)
    bump = (jnp.ones_like(kv.lengths) if active is None
            else active.astype(kv.lengths.dtype))
    new_cache = cache_lib.KVCache(k=k_upd, v=v_upd,
                                  lengths=kv.lengths + bump)
    return logits, new_cache


def _decode_layer(config, x, layer, cos, sin, k_cache, v_cache,
                  positions, mask):
    slots, _, d = x.shape
    hq, hkv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    group = hq // hkv

    h = norms.rms_norm(x, layer['attn_norm'], config.norm_eps)
    q = quant_lib.qdot(h, layer['wq']).reshape(slots, 1, hq, hd)
    k = quant_lib.qdot(h, layer['wk']).reshape(slots, 1, hkv, hd)
    v = quant_lib.qdot(h, layer['wv']).reshape(slots, 1, hkv, hd)
    q = rope_lib.apply_rope(q, cos, sin, positions[:, None])
    k = rope_lib.apply_rope(k, cos, sin, positions[:, None])

    # Write the new K/V into the cache FIRST, then attend over the cache —
    # the new token sees itself through the mask (pos <= length).
    k_cache, v_cache = cache_lib.append_token(
        k_cache, v_cache, k[:, 0], v[:, 0], positions)

    qg = q[:, 0].reshape(slots, hkv, group, hd).astype(jnp.float32)
    kc = k_cache.astype(jnp.float32)             # [slots, S, kv, hd]
    vc = v_cache.astype(jnp.float32)
    scores = jnp.einsum('bkgd,bskd->bkgs', qg, kc) * (hd ** -0.5)
    scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    att = jnp.einsum('bkgs,bskd->bkgd', probs, vc)
    att = att.reshape(slots, 1, hq * hd).astype(x.dtype)
    x = x + quant_lib.qdot(att, layer['wo'])

    x = llama.mlp_block(config, x, layer)
    return x, k_cache, v_cache


def mixed_step(config: llama.LlamaConfig, params: llama.Params,
               kv: cache_lib.KVCache, slot: jnp.ndarray,
               chunk_tokens: jnp.ndarray, offset: jnp.ndarray,
               true_len: jnp.ndarray, decode_tokens: jnp.ndarray,
               active: jnp.ndarray
               ) -> Tuple[jnp.ndarray, jnp.ndarray,
                          cache_lib.KVCache]:
    """FUSED mixed step over the dense cache: ONE prefill chunk of one
    slot AND one decode token for every active slot in a single
    compiled program (docs/serving.md "Fused mixed steps").

    Per layer the chunk half runs first (write-then-attend into
    ``slot``), then the decode half (append-then-attend for every
    slot) — exactly the order the unfused step produced with two
    dispatches, so the cache state and both logit sets are the same
    math as ``prefill_chunk`` followed by ``decode_step``. The win is
    the layer scan itself: each layer's weights stream through the
    chip ONCE for chunk + decode combined, and the standalone prefill
    dispatch that used to sit between two decode dispatches (the ITL
    stall) is gone.

    The chunk's slot must NOT be in ``active``: a chunk that completes
    its prompt joins the NEXT step's decode (its first token is
    sampled from ``chunk_logits`` by the engine wrapper and parked in
    the last-token vector — one extra step, zero token-sequence
    difference). Returns (chunk_logits [vocab] at local position
    true_len-1, decode_logits [slots, vocab], cache') with lengths =
    chunk frontier advanced to offset+true_len, then +1 per active
    decode slot.
    """
    C = chunk_tokens.shape[0]
    xc = quant_lib.qembed(params['embed'],
                          chunk_tokens)[None]         # [1, C, d]
    xd = quant_lib.qembed(params['embed'],
                          decode_tokens)[:, None]     # [slots, 1, d]
    cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                         config.max_seq_len,
                                         config.rope_theta)
    S = kv.max_seq_len
    cpos = offset + jnp.arange(C, dtype=jnp.int32)    # [C]
    cmask = jnp.arange(S)[None, :] <= cpos[:, None]
    # The decode half sees the chunk's frontier advance — the unfused
    # decode program ran AFTER the prefill program had set lengths.
    lengths_mid = kv.lengths.at[slot].set(
        (offset + true_len).astype(jnp.int32))
    dpos = lengths_mid
    dmask = jnp.arange(S)[None, :] <= dpos[:, None]

    def body(carry, xs):
        hc, hd_ = carry
        layer, k_layer, v_layer = xs
        hc, k_layer, v_layer = _chunk_layer(
            config, hc, layer, cos, sin, k_layer, v_layer, slot,
            cpos, cmask)
        hd_, k_layer, v_layer = _decode_layer(
            config, hd_, layer, cos, sin, k_layer, v_layer, dpos,
            dmask)
        return (hc, hd_), (k_layer, v_layer)

    (xc, xd), (k_upd, v_upd) = jax.lax.scan(
        body, (xc, xd), (params['layers'], kv.k, kv.v))
    xc = norms.rms_norm(xc, params['final_norm'], config.norm_eps)
    last = jax.lax.dynamic_index_in_dim(xc[0], true_len - 1, axis=0,
                                        keepdims=False)
    chunk_logits = quant_lib.qdot(
        last, params['lm_head']).astype(jnp.float32)
    xd = norms.rms_norm(xd, params['final_norm'], config.norm_eps)
    dec_logits = quant_lib.qdot(
        xd[:, 0], params['lm_head']).astype(jnp.float32)
    bump = active.astype(lengths_mid.dtype)
    return chunk_logits, dec_logits, cache_lib.KVCache(
        k=k_upd, v=v_upd, lengths=lengths_mid + bump)


def paged_mixed_step(config: llama.LlamaConfig, params: llama.Params,
                     pkv: paged_cache_lib.PagedKVCache,
                     slot: jnp.ndarray, table_row: jnp.ndarray,
                     chunk_tokens: jnp.ndarray, offset: jnp.ndarray,
                     true_len: jnp.ndarray,
                     block_tables: jnp.ndarray,
                     decode_tokens: jnp.ndarray, active: jnp.ndarray
                     ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                paged_cache_lib.PagedKVCache]:
    """``mixed_step`` over the paged cache (both KV flavors): the
    chunk's K/V land in ``table_row``'s pages and the decode appends
    ride ``block_tables``, same per-layer chunk-then-decode order as
    the dense version — the unfused two-dispatch state, one launch."""
    C = chunk_tokens.shape[0]
    xc = quant_lib.qembed(params['embed'], chunk_tokens)[None]
    xd = quant_lib.qembed(params['embed'], decode_tokens)[:, None]
    cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                         config.max_seq_len,
                                         config.rope_theta)
    cpos = offset + jnp.arange(C, dtype=jnp.int32)
    lengths_mid = pkv.lengths.at[slot].set(
        (offset + true_len).astype(jnp.int32))
    dpos = lengths_mid

    def body(carry, xs):
        hc, hd_ = carry
        layer, k_layer, v_layer, ks, vs = _unpack_layer_xs(xs)
        hc, k_layer, v_layer, ks, vs = _paged_chunk_layer(
            config, hc, layer, cos, sin, k_layer, v_layer,
            table_row, cpos, offset, true_len, ks, vs)
        hd_, k_layer, v_layer, ks, vs = _paged_decode_layer(
            config, hd_, layer, cos, sin, k_layer, v_layer,
            block_tables, dpos, ks, vs)
        return (hc, hd_), _pack_layer_ys(k_layer, v_layer, ks, vs)

    (xc, xd), ys = jax.lax.scan(body, (xc, xd),
                                _layer_xs(params, pkv))
    k_upd, v_upd, ks_upd, vs_upd = _unpack_layer_upd(pkv, ys)
    xc = norms.rms_norm(xc, params['final_norm'], config.norm_eps)
    last = jax.lax.dynamic_index_in_dim(xc[0], true_len - 1, axis=0,
                                        keepdims=False)
    chunk_logits = quant_lib.qdot(
        last, params['lm_head']).astype(jnp.float32)
    xd = norms.rms_norm(xd, params['final_norm'], config.norm_eps)
    dec_logits = quant_lib.qdot(
        xd[:, 0], params['lm_head']).astype(jnp.float32)
    bump = active.astype(lengths_mid.dtype)
    return chunk_logits, dec_logits, paged_cache_lib.PagedKVCache(
        k_pages=k_upd, v_pages=v_upd, lengths=lengths_mid + bump,
        k_scales=ks_upd, v_scales=vs_upd)
