"""Prefill + decode paths over ``models/llama.py`` parameters.

Same weights, two execution shapes:

- **prefill_chunk**: the prompt in bounded chunks with cache context
  (MXU-bound; interleaves with decode so long prompts never
  head-of-line block active slots).
- **decode**: ONE token for every slot in one fused step
  (HBM-bandwidth-bound: the work is streaming the KV cache through the
  chip once). Attention is computed dense over the static cache with a
  length mask — at seq=1 there is nothing for a flash kernel to tile, so
  the einsum form is the fast form.

Both are pure functions jitted by the engine with buffer donation on the
cache (XLA updates it in place).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from skypilot_tpu.infer import cache as cache_lib
from skypilot_tpu.infer import paged_cache as paged_cache_lib
from skypilot_tpu.models import llama
from skypilot_tpu.ops import norms
from skypilot_tpu.ops import paged_attention as paged_attn
from skypilot_tpu.ops import quant as quant_lib
from skypilot_tpu.ops import rope as rope_lib


def prefill_chunk(config: llama.LlamaConfig, params: llama.Params,
                  kv: cache_lib.KVCache, slot: jnp.ndarray,
                  tokens: jnp.ndarray, offset: jnp.ndarray,
                  true_len: jnp.ndarray
                  ) -> Tuple[cache_lib.KVCache, jnp.ndarray]:
    """Process ONE chunk of a prompt with cache context (chunked /
    incremental prefill — the fix for prefill head-of-line blocking:
    long prompts no longer monopolize the device between decode steps).

    tokens: [C] int32, a chunk padded to the chunk bucket; offset =
    tokens of this slot already in the cache; true_len = valid tokens in
    this chunk. K/V of the chunk are written into ``slot`` at
    [offset, offset+C) (write-then-attend, like decode), the chunk's
    queries attend to the slot's cached prefix plus the chunk itself
    (causal), and lengths[slot] advances to offset+true_len. Returns
    (cache', last_logits [vocab]) — logits at local position
    true_len-1, meaningful on the final chunk.

    The pad tail writes garbage at [offset+true_len, offset+C), beyond
    the slot's frontier: unreadable (every mask stops at the frontier)
    and overwritten by the next chunk/decode write before the frontier
    reaches it.
    """
    C = tokens.shape[0]
    x = quant_lib.qembed(params['embed'], tokens)[None]   # [1, C, d]
    cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                         config.max_seq_len,
                                         config.rope_theta)
    positions = offset + jnp.arange(C, dtype=jnp.int32)   # [C]
    S = kv.max_seq_len
    # [C, S]: causal over cache prefix + chunk (key_pos <= query_pos).
    mask = jnp.arange(S)[None, :] <= positions[:, None]

    def body(carry, xs):
        layer, k_layer, v_layer = xs
        h, k_new, v_new = _chunk_layer(config, carry, layer, cos, sin,
                                       k_layer, v_layer, slot,
                                       positions, mask)
        return h, (k_new, v_new)

    x, (k_upd, v_upd) = jax.lax.scan(
        body, x, (params['layers'], kv.k, kv.v))
    x = norms.rms_norm(x, params['final_norm'], config.norm_eps)
    last = jax.lax.dynamic_index_in_dim(x[0], true_len - 1, axis=0,
                                        keepdims=False)
    logits = quant_lib.qdot(last,
                            params['lm_head']).astype(jnp.float32)
    lengths = kv.lengths.at[slot].set(
        (offset + true_len).astype(jnp.int32))
    return cache_lib.KVCache(k=k_upd, v=v_upd, lengths=lengths), logits


def _chunk_layer(config, x, layer, cos, sin, k_cache, v_cache, slot,
                 positions, mask):
    """One layer of chunked prefill. k_cache/v_cache: [slots, S, kv, hd]
    (this layer); x: [1, C, d]."""
    _, C, d = x.shape
    hq, hkv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    group = hq // hkv

    h = norms.rms_norm(x, layer['attn_norm'], config.norm_eps)
    q = quant_lib.qdot(h, layer['wq']).reshape(1, C, hq, hd)
    k = quant_lib.qdot(h, layer['wk']).reshape(1, C, hkv, hd)
    v = quant_lib.qdot(h, layer['wv']).reshape(1, C, hkv, hd)
    q = rope_lib.apply_rope(q, cos, sin, positions[None])
    k = rope_lib.apply_rope(k, cos, sin, positions[None])

    # Write the chunk's K/V into the slot FIRST, then attend over the
    # cache — the chunk sees itself through the causal mask.
    k_cache = jax.lax.dynamic_update_slice(
        k_cache, k.astype(k_cache.dtype), (slot, positions[0], 0, 0))
    v_cache = jax.lax.dynamic_update_slice(
        v_cache, v.astype(v_cache.dtype), (slot, positions[0], 0, 0))

    kc = jax.lax.dynamic_index_in_dim(k_cache, slot, axis=0,
                                      keepdims=False)  # [S, kv, hd]
    vc = jax.lax.dynamic_index_in_dim(v_cache, slot, axis=0,
                                      keepdims=False)
    qg = q[0].reshape(C, hkv, group, hd).astype(jnp.float32)
    scores = jnp.einsum('ckgd,skd->ckgs', qg,
                        kc.astype(jnp.float32)) * (hd ** -0.5)
    scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    att = jnp.einsum('ckgs,skd->ckgd', probs, vc.astype(jnp.float32))
    att = att.reshape(1, C, hq * hd).astype(x.dtype)
    x = x + quant_lib.qdot(att, layer['wo'])
    x = llama.mlp_block(config, x, layer)
    return x, k_cache, v_cache


def paged_prefill_chunk(config: llama.LlamaConfig, params: llama.Params,
                        pkv: paged_cache_lib.PagedKVCache,
                        slot: jnp.ndarray, table_row: jnp.ndarray,
                        tokens: jnp.ndarray, offset: jnp.ndarray,
                        true_len: jnp.ndarray
                        ) -> Tuple[paged_cache_lib.PagedKVCache,
                                   jnp.ndarray]:
    """prefill_chunk over the paged cache: same contract, but the
    chunk's K/V land in the slot's PAGES (block table row) and the
    chunk attends through the tiled ``paged_prefill_attention`` kernel
    — O(C * len) bandwidth instead of the dense path's O(C * S) fp32
    einsum over the whole static cache (VERDICT r4 weak #1).

    The engine guarantees: chunk size C is a multiple of the page
    size, offset is PAGE-aligned (not necessarily C-aligned — a
    prefix-cache match starts prefill at an arbitrary page boundary),
    and `table_row` already covers positions [0, offset + C). Kernel
    work must not assume offset % C == 0.
    """
    C = tokens.shape[0]
    x = quant_lib.qembed(params['embed'], tokens)[None]   # [1, C, d]
    cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                         config.max_seq_len,
                                         config.rope_theta)
    positions = offset + jnp.arange(C, dtype=jnp.int32)

    def body(carry, xs):
        layer, k_layer, v_layer = xs
        h, k_new, v_new = _paged_chunk_layer(
            config, carry, layer, cos, sin, k_layer, v_layer,
            table_row, positions, offset, true_len)
        return h, (k_new, v_new)

    x, (k_upd, v_upd) = jax.lax.scan(
        body, x, (params['layers'], pkv.k_pages, pkv.v_pages))
    x = norms.rms_norm(x, params['final_norm'], config.norm_eps)
    last = jax.lax.dynamic_index_in_dim(x[0], true_len - 1, axis=0,
                                        keepdims=False)
    logits = quant_lib.qdot(last,
                            params['lm_head']).astype(jnp.float32)
    lengths = pkv.lengths.at[slot].set(
        (offset + true_len).astype(jnp.int32))
    return paged_cache_lib.PagedKVCache(
        k_pages=k_upd, v_pages=v_upd, lengths=lengths), logits


def _paged_chunk_layer(config, x, layer, cos, sin, k_pages, v_pages,
                       table_row, positions, offset, true_len):
    """One layer of paged chunked prefill. k_pages/v_pages:
    [hkv, P, page, hd] (this layer); x: [1, C, d]."""
    _, C, d = x.shape
    hq, hkv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    group = hq // hkv

    h = norms.rms_norm(x, layer['attn_norm'], config.norm_eps)
    q = quant_lib.qdot(h, layer['wq']).reshape(1, C, hq, hd)
    k = quant_lib.qdot(h, layer['wk']).reshape(1, C, hkv, hd)
    v = quant_lib.qdot(h, layer['wv']).reshape(1, C, hkv, hd)
    q = rope_lib.apply_rope(q, cos, sin, positions[None])
    k = rope_lib.apply_rope(k, cos, sin, positions[None])

    # Write-then-attend, page edition.
    k_pages, v_pages = paged_attn.write_chunk_pages(
        k_pages, v_pages, k[0], v[0], table_row, offset)
    qg = q[0].reshape(C, hkv, group, hd)
    att = paged_attn.paged_prefill_attention(
        qg, k_pages, v_pages, table_row, offset, true_len)
    att = att.reshape(1, C, hq * hd).astype(x.dtype)
    x = x + quant_lib.qdot(att, layer['wo'])
    x = llama.mlp_block(config, x, layer)
    return x, k_pages, v_pages


def paged_decode_step(config: llama.LlamaConfig, params: llama.Params,
                      pkv: paged_cache_lib.PagedKVCache,
                      block_tables: jnp.ndarray, tokens: jnp.ndarray,
                      active: Optional[jnp.ndarray] = None
                      ) -> Tuple[jnp.ndarray,
                                 paged_cache_lib.PagedKVCache]:
    """decode_step over the paged cache: one token for every slot, HBM
    traffic ∝ sum(ceil(len_i/page)) pages via the scalar-prefetch decode
    kernel (dead page steps skip their DMA; ops/paged_attention.py).

    The engine guarantees every active slot's table covers position
    lengths[slot] (the incoming token's write target).
    """
    positions = pkv.lengths
    x = quant_lib.qembed(params['embed'], tokens)[:, None]
    cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                         config.max_seq_len,
                                         config.rope_theta)

    def body(carry, xs):
        layer, k_layer, v_layer = xs
        h, k_new, v_new = _paged_decode_layer(
            config, carry, layer, cos, sin, k_layer, v_layer,
            block_tables, positions)
        return h, (k_new, v_new)

    x, (k_upd, v_upd) = jax.lax.scan(
        body, x, (params['layers'], pkv.k_pages, pkv.v_pages))
    x = norms.rms_norm(x, params['final_norm'], config.norm_eps)
    logits = quant_lib.qdot(x[:, 0],
                            params['lm_head']).astype(jnp.float32)
    bump = (jnp.ones_like(pkv.lengths) if active is None
            else active.astype(pkv.lengths.dtype))
    new_cache = paged_cache_lib.PagedKVCache(
        k_pages=k_upd, v_pages=v_upd, lengths=pkv.lengths + bump)
    return logits, new_cache


def _paged_decode_layer(config, x, layer, cos, sin, k_pages, v_pages,
                        block_tables, positions):
    slots, _, d = x.shape
    hq, hkv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    group = hq // hkv

    h = norms.rms_norm(x, layer['attn_norm'], config.norm_eps)
    q = quant_lib.qdot(h, layer['wq']).reshape(slots, 1, hq, hd)
    k = quant_lib.qdot(h, layer['wk']).reshape(slots, 1, hkv, hd)
    v = quant_lib.qdot(h, layer['wv']).reshape(slots, 1, hkv, hd)
    q = rope_lib.apply_rope(q, cos, sin, positions[:, None])
    k = rope_lib.apply_rope(k, cos, sin, positions[:, None])

    # Write the new K/V into the slot's current page, then attend over
    # positions <= length (the new token sees itself).
    k_pages, v_pages = paged_attn.append_token_pages(
        k_pages, v_pages, k[:, 0], v[:, 0], block_tables, positions)
    qg = q[:, 0].reshape(slots, hkv, group, hd)
    att = paged_attn.paged_decode_attention(
        qg, k_pages, v_pages, block_tables, positions + 1)
    att = att.reshape(slots, 1, hq * hd).astype(x.dtype)
    x = x + quant_lib.qdot(att, layer['wo'])
    x = llama.mlp_block(config, x, layer)
    return x, k_pages, v_pages


def verify_step(config: llama.LlamaConfig, params: llama.Params,
                kv: cache_lib.KVCache, tokens: jnp.ndarray
                ) -> Tuple[jnp.ndarray, cache_lib.KVCache]:
    """Speculative verify over the dense cache: R = spec_k+1 tokens
    for EVERY slot in one fused step.

    tokens: [slots, R] int32 — column 0 the slot's last sampled token,
    columns 1..R-1 the (padded) draft candidates. K/V for all R
    positions are written at lengths[slot]..lengths[slot]+R-1
    (write-then-attend; ``cache_lib.append_run`` guards positions past
    the cache end), each query attends causally through the cache plus
    the run prefix up to itself, and the logits at every position come
    back — the engine's acceptance rule (sampling.speculative_accept)
    turns them into 1..R emitted tokens. ``lengths`` is NOT advanced
    here: only the engine knows the accepted length (it bumps by
    accepted+1 in its jitted wrapper).

    Returns (logits [slots, R, vocab] fp32, cache with K/V written,
    lengths unchanged).
    """
    slots, R = tokens.shape
    positions = kv.lengths[:, None] + jnp.arange(
        R, dtype=jnp.int32)[None, :]                  # [slots, R]
    x = quant_lib.qembed(params['embed'], tokens)     # [slots, R, d]
    cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                         config.max_seq_len,
                                         config.rope_theta)
    S = kv.max_seq_len
    # [slots, R, S]: query i sees cached positions <= lengths + i
    # (itself included — its K/V is written before the attend).
    mask = jnp.arange(S)[None, None, :] <= positions[:, :, None]

    def body(carry, xs):
        layer, k_layer, v_layer = xs
        h, k_new, v_new = _verify_layer(config, carry, layer, cos, sin,
                                        k_layer, v_layer, positions,
                                        mask)
        return h, (k_new, v_new)

    x, (k_upd, v_upd) = jax.lax.scan(
        body, x, (params['layers'], kv.k, kv.v))
    x = norms.rms_norm(x, params['final_norm'], config.norm_eps)
    logits = quant_lib.qdot(x, params['lm_head']).astype(jnp.float32)
    return logits, cache_lib.KVCache(k=k_upd, v=v_upd,
                                     lengths=kv.lengths)


def _verify_layer(config, x, layer, cos, sin, k_cache, v_cache,
                  positions, mask):
    """One layer of the dense verify step. x: [slots, R, d];
    positions: [slots, R]; mask: [slots, R, S]."""
    slots, R, d = x.shape
    hq, hkv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    group = hq // hkv

    h = norms.rms_norm(x, layer['attn_norm'], config.norm_eps)
    q = quant_lib.qdot(h, layer['wq']).reshape(slots, R, hq, hd)
    k = quant_lib.qdot(h, layer['wk']).reshape(slots, R, hkv, hd)
    v = quant_lib.qdot(h, layer['wv']).reshape(slots, R, hkv, hd)
    q = rope_lib.apply_rope(q, cos, sin, positions)
    k = rope_lib.apply_rope(k, cos, sin, positions)

    k_cache, v_cache = cache_lib.append_run(
        k_cache, v_cache, k, v, positions[:, 0])

    qg = q.reshape(slots, R, hkv, group, hd).astype(jnp.float32)
    kc = k_cache.astype(jnp.float32)             # [slots, S, kv, hd]
    vc = v_cache.astype(jnp.float32)
    scores = jnp.einsum('brkgd,bskd->brkgs', qg, kc) * (hd ** -0.5)
    scores = jnp.where(mask[:, :, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    att = jnp.einsum('brkgs,bskd->brkgd', probs, vc)
    att = att.reshape(slots, R, hq * hd).astype(x.dtype)
    x = x + quant_lib.qdot(att, layer['wo'])
    x = llama.mlp_block(config, x, layer)
    return x, k_cache, v_cache


def paged_verify_step(config: llama.LlamaConfig, params: llama.Params,
                      pkv: paged_cache_lib.PagedKVCache,
                      block_tables: jnp.ndarray, tokens: jnp.ndarray
                      ) -> Tuple[jnp.ndarray,
                                 paged_cache_lib.PagedKVCache]:
    """verify_step over the paged cache: the run's K/V land in the
    slot's pages (positions past the block-table coverage redirect to
    the sink page) and all R queries stream each owned page ONCE via
    the verify kernel — the bandwidth bill of a single decode step for
    up to R tokens of progress. ``lengths`` is not advanced (the
    engine bumps by accepted+1)."""
    slots, R = tokens.shape
    positions = pkv.lengths[:, None] + jnp.arange(
        R, dtype=jnp.int32)[None, :]                  # [slots, R]
    x = quant_lib.qembed(params['embed'], tokens)     # [slots, R, d]
    cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                         config.max_seq_len,
                                         config.rope_theta)

    def body(carry, xs):
        layer, k_layer, v_layer = xs
        h, k_new, v_new = _paged_verify_layer(
            config, carry, layer, cos, sin, k_layer, v_layer,
            block_tables, positions, pkv.lengths)
        return h, (k_new, v_new)

    x, (k_upd, v_upd) = jax.lax.scan(
        body, x, (params['layers'], pkv.k_pages, pkv.v_pages))
    x = norms.rms_norm(x, params['final_norm'], config.norm_eps)
    logits = quant_lib.qdot(x, params['lm_head']).astype(jnp.float32)
    return logits, paged_cache_lib.PagedKVCache(
        k_pages=k_upd, v_pages=v_upd, lengths=pkv.lengths)


def _paged_verify_layer(config, x, layer, cos, sin, k_pages, v_pages,
                        block_tables, positions, lengths):
    slots, R, d = x.shape
    hq, hkv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    group = hq // hkv

    h = norms.rms_norm(x, layer['attn_norm'], config.norm_eps)
    q = quant_lib.qdot(h, layer['wq']).reshape(slots, R, hq, hd)
    k = quant_lib.qdot(h, layer['wk']).reshape(slots, R, hkv, hd)
    v = quant_lib.qdot(h, layer['wv']).reshape(slots, R, hkv, hd)
    q = rope_lib.apply_rope(q, cos, sin, positions)
    k = rope_lib.apply_rope(k, cos, sin, positions)

    # Write-then-attend, run edition (sink-redirected past coverage).
    k_pages, v_pages = paged_attn.append_run_pages(
        k_pages, v_pages, k, v, block_tables, lengths)
    qg = q.reshape(slots, R, hkv, group, hd)
    att = paged_attn.paged_verify_attention(
        qg, k_pages, v_pages, block_tables, lengths)
    att = att.reshape(slots, R, hq * hd).astype(x.dtype)
    x = x + quant_lib.qdot(att, layer['wo'])
    x = llama.mlp_block(config, x, layer)
    return x, k_pages, v_pages


def decode_step(config: llama.LlamaConfig, params: llama.Params,
                kv: cache_lib.KVCache, tokens: jnp.ndarray,
                active: Optional[jnp.ndarray] = None
                ) -> Tuple[jnp.ndarray, cache_lib.KVCache]:
    """One decode token for every slot.

    tokens: [slots] int32 (last sampled token per slot). Returns
    (logits [slots, vocab] fp32, cache with K/V appended and lengths
    advanced). Inactive slots (``active`` False — free, or mid-way
    through a chunked prefill) compute garbage that the engine ignores
    and their lengths DON'T advance; their garbage K/V write lands at
    the slot frontier, which the next real write covers. Uniform work
    keeps the step a single static program.
    """
    positions = kv.lengths                       # write offset = length
    x = quant_lib.qembed(params['embed'],
                         tokens)[:, None]        # [slots, 1, d]
    cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                         config.max_seq_len,
                                         config.rope_theta)
    S = kv.max_seq_len
    # mask [slots, S]: attend to cached positions 0..len-1 plus the new
    # token at position len.
    mask = jnp.arange(S)[None, :] <= positions[:, None]

    def body(carry, xs):
        layer, k_layer, v_layer = xs
        h, k_new, v_new = _decode_layer(config, carry, layer, cos, sin,
                                        k_layer, v_layer, positions, mask)
        return h, (k_new, v_new)

    x, (k_upd, v_upd) = jax.lax.scan(
        body, x, (params['layers'], kv.k, kv.v))
    x = norms.rms_norm(x, params['final_norm'], config.norm_eps)
    logits = quant_lib.qdot(x[:, 0],
                            params['lm_head']).astype(jnp.float32)
    bump = (jnp.ones_like(kv.lengths) if active is None
            else active.astype(kv.lengths.dtype))
    new_cache = cache_lib.KVCache(k=k_upd, v=v_upd,
                                  lengths=kv.lengths + bump)
    return logits, new_cache


def _decode_layer(config, x, layer, cos, sin, k_cache, v_cache,
                  positions, mask):
    slots, _, d = x.shape
    hq, hkv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    group = hq // hkv

    h = norms.rms_norm(x, layer['attn_norm'], config.norm_eps)
    q = quant_lib.qdot(h, layer['wq']).reshape(slots, 1, hq, hd)
    k = quant_lib.qdot(h, layer['wk']).reshape(slots, 1, hkv, hd)
    v = quant_lib.qdot(h, layer['wv']).reshape(slots, 1, hkv, hd)
    q = rope_lib.apply_rope(q, cos, sin, positions[:, None])
    k = rope_lib.apply_rope(k, cos, sin, positions[:, None])

    # Write the new K/V into the cache FIRST, then attend over the cache —
    # the new token sees itself through the mask (pos <= length).
    k_cache, v_cache = cache_lib.append_token(
        k_cache, v_cache, k[:, 0], v[:, 0], positions)

    qg = q[:, 0].reshape(slots, hkv, group, hd).astype(jnp.float32)
    kc = k_cache.astype(jnp.float32)             # [slots, S, kv, hd]
    vc = v_cache.astype(jnp.float32)
    scores = jnp.einsum('bkgd,bskd->bkgs', qg, kc) * (hd ** -0.5)
    scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    att = jnp.einsum('bkgs,bskd->bkgd', probs, vc)
    att = att.reshape(slots, 1, hq * hd).astype(x.dtype)
    x = x + quant_lib.qdot(att, layer['wo'])

    x = llama.mlp_block(config, x, layer)
    return x, k_cache, v_cache
