"""Prefill + decode paths over ``models/llama.py`` parameters.

Same weights, two execution shapes:

- **prefill**: the full prompt in one pass (MXU-bound, flash attention),
  emitting every position's K/V for cache insertion plus the last
  position's logits.
- **decode**: ONE token for every slot in one fused step
  (HBM-bandwidth-bound: the work is streaming the KV cache through the
  chip once). Attention is computed dense over the static cache with a
  length mask — at seq=1 there is nothing for a flash kernel to tile, so
  the einsum form is the fast form.

Both are pure functions jitted by the engine with buffer donation on the
cache (XLA updates it in place).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from skypilot_tpu.infer import cache as cache_lib
from skypilot_tpu.models import llama
from skypilot_tpu.ops import norms
from skypilot_tpu.ops import rope as rope_lib


def prefill(config: llama.LlamaConfig, params: llama.Params,
            tokens: jnp.ndarray, true_len: jnp.ndarray
            ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Run the prompt; return (k [L,P,kv,hd], v [L,P,kv,hd],
    last_logits [vocab]).

    tokens: [P] int32, padded to a bucket size; true_len: scalar int32.
    The pad tail's K/V are garbage but unreachable (cache lengths stop at
    true_len); last_logits reads position true_len-1.
    """
    x = params['embed'][tokens][None]          # [1, P, d]
    cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                         config.max_seq_len,
                                         config.rope_theta)

    def body(carry, layer):
        h, kv = _prefill_layer(config, carry, layer, cos, sin)
        return h, kv

    x, (ks, vs) = jax.lax.scan(body, x, params['layers'])
    x = norms.rms_norm(x, params['final_norm'], config.norm_eps)
    last = jax.lax.dynamic_index_in_dim(x[0], true_len - 1, axis=0,
                                        keepdims=False)
    logits = (last @ params['lm_head']).astype(jnp.float32)
    return ks, vs, logits


def _prefill_layer(config, x, layer, cos, sin):
    x, k, v = llama.attention_block(config, x, layer, cos, sin, None)
    h = norms.rms_norm(x, layer['mlp_norm'], config.norm_eps)
    gate = jax.nn.silu(h @ layer['w_gate'])
    x = x + (gate * (h @ layer['w_up'])) @ layer['w_down']
    # [s, kv, hd] for the cache (batch=1 squeezed).
    return x, (k[0], v[0])


def decode_step(config: llama.LlamaConfig, params: llama.Params,
                kv: cache_lib.KVCache, tokens: jnp.ndarray
                ) -> Tuple[jnp.ndarray, cache_lib.KVCache]:
    """One decode token for every slot.

    tokens: [slots] int32 (last sampled token per slot). Returns
    (logits [slots, vocab] fp32, cache with K/V appended and lengths+1).
    Inactive slots (length 0) compute garbage that the engine ignores —
    uniform work keeps the step a single static program.
    """
    positions = kv.lengths                       # write offset = length
    x = params['embed'][tokens][:, None]         # [slots, 1, d]
    cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                         config.max_seq_len,
                                         config.rope_theta)
    S = kv.max_seq_len
    # mask [slots, S]: attend to cached positions 0..len-1 plus the new
    # token at position len.
    mask = jnp.arange(S)[None, :] <= positions[:, None]

    def body(carry, xs):
        layer, k_layer, v_layer = xs
        h, k_new, v_new = _decode_layer(config, carry, layer, cos, sin,
                                        k_layer, v_layer, positions, mask)
        return h, (k_new, v_new)

    x, (k_upd, v_upd) = jax.lax.scan(
        body, x, (params['layers'], kv.k, kv.v))
    x = norms.rms_norm(x, params['final_norm'], config.norm_eps)
    logits = (x[:, 0] @ params['lm_head']).astype(jnp.float32)
    new_cache = cache_lib.KVCache(k=k_upd, v=v_upd,
                                  lengths=kv.lengths + 1)
    return logits, new_cache


def _decode_layer(config, x, layer, cos, sin, k_cache, v_cache,
                  positions, mask):
    slots, _, d = x.shape
    hq, hkv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    group = hq // hkv

    h = norms.rms_norm(x, layer['attn_norm'], config.norm_eps)
    q = (h @ layer['wq']).reshape(slots, 1, hq, hd)
    k = (h @ layer['wk']).reshape(slots, 1, hkv, hd)
    v = (h @ layer['wv']).reshape(slots, 1, hkv, hd)
    q = rope_lib.apply_rope(q, cos, sin, positions[:, None])
    k = rope_lib.apply_rope(k, cos, sin, positions[:, None])

    # Write the new K/V into the cache FIRST, then attend over the cache —
    # the new token sees itself through the mask (pos <= length).
    k_cache, v_cache = cache_lib.append_token(
        k_cache, v_cache, k[:, 0], v[:, 0], positions)

    qg = q[:, 0].reshape(slots, hkv, group, hd).astype(jnp.float32)
    kc = k_cache.astype(jnp.float32)             # [slots, S, kv, hd]
    vc = v_cache.astype(jnp.float32)
    scores = jnp.einsum('bkgd,bskd->bkgs', qg, kc) * (hd ** -0.5)
    scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    att = jnp.einsum('bkgs,bskd->bkgd', probs, vc)
    att = att.reshape(slots, 1, hq * hd).astype(x.dtype)
    x = x + att @ layer['wo']

    h = norms.rms_norm(x, layer['mlp_norm'], config.norm_eps)
    gate = jax.nn.silu(h @ layer['w_gate'])
    x = x + (gate * (h @ layer['w_up'])) @ layer['w_down']
    return x, k_cache, v_cache
