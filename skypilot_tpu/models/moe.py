"""Mixture-of-Experts transformer (Mixtral-style) with expert parallelism.

Absent from the reference (SURVEY.md §2.8: EP delegated to user
frameworks); built TPU-first here:

- **GShard-style fixed-capacity dispatch**: routing produces dense
  dispatch/combine tensors, and expert compute is batched einsums over
  ``[experts, capacity, dim]`` — static shapes, MXU-shaped, no gather
  loops.
- **Expert parallelism is a sharding, not code**: expert-stacked weights
  carry ``P('ep')`` on the expert axis; under jit the dispatch/combine
  einsums lower to all-to-alls over the ``ep`` mesh axis automatically.
- Attention/norms/RoPE are shared with ``models/llama.py`` (same layer
  fn); only the MLP is replaced by the routed expert MLP.
- Router aux losses: load-balancing (Switch-style) + router z-loss,
  returned separately so the trainer can weight them.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from skypilot_tpu.models import llama
from skypilot_tpu.ops import norms
from skypilot_tpu.ops import rope as rope_lib

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32_000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14_336          # per-expert hidden dim
    n_experts: int = 8
    experts_per_token: int = 2     # top-k routing
    capacity_factor: float = 1.25  # expert capacity vs perfect balance
    max_seq_len: int = 8192
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    dtype: str = 'bfloat16'
    attention_impl: str = 'auto'
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def num_params(self) -> int:
        d, f, v = self.dim, self.ffn_dim, self.vocab_size
        per_layer = (d * self.n_heads * self.head_dim
                     + 2 * d * self.n_kv_heads * self.head_dim
                     + self.n_heads * self.head_dim * d
                     + self.n_experts * 3 * d * f
                     + d * self.n_experts      # router
                     + 2 * d)
        return self.n_layers * per_layer + 2 * v * d + d

    @staticmethod
    def mixtral_8x7b(**kw) -> 'MoEConfig':
        return MoEConfig(**kw)

    @staticmethod
    def tiny(**kw) -> 'MoEConfig':
        base = dict(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                    n_kv_heads=2, ffn_dim=96, n_experts=4,
                    experts_per_token=2, max_seq_len=128,
                    dtype='float32')
        base.update(kw)
        return MoEConfig(**base)

    def as_llama(self) -> llama.LlamaConfig:
        """Attention-relevant view for reusing llama layer pieces."""
        return llama.LlamaConfig(
            vocab_size=self.vocab_size, dim=self.dim,
            n_layers=self.n_layers, n_heads=self.n_heads,
            n_kv_heads=self.n_kv_heads, ffn_dim=self.ffn_dim,
            max_seq_len=self.max_seq_len, rope_theta=self.rope_theta,
            norm_eps=self.norm_eps, dtype=self.dtype,
            attention_impl=self.attention_impl, remat=self.remat)


# Tree skeleton for sharding specs (see llama.LLAMA_LAYER_TREE).
MOE_LAYER_TREE: Dict[str, int] = {
    'attn_norm': 0, 'wq': 0, 'wk': 0, 'wv': 0, 'wo': 0,
    'mlp_norm': 0, 'router': 0, 'w_gate': 0, 'w_up': 0, 'w_down': 0,
}


def init_params(config: MoEConfig, key: jax.Array) -> Params:
    dtype = jnp.dtype(config.dtype)
    d, hd, f = config.dim, config.head_dim, config.ffn_dim
    L, E = config.n_layers, config.n_experts
    k_embed, k_layers, k_head = jax.random.split(key, 3)

    def normal(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32)
                * scale).astype(dtype)

    ks = jax.random.split(k_layers, 8)
    scale = d ** -0.5
    out_scale = scale / (2 * L) ** 0.5
    layers = {
        'attn_norm': jnp.ones((L, d), dtype),
        'wq': normal(ks[0], (L, d, config.n_heads * hd), scale),
        'wk': normal(ks[1], (L, d, config.n_kv_heads * hd), scale),
        'wv': normal(ks[2], (L, d, config.n_kv_heads * hd), scale),
        'wo': normal(ks[3], (L, config.n_heads * hd, d), out_scale),
        'mlp_norm': jnp.ones((L, d), dtype),
        # Router in fp32: routing logits are precision-sensitive.
        'router': jax.random.normal(ks[4], (L, d, E),
                                    jnp.float32) * scale,
        'w_gate': normal(ks[5], (L, E, d, f), scale),
        'w_up': normal(ks[6], (L, E, d, f), scale),
        'w_down': normal(ks[7], (L, E, f, d), out_scale),
    }
    return {
        'embed': normal(k_embed, (config.vocab_size, d), 1.0),
        'layers': layers,
        'final_norm': jnp.ones((d,), dtype),
        'lm_head': normal(k_head, (d, config.vocab_size), scale),
    }


def _route(config: MoEConfig, h: jnp.ndarray, router_w: jnp.ndarray,
           capacity: int) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-k routing with fixed capacity.

    h: [T, d] tokens. Returns (dispatch [T, E, C] one-hot-ish fp,
    combine [T, E, C] gate-weighted, aux metrics dict-free tuple).
    Tokens overflowing an expert's capacity are dropped for that expert
    (Switch/GShard semantics).
    """
    T = h.shape[0]
    E, K = config.n_experts, config.experts_per_token
    logits = h.astype(jnp.float32) @ router_w            # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, K)      # [T, K]
    # Renormalize the top-k gates (Mixtral convention).
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)

    # Position of each (token, k) within its expert's capacity buffer:
    # rank tokens per expert by arrival order via cumsum over one-hots.
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [T,K,E]
    flat = onehot.reshape(T * K, E)
    # K choices of one token occupy distinct slots: cumsum over the
    # flattened (token-major) order.
    pos_in_expert = (jnp.cumsum(flat, axis=0) - flat)     # [T*K, E]
    pos = (pos_in_expert * flat).sum(-1).reshape(T, K).astype(jnp.int32)
    keep = pos < capacity
    gate_vals = gate_vals * keep

    cap_onehot = jax.nn.one_hot(pos, capacity,
                                dtype=jnp.float32)        # [T, K, C]
    # [T, K, E, C] -> sum over K -> [T, E, C]
    dispatch = jnp.einsum('tke,tkc->tec', onehot,
                          cap_onehot * keep[..., None])
    combine = jnp.einsum('tke,tkc->tec', onehot,
                         cap_onehot * gate_vals[..., None])

    # Aux: Switch load-balance loss + router z-loss.
    frac_tokens = onehot.sum(1).mean(0)                  # [E]
    frac_probs = probs.mean(0)                           # [E]
    lb_loss = E * jnp.sum(frac_tokens * frac_probs)
    z_loss = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
    return dispatch, combine, (lb_loss, z_loss)


def _moe_mlp(config: MoEConfig, h: jnp.ndarray, layer: Params
             ) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    """Routed expert MLP. h: [b, s, d]."""
    b, s, d = h.shape
    T = b * s
    E, K = config.n_experts, config.experts_per_token
    capacity = max(1, int(config.capacity_factor * T * K / E))
    flat = h.reshape(T, d)
    dispatch, combine, aux = _route(config, flat, layer['router'],
                                    capacity)
    dtype = flat.dtype
    # All-to-all happens HERE under an ep-sharded mesh: dispatch is
    # token-sharded, expert buffers are ep-sharded — XLA inserts it.
    xs = jnp.einsum('tec,td->ecd', dispatch.astype(dtype), flat)
    gate = jax.nn.silu(jnp.einsum('ecd,edf->ecf', xs, layer['w_gate']))
    up = jnp.einsum('ecd,edf->ecf', xs, layer['w_up'])
    out = jnp.einsum('ecf,efd->ecd', gate * up, layer['w_down'])
    y = jnp.einsum('tec,ecd->td', combine.astype(dtype), out)
    return y.reshape(b, s, d), aux


def _layer(config: MoEConfig, x: jnp.ndarray, layer: Params,
           cos: jnp.ndarray, sin: jnp.ndarray
           ) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    x, _, _ = llama.attention_block(config.as_llama(), x, layer, cos,
                                    sin, None)
    h = norms.rms_norm(x, layer['mlp_norm'], config.norm_eps)
    y, aux = _moe_mlp(config, h, layer)
    return x + y, aux


def forward(config: MoEConfig, params: Params, tokens: jnp.ndarray
            ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """tokens [b, s] -> (logits [b, s, vocab] fp32, aux losses)."""
    x = params['embed'][tokens]
    cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                         config.max_seq_len,
                                         config.rope_theta)

    def body(carry, layer):
        fn = _layer
        if config.remat:
            fn = jax.checkpoint(_layer, static_argnums=(0,))
        x, aux = fn(config, carry, layer, cos, sin)
        return x, aux

    x, (lb, z) = jax.lax.scan(body, x, params['layers'])
    x = norms.rms_norm(x, params['final_norm'], config.norm_eps)
    logits = (x @ params['lm_head']).astype(jnp.float32)
    return logits, {'load_balance_loss': jnp.mean(lb),
                    'router_z_loss': jnp.mean(z)}


def loss_fn(config: MoEConfig, params: Params, tokens: jnp.ndarray,
            targets: jnp.ndarray, *, lb_coef: float = 0.01,
            z_coef: float = 1e-3) -> Tuple[jnp.ndarray, Dict[str, Any]]:
    logits, aux = forward(config, params, tokens)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    ce = jnp.mean(nll)
    total = (ce + lb_coef * aux['load_balance_loss']
             + z_coef * aux['router_z_loss'])
    return total, {'ce_loss': ce, **aux}


def param_specs(pp_axis: Optional[str] = None):
    """PartitionSpecs for MoE params: experts over ``ep``, megatron tp on
    expert hidden dim, fsdp on model dims (compose with parallel/sharding
    conventions)."""
    from jax.sharding import PartitionSpec as P
    lead = (pp_axis,) if pp_axis else (None,)
    return {
        'embed': P('tp', 'fsdp'),
        'layers': {
            'attn_norm': P(*lead, None),
            'wq': P(*lead, 'fsdp', 'tp'),
            'wk': P(*lead, 'fsdp', 'tp'),
            'wv': P(*lead, 'fsdp', 'tp'),
            'wo': P(*lead, 'tp', 'fsdp'),
            'mlp_norm': P(*lead, None),
            'router': P(*lead, 'fsdp', None),
            'w_gate': P(*lead, 'ep', 'fsdp', 'tp'),
            'w_up': P(*lead, 'ep', 'fsdp', 'tp'),
            'w_down': P(*lead, 'ep', 'tp', 'fsdp'),
        },
        'final_norm': P(None),
        'lm_head': P('fsdp', 'tp'),
    }
