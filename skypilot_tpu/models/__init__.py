"""Model zoo: Llama-family transformer (flagship) + ResNet example."""
