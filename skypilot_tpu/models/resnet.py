"""ResNet (v1.5) in pure JAX — the vision workload for DDP baselines.

BASELINE.md config #2 replaces the reference's
``examples/resnet_distributed_torch.yaml`` (torchrun DDP) with a
TPU-first equivalent. Design notes:

- Pure-JAX pytree params like the other model families (no flax module
  state to thread through pjit).
- **GroupNorm instead of BatchNorm**: no running statistics means the
  model stays a pure function — no cross-replica stat sync, no
  train/eval mode flag — and GN matches BN accuracy at ResNet scale.
- NHWC layout + lax.conv_general_dilated: the layout XLA:TPU prefers
  (channels minor → MXU-friendly im2col).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (2, 2, 2, 2)     # resnet18
    num_classes: int = 1000
    width: int = 64
    groups: int = 32                                # groupnorm groups
    dtype: str = 'bfloat16'

    @staticmethod
    def resnet18(**kw) -> 'ResNetConfig':
        return ResNetConfig(**kw)

    @staticmethod
    def resnet50(**kw) -> 'ResNetConfig':
        base = dict(stage_sizes=(3, 4, 6, 3))
        base.update(kw)
        return ResNetConfig(**base)

    @staticmethod
    def tiny(**kw) -> 'ResNetConfig':
        base = dict(stage_sizes=(1, 1), num_classes=10, width=8,
                    groups=4, dtype='float32')
        base.update(kw)
        return ResNetConfig(**base)


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    w = jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)
    return (w * (2.0 / fan_in) ** 0.5).astype(dtype)


def init_params(config: ResNetConfig, key: jax.Array) -> Params:
    dtype = jnp.dtype(config.dtype)
    keys = iter(jax.random.split(key, 256))
    w = config.width
    params: Params = {
        'stem': _conv_init(next(keys), 7, 7, 3, w, dtype),
        'stem_gn': {'scale': jnp.ones((w,), dtype),
                    'bias': jnp.zeros((w,), dtype)},
        'stages': [],
    }
    cin = w
    for i, blocks in enumerate(config.stage_sizes):
        cout = w * (2 ** i)
        stage: List[Dict[str, Any]] = []
        for b in range(blocks):
            stride = 2 if (b == 0 and i > 0) else 1
            # stride is derived from block position in forward(), never a
            # pytree leaf (int leaves break grad/tree_map).
            block = {
                'conv1': _conv_init(next(keys), 3, 3, cin, cout, dtype),
                'gn1': {'scale': jnp.ones((cout,), dtype),
                        'bias': jnp.zeros((cout,), dtype)},
                'conv2': _conv_init(next(keys), 3, 3, cout, cout, dtype),
                'gn2': {'scale': jnp.ones((cout,), dtype),
                        'bias': jnp.zeros((cout,), dtype)},
            }
            if stride != 1 or cin != cout:
                block['proj'] = _conv_init(next(keys), 1, 1, cin, cout,
                                           dtype)
            stage.append(block)
            cin = cout
        params['stages'].append(stage)
    params['head'] = (jax.random.normal(
        next(keys), (cin, config.num_classes), jnp.float32)
        * cin ** -0.5).astype(dtype)
    return params


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding='SAME',
        dimension_numbers=('NHWC', 'HWIO', 'NHWC'))


def _group_norm(x, gn, groups, eps=1e-5):
    n, h, w, c = x.shape
    g = min(groups, c)
    xf = x.astype(jnp.float32).reshape(n, h, w, g, c // g)
    mean = xf.mean(axis=(1, 2, 4), keepdims=True)
    var = xf.var(axis=(1, 2, 4), keepdims=True)
    xf = (xf - mean) * jax.lax.rsqrt(var + eps)
    xf = xf.reshape(n, h, w, c)
    return (xf * gn['scale'].astype(jnp.float32)
            + gn['bias'].astype(jnp.float32)).astype(x.dtype)


def forward(config: ResNetConfig, params: Params,
            images: jnp.ndarray) -> jnp.ndarray:
    """images [n, h, w, 3] -> logits [n, classes] (fp32)."""
    gn = functools.partial(_group_norm, groups=config.groups)
    x = images.astype(jnp.dtype(config.dtype))
    x = _conv(x, params['stem'], stride=2)
    x = jax.nn.relu(gn(x, params['stem_gn']))
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), 'SAME')
    for i, stage in enumerate(params['stages']):
        for b, block in enumerate(stage):
            stride = 2 if (b == 0 and i > 0) else 1
            h = jax.nn.relu(gn(_conv(x, block['conv1'], stride),
                               block['gn1']))
            h = gn(_conv(h, block['conv2']), block['gn2'])
            shortcut = (_conv(x, block['proj'], stride)
                        if 'proj' in block else x)
            x = jax.nn.relu(shortcut + h)
    x = x.mean(axis=(1, 2))                        # global avg pool
    return (x @ params['head']).astype(jnp.float32)


def loss_fn(config: ResNetConfig, params: Params, images: jnp.ndarray,
            labels: jnp.ndarray) -> jnp.ndarray:
    logits = forward(config, params, images)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None],
                                         axis=-1))
