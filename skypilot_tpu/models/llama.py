"""Llama-family decoder-only transformer, pure JAX, scan-over-layers.

The framework's flagship model (BASELINE.md: Llama-3-8B finetune is the
north-star workload). Design choices for TPU/XLA:

- **Params are a pytree of stacked arrays** ([n_layers, ...] leading axis)
  consumed by ``lax.scan`` — one layer gets compiled once, not n_layers
  times, and remat applies per scan step.
- **bf16 params/activations, fp32 softmax/norm internals** — MXU-native.
- GQA (n_kv_heads < n_heads), SwiGLU MLP, RMSNorm, RoPE — Llama-3
  architecture.
- Attention dispatches to the Pallas flash kernel on TPU
  (``ops/attention.py``) and dense elsewhere.

Sharding of these params is defined in ``parallel/sharding.py`` (the model
is sharding-agnostic; `jit` + NamedSharding do the work).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.ad_checkpoint
import jax.numpy as jnp

from skypilot_tpu.ops import attention as attention_lib
from skypilot_tpu.ops import norms
from skypilot_tpu.ops import quant as quant_lib
from skypilot_tpu.ops import rope as rope_lib

Params = Dict[str, Any]

# Tree skeleton of one stacked layer group (leaves are placeholders) —
# lets sharding/pipeline code tree_map PartitionSpecs over the layer dict
# without materializing params.
LLAMA_LAYER_TREE: Dict[str, int] = {
    'attn_norm': 0, 'wq': 0, 'wk': 0, 'wv': 0, 'wo': 0,
    'mlp_norm': 0, 'w_gate': 0, 'w_up': 0, 'w_down': 0,
}


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14_336
    max_seq_len: int = 8192
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    dtype: str = 'bfloat16'
    attention_impl: str = 'auto'    # 'auto' | 'flash' | 'dense'
    # Flash-attention tile sizes (None → ops/attention defaults). Tuned
    # per chip generation; bench.py sweeps these on the real device.
    attn_block_q: Optional[int] = None
    attn_block_k: Optional[int] = None
    remat: bool = True              # rematerialize each layer in backward
    # 'full' (default): recompute everything — minimum memory, and what
    # every pre-existing config was sized against. 'dots' saves matmul
    # outputs and recomputes only elementwise ops (measured worse on the
    # v5e bench: too much saved, HBM pressure). 'save_attn' saves ONLY
    # the attention outputs — the flash kernel is the priciest recompute
    # while its output is a tiny [b, s, d]; +1.5% tok/s at seq 8192,
    # noise-level at 2048.
    remat_policy: str = 'full'      # 'full' | 'dots' | 'save_attn'
    # Vocab-chunked cross-entropy (ops/cross_entropy.py). None = dense
    # (XLA's fused log-softmax wins at 32k vocab — measured on v5e);
    # set for 100k+ vocabs where fp32 [b*s, V] logits (4.3 GB for
    # Llama-3's 128256 at b4 s2048) must never materialize.
    loss_vocab_chunks: Optional[int] = None
    # Fused Pallas cross-entropy (ops/cross_entropy.py
    # fused_cross_entropy): logits tiles live and die in VMEM — HBM
    # traffic drops to the matmul operands. Requires b*s and vocab
    # divisible by 512. Overrides loss_vocab_chunks when set.
    fused_loss: bool = False

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def num_params(self) -> int:
        d, f, v = self.dim, self.ffn_dim, self.vocab_size
        per_layer = (d * self.n_heads * self.head_dim            # wq
                     + 2 * d * self.n_kv_heads * self.head_dim   # wk, wv
                     + self.n_heads * self.head_dim * d          # wo
                     + 3 * d * f                                 # gate/up/down
                     + 2 * d)                                    # norms
        return self.n_layers * per_layer + 2 * v * d + d

    # ---- presets --------------------------------------------------------
    @staticmethod
    def llama3_8b(**kw) -> 'LlamaConfig':
        kw.setdefault('loss_vocab_chunks', 16)   # 128k vocab
        return LlamaConfig(**kw)

    @staticmethod
    def llama3_70b(**kw) -> 'LlamaConfig':
        kw.setdefault('loss_vocab_chunks', 16)   # 128k vocab
        return LlamaConfig(dim=8192, n_layers=80, n_heads=64, n_kv_heads=8,
                           ffn_dim=28_672, **kw)

    @staticmethod
    def bench_350m(**kw) -> 'LlamaConfig':
        """~350M params: fits one v5e chip with Adam states for bench."""
        base = dict(vocab_size=32_768, dim=1024, n_layers=16,
                    n_heads=16, n_kv_heads=8, ffn_dim=4096,
                    max_seq_len=2048)
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def bench_1b(**kw) -> 'LlamaConfig':
        """~1B params: the single-chip bench workload. Fills the v5e MXU
        far better than the 350M config (dim 1536 keeps matmuls wide
        enough); full remat + bf16 Adam moments fit it in 16 GiB HBM
        with seq 2048. Flash tiles 512x512: the round-3 on-chip sweep
        measured 0.578 MFU vs 0.520 at the generic 256x256 (bigger
        tiles amortize the VMEM pipeline; 1024 tiles regress — VMEM
        pressure), and seq-8192 batch-1 trains at 0.617 MFU without
        OOM (the backward kernel's O(s) memory claim, proven)."""
        base = dict(vocab_size=32_768, dim=1536, n_layers=24,
                    n_heads=12, n_kv_heads=12, ffn_dim=6144,
                    max_seq_len=2048, remat_policy='full',
                    attn_block_q=512, attn_block_k=512)
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def tiny(**kw) -> 'LlamaConfig':
        """Test-sized config (CPU-fast)."""
        base = dict(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                    n_kv_heads=2, ffn_dim=128, max_seq_len=128,
                    dtype='float32')
        base.update(kw)
        return LlamaConfig(**base)


# Checkpoint tag shared by attention_block's checkpoint_name and the
# 'save_attn' policy — save_only_these_names silently matches nothing if
# the strings drift, which would degrade to full remat with no error.
_ATTN_OUT_NAME = 'attn_out'


def init_params(config: LlamaConfig, key: jax.Array) -> Params:
    """Scaled-normal init, layers stacked on axis 0."""
    dtype = jnp.dtype(config.dtype)
    d, hd = config.dim, config.head_dim
    L = config.n_layers
    k_embed, k_layers, k_head = jax.random.split(key, 3)

    def normal(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(
            dtype)

    ks = jax.random.split(k_layers, 7)
    scale = d ** -0.5
    out_scale = scale / (2 * L) ** 0.5   # GPT-2-style residual scaling
    layers = {
        'attn_norm': jnp.ones((L, d), dtype),
        'wq': normal(ks[0], (L, d, config.n_heads * hd), scale),
        'wk': normal(ks[1], (L, d, config.n_kv_heads * hd), scale),
        'wv': normal(ks[2], (L, d, config.n_kv_heads * hd), scale),
        'wo': normal(ks[3], (L, config.n_heads * hd, d), out_scale),
        'mlp_norm': jnp.ones((L, d), dtype),
        'w_gate': normal(ks[4], (L, d, config.ffn_dim), scale),
        'w_up': normal(ks[5], (L, d, config.ffn_dim), scale),
        'w_down': normal(ks[6], (L, config.ffn_dim, d), out_scale),
    }
    return {
        'embed': normal(k_embed, (config.vocab_size, d), 1.0),
        'layers': layers,
        'final_norm': jnp.ones((d,), dtype),
        'lm_head': normal(k_head, (d, config.vocab_size), scale),
    }


def attention_block(config: LlamaConfig, x: jnp.ndarray, layer: Params,
                    cos: jnp.ndarray, sin: jnp.ndarray,
                    positions: Optional[jnp.ndarray]
                    ) -> tuple:
    """norm → QKV → RoPE → attention → residual. THE shared attention
    block — MoE layers and the inference prefill path reuse it so the
    attention math exists exactly once. Returns (x, k, v) with k/v
    post-RoPE [b, s, kv_heads, head_dim] (cache insertion needs them)."""
    b, s, d = x.shape
    hq, hkv, hd = config.n_heads, config.n_kv_heads, config.head_dim

    h = norms.rms_norm(x, layer['attn_norm'], config.norm_eps)
    # qdot: plain `@` for training params, dequantizing matmul for the
    # int8 serving path (ops/quant.py) — one attention implementation.
    q = quant_lib.qdot(h, layer['wq']).reshape(b, s, hq, hd)
    k = quant_lib.qdot(h, layer['wk']).reshape(b, s, hkv, hd)
    v = quant_lib.qdot(h, layer['wv']).reshape(b, s, hkv, hd)
    q = rope_lib.apply_rope(q, cos, sin, positions)
    k = rope_lib.apply_rope(k, cos, sin, positions)
    # [b, s, h, hd] -> [b, h, s, hd] for the attention kernels.
    att = attention_lib.attention(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), causal=True,
        impl=config.attention_impl,
        block_q=config.attn_block_q, block_k=config.attn_block_k)
    # Named for selective remat ('save_attn' policy): saving just this
    # tensor (b*s*d, tiny vs the O(s^2)-work flash kernel that produced
    # it) lets the backward skip re-running attention entirely.
    att = jax.ad_checkpoint.checkpoint_name(att, _ATTN_OUT_NAME)
    att = att.transpose(0, 2, 1, 3).reshape(b, s, hq * hd)
    return x + quant_lib.qdot(att, layer['wo']), k, v


def mlp_block(config: LlamaConfig, x: jnp.ndarray,
              layer: Params) -> jnp.ndarray:
    """norm -> SwiGLU -> residual; shared with the inference paths so
    the MLP math (and its quantized form) exists exactly once."""
    h = norms.rms_norm(x, layer['mlp_norm'], config.norm_eps)
    gate = jax.nn.silu(quant_lib.qdot(h, layer['w_gate']))
    return x + quant_lib.qdot(gate * quant_lib.qdot(h, layer['w_up']),
                              layer['w_down'])


def _layer(config: LlamaConfig, x: jnp.ndarray, layer: Params,
           cos: jnp.ndarray, sin: jnp.ndarray,
           positions: Optional[jnp.ndarray]) -> jnp.ndarray:
    x, _, _ = attention_block(config, x, layer, cos, sin, positions)
    return mlp_block(config, x, layer)


def backbone(config: LlamaConfig, params: Params, tokens: jnp.ndarray,
             positions: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """tokens [b, s] int32 -> final-norm hidden states [b, s, d]."""
    x = params['embed'][tokens]
    cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                         config.max_seq_len,
                                         config.rope_theta)

    def body(carry, layer):
        fn = _layer
        if config.remat:
            if config.remat_policy == 'dots':
                policy = (jax.checkpoint_policies
                          .dots_with_no_batch_dims_saveable)
            elif config.remat_policy == 'save_attn':
                # Full remat EXCEPT the attention outputs: the flash
                # kernel is the most expensive recompute per layer while
                # its output is only [b, s, d] — the best FLOPs-per-byte
                # trade on the menu.
                policy = jax.checkpoint_policies.save_only_these_names(
                    _ATTN_OUT_NAME)
            elif config.remat_policy == 'full':
                policy = None
            else:
                # A typo must not silently bench as full remat.
                raise ValueError(
                    f'Unknown remat_policy {config.remat_policy!r}; '
                    f"expected 'full', 'dots' or 'save_attn'")
            fn = jax.checkpoint(_layer, static_argnums=(0,),
                                policy=policy)
        return fn(config, carry, layer, cos, sin, positions), None

    x, _ = jax.lax.scan(body, x, params['layers'])
    return norms.rms_norm(x, params['final_norm'], config.norm_eps)


def forward(config: LlamaConfig, params: Params, tokens: jnp.ndarray,
            positions: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """tokens [b, s] int32 -> logits [b, s, vocab] (fp32)."""
    x = backbone(config, params, tokens, positions)
    return quant_lib.qdot(x, params['lm_head']).astype(jnp.float32)


def loss_fn(config: LlamaConfig, params: Params, tokens: jnp.ndarray,
            targets: jnp.ndarray,
            mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Causal LM cross-entropy.

    Dense fp32 log-softmax by default (XLA fuses it well at 32k vocab);
    ``config.loss_vocab_chunks`` switches to the vocab-chunked
    custom-VJP path (ops/cross_entropy.py) that never materializes the
    fp32 [b*s, vocab] logits — required headroom at 100k+ vocabs.
    """
    if config.fused_loss:
        from skypilot_tpu.ops import cross_entropy as ce
        b, s = tokens.shape
        x = backbone(config, params, tokens)
        nll = ce.fused_cross_entropy(
            x.reshape(b * s, config.dim), params['lm_head'],
            targets.reshape(b * s).astype(jnp.int32)).reshape(b, s)
    elif config.loss_vocab_chunks:
        from skypilot_tpu.ops import cross_entropy as ce
        b, s = tokens.shape
        x = backbone(config, params, tokens)
        nll = ce.chunked_cross_entropy(
            x.reshape(b * s, config.dim), params['lm_head'],
            targets.reshape(b * s).astype(jnp.int32),
            config.loss_vocab_chunks).reshape(b, s)
    else:
        logits = forward(config, params, tokens)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None],
                                   axis=-1)[..., 0]
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)
    return jnp.mean(nll)


def flops_per_token(config: LlamaConfig) -> float:
    """Training FLOPs/token ~ 6 * params + attention quadratic term
    (2*2*3*s*d per token at seq s, fwd+bwd)."""
    base = 6.0 * config.num_params
    attn = 12.0 * config.n_layers * config.max_seq_len * config.head_dim \
        * config.n_heads
    return base + attn
