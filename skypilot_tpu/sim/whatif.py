"""What-if API: headless twin runs over recorded traces
(docs/simulation.md "What-if simulation").

``sky-tpu simulate --spec service.yaml --trace trace.jsonl`` builds a
Scenario two ways:

- a **literal trace** (loadgen ``kind: trace``) replays its arrivals
  verbatim through the twin (``Scenario.trace_events``);
- an **exported incident** (``kind: incident``) re-synthesizes
  full-duration traffic from the reconstructed per-tenant arrival
  process and re-injects the inferred fault timeline with inter-event
  spacing preserved — the recorded ring window is far too short to
  sustain a burn-rate alert on its own.

:func:`run_simulate` reports the planner's view: SLO burn per tier,
shed/resume/quarantine counts, autoscaler churn, metered cost (the
fleet cost plane's billing totals), and the decision-log digest that
makes two runs comparable at a glance. :func:`run_sweep` varies ONE
scenario knob across values at a fixed seed and ranks the outcomes —
every row backed by a byte-identical-per-seed decision log, so a
ranking is evidence, not anecdote.

``python -m skypilot_tpu.sim.whatif`` is the ``make simulate-smoke``
entry.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu.sim import scenarios as scenarios_lib
from skypilot_tpu.sim import tracefmt

# Virtual warm-up before the incident timeline starts: the burn
# windows need baseline good-traffic history, and the fleet needs to
# finish provisioning (Scenario.initial_delay_s) first.
TRAFFIC_START_S = 420.0
_FAULT_LEAD_S = 300.0   # good traffic before the first fault lands
_TAIL_S = 600.0         # replay continues past the recorded span


def incident_scenario(trace: tracefmt.Trace,
                      **overrides: Any) -> scenarios_lib.Scenario:
    """Incident trace → replayable Scenario. Reconstruction, not
    literal replay: traffic synthesizes from the recorded arrival
    process for the whole replay, faults/kills land on an anchored
    timeline with their recorded spacing, and the provisioning delay
    stretches to ``hold_outage_s`` so the outage persists at least as
    long past the first fault as it did in production."""
    meta = trace.meta
    kind = trace.kind
    faults = [dict(f) for f in trace.faults]
    kills = [dict(k) for k in trace.kills]
    rel_ts = [float(f.get('t') or 0.0) for f in faults] + [
        float(k.get('t') or 0.0) for k in kills]
    first_rel = min(rel_ts) if rel_ts else 0.0
    hold = float(meta.get('hold_outage_s') or 0.0)
    ready_offsets = [float(o) for o in
                     (meta.get('ready_offsets_s') or [])]
    if ready_offsets:
        # The dump caught replicas becoming ready AROUND the recorded
        # arrivals (traffic racing provisioning — the cold-start-crush
        # shape). Recreate that ordering: provisioning completes the
        # same offsets after traffic start that the ring recorded.
        lo = TRAFFIC_START_S + max(0.0, min(ready_offsets))
        provision = (round(lo, 6),
                     round(max(lo + 30.0,
                               TRAFFIC_START_S + max(ready_offsets)),
                           6))
    elif hold > 0:
        # No ready edges in the ring (fleet was up long before the
        # window): stretch provisioning so the outage persists at
        # least as long past the first fault as it did in production.
        provision = (round(hold, 6), round(hold + 60.0, 6))
    else:
        provision = None
    # The fault timeline must land AFTER the initial fleet is ready
    # (provisioning upper bound) or the faults kill replicas that are
    # still provisioning and the replay degenerates into one long
    # no-replica outage that can't reproduce TTFT/shed transitions.
    ready_hi = provision[1] if provision else 90.0
    anchor = max(TRAFFIC_START_S + _FAULT_LEAD_S, ready_hi + 120.0)

    def at(rel_t: float) -> float:
        return round(anchor + (float(rel_t) - first_rel), 6)

    span = (max(rel_ts) - first_rel) if rel_ts else 0.0
    duration = anchor + span + max(hold, 0.0) + _TAIL_S
    fault_objs = [
        scenarios_lib.Fault(**{**f, 't': at(f.pop('t', 0.0))})
        for f in faults]
    kill_objs = [
        scenarios_lib.KillSpec(target=str(k.get('target')
                                          or 'controller'),
                               at_t=at(k.get('t', 0.0)))
        for k in kills]
    tenants: Dict[str, Dict[str, Any]]
    trace_events: Optional[List[Any]] = None
    if kind == 'incident':
        tenants = {name: dict(spec) for name, spec in
                   (meta.get('tenants') or {}).items()}
        if not tenants:
            # Pure fleet dump (zero request events): a minimal probe
            # load keeps the replay's SLIs non-vacuous.
            tenants = {'synthetic': {'rps': 0.5, 'prompt_mean': 16,
                                     'prompt_max': 32, 'max_new': 8}}
    else:
        tenants = {}
        trace_events = list(trace.events)
        if trace.events:
            duration = max(duration, anchor + max(
                ev.t for ev in trace.events) + _TAIL_S)
    fields: Dict[str, Any] = {
        'name': f"incident_{(meta.get('trigger') or 'trace')}",
        'replicas': max(1, int(meta.get('replicas') or 1)),
        'use_spot': True,
        'duration_s': duration,
        'traffic_start_s': TRAFFIC_START_S,
        'tenants': tenants,
        'trace_events': trace_events,
        'faults': fault_objs,
        'kills': kill_objs,
        'slo': list(meta.get('slo') or []) or None,
    }
    if meta.get('lb_policy'):
        fields['lb_policy'] = str(meta['lb_policy'])
    if meta.get('sync_interval_s'):
        fields['lb_sync_s'] = float(meta['sync_interval_s'])
    if any(f.kind == 'sdc' for f in fault_objs):
        fields['probe_interval_s'] = float(
            meta.get('probe_interval_s') or 20.0)
    if provision is not None:
        fields['provision_delay_s'] = provision
    fields.update(overrides)
    return scenarios_lib.Scenario(**fields)


def scenario_from_spec(spec: Dict[str, Any],
                       trace: tracefmt.Trace) -> scenarios_lib.Scenario:
    """Service-spec + trace → Scenario: the service.yaml's
    ``replica_policy`` / ``load_balancing_policy`` / ``slo`` sections
    override what the trace carries, and an optional ``sim:`` section
    sets twin-only knobs (slots, scheduler, perf_scale, ...) that no
    spec or dump records."""
    pol = dict(spec.get('replica_policy') or {})
    overrides: Dict[str, Any] = {}
    if pol.get('min_replicas') is not None:
        overrides['replicas'] = max(1, int(pol['min_replicas']))
        overrides['min_replicas'] = int(pol['min_replicas'])
    if pol.get('max_replicas') is not None:
        overrides['max_replicas'] = int(pol['max_replicas'])
    if pol.get('queue_length_threshold') is not None:
        overrides['queue_length_threshold'] = float(
            pol['queue_length_threshold'])
    if pol.get('upscale_delay_seconds') is not None:
        overrides['upscale_delay_s'] = float(
            pol['upscale_delay_seconds'])
    if pol.get('downscale_delay_seconds') is not None:
        overrides['downscale_delay_s'] = float(
            pol['downscale_delay_seconds'])
    if spec.get('load_balancing_policy'):
        overrides['lb_policy'] = str(spec['load_balancing_policy'])
    if spec.get('slo') is not None:
        overrides['slo'] = list(spec['slo'])
    sim = dict(spec.get('sim') or {})
    for key in ('slots', 'scheduler', 'perf_scale', 'lb_sync_s',
                'controller_tick_s', 'max_queue_requests',
                'probe_interval_s', 'kv_page', 'prefill_fraction'):
        if sim.get(key) is not None:
            overrides[key] = sim[key]
    return incident_scenario(trace, **overrides)


def run_simulate(scenario: scenarios_lib.Scenario,
                 seed: int = 0) -> Dict[str, Any]:
    """One headless twin run → the planner's summary. Deterministic
    per (scenario, seed); ``decision_log_sha256`` is the evidence two
    runs are byte-identical."""
    from skypilot_tpu.sim import twin as twin_lib
    report = twin_lib.DigitalTwin(scenario, seed=seed).run()
    page_firing: List[str] = []
    tiers: Dict[str, int] = {}
    for a in report.slo_alerts:
        if a['state'] == 'firing':
            tiers[a['tier']] = tiers.get(a['tier'], 0) + 1
            if (a['tier'] == 'page'
                    and a['objective'] not in page_firing):
                page_firing.append(a['objective'])
    targets = report.scale_targets
    churn = sum(1 for i in range(1, len(targets))
                if targets[i] != targets[i - 1])
    slo_gauges = report.lb_metrics.get('slo') or {}
    return {
        'scenario': scenario.name, 'seed': seed,
        'requests': len(report.records),
        'completed': report.completed,
        'shed': report.shed,
        'client_errors': len(report.client_errors),
        'resumed': report.resumed_requests,
        'quarantines': sum(1 for d in report.decisions
                           if d['kind'] == 'quarantine'),
        'slo': {
            'page_firing': page_firing,
            'alerts_by_tier': tiers,
            'burn': {obj: {'burn_short': row.get('burn_short'),
                           'budget_remaining':
                               row.get('error_budget_remaining')}
                     for obj, row in sorted(slo_gauges.items())},
        },
        'autoscaler': {'targets': targets, 'churn': churn,
                       'launches': report.launches,
                       'drains': report.drains},
        'cost': report.cost,
        'ttft_p50_s': report.lb_metrics.get('ttft_p50_s'),
        'ttft_p99_s': report.lb_metrics.get('ttft_p99_s'),
        'decision_log_sha256': hashlib.sha256(
            report.decision_log_jsonl().encode()).hexdigest(),
    }


def parse_sweep(arg: str) -> Tuple[str, List[str]]:
    """``key=a,b,c`` → (key, raw values); loud on anything else."""
    if '=' not in arg:
        raise ValueError(
            f'--sweep wants key=v1,v2,... (got {arg!r})')
    key, _, raw = arg.partition('=')
    key = key.strip()
    values = [v.strip() for v in raw.split(',') if v.strip()]
    if not key or not values:
        raise ValueError(
            f'--sweep wants key=v1,v2,... (got {arg!r})')
    fields = {f.name for f in dataclasses.fields(
        scenarios_lib.Scenario)}
    if key not in fields:
        raise ValueError(f'unknown Scenario knob {key!r} '
                         f'(knows {sorted(fields)})')
    return key, values


def _coerce(scenario: scenarios_lib.Scenario, key: str,
            raw: str) -> Any:
    """Coerce a sweep value to the knob's current type (the field
    default decides: int stays int, float float, bool bool)."""
    cur = getattr(scenario, key)
    if isinstance(cur, bool):
        return raw.lower() in ('1', 'true', 'yes', 'on')
    if isinstance(cur, int):
        return int(raw)
    if isinstance(cur, float):
        return float(raw)
    if cur is None:
        try:
            return json.loads(raw)
        except ValueError:
            return raw
    return type(cur)(raw) if not isinstance(cur, str) else raw


def run_sweep(scenario: scenarios_lib.Scenario, key: str,
              raw_values: List[str], seed: int = 0
              ) -> List[Dict[str, Any]]:
    """One-knob sweep at a fixed seed: every run summarized, rows
    ranked best-first by (client errors, pages fired, sheds, cost,
    TTFT p99). The per-row decision-log digest is the byte-identity
    evidence the ranking rests on."""
    rows = []
    for raw in raw_values:
        value = _coerce(scenario, key, raw)
        sc = dataclasses.replace(
            scenario, name=f'{scenario.name}@{key}={raw}',
            **{key: value})
        summary = run_simulate(sc, seed=seed)
        summary['sweep'] = {'key': key, 'value': value}
        rows.append(summary)
    rows.sort(key=lambda r: (
        r['client_errors'], len(r['slo']['page_firing']), r['shed'],
        float((r['cost'] or {}).get('total_cost') or 0.0),
        float(r['ttft_p99_s'] or 0.0)))
    return rows


def sweep_table(rows: List[Dict[str, Any]]) -> str:
    """The ranked table ``sky-tpu simulate --sweep`` prints."""
    header = (f"{'rank':<5}{'value':<14}{'errors':<8}{'pages':<7}"
              f"{'shed':<7}{'cost':<10}{'ttft_p99':<10}"
              f"{'decision_log':<14}")
    lines = [header, '-' * len(header)]
    for i, r in enumerate(rows, start=1):
        cost = (r['cost'] or {}).get('total_cost')
        ttft = r['ttft_p99_s']
        lines.append(
            f"{i:<5}{str(r['sweep']['value']):<14}"
            f"{r['client_errors']:<8}"
            f"{len(r['slo']['page_firing']):<7}{r['shed']:<7}"
            f"{'' if cost is None else round(cost, 2):<10}"
            f"{'' if ttft is None else round(ttft, 4):<10}"
            f"{r['decision_log_sha256'][:12]:<14}")
    return '\n'.join(lines)


def _smoke() -> int:
    """``make simulate-smoke``: a small literal-trace simulate run +
    a two-value sweep, asserting per-seed determinism of the summary
    digest."""
    import tempfile

    from tests.load_tests import loadgen

    events = loadgen.synthesize(
        7, {'web': {'rps': 2.0, 'prompt_mean': 24, 'prompt_max': 64,
                    'max_new': 8, 'until': 240.0}},
        duration_s=240.0)
    with tempfile.TemporaryDirectory() as tmp:
        path = f'{tmp}/trace.jsonl'
        loadgen.save_trace(events, path)
        trace = tracefmt.load(path)
    sc = incident_scenario(trace, replicas=2, duration_s=1500.0)
    first = run_simulate(sc, seed=7)
    second = run_simulate(sc, seed=7)
    assert first == second, 'same-seed simulate summaries diverged'
    assert first['requests'] == len(events)
    assert first['client_errors'] == 0, first
    rows = run_sweep(sc, 'slots', ['8', '2'], seed=7)
    assert len(rows) == 2
    assert {r['sweep']['value'] for r in rows} == {8, 2}
    print(sweep_table(rows))
    print(json.dumps({'simulate_smoke': 'ok',
                      'requests': first['requests'],
                      'digest': first['decision_log_sha256'][:12]},
                     indent=2, sort_keys=True))
    return 0


if __name__ == '__main__':
    raise SystemExit(_smoke())
