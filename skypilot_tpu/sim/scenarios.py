"""Scenario library for the digital twin (docs/robustness.md).

A :class:`Scenario` is everything one replay needs: the fleet shape
(service spec the REAL controller consumes), the traffic (a seeded
``tests/load_tests/loadgen`` tenant spec, diurnal/flash envelopes
included), the fault schedule, and the control-loop cadences. The
factories below are the shipped catalog; a new scenario is one
function returning a ``Scenario`` — see "How to add a scenario" in
docs/robustness.md.

Cadence note: fleet-scale replays run the controller/LB loops at
coarser virtual intervals than the 1s production defaults — exactly
what a 1000-replica deployment does in practice (and what the
env-tunable ``SKY_TPU_LB_SYNC_INTERVAL_S`` exists for). Gates assert
on outcomes (zero client errors, convergence, starvation bounds),
which do not depend on the cadence being 1s.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple


@dataclasses.dataclass
class Fault:
    """One scheduled fault. ``t`` is virtual seconds from replay
    start. Kinds: ``reclaim_storm`` (``frac`` of the live spot fleet;
    ``notice_frac`` of victims get a ``notice_lead_s`` advance warning
    — the drain path — the rest die hard — the resume path),
    ``zone_outage`` (every slice in ``zone``), ``brownout``
    (``frac`` of the fleet runs ``factor``x slower for
    ``duration_s``), ``wedge`` (``count`` replicas answer probes but
    fail every request for ``duration_s`` — breaker food)."""

    t: float
    kind: str
    frac: float = 0.2
    notice_frac: float = 0.7
    notice_lead_s: float = 45.0
    zone: str = ''
    duration_s: float = 120.0
    factor: float = 8.0
    count: int = 1
    # ``sdc`` faults only: ``token_flip`` serves silently wrong
    # tokens on short prompts (golden-probe food); ``nan`` trips the
    # modeled on-device sentinel (docs/robustness.md "Data
    # integrity").
    flavor: str = 'token_flip'


@dataclasses.dataclass
class KillSpec:
    """A virtual-time process kill of one control-plane component
    (docs/robustness.md "Crash safety"). ``target`` is ``'controller'``
    or ``'lb'``; the kill lands either at virtual time ``at_t`` or the
    instant decision-log entry ``at_seq`` is appended (the
    kill-anywhere sweep's boundary injection — a kill armed at a
    cloud-facing decision tears the operation at its real crash
    window via the VirtualCloud crash gate). The component restarts
    ``restart_delay_s`` later: a fresh ``ServeController`` whose
    startup reconciliation replays the journal (run twice — the gate
    asserts the second pass is a no-op), or a fresh LB rebuilt from
    the state DB, with severed client streams retried against it
    carrying ``resume_from`` (the PR 5 splice contract, client side)."""

    target: str                         # 'controller' | 'lb'
    at_t: Optional[float] = None
    at_seq: Optional[int] = None
    restart_delay_s: float = 30.0


@dataclasses.dataclass
class Scenario:
    name: str
    # Fleet shape (feeds the REAL ServiceSpec/ReplicaPolicy).
    replicas: int = 8
    max_replicas: Optional[int] = None
    # Floor override: None keeps the historical behavior (floor ==
    # ``replicas``); 0 + wake_on_request is the scale-to-zero shape.
    min_replicas: Optional[int] = None
    queue_length_threshold: Optional[float] = None
    upscale_delay_s: float = 60.0
    downscale_delay_s: float = 600.0
    use_spot: bool = True
    lb_policy: str = 'round_robin'
    # Cost plane (docs/cost.md): when ``cost_optimized`` the REAL
    # FleetPlacer runs inside the twin's controller against a
    # FleetCatalog built from ``market`` (per-(region, zone)
    # {'ondemand', 'spot', 'reclaim_per_hour'} — prices per
    # replica-hour, reclaims per slice-hour). The same market dict
    # drives VirtualCloud's pre-sampled Poisson reclaim streams and
    # its billing meters, market or not cost-optimized.
    cost_optimized: bool = False
    market: Optional[Dict[Tuple[str, str], Dict[str, float]]] = None
    relaunch_overhead_s: float = 180.0
    # Scale-to-zero (docs/cost.md "Scale to zero").
    wake_on_request: bool = False
    max_parked_requests: int = 32
    # Traffic (loadgen tenant spec; envelope shapes welcome).
    tenants: Dict[str, Dict[str, Any]] = dataclasses.field(
        default_factory=dict)
    traffic_start_s: float = 420.0
    duration_s: float = 3600.0
    # Modeled replica shape (REAL scheduler inside).
    scheduler: str = 'fcfs'
    tenant_weights: Optional[Dict[str, float]] = None
    max_queue_requests: Optional[int] = 64
    max_queue_tokens: Optional[int] = None
    slots: int = 8
    perf_scale: float = 1.0
    bench_json: Optional[str] = None
    # Virtual cloud.
    provision_delay_s: Tuple[float, float] = (30.0, 90.0)
    zones: Optional[List[Tuple[str, str]]] = None
    # Control-loop cadences (virtual seconds).
    controller_tick_s: float = 15.0
    lb_sync_s: float = 5.0
    stats_flush_s: float = 10.0
    initial_delay_s: float = 300.0
    faults: List[Fault] = dataclasses.field(default_factory=list)
    # Process kills (crash scenarios embed one; the kill-anywhere
    # sweep injects its own per boundary).
    kills: List[KillSpec] = dataclasses.field(default_factory=list)
    # Service-level objectives (docs/observability.md "SLOs and
    # alerting"): flows through the REAL spec validation into the
    # service row, where the REAL LB's burn-rate evaluator loads it —
    # the alert-fidelity gates in tests/sim/test_slo_alerts.py arm
    # these. None = no objectives, the SLO layer stays inert.
    slo: Optional[List[Dict[str, Any]]] = None
    # Data-integrity plane (docs/robustness.md "Data integrity"):
    # a per-replica golden-probe cadence arms the REAL LB probe
    # scheduler against the sim oracle's golden fixture. None = probes
    # unarmed — every pre-existing scenario replays byte-identically.
    probe_interval_s: Optional[float] = None
    # Disaggregated prefill/decode (docs/serving.md "Disaggregated
    # prefill/decode"): ``kv_page`` > 0 arms the modeled KV prefix
    # tier — replicas index chained page hashes, the REAL
    # FleetPrefixIndex folds them at the LB, donor pulls ride the
    # VirtualCloud's transfer-latency curve. 0 keeps every
    # pre-existing scenario byte-identical. ``prefill_fraction``
    # carves that share of launches into dedicated prefill replicas
    # (role-steered by the LB, donors for the decode pool);
    # ``fleet_routing`` False is the owner-only baseline the hit-rate
    # gate compares against.
    kv_page: int = 0
    kv_bytes_per_token: int = 65536
    kv_link_gbps: float = 10.0
    kv_transfer_floor_s: float = 0.005
    # Idle TTL on a replica's indexed prefixes — the model of
    # decode-page-pressure eviction (a prefix nobody re-touches loses
    # its pages to the allocator). 0 = never expires.
    kv_ttl_s: float = 0.0
    prefill_fraction: float = 0.0
    fleet_routing: bool = True
    # Prefill budget override (tokens per virtual step); None keeps
    # the PerfModel default. Disagg scenarios lower it so warm-prefix
    # prefill is measurably cheaper than cold.
    prefill_tokens_per_step: Optional[float] = None
    # Recorded-trace override (docs/simulation.md): a list of
    # tracefmt.TraceEvent arrivals replayed VERBATIM (offsets from
    # traffic_start_s) instead of synthesizing from ``tenants`` —
    # how `sky-tpu simulate --trace` and literal-trace replays drive
    # the twin. None keeps the loadgen path.
    trace_events: Optional[List[Any]] = None


def reclaim_storm(*, replicas: int = 40, duration_s: float = 2400.0,
                  storm_frac: float = 0.25,
                  rps: float = 10.0) -> Scenario:
    """A quarter-fleet spot-reclaim storm mid-replay: half the victims
    get the advance notice (drain handoff), the rest die hard
    mid-stream (resume splice). Streams run long enough (32 tokens at
    a 2x-scaled ITL curve) that hard kills reliably land MID-stream —
    the resume gate must be non-vacuous. Gate: ZERO client-visible
    errors."""
    storm_t = duration_s * 0.5
    return Scenario(
        name='reclaim_storm', replicas=replicas, use_spot=True,
        duration_s=duration_s, perf_scale=2.0,
        tenants={'prod': {'rps': rps, 'prompt_mean': 48,
                          'prompt_max': 256, 'max_new': 32,
                          'until': duration_s * 0.75}},
        faults=[Fault(t=storm_t, kind='reclaim_storm',
                      frac=storm_frac, notice_frac=0.5)])


def incident_page_storm(*, replicas: int = 4,
                        duration_s: float = 1500.0,
                        rps: float = 16.0) -> Scenario:
    """The incident-replay seed scenario (docs/simulation.md): a
    3-of-4 reclaim storm under enough load that the surviving replica
    saturates and the ttft_p99 PAGE fires — which writes an
    ``slo_page`` fleet dump the converter exports. Every knob the
    flight recorder does NOT capture (slots, scheduler, perf model)
    stays at the Scenario DEFAULT, so the converter's reconstruction
    replays against the same capacity model that grew the dump."""
    storm_t = duration_s * 0.45
    return Scenario(
        name='incident_page_storm', replicas=replicas, use_spot=True,
        duration_s=duration_s,
        # Replacements stay out long enough for the 5m page window to
        # breach (the multi-window rule needs a sustained burn).
        provision_delay_s=(420.0, 480.0),
        tenants={'prod': {'rps': rps, 'prompt_mean': 48,
                          'prompt_max': 256, 'max_new': 32,
                          'shared_prefix_frac': 0.3,
                          'until': duration_s * 0.85}},
        slo=[{'metric': 'ttft_p99', 'threshold_s': 2.0,
              'target': 0.99},
             {'metric': 'itl_p99', 'threshold_s': 0.5,
              'target': 0.99},
             {'metric': 'availability', 'target': 0.999},
             {'metric': 'shed_rate', 'target': 0.99}],
        faults=[Fault(t=storm_t, kind='reclaim_storm', frac=0.75,
                      notice_frac=0.5)])


def flash_crowd(*, base_replicas: int = 2, max_replicas: int = 10,
                duration_s: float = 5400.0) -> Scenario:
    """A 15x flash crowd against the REAL QueueLengthAutoscaler: the
    crowd saturates the base fleet (slots x step-time make per-replica
    throughput ~2 rps), queue depth crosses the threshold, the target
    climbs with hysteresis, and drains back down after the crowd.
    Gate: scale-up happened, settled back, and the target moved in at
    most two directions (up, then down — no oscillation)."""
    flash_at = duration_s * 0.3
    return Scenario(
        name='flash_crowd', replicas=base_replicas,
        max_replicas=max_replicas, queue_length_threshold=6.0,
        upscale_delay_s=30.0, downscale_delay_s=240.0,
        duration_s=duration_s, slots=2, max_queue_requests=64,
        perf_scale=3.0, controller_tick_s=15.0,
        provision_delay_s=(20.0, 45.0),
        tenants={'web': {
            'rps': 1.0, 'prompt_mean': 24, 'prompt_max': 64,
            'max_new': 12, 'until': duration_s * 0.8,
            'envelope': {'kind': 'flash', 'at': flash_at,
                         'duration_s': 420.0, 'mult': 15.0}}})


def regional_failover(*, replicas: int = 12,
                      duration_s: float = 2400.0) -> Scenario:
    """A whole zone dies at once. Gates: the fleet relaunches to
    target, every relaunch lands OUTSIDE the dead zone (spot placer's
    blocked placements), clients ride through on retry/resume."""
    return Scenario(
        name='regional_failover', replicas=replicas,
        duration_s=duration_s,
        tenants={'prod': {'rps': 4.0, 'prompt_mean': 32,
                          'prompt_max': 96, 'max_new': 10,
                          'until': duration_s * 0.75}},
        faults=[Fault(t=duration_s * 0.5, kind='zone_outage',
                      zone='sim-r1-a')])


def slow_brownout(*, replicas: int = 8,
                  duration_s: float = 2400.0) -> Scenario:
    """A quarter of the fleet browns out (8x slower steps, probes
    still green). Gate: no client-visible errors — slow is not dead,
    and the breaker must NOT amputate replicas that still answer."""
    return Scenario(
        name='slow_brownout', replicas=replicas, duration_s=duration_s,
        lb_policy='least_load',
        tenants={'prod': {'rps': 5.0, 'prompt_mean': 24,
                          'prompt_max': 64, 'max_new': 8,
                          'until': duration_s * 0.75}},
        faults=[Fault(t=duration_s * 0.45, kind='brownout', frac=0.25,
                      duration_s=600.0, factor=8.0)])


def breaker_flap(*, replicas: int = 6,
                 duration_s: float = 2400.0) -> Scenario:
    """One replica wedges (probes green, every request fails) for two
    breaker cooldowns, then heals. Gates: the breaker OPENS (stops the
    bleeding), re-CLOSES after recovery, and no client ever sees the
    wedge (pre-stream failover)."""
    return Scenario(
        name='breaker_flap', replicas=replicas, duration_s=duration_s,
        tenants={'prod': {'rps': 6.0, 'prompt_mean': 16,
                          'prompt_max': 48, 'max_new': 8,
                          'until': duration_s * 0.75}},
        faults=[Fault(t=duration_s * 0.45, kind='wedge', count=1,
                      duration_s=300.0)])


def sdc_storm(*, replicas: int = 8,
              duration_s: float = 2400.0) -> Scenario:
    """Silent data corruption mid-fleet (docs/robustness.md "Data
    integrity"): one replica starts flipping tokens (silently wrong
    bytes, liveness probes green) and later another's logits go
    non-finite (the modeled on-device sentinel). Golden probes run
    every ``probe_interval_s`` against every READY replica. Gates:
    every poisoned replica QUARANTINED within three probe rounds and
    replaced by the autoscaler; every COMPLETED client stream
    bit-identical to a same-seed uncorrupted run (the quarantine cut
    + resume splice — non-vacuous: streams are long enough to be in
    flight at quarantine time); zero false quarantines.

    Tenant prompts are sized ≥ ``prompt_mean/2`` = 12 tokens — above
    the modeled corruptor's short-prompt reach (the 4-token golden
    probe is inside it), mirroring real SDC's address-dependence:
    the probe sees corruption tenants have not hit yet."""
    return Scenario(
        name='sdc_storm', replicas=replicas, duration_s=duration_s,
        perf_scale=2.0, probe_interval_s=20.0,
        tenants={'prod': {'rps': 4.0, 'prompt_mean': 24,
                          'prompt_max': 64, 'max_new': 32,
                          'until': duration_s * 0.75}},
        faults=[Fault(t=duration_s * 0.40, kind='sdc', count=1,
                      flavor='token_flip'),
                Fault(t=duration_s * 0.55, kind='sdc', count=1,
                      flavor='nan')])


def wfq_fleet(*, replicas: int = 4, duration_s: float = 900.0,
              aggressor: bool = True) -> Scenario:
    """Fleet-scale starvation gate: the REAL wfq scheduler (weights +
    per-tenant quotas) inside every modeled replica, a 10:1 aggressor
    flood through the REAL LB. Run once with the aggressor and once
    without (same seed) — the victim's scheduler-virtual steps_waited
    must hold the 3x bound with zero victim sheds."""
    tenants: Dict[str, Dict[str, Any]] = {
        'victim': {'rps': 2.0, 'burst': 3, 'prompt_mean': 12,
                   'prompt_max': 24, 'max_new': 8,
                   'until': duration_s * 0.7}}
    if aggressor:
        tenants['aggressor'] = {
            'rps': 20.0, 'burst': 10, 'prompt_mean': 24,
            'prompt_max': 48, 'max_new': 8,
            'until': duration_s * 0.7}
    # Saturation is the point: per-replica throughput ~= slots /
    # (max_new x step) ~= 2 rps, fleet ~= 8 rps, offered load ~= 22 —
    # the aggressor MUST outrun its share or the quota gate is
    # vacuous.
    return Scenario(
        name='wfq_fleet', replicas=replicas, duration_s=duration_s,
        scheduler='wfq', slots=4, max_queue_requests=16,
        perf_scale=5.0,
        tenant_weights={'victim': 2.0, 'aggressor': 1.0},
        tenants=tenants)


def crash_controller_mid_storm(*, replicas: int = 12,
                               duration_s: float = 1800.0) -> Scenario:
    """kill -9 the controller in the MIDDLE of a reclaim storm — half
    the fleet's recovery (drains in flight, replacements mid-launch,
    carcass cleanups queued) dies with it. Gates: the restarted
    controller's startup reconciliation converges the fleet back to
    target (adopting orphans it launched but never recorded, finishing
    half-done teardowns), reconciliation is idempotent, and clients
    ride through on the LB's retry/resume with ZERO visible errors."""
    storm_t = duration_s * 0.4
    return Scenario(
        name='crash_controller_mid_storm', replicas=replicas,
        use_spot=True, duration_s=duration_s, perf_scale=2.0,
        tenants={'prod': {'rps': 3.0, 'prompt_mean': 32,
                          'prompt_max': 128, 'max_new': 12,
                          'until': duration_s * 0.7}},
        faults=[Fault(t=storm_t, kind='reclaim_storm', frac=0.3,
                      notice_frac=0.5)],
        # Landing 20s after the storm hits puts the kill inside the
        # drain/replace churn (controller tick is 15s: the first
        # recovery tick has run, its launches/drains are in flight).
        kills=[KillSpec(target='controller', at_t=storm_t + 20.0,
                        restart_delay_s=45.0)])


def crash_lb_mid_stream(*, replicas: int = 6,
                        duration_s: float = 1200.0) -> Scenario:
    """kill -9 the LB with token streams in flight. The severed
    clients retry against the restarted LB with
    ``resume_from = delivered`` (the SDK-visible half of PR 5's resume
    splice), which rebuilds its replica set from the state DB before
    serving. Gates: zero client-visible errors, retried streams
    bit-identical to unkilled runs, retries non-vacuous."""
    kill_t = duration_s * 0.55
    # Streams must reliably be IN FLIGHT at the kill instant (the
    # resume-retry gate is vacuous otherwise): 32 tokens at a
    # 6x-scaled ITL curve keeps each stream alive ~6 virtual seconds,
    # so 3 rps holds ~19 concurrent through the kill window even at a
    # burst trough — while fleet capacity (~8 rps) stays ahead of
    # offered load, so admission never sheds and the zero-error gate
    # is pure.
    return Scenario(
        name='crash_lb_mid_stream', replicas=replicas,
        duration_s=duration_s, perf_scale=6.0,
        tenants={'prod': {'rps': 3.0, 'prompt_mean': 48,
                          'prompt_max': 128, 'max_new': 32,
                          'until': duration_s * 0.7}},
        kills=[KillSpec(target='lb', at_t=kill_t,
                        restart_delay_s=10.0)])


def crash_sweep(*, replicas: int = 4,
                duration_s: float = 600.0) -> Scenario:
    """The kill-anywhere sweep's BASE replay: a small spot fleet, a
    half-fleet storm with a notice/hard mix, steady short streams —
    small enough that one full replay is milliseconds, rich enough
    that its decision log crosses every lifecycle edge (launch, drain,
    terminate, notice, reclaim, scale). ``sim/crash.py`` replays it
    once unkilled, then once per control-plane decision boundary per
    target with a kill injected there (docs/robustness.md
    "Crash safety")."""
    # The storm MUST land inside the traffic window: its drains, hard
    # kills, and replacement launches are the boundaries where kills
    # meet in-flight streams. 24-token streams at a 4x ITL curve live
    # ~2-3 virtual seconds, so several ride through every storm-window
    # boundary — LB kills sever real streams (client resume-retry
    # non-vacuous) and the storm's hard kills land mid-stream (LB
    # resume splice non-vacuous). Sized for tier-1 wall clock: every
    # killed replay of the sweep replays this whole scenario.
    storm_t = duration_s * 0.7
    return Scenario(
        name='crash_sweep', replicas=replicas, use_spot=True,
        duration_s=duration_s, perf_scale=4.0,
        traffic_start_s=240.0,
        tenants={'prod': {'rps': 2.0, 'burst': 2, 'prompt_mean': 24,
                          'prompt_max': 64, 'max_new': 24,
                          'until': duration_s * 0.6}},
        faults=[Fault(t=storm_t, kind='reclaim_storm', frac=0.5,
                      notice_frac=0.5)])


def fleet_storm_24h(*, replicas: int = 1000,
                    requests: float = 0.12) -> Scenario:
    """THE acceptance gate: a 24h diurnal day at 1000 modeled
    replicas, a 20%-fleet reclaim storm at the evening peak — replayed
    in seconds of wall clock, byte-identical per seed. ``requests``
    scales the diurnal rate (0.12 rps peak-mean ≈ several thousand
    requests over the day — the decision density that matters; the
    fleet-size axis is what this gate exists to prove)."""
    day = 86400.0
    return Scenario(
        name='fleet_storm_24h', replicas=replicas, use_spot=True,
        duration_s=day + 2400.0, traffic_start_s=900.0,
        controller_tick_s=60.0, lb_sync_s=60.0, stats_flush_s=45.0,
        provision_delay_s=(60.0, 240.0), initial_delay_s=600.0,
        max_queue_requests=128,
        tenants={'world': {
            'rps': requests, 'prompt_mean': 48, 'prompt_max': 192,
            'max_new': 10, 'until': day,
            'envelope': {'kind': 'diurnal', 'period_s': day,
                         'low': 0.15}}},
        # Notice lead MUST clear the controller tick cadence or the
        # drain never happens: a notice only turns into a planned
        # handoff when a tick observes it before the provider's kill.
        faults=[Fault(t=900.0 + day * 0.58, kind='reclaim_storm',
                      frac=0.2, notice_lead_s=240.0)])


def spot_market_week(*, replicas: int = 6, days: float = 7.0,
                     cost_optimized: bool = True,
                     use_spot: bool = True) -> Scenario:
    """THE cost-plane acceptance gate (docs/cost.md): a week of
    diurnal traffic over a three-zone spot market with distinct
    prices and reclaim intensities. Run cost-optimized (the REAL
    FleetPlacer chooses the spot/on-demand mix per tick) and once
    more all-on-demand (``cost_optimized=False, use_spot=False``,
    same seed) — the gate asserts real dollars saved at SLO: billed
    total well under the baseline, ZERO client-visible errors, ZERO
    page-tier SLO alert transitions, and the placement decision log
    byte-identical across same-seed replays.

    Deliberately a FIXED-target fleet (no ``queue_length_threshold``):
    the week-scale cadences (90s stats flush) sit far beyond the
    inflight gauge's 30s staleness window, so a queue-length
    autoscaler would always read zero here — the market mix, not the
    replica count, is what this scenario exercises."""
    day = 86400.0
    duration = days * day + 3600.0
    market = {
        ('sim-r1', 'sim-r1-a'): {'ondemand': 10.0, 'spot': 3.0,
                                 'reclaim_per_hour': 0.05},
        ('sim-r1', 'sim-r1-b'): {'ondemand': 10.0, 'spot': 3.5,
                                 'reclaim_per_hour': 0.12},
        ('sim-r2', 'sim-r2-a'): {'ondemand': 11.0, 'spot': 4.2,
                                 'reclaim_per_hour': 0.02},
    }
    return Scenario(
        name='spot_market_week', replicas=replicas,
        use_spot=use_spot, cost_optimized=cost_optimized,
        market=market, relaunch_overhead_s=420.0,
        zones=sorted(market),
        duration_s=duration, traffic_start_s=1800.0,
        controller_tick_s=120.0, lb_sync_s=120.0, stats_flush_s=90.0,
        provision_delay_s=(120.0, 300.0), initial_delay_s=600.0,
        tenants={'world': {
            'rps': 0.03, 'prompt_mean': 32, 'prompt_max': 96,
            'max_new': 8, 'until': days * day,
            'envelope': {'kind': 'diurnal', 'period_s': day,
                         'low': 0.25}}},
        # Armed objectives make the zero-page gate non-vacuous: a
        # placer that chases cheap spot into reclaim churn pages here.
        slo=[{'metric': 'ttft_p99', 'threshold_s': 2.0,
              'target': 0.99},
             {'metric': 'availability', 'target': 0.999}])


def scale_to_zero(*, duration_s: float = 7200.0) -> Scenario:
    """Scale-to-zero lifecycle (docs/cost.md "Scale to zero"): the
    fleet parks (min_replicas 0) before traffic arrives, the first
    request parks in the LB's bounded wake queue, the inflight gauge
    wakes the autoscaler, a replica cold-starts, the parked requests
    drain, and after the burst the fleet parks again. Gates: at least
    one real cold start sampled (park -> ready wall time), zero
    client-visible errors, final service status PARKED.

    ``stats_flush_s`` MUST stay under the inflight gauge's 30s
    staleness window — a coarser cadence reads parked requests as
    zero and the fleet never wakes."""
    return Scenario(
        name='scale_to_zero', replicas=1, max_replicas=3,
        min_replicas=0, wake_on_request=True, max_parked_requests=32,
        queue_length_threshold=4.0,
        upscale_delay_s=15.0, downscale_delay_s=600.0,
        duration_s=duration_s, traffic_start_s=2400.0,
        controller_tick_s=15.0, lb_sync_s=10.0, stats_flush_s=20.0,
        provision_delay_s=(30.0, 90.0), initial_delay_s=120.0,
        # Trace times are RELATIVE to traffic_start_s: a 900s burst at
        # t=2400..3300, then quiet — the fleet must be PARKED at both
        # ends of the replay.
        tenants={'jobs': {'rps': 0.2, 'prompt_mean': 24,
                          'prompt_max': 64, 'max_new': 8,
                          'until': 900.0}})


def disagg_fleet(*, replicas: int = 1000, duration_s: float = 3600.0,
                 fleet_routing: bool = True,
                 rps: float = 2.0) -> Scenario:
    """THE disaggregation acceptance gate (docs/serving.md
    "Disaggregated prefill/decode"): a 1000-replica fleet serving a
    shared-system-prompt diurnal cohort through the REAL cache-aware
    LB with the fleet prefix index armed, a 20% spot-reclaim storm
    landing mid-window so donors die with transfers pending. Run
    once fleet-routed and once ``fleet_routing=False`` (owner-only
    consistent hashing, same seed): the gates assert the fleet index
    at least DOUBLES the warm-prefix rate, TTFT p99 improves, zero
    client-visible errors ride through the storm (donor-death
    recompute fallback non-vacuous), and two same-seed replays emit
    byte-identical decision logs.

    Prompt shape: a 48-token shared system prompt (3 pages at
    ``kv_page`` 16) on ~nine of ten requests, heavy-tail user tails.
    48 < the LB's 64-token affinity lead, so the owner-only baseline
    keys on prefix+tail and SCATTERS the cohort across the ring —
    each replica sees a cohort request every ~8 virtual minutes,
    past the 300 s idle TTL (the decode-page-pressure eviction
    model), so its prefix is cold again.  The fleet index instead
    keys on the longest indexed chain link and steers to live
    holders, which stay hot.  ``prefill_tokens_per_step`` 32 makes a
    cold ~72-token prefill cost ~3 virtual steps and a warm one 1 —
    the TTFT gap the transfer either buys (fleet) or does not."""
    storm_t = duration_s * 0.55
    return Scenario(
        name='disagg_fleet', replicas=replicas, use_spot=True,
        duration_s=duration_s, traffic_start_s=600.0,
        controller_tick_s=60.0, lb_sync_s=30.0, stats_flush_s=45.0,
        provision_delay_s=(60.0, 240.0), initial_delay_s=480.0,
        lb_policy='cache_aware', max_queue_requests=64,
        perf_scale=2.0, prefill_tokens_per_step=32.0,
        kv_page=16, kv_ttl_s=300.0, prefill_fraction=0.1,
        fleet_routing=fleet_routing,
        tenants={'world': {
            'rps': rps, 'prompt_mean': 48, 'prompt_max': 128,
            'max_new': 10, 'shared_prefix_frac': 0.9,
            'prefix_tokens': 48, 'until': duration_s * 0.8,
            'envelope': {'kind': 'diurnal', 'period_s': duration_s,
                         'low': 0.3}}},
        faults=[
            # Targeted reclaim of the active donor, trapped to land
            # mid-transfer — the recompute-fallback gate's worst case,
            # deterministic across seeds (a storm alone only fells
            # the donor by luck).
            Fault(t=duration_s * 0.4, kind='donor_reclaim'),
            Fault(t=storm_t, kind='reclaim_storm', frac=0.2,
                  notice_frac=0.25, notice_lead_s=120.0)])


SCENARIOS = {
    'reclaim_storm': reclaim_storm,
    'incident_page_storm': incident_page_storm,
    'flash_crowd': flash_crowd,
    'regional_failover': regional_failover,
    'slow_brownout': slow_brownout,
    'breaker_flap': breaker_flap,
    'sdc_storm': sdc_storm,
    'wfq_fleet': wfq_fleet,
    'crash_controller_mid_storm': crash_controller_mid_storm,
    'crash_lb_mid_stream': crash_lb_mid_stream,
    'crash_sweep': crash_sweep,
    'fleet_storm_24h': fleet_storm_24h,
    'spot_market_week': spot_market_week,
    'scale_to_zero': scale_to_zero,
    'disagg_fleet': disagg_fleet,
}
