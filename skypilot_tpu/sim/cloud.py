"""Virtual cloud + deterministic executor behind the RM's seams.

The REAL ``ReplicaManager`` lifecycle state machine (PENDING →
PROVISIONING → STARTING → READY, preemption-notice drains, probe
streaks, carcass cleanup) runs unmodified; this module supplies its
two injection points:

- :class:`SimExecutor` replaces the launch/teardown thread pool with
  kernel events — work still runs "asynchronously" w.r.t. the
  controller tick (it is a later event at the same virtual instant),
  but in a deterministic order on one thread.
- :class:`VirtualCloud` implements ``CloudAdapter``: launches model a
  provisioning delay (probes fail until the slice is "up"), zone
  placement honors the spot placer's blocked list (so
  regional-failover scenarios prove relaunches avoid the dead zone),
  and the fault API (``reclaim``, ``zone_outage``) feeds storms.
"""
from __future__ import annotations

import concurrent.futures
import random
from types import SimpleNamespace
from typing import Any, Callable, Dict, List, Optional, Tuple

from skypilot_tpu.serve import replica_managers
from skypilot_tpu.sim import kernel as kernel_lib
from skypilot_tpu.sim import replica as replica_lib


class SimCrashError(Exception):
    """The virtual kill -9: raised by the twin's crash gate inside a
    cloud-facing operation to tear it at the real crash window (slice
    created, DB not yet written; drain done, terminate not). Escapes
    into the dead executor's future, which nobody reaps — the manager
    object is gone, exactly like the process."""


class SimExecutor:
    """``concurrent.futures``-shaped executor whose submissions run as
    kernel events. Real ``Future`` objects are returned so the replica
    manager's ``fut.done()`` / ``fut.exception()`` reaping works
    untouched. ``kill()`` models the controller process dying: queued
    submissions never run (their threads died with the process) and
    their futures stay pending forever."""

    def __init__(self, kern: kernel_lib.Kernel) -> None:
        self.kernel = kern
        self.dead = False

    def submit(self, fn: Callable, *args: Any,
               **kwargs: Any) -> concurrent.futures.Future:
        fut: concurrent.futures.Future = concurrent.futures.Future()
        fut.set_running_or_notify_cancel()

        def run() -> None:
            if self.dead:
                return   # the pool died with its controller
            try:
                fut.set_result(fn(*args, **kwargs))
            except BaseException as e:  # noqa: BLE001 — reaped by sync()
                fut.set_exception(e)

        self.kernel.call_later(0.0, run)
        return fut

    def kill(self) -> None:
        self.dead = True

    def shutdown(self, wait: bool = False) -> None:
        del wait


class _Slice:
    __slots__ = ('cluster_name', 'url', 'region', 'zone', 'is_spot',
                 'accelerator', 'provisioned_at', 'alive', 'notice',
                 'model', 'created_at', 'billed')

    def __init__(self, cluster_name: str, url: str, region: str,
                 zone: str, is_spot: bool, accelerator: Optional[str],
                 provisioned_at: float,
                 model: replica_lib.ModelReplica,
                 created_at: float = 0.0) -> None:
        self.cluster_name = cluster_name
        self.url = url
        self.region = region
        self.zone = zone
        self.is_spot = is_spot
        self.accelerator = accelerator
        self.provisioned_at = provisioned_at
        self.alive = True
        self.notice = False
        self.model = model
        # Billing meter (market model): clouds bill from provision
        # START, and a slice is billed exactly once.
        self.created_at = created_at
        self.billed = False


class VirtualCloud(replica_managers.CloudAdapter):
    """The provider the twin's replica manager provisions against."""

    def __init__(self, kern: kernel_lib.Kernel, *,
                 make_replica: Callable[[str], replica_lib.ModelReplica],
                 log: Callable[..., None],
                 zones: Optional[List[Tuple[str, str]]] = None,
                 provision_delay_s: Tuple[float, float] = (30.0, 90.0),
                 seed: int = 0,
                 market: Optional[Dict[Tuple[str, str], dict]] = None,
                 market_horizon_s: float = 0.0,
                 reclaim_notice_s: float = 30.0,
                 kv_link_gbps: float = 10.0,
                 kv_transfer_floor_s: float = 0.005) -> None:
        self.kernel = kern
        self.make_replica = make_replica
        self.log = log
        self.zones = zones or [('sim-r1', f'sim-r1-{z}')
                               for z in 'abc']
        self.provision_delay_s = provision_delay_s
        self.rng = random.Random(f'cloud/{seed}')
        self.slices: Dict[str, _Slice] = {}
        self.by_url: Dict[str, _Slice] = {}
        self._ip = 0
        # Spot-market model (docs/cost.md "The market week"): per-zone
        # prices + a Poisson reclaim process. Every zone's reclaim
        # event times are PRE-SAMPLED from a purpose-keyed RNG at
        # construction, so the reclaim stream is a property of
        # (seed, zone) alone — fleet state (how many launches have
        # consumed self.rng) can never perturb it, which is what keeps
        # the placer decision log byte-identical across replays.
        self.market: Dict[Tuple[str, str], dict] = market or {}
        self.reclaim_notice_s = reclaim_notice_s
        # KV-transfer latency curve (docs/serving.md "Disaggregated
        # prefill/decode"): replica-to-replica page streaming pays a
        # per-transfer floor (connection + header round trip) plus the
        # serialization time of the int8 pages over the modeled
        # inter-replica link.
        self.kv_link_gbps = kv_link_gbps
        self.kv_transfer_floor_s = kv_transfer_floor_s
        self._billed = {'spot_cost': 0.0, 'ondemand_cost': 0.0,
                        'spot_hours': 0.0, 'ondemand_hours': 0.0}
        if self.market and market_horizon_s > 0:
            for (region, zone) in sorted(self.market):
                rate = float(self.market[(region, zone)]
                             .get('reclaim_per_hour') or 0.0)
                if rate <= 0:
                    continue
                zrng = random.Random(f'market/{seed}/{region}/{zone}')
                t = zrng.expovariate(rate / 3600.0)
                while t < market_horizon_s:
                    self.kernel.call_later(t, self._market_reclaim,
                                           region, zone)
                    t += zrng.expovariate(rate / 3600.0)
        # Crash gate (kill-anywhere sweep): the twin installs a
        # callable invoked at each real crash window of a cloud-facing
        # operation — after the provider side-effect, before the
        # manager's DB write. Raising SimCrashError there tears the
        # operation exactly where a kill -9 would.
        self.crash_gate: Optional[Callable[[str], None]] = None

    def _gate(self, window: str) -> None:
        if self.crash_gate is not None:
            self.crash_gate(window)

    def kv_transfer_s(self, nbytes: int) -> float:
        """Virtual seconds one donor-to-puller KV prefix transfer of
        ``nbytes`` takes: floor + wire time at the link bandwidth."""
        return (self.kv_transfer_floor_s
                + nbytes * 8.0 / (self.kv_link_gbps * 1e9))

    # ---- CloudAdapter --------------------------------------------------
    def launch(self, task, cluster_name: str, blocked_placements,
               avoid_placements=None):
        blocked = {tuple(b) for b in (blocked_placements or [])}
        avoid = {tuple(b) for b in (avoid_placements or [])}
        counts: Dict[Tuple[str, str], int] = {
            z: 0 for z in self.zones}
        for s in self.slices.values():
            if s.alive and (s.region, s.zone) in counts:
                counts[(s.region, s.zone)] += 1
        # Placement: least-populated zone (lexical ties) — the
        # candidate order the optimizer's best-first walk would
        # produce — under execution.launch's two relaxation tiers:
        # HARD preemption blocks fall back to the full list only when
        # they exclude everything; SOFT spreading avoids are dropped
        # against the hard-filtered list.
        candidates = [z for z in self.zones if z not in blocked] \
            or list(self.zones)
        candidates = [z for z in candidates if z not in avoid] \
            or candidates
        region, zone = min(candidates, key=lambda z: (counts[z], z))
        self._ip += 1
        ip = f'10.{(self._ip >> 16) & 255}.{(self._ip >> 8) & 255}' \
             f'.{self._ip & 255}'
        port = int(task.envs.get('SKYPILOT_SERVE_PORT', 8080) or 8080)
        url = f'http://{ip}:{port}'
        lo, hi = self.provision_delay_s
        delay = self.rng.uniform(lo, hi)
        model = self.make_replica(url)
        accel = None
        if task.resources.accelerators:
            accel = next(iter(task.resources.accelerators))
        s = _Slice(cluster_name, url, region, zone,
                   task.resources.use_spot, accel,
                   self.kernel.now + delay, model,
                   created_at=self.kernel.now)
        self.slices[cluster_name] = s
        self.by_url[url] = s
        self.log('launch', cluster=cluster_name, zone=f'{region}/{zone}',
                 spot=bool(task.resources.use_spot),
                 provision_s=round(delay, 3))
        # The torn window: the slice exists, the replica row doesn't
        # know — a kill here leaves the orphan reconcile must adopt.
        self._gate('launch.post_create')
        return SimpleNamespace(
            head=SimpleNamespace(external_ip=ip, internal_ip=ip,
                                 agent_url=url),
            tpu_slice=accel, region=region, zone=zone)

    def probe_url(self, url: str, probe) -> bool:
        s = self.by_url.get(url)
        # A wedged or browned-out replica still answers its health
        # endpoint — that is precisely what makes those failure modes
        # interesting to the LB's breaker.
        return (s is not None and s.alive and s.model.alive
                and self.kernel.now >= s.provisioned_at)

    def probe_pool_worker(self, cluster_name: str,
                          timeout_s: float) -> bool:
        s = self.slices.get(cluster_name)
        return (s is not None and s.alive
                and self.kernel.now >= s.provisioned_at)

    def provider_alive(self, cluster_name: str) -> Optional[bool]:
        s = self.slices.get(cluster_name)
        if s is None:
            return None
        return s.alive

    def preemption_notice(self, cluster_name: str) -> bool:
        s = self.slices.get(cluster_name)
        return s is not None and s.notice

    def drain(self, url: str, deadline_s: float) -> Optional[dict]:
        s = self.by_url.get(url)
        if s is None or not s.model.alive:
            return None
        n = len(s.model.active) + s.model.sched.pending()
        s.model.drain_flush()
        self.log('drain', cluster=s.cluster_name, flushed=n)
        # Half-done drain: the replica drained but its slice survives
        # and the row still says DRAINING — recovery must finish the
        # teardown.
        self._gate('drain.post_flush')
        return {'status': 'drained', 'flushed': n}

    def terminate(self, cluster_name: str) -> None:
        s = self.slices.pop(cluster_name, None)
        if s is None:
            return
        self.by_url.pop(s.url, None)
        self._bill(s)
        s.alive = False
        s.model.kill()
        self.log('terminate', cluster=cluster_name)
        # Slice dead, replica row still present: recovery re-runs the
        # teardown (terminate of a gone slice is a no-op) and drops
        # the row.
        self._gate('terminate.post_kill')

    def describe_cluster(self, cluster_name: str,
                         port: int) -> Optional[dict]:
        del port   # the virtual slice already knows its url
        s = self.slices.get(cluster_name)
        if s is None or not s.alive:
            return None
        return {'url': s.url, 'zone': f'{s.region}/{s.zone}',
                'accelerator': s.accelerator}

    def terminate_by_name(self, cluster_name: str,
                          cloud_hint: Optional[str] = None) -> None:
        del cloud_hint   # the virtual provider always resolves by name
        self.terminate(cluster_name)

    # ---- fault API (the scenario schedule calls these) -----------------
    def live_slices(self) -> List[_Slice]:
        return [self.slices[k] for k in sorted(self.slices)
                if self.slices[k].alive]

    def reclaim(self, cluster_name: str, *,
                notice_lead_s: float = 0.0) -> None:
        """Spot reclaim. With a notice lead the provider warns first
        (the manager's next tick turns it into a planned drain) and
        the hard kill lands ``notice_lead_s`` later IF the slice still
        exists — the real race between drain and reclaim."""
        s = self.slices.get(cluster_name)
        if s is None or not s.alive:
            return
        if notice_lead_s > 0:
            s.notice = True
            self.log('preemption_notice', cluster=cluster_name,
                     lead_s=notice_lead_s)
            self.kernel.call_later(notice_lead_s, self.hard_kill,
                                   cluster_name)
        else:
            self.hard_kill(cluster_name)

    def hard_kill(self, cluster_name: str) -> None:
        s = self.slices.get(cluster_name)
        if s is None or not s.alive:
            return
        self._bill(s)
        s.alive = False
        s.model.kill()
        self.log('reclaim_kill', cluster=cluster_name,
                 zone=f'{s.region}/{s.zone}')

    def _market_reclaim(self, region: str, zone: str) -> None:
        """One pre-sampled market reclaim event: the provider takes
        back every live SPOT slice in the zone (capacity reclaims are
        zone-correlated — that correlation is why the spot placer
        spreads), each with the standard preemption notice lead.
        On-demand capacity is never touched."""
        victims = [s for s in self.live_slices()
                   if s.is_spot and s.region == region
                   and s.zone == zone]
        if not victims:
            return
        self.log('market_reclaim', zone=f'{region}/{zone}',
                 killed=len(victims))
        for s in victims:
            self.reclaim(s.cluster_name,
                         notice_lead_s=self.reclaim_notice_s)

    def _bill(self, s: _Slice) -> None:
        """Close a slice's billing meter exactly once: lifetime
        (provision start → now) times the zone's market price for its
        pricing tier. Zones outside the market model bill $0 but
        still count hours, so the utilization denominators stay
        honest."""
        if s.billed:
            return
        s.billed = True
        hours = max(0.0, self.kernel.now - s.created_at) / 3600.0
        econ = self.market.get((s.region, s.zone)) or {}
        if s.is_spot:
            self._billed['spot_hours'] += hours
            self._billed['spot_cost'] += hours * float(
                econ.get('spot') or 0.0)
        else:
            self._billed['ondemand_hours'] += hours
            self._billed['ondemand_cost'] += hours * float(
                econ.get('ondemand') or 0.0)

    def billing(self) -> Dict[str, float]:
        """Cumulative fleet bill at the current virtual instant,
        including still-running slices (their meters are read, not
        closed). The twin's $-saved-at-SLO gate compares this total
        across the cost-optimized and all-on-demand runs."""
        out = dict(self._billed)
        for s in self.slices.values():
            if s.billed:
                continue
            hours = max(0.0, self.kernel.now - s.created_at) / 3600.0
            econ = self.market.get((s.region, s.zone)) or {}
            if s.is_spot:
                out['spot_hours'] += hours
                out['spot_cost'] += hours * float(
                    econ.get('spot') or 0.0)
            else:
                out['ondemand_hours'] += hours
                out['ondemand_cost'] += hours * float(
                    econ.get('ondemand') or 0.0)
        out = {k: round(v, 6) for k, v in out.items()}
        out['total_cost'] = round(
            out['spot_cost'] + out['ondemand_cost'], 6)
        return out

    def zone_outage(self, zone_suffix: str) -> int:
        """Kill every live slice in a zone (regional failover)."""
        n = 0
        for s in self.live_slices():
            if s.zone == zone_suffix:
                self.hard_kill(s.cluster_name)
                n += 1
        self.log('zone_outage', zone=zone_suffix, killed=n)
        return n
