"""Virtual transport: the REAL LoadBalancer over modeled replicas.

:class:`TwinLoadBalancer` subclasses the production ``LoadBalancer``
and overrides ONLY its transport seams — the proxy attempt, the
/metrics fetch, and the blocking-DB offload. Everything that makes
the LB interesting runs for real: ``handle()``'s retry/resume loop,
``_select``'s breaker-aware choice with cache-affinity fallback, the
circuit breaker itself, saturation rerouting (429/503), deadline
budget forwarding, the ``_StreamSplice`` delivered-token ledger and
its dedupe rule, per-tenant edge metrics, and the fleet history tier.

The failpoint seams (``lb.proxy``, ``serve.lb.midstream_kill``) are
re-armed at the same positions as the real transport, so env-driven
chaos composes with scenario faults inside a replay (``error``
actions only — a ``delay`` would need an asyncio loop the kernel
deliberately does not have).
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu.observability import integrity
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.sim import kernel as kernel_lib
from skypilot_tpu.sim import replica as replica_lib
from skypilot_tpu.utils import common
from skypilot_tpu.utils import failpoints


class SimRequest:
    """Duck-typed stand-in for ``aiohttp.web.Request`` — exactly the
    attribute surface ``LoadBalancer.handle`` touches. ``splice`` is
    the twin's window into the in-flight stream state: the transport
    stamps the LB's ``_StreamSplice`` here so a kill-anywhere LB crash
    can read how many tokens the "client" already holds (the
    resume_from of its retry against the restarted LB)."""

    __slots__ = ('method', 'path', 'headers', '_body', 'splice')

    def __init__(self, path: str, body: bytes,
                 headers: Optional[Dict[str, str]] = None,
                 method: str = 'POST') -> None:
        self.method = method
        self.path = path
        self.headers = dict(headers or {})
        self._body = body
        self.splice = None

    @property
    def path_qs(self) -> str:
        return self.path

    @property
    def query(self) -> Dict[str, str]:
        # aiohttp's parsed query surface (the LB's format= switch);
        # the twin's traffic never carries one.
        return {}

    async def read(self) -> bytes:
        return self._body


class SimStreamResponse:
    """What ``splice.resp`` becomes on the virtual wire: records the
    forwarded jsonlines so the twin can audit exactly what the client
    received (token ids, done line, resume stamps, in-band errors)."""

    __slots__ = ('status', 'chunks', 'eof')

    def __init__(self) -> None:
        self.status = 200
        self.chunks: List[bytes] = []
        self.eof = False

    async def write(self, data: bytes) -> None:
        self.chunks.append(data)

    async def write_eof(self) -> None:
        self.eof = True

    def lines(self) -> List[Dict[str, Any]]:
        out = []
        for raw in b''.join(self.chunks).splitlines():
            if raw.strip():
                out.append(json.loads(raw))
        return out


class TwinLoadBalancer(lb_lib.LoadBalancer):
    """The real LB bound to the twin's kernel clock and replica map."""

    def __init__(self, service_name: str, policy_name: str, *,
                 clock, model_by_url, kernel=None,
                 probe_fixture=None, probe_fingerprint=None,
                 probe_interval_s=None, fleet_routing=None) -> None:
        super().__init__(service_name, policy_name, clock=clock,
                         probe_fixture=probe_fixture,
                         probe_fingerprint=probe_fingerprint,
                         probe_interval_s=probe_interval_s,
                         fleet_routing=fleet_routing)
        self._model_by_url = model_by_url
        self._kernel = kernel

    # ---- seams ---------------------------------------------------------
    async def _offload(self, fn, *args):
        # One thread, one sqlite, deterministic order: run inline.
        return fn(*args)

    def _spawn_task(self, coro):
        # Probes must run on the kernel trampoline, in virtual time —
        # asyncio.ensure_future would need a real loop.
        return self._kernel.spawn(coro)

    async def _probe_transport(self, url: str, payload: dict):
        """Golden probe against the modeled replica: same verdict
        surface as the real aiohttp transport. ``ReplicaQuarantined``
        (the modeled sentinel self-report) maps to ``corrupt``; every
        other shed/death is a transport ``error`` — never a
        quarantine."""
        model = self._model_by_url(url)
        if model is None or not model.alive or model.wedged:
            return 'error', f'replica {url} unreachable'
        try:
            stream = model.submit(payload, integrity.PROBE_TENANT,
                                  [])
        except replica_lib.ReplicaQuarantined as e:
            return 'corrupt', str(e)
        except replica_lib.ReplicaShed as e:
            return 'error', f'shed {e.status}'
        except ConnectionError as e:
            return 'error', str(e)
        tokens: List[int] = []
        while True:
            kind, obj = await stream.next_event()
            if kind == 'dead':
                return 'error', f'replica {url} died mid-probe'
            if obj.get('error'):
                return 'error', obj['error']
            toks = obj.get('tokens')
            if isinstance(toks, list):
                tokens.extend(int(t) for t in toks)
            if obj.get('done'):
                return 'ok', tokens

    def _new_waiter(self):
        # Scale-to-zero parking: the kernel trampoline rejects foreign
        # awaitables, so a parked request suspends on a SimFuture and
        # resumes when the wake tick resolves it — in virtual time.
        return kernel_lib.SimFuture()

    async def _fetch_all_metrics(self, urls: List[str]) -> List[tuple]:
        rows = []
        for url in urls:
            model = self._model_by_url(url)
            if model is not None and model.alive:
                # Same delta-encoding handshake as the real fetch's
                # ?prefix_gen= query: the modeled replica snapshots
                # its radix index against our mirror's generation.
                since = (self.fleet_index.last_gen(url)
                         if self.fleet_routing else None)
                rows.append(model.metrics_row(since_gen=since))
        return rows

    async def _proxy_stream_attempt(self, request, url: str,
                                    headers: Dict[str, str],
                                    t_arrival: float, splice):
        request.splice = splice   # the LB-crash resume window
        splice.buf = b''
        try:
            await failpoints.hit_async('lb.proxy')
        except failpoints.FailpointError as e:
            raise lb_lib._UpstreamDead(e) from e  # noqa: SLF001
        model = self._model_by_url(url)
        if model is None or not model.alive or model.wedged:
            raise lb_lib._UpstreamDead(  # noqa: SLF001
                ConnectionError(f'replica {url} unreachable'))
        resume = list(splice.client_resume) + list(splice.delivered)
        try:
            # The donor header the REAL handle() armed from the fleet
            # index rides the virtual wire like any other header.
            stream = model.submit(
                splice.payload, headers.get(common.TENANT_HEADER),
                resume, donor=headers.get(common.KV_DONOR_HEADER))
        except replica_lib.ReplicaShed as e:
            raise lb_lib._ReplicaSaturated(  # noqa: SLF001
                e.status, str(e).encode(),
                {'Retry-After': f'{e.retry_after_s:.0f}'}) from e
        except ConnectionError as e:
            raise lb_lib._UpstreamDead(e) from e  # noqa: SLF001
        if splice.resp is None:
            splice.resp = SimStreamResponse()
        while True:
            kind, obj = await stream.next_event()
            if kind == 'dead':
                raise lb_lib._UpstreamDead(  # noqa: SLF001
                    ConnectionError(f'replica {url} died mid-stream'))
            line = json.dumps(obj).encode()
            # THE real ledger: TTFT/ITL stamps, delivered-token
            # bookkeeping, done-line resume stamping.
            out = self._admit_stream_line(splice, line, t_arrival)
            if out is None:
                raise lb_lib._UpstreamDead(  # noqa: SLF001
                    RuntimeError('replica reported an in-stream error'))
            await splice.resp.write(out)
            if splice.done:
                break
            try:
                await failpoints.hit_async('serve.lb.midstream_kill')
            except failpoints.FailpointError as e:
                raise lb_lib._UpstreamDead(e) from e  # noqa: SLF001
            # Same line-boundary quarantine cut as the real transport.
            if url in self._quarantined_urls:
                raise lb_lib._QuarantineCut()  # noqa: SLF001
        await splice.resp.write_eof()
        return splice.resp, True

    async def _proxy_attempt(self, request, url: str, body: bytes,
                             headers: Dict[str, str], t_arrival: float,
                             gen: bool = False,
                             tenant: Optional[str] = None
                             ) -> Tuple[Any, bool]:
        # The twin's traffic is streaming /generate; a non-stream
        # attempt reaching here means a scenario forgot stream=True.
        raise NotImplementedError(
            'the digital twin models streaming /generate only — set '
            "payload['stream'] = True in the trace")
