"""Modeled replicas: a REAL engine scheduler fronting virtual slots.

Each modeled replica embeds a real ``infer/sched`` policy instance
(fcfs / EDF / wfq — the exact admission, quota, and ordering code the
production step loop drives), so fleet-scale gates prove the REAL
per-tenant shed and starvation behavior. Only the device is modeled:
decode advances one token per slot per virtual step, and the step
cadence follows the measured ITL-vs-concurrency curve from the bench
JSONs (TTFT_r06/r07) — so queueing, batching pressure, and admission
interact with arrival shapes the way the real engine's do.

Failure surface (what the scenarios drive):

- ``kill()`` — hard preemption: every in-flight stream dies mid-line
  (the LB's resume splice heals it);
- ``drain_flush()`` — the planned handoff: stop admitting, finish all
  in-flight work at the drain instant (the twin models drain latency
  as an atomic flush — ORDERING is what it proves: DRAINING before
  teardown, ready-set removal before death, zero client errors);
- ``wedged`` — answers probes but fails requests (breaker-flap food);
- ``slow_factor`` — brownout: steps stretch, tails grow, probes pass;
- ``poison(flavor)`` — silent data corruption (docs/robustness.md
  "Data integrity"): ``token_flip`` serves deterministically WRONG
  tokens for short prompts (address-localized corruption — the golden
  probe's tiny prompt hits it, long tenant prompts do not), ``nan``
  models a sentinel trip (in-flight streams die, new submits shed a
  503 with the ``quarantined`` marker). Probes pass either way — only
  the integrity plane can tell a poisoned replica from a healthy one.
"""
from __future__ import annotations

import dataclasses
import json
import math
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from skypilot_tpu.infer import sched as sched_lib
from skypilot_tpu.sim import kernel as kernel_lib
from skypilot_tpu.utils import prefix_hash


class ReplicaShed(Exception):
    """The modeled replica refused the request (429 admission-full
    from the REAL scheduler's quota logic, or 503 while draining)."""

    def __init__(self, status: int, message: str,
                 retry_after_s: float) -> None:
        super().__init__(message)
        self.status = status
        self.retry_after_s = retry_after_s


class ReplicaQuarantined(ReplicaShed):
    """503 from a sentinel-tripped replica: the body carries the
    ``quarantined`` reason marker — the twin's mirror of the infer
    server's corrupt-health contract (503 + ``Retry-After`` +
    ``{'error': 'replica corrupt', 'quarantined': true}``). The LB
    releases (never breaker-fails) it, exactly like a drain 503."""

    def __init__(self) -> None:
        super().__init__(
            503, json.dumps({'error': 'replica corrupt',
                             'quarantined': True}),
            retry_after_s=1.0)


# Address-locality bound of the token_flip corruption model: only
# prompts at most this many tokens long hit the corrupt rows (a bad
# HBM bank corrupts SOME addresses, not the whole model — modeled as
# the embedding rows the golden probe's tiny prompt touches). Long
# tenant prompts decode correctly, which is exactly what makes the
# corruption SILENT to every liveness signal and non-vacuous for the
# probe plane to catch.
CORRUPT_SHORT_PROMPT_MAX = 6

# Bumped when the sim oracle's token function changes — the golden
# fixture fingerprint (observability/integrity.py) is minted against
# it, and a mismatch must fail loudly at probe-arm time.
ORACLE_VERSION = 1


def oracle_fingerprint() -> str:
    """The sim oracle's identity string — what a golden fixture for
    model key ``'sim'`` must have been minted against."""
    return f'sim-greedy-v{ORACLE_VERSION}'


@dataclasses.dataclass
class PerfModel:
    """Measured performance curves: virtual step time as a function of
    decode concurrency (piecewise-linear over the bench sweep levels),
    plus the prefill budget per step that sets modeled TTFT."""

    # (concurrency, step_seconds), ascending concurrency.
    itl_curve: List[Tuple[float, float]]
    prefill_tokens_per_step: float = 256.0
    # Uniform stretch: the bench box's tiny-model ITLs are ~ms; a
    # scenario can scale toward production-shaped tens of ms without
    # re-deriving the curve's SHAPE.
    scale: float = 1.0

    def step_s(self, concurrency: int) -> float:
        c = max(1.0, float(concurrency))
        curve = self.itl_curve
        if c <= curve[0][0]:
            base = curve[0][1]
        elif c >= curve[-1][0]:
            base = curve[-1][1]
        else:
            base = curve[-1][1]
            for (ca, sa), (cb, sb) in zip(curve, curve[1:]):
                if c < cb:
                    base = sa + (sb - sa) * (c - ca) / (cb - ca)
                    break
        return base * self.scale

    @classmethod
    def default(cls, scale: float = 1.0) -> 'PerfModel':
        return cls(itl_curve=[(1, 0.020), (8, 0.030), (16, 0.045)],
                   scale=scale)

    @classmethod
    def from_bench_json(cls, path: str, *, scale: float = 1.0,
                        lane: str = 'spec_on') -> 'PerfModel':
        """Derive the curve from a ``bench_ttft`` sweep JSON
        (TTFT_r06-style: per-level ``concurrency`` + per-lane
        ``itl_p50_ms``). Falls back to :meth:`default` when the file
        has no usable sweep — a missing bench must not fail a replay."""
        try:
            with open(path, encoding='utf-8') as f:
                doc = json.load(f)
            pts: List[Tuple[float, float]] = []
            for level in doc.get('sweep') or []:
                conc = level.get('concurrency')
                row = level.get(lane) if isinstance(level.get(lane),
                                                   dict) else level
                itl = (row or {}).get('itl_p50_ms')
                if conc and itl:
                    pts.append((float(conc), float(itl) / 1e3))
            if pts:
                return cls(itl_curve=sorted(pts), scale=scale)
        except (OSError, ValueError, TypeError):
            pass
        return cls.default(scale=scale)


class _Req:
    """The request object handed to the REAL scheduler: exactly the
    attribute surface ``infer/sched`` relies on (tenant, prompt and
    output token lists for ``request_cost``, cancelled/deadline for
    sweeps, submitted_at for victim choice)."""

    __slots__ = ('tenant', 'prompt_tokens', 'output_tokens',
                 'cancelled', 'deadline', 'submitted_at',
                 'max_new_tokens', 'resume_len', 'stream',
                 'submit_step', 'first_token_step', 'prefill_left',
                 'dispatched_at', 'prompt_key', 'chain')

    def __init__(self, tenant: str, prompt_tokens: List[int],
                 max_new_tokens: int, resume_from: List[int],
                 submitted_at: float, submit_step: int,
                 prefill_left: int) -> None:
        self.tenant = tenant
        self.prompt_tokens = list(prompt_tokens)
        # Resume tokens pre-seed the output exactly like the engine's
        # resume_from splice path: they count toward request_cost (the
        # re-prefill the scheduler charges) and are never re-emitted.
        self.output_tokens: List[int] = list(resume_from)
        self.cancelled = False
        self.deadline: Optional[float] = None
        self.submitted_at = submitted_at
        self.max_new_tokens = max_new_tokens
        self.resume_len = len(resume_from)
        self.stream = SimStream()
        self.submit_step = submit_step
        self.first_token_step: Optional[int] = None
        self.prefill_left = prefill_left
        self.dispatched_at: Optional[float] = None
        # Chained page hashes of the prompt (fleet KV index key
        # space); empty when the replica's KV modeling is unarmed.
        self.chain: List[int] = []
        # The whole greedy continuation is a pure function of the
        # prompt (deterministic resume bit-identity); hash it once.
        self.prompt_key = zlib.crc32(
            json.dumps(self.prompt_tokens).encode())


class SimStream:
    """The virtual wire between a modeled replica and one LB proxy
    leg: the replica pushes ``('line', dict)`` events, the transport
    awaits them; ``('dead', None)`` models the connection dying with
    the replica."""

    __slots__ = ('_buf', '_waiter', '_dead')

    def __init__(self) -> None:
        self._buf: List[Tuple[str, Any]] = []
        self._waiter: Optional[kernel_lib.SimFuture] = None
        self._dead = False

    def push_line(self, obj: Dict[str, Any]) -> None:
        self._push(('line', obj))

    def fail(self) -> None:
        self._dead = True
        self._push(('dead', None))

    def _push(self, event: Tuple[str, Any]) -> None:
        waiter, self._waiter = self._waiter, None
        if waiter is not None:
            waiter.set_result(event)
        else:
            self._buf.append(event)

    def next_event(self) -> kernel_lib.SimFuture:
        fut = kernel_lib.SimFuture()
        if self._buf:
            fut.set_result(self._buf.pop(0))
        elif self._dead:
            fut.set_result(('dead', None))
        else:
            if self._waiter is not None:
                raise RuntimeError('one consumer per stream')
            self._waiter = fut
        return fut


def expected_continuation(prompt_tokens: List[int],
                          n: int) -> List[int]:
    """The exact token ids an UNKILLED run of this prompt produces —
    the oracle the twin audits every delivered stream against (a
    resumed/spliced stream must match it byte for byte)."""
    key = zlib.crc32(
        json.dumps([int(t) for t in prompt_tokens]).encode())
    return [_token(key, i) for i in range(n)]


def _token(prompt_key: int, index: int) -> int:
    """Deterministic, process-stable token id (NEVER builtin hash():
    PYTHONHASHSEED would break the cross-run byte-identity gate). A
    killed-and-resumed request regenerates the exact continuation, so
    the LB's splice is bit-identical to an unkilled run — same
    contract the real engine's greedy resume provides."""
    return 2 + (zlib.crc32(f'{prompt_key}/{index}'.encode())
                % 200)


class ModelReplica:
    """One modeled serving replica on the virtual transport."""

    # Modeled radix index bound (mirrors the engine's bounded wire
    # summary): oldest chains evict first, journaled as removals so
    # the LB's delta mirror tracks them.
    MAX_KV_HASHES = 8192
    _KV_JOURNAL_KEEP = 1024
    _KV_WINDOW = 256

    def __init__(self, kern: kernel_lib.Kernel, url: str, *,
                 scheduler: str = 'fcfs',
                 sched_config: Optional[sched_lib.SchedulerConfig] = None,
                 slots: int = 8,
                 perf: Optional[PerfModel] = None,
                 on_request_done: Optional[Callable[..., None]] = None,
                 role: str = 'mixed',
                 kv_page: int = 0,
                 kv_ttl_s: float = 0.0,
                 kv_bytes_per_token: int = 65536,
                 kv_pull: Optional[Callable[[str], Any]] = None,
                 transfer_s: Optional[Callable[[int], float]] = None,
                 kv_stats: Optional[Dict[str, int]] = None,
                 on_kv_event: Optional[Callable[..., None]] = None
                 ) -> None:
        self.kernel = kern
        self.url = url
        self.sched = sched_lib.make(scheduler, sched_config)
        self.slots = slots
        self.perf = perf or PerfModel.default()
        self.on_request_done = on_request_done
        self.alive = True
        self.draining = False
        self.wedged = False
        self.slow_factor = 1.0
        self.corrupt_flavor: Optional[str] = None
        self.active: List[_Req] = []
        self.steps = 0
        self.decode_tokens = 0
        self._step_scheduled = False
        # Disaggregated prefill/decode modeling (docs/serving.md):
        # ``kv_page`` 0 keeps the whole plane inert — pre-existing
        # scenarios replay byte-identically. The modeled radix index
        # lives in the SAME chained-hash key space as real engines
        # (utils/prefix_hash.py), so the REAL FleetPrefixIndex folds
        # it without knowing it is modeled.
        self.role = role
        self.kv_page = int(kv_page)
        # Idle TTL — the model of decode-page-pressure eviction: a
        # prefix nobody re-touches for ``kv_ttl_s`` virtual seconds is
        # gone (LRU under allocator pressure, abstracted to idle
        # lifetime). 0 = never expires.
        self.kv_ttl_s = float(kv_ttl_s)
        self.kv_bytes_per_token = int(kv_bytes_per_token)
        self.kv_pull = kv_pull
        self.transfer_s = transfer_s
        self.kv_stats = kv_stats
        self.on_kv_event = on_kv_event
        # hash -> last-touch virtual time (insertion-ordered) + the
        # (gen, op, hash) journal build_snapshot delta-encodes from.
        self.kv_hashes: Dict[int, float] = {}
        self.kv_gen = 0
        self.kv_journal: List[Tuple[int, str, int]] = []
        self.kv_transfers = 0
        self.kv_transfer_bytes = 0
        self.kv_transfer_failures = 0
        self.kv_transfer_durs: List[float] = []
        self._kv_pending: List[_Req] = []

    # ---- ingress ---------------------------------------------------------
    def submit(self, payload: Dict[str, Any], tenant: str,
               resume_from: List[int],
               donor: Optional[str] = None) -> SimStream:
        if not self.alive:
            raise ConnectionError(f'{self.url} is dead')
        now = self.kernel.now
        if self.corrupt_flavor == 'nan':
            # The on-device sentinel tripped: the server's admission
            # edge sheds everything with the quarantined marker
            # (mirroring infer/server._admit_generate's corrupt 503)
            # until the control plane replaces the replica.
            raise ReplicaQuarantined()
        if self.draining:
            raise ReplicaShed(503, 'draining', retry_after_s=1.0)
        prompt = [int(t) for t in payload.get('tokens') or []]
        max_new = int(payload.get('max_new_tokens') or 8)
        prefill_left = max(1, math.ceil(
            len(prompt) / self.perf.prefill_tokens_per_step))
        req = _Req(tenant or sched_lib.DEFAULT_TENANT, prompt, max_new,
                   resume_from, now, self.steps, prefill_left)
        try:
            # THE real admission code: global bounds under fcfs/EDF,
            # weight-share quotas + tenant-scoped Retry-After under
            # wfq.
            self.sched.admit(req, drain_tps=self._drain_tps())
        except sched_lib.AdmissionError as e:
            raise ReplicaShed(429, str(e),
                              retry_after_s=e.retry_after_s) from e
        if self.kv_page and not self._kv_admit(req, donor):
            return req.stream   # enqueue deferred behind a KV pull
        self._enqueue_ready(req)
        return req.stream

    def _enqueue_ready(self, req: _Req) -> None:
        """The one enqueue edge — shared by plain admission and the
        deferred KV-pull path so the kernel thread's scheduler calls
        stay at a single audited site."""
        self.sched.enqueue(req)
        self._ensure_step()

    # ---- KV prefix tier (docs/serving.md "Disaggregated
    # prefill/decode") ----------------------------------------------------
    def _kv_stat(self, key: str, n: int = 1) -> None:
        if self.kv_stats is not None:
            self.kv_stats[key] = self.kv_stats.get(key, 0) + n

    def _kv_admit(self, req: _Req, donor: Optional[str]) -> bool:
        """Price the request's prefill against the modeled radix index
        and (when the LB named a donor holding a longer prefix) start
        the donor pull. Returns False when the enqueue is deferred
        until the transfer lands — the caller must NOT enqueue."""
        req.chain = prefix_hash.chain_hashes(req.prompt_tokens,
                                             self.kv_page)
        self._kv_stat('submits')
        self._kv_sweep()
        local = prefix_hash.match_depth(req.chain, self.kv_hashes)
        if local:
            self._kv_touch(req.chain[:local])
        if donor is not None and self.kv_pull is not None:
            dm = self.kv_pull(donor)
            d_depth = (prefix_hash.match_depth(req.chain, dm.kv_hashes)
                       if dm is not None and dm.alive else 0)
            if dm is None or not dm.alive:
                # The LB routed against a donor that died before the
                # pull: degrade to recompute, never an error.
                self.kv_transfer_failures += 1
                self._kv_stat('failures')
                self._kv_event(req, donor, ok=False, pages=0)
            elif d_depth > local:
                pages = d_depth - local
                nbytes = pages * self.kv_page * self.kv_bytes_per_token
                delay = (self.transfer_s(nbytes)
                         if self.transfer_s is not None else 0.0)
                self._kv_pending.append(req)
                self.kernel.call_later(
                    delay, self._kv_pull_done, req, donor, d_depth,
                    nbytes, delay)
                return False
        if local > 0:
            self._kv_stat('warm')
            self._kv_stat('local_warm')
        self._set_prefill(req, local)
        return True

    def _kv_pull_done(self, req: _Req, donor: str, d_depth: int,
                      nbytes: int, dur: float) -> None:
        """The deferred half of a donor pull: the transfer's virtual
        latency has elapsed — attach (donor still alive) or fall back
        to plain recompute (donor died mid-transfer)."""
        if req not in self._kv_pending:
            return   # this replica died first; the stream already failed
        self._kv_pending.remove(req)
        if not self.alive:
            return
        local = prefix_hash.match_depth(req.chain, self.kv_hashes)
        dm = self.kv_pull(donor) if self.kv_pull is not None else None
        if dm is None or not dm.alive:
            # Donor died mid-transfer: recompute from whatever the
            # local index already covers. Client-invisible by design.
            self.kv_transfer_failures += 1
            self._kv_stat('failures')
            self._kv_event(req, donor, ok=False, pages=d_depth - local)
        else:
            depth = max(local,
                        min(d_depth, prefix_hash.match_depth(
                            req.chain, dm.kv_hashes)))
            self._kv_add(req.chain[:depth])
            self.kv_transfers += 1
            self.kv_transfer_bytes += nbytes
            self.kv_transfer_durs.append(dur)
            del self.kv_transfer_durs[:-self._KV_WINDOW]
            self._kv_stat('transfers')
            self._kv_stat('transfer_bytes', nbytes)
            self._kv_stat('warm')
            self._kv_event(req, donor, ok=True, pages=depth - local)
            local = depth
        self._set_prefill(req, local)
        self._enqueue_ready(req)

    def _kv_event(self, req: _Req, donor: str, *, ok: bool,
                  pages: int) -> None:
        if self.on_kv_event is not None:
            self.on_kv_event(url=self.url, donor=donor, ok=ok,
                             pages=pages, tenant=req.tenant)

    def _set_prefill(self, req: _Req, warm_depth: int) -> None:
        """Re-price the prefill with ``warm_depth`` pages already
        attached — the boundary-only prefill that makes transfers
        faster than recompute."""
        warm = warm_depth * self.kv_page
        req.prefill_left = max(1, math.ceil(
            max(0, len(req.prompt_tokens) - warm)
            / self.perf.prefill_tokens_per_step))

    def _kv_add(self, hashes: List[int]) -> None:
        """Index chain links (journaled adds), evicting oldest past
        the bound (journaled removals) — the delta wire the REAL
        FleetPrefixIndex mirrors."""
        now = self.kernel.now
        for h in hashes:
            if h in self.kv_hashes:
                self.kv_hashes[h] = now   # refresh idle TTL
                continue
            self.kv_hashes[h] = now
            self.kv_gen += 1
            self.kv_journal.append((self.kv_gen, '+', h))
        while len(self.kv_hashes) > self.MAX_KV_HASHES:
            old = next(iter(self.kv_hashes))
            del self.kv_hashes[old]
            self.kv_gen += 1
            self.kv_journal.append((self.kv_gen, '-', old))
        del self.kv_journal[:-self._KV_JOURNAL_KEEP]

    def _kv_touch(self, hashes: List[int]) -> None:
        now = self.kernel.now
        for h in hashes:
            if h in self.kv_hashes:
                self.kv_hashes[h] = now

    def _kv_sweep(self) -> None:
        """Expire idle prefixes — the model of decode-page-pressure
        eviction (an untouched prefix loses its pages to the
        allocator). Journaled like any other removal so the LB mirror
        converges through the same delta wire."""
        if self.kv_ttl_s <= 0.0 or not self.kv_hashes:
            return
        cutoff = self.kernel.now - self.kv_ttl_s
        dead = [h for h, t in self.kv_hashes.items() if t < cutoff]
        for h in dead:
            del self.kv_hashes[h]
            self.kv_gen += 1
            self.kv_journal.append((self.kv_gen, '-', h))
        del self.kv_journal[:-self._KV_JOURNAL_KEEP]

    def _drain_tps(self) -> float:
        if not self.steps:
            return 0.0
        return self.decode_tokens / max(
            1e-9, self.steps * self.perf.step_s(self.slots))

    # ---- the virtual step loop -------------------------------------------
    def _ensure_step(self) -> None:
        if (self._step_scheduled or not self.alive
                or (not self.active and not self.sched.pending())):
            return
        self._step_scheduled = True
        delay = self.perf.step_s(max(1, len(self.active))) \
            * self.slow_factor
        self.kernel.call_later(delay, self._step)

    def _step(self) -> None:
        self._step_scheduled = False
        if not self.alive:
            return
        self.steps += 1
        now = self.kernel.now
        # Slot refill through the real policy (wfq rotates tenants,
        # EDF picks the most urgent, fcfs pops FIFO).
        while len(self.active) < self.slots:
            req = self.sched.pop_next()
            if req is None:
                break
            req.dispatched_at = now
            self.sched.note_queue_wait(req, now - req.submitted_at)
            self.active.append(req)
        for req in list(self.active):
            if len(req.output_tokens) >= req.max_new_tokens:
                # A resume leg whose boundary already covers the whole
                # budget (the kill landed after the last token but
                # before the done line): only the done line is owed.
                self._finish(req, 'length')
                continue
            if req.prefill_left > 0:
                req.prefill_left -= 1
                if req.prefill_left == 0 and req.chain:
                    # Prefill landed: the prompt's pages are now
                    # cached here — index the whole chain so the next
                    # sync tick advertises it fleet-wide.
                    self._kv_add(req.chain)
                continue
            self._emit_one(req)
        self._ensure_step()

    def _emit_one(self, req: _Req) -> None:
        idx = len(req.output_tokens)
        tok = _token(req.prompt_key, idx)
        if (self.corrupt_flavor == 'token_flip'
                and len(req.prompt_tokens) <= CORRUPT_SHORT_PROMPT_MAX):
            # Silent corruption: a deterministically WRONG token (the
            # oracle never emits it for this position), only on
            # prompts short enough to hit the corrupt addresses.
            tok += 1
        req.output_tokens.append(tok)
        self.decode_tokens += 1
        self.sched.note_tokens(req, 1)
        if req.first_token_step is None:
            req.first_token_step = self.steps
            self.sched.note_first_token(
                req, self.kernel.now - req.submitted_at)
        # Only post-resume-boundary tokens go on the wire (the engine's
        # resume contract — the LB already delivered the rest); the
        # budget is TOTAL output across legs, so the spliced stream
        # carries exactly max_new_tokens like an unkilled run.
        req.stream.push_line({'tokens': [tok]})
        if len(req.output_tokens) >= req.max_new_tokens:
            self._finish(req, 'length')

    def _finish(self, req: _Req, reason: str) -> None:
        self.active.remove(req)
        waited = ((req.first_token_step or self.steps)
                  - req.submit_step)
        req.stream.push_line({
            'done': True, 'finish_reason': reason,
            'queue_wait_s': round(
                (req.dispatched_at or req.submitted_at)
                - req.submitted_at, 6),
            # Scheduler-virtual fairness clock (the starvation gates
            # assert on this, not wall time — the PR 11 rule).
            'steps_waited': waited,
        })
        if self.on_request_done is not None:
            self.on_request_done(self.url, req, reason)

    # ---- failure surface -------------------------------------------------
    def _fail_all_streams(self) -> None:
        """Fail every admitted stream — active and queued — at this
        instant. Shared by kill (power loss) and poison('nan') (the
        sentinel sheds the whole batch); the LB resume splice is what
        heals the clients either way."""
        for req in self.active:
            req.stream.fail()
        self.active.clear()
        # Requests parked behind an in-flight KV pull die with the
        # replica too (their enqueue never happened).
        for req in self._kv_pending:
            req.stream.fail()
        self._kv_pending.clear()
        while True:
            req = self.sched.pop_next()
            if req is None:
                break
            req.stream.fail()

    def kill(self) -> None:
        """Hard death (spot reclaim without notice, zone outage):
        every in-flight and queued stream dies mid-flight; the LB's
        resume path is what heals the clients."""
        if not self.alive:
            return
        self.alive = False
        self._fail_all_streams()

    def poison(self, flavor: str) -> None:
        """Silent data corruption onset (bad HBM bank, flaky chip).

        ``token_flip``: the replica keeps serving but emits WRONG
        tokens for short prompts (address-localized corruption) — the
        liveness probe still passes; only the golden-probe canary's
        byte compare can see it. ``nan``: the on-device sentinel
        trips — in-flight streams die (their clients heal through the
        LB resume splice), and every new submit sheds 503 with the
        quarantined marker; the HTTP surface stays up (alive=True) so
        death-detection never fires — quarantine must come from the
        integrity plane, not the breaker."""
        if flavor not in ('token_flip', 'nan'):
            raise ValueError(f'unknown corruption flavor {flavor!r}')
        self.corrupt_flavor = flavor
        if flavor == 'nan':
            self._fail_all_streams()

    def drain_flush(self) -> None:
        """The planned handoff: stop admitting (new requests shed 503
        and reroute), then finish EVERY admitted request — active and
        queued — at the drain instant. Latency of the drain itself is
        modeled as atomic; what the twin proves is the ordering
        contract (drain before teardown ⇒ zero client-visible
        errors)."""
        self.draining = True
        while True:
            req = self.sched.pop_next()
            if req is None:
                break
            req.dispatched_at = req.dispatched_at or self.kernel.now
            self.active.append(req)
        for req in list(self.active):
            req.prefill_left = 0
            while len(req.output_tokens) < req.max_new_tokens:
                self._emit_one(req)
            if req in self.active:    # boundary-covered resume leg
                self._finish(req, 'length')

    # ---- observability (the LB's /metrics fetch) -------------------------
    def metrics_row(self, since_gen: Optional[int] = None
                    ) -> Tuple[str, int, Dict[str, Any]]:
        """The ``(url, num_waiting, eff)`` row the LB sync tick
        ingests — same keys the real ``/metrics`` fetch extracts.
        ``since_gen`` (the LB mirror's generation) asks for the
        delta-encoded radix summary, exactly like the real fetch's
        ``?prefix_gen=`` query."""
        tps = (round(self.decode_tokens / self.steps, 4)
               if self.steps else None)
        eff = {'decode_tokens': self.decode_tokens}
        if tps is not None:
            eff['tokens_per_step'] = tps
        if self.kv_page:
            self._kv_sweep()
            durs = sorted(self.kv_transfer_durs)
            eff['kv_transfers_total'] = self.kv_transfers
            eff['kv_transfer_bytes'] = self.kv_transfer_bytes
            eff['kv_transfer_failures'] = self.kv_transfer_failures
            if durs:
                eff['kv_transfer_p99_s'] = round(
                    durs[min(len(durs) - 1, int(len(durs) * 0.99))], 6)
            eff['role'] = self.role
            if since_gen is not None:
                eff['kv_prefix_index'] = prefix_hash.build_snapshot(
                    self.kv_gen,
                    prefix_hash.fold_crc(self.kv_hashes),
                    self.kv_page, self.kv_journal, self.kv_hashes,
                    since_gen)
        return self.url, self.sched.pending(), eff
