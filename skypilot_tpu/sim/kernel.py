"""Discrete-event kernel + coroutine trampoline for the digital twin.

The determinism contract (docs/robustness.md "Digital twin"): every
state change in a replay happens inside a kernel callback, callbacks
execute in strict ``(virtual_time, sequence)`` order, and the only
sources of randomness are seeded ``random.Random`` instances owned by
the scenario. No real threads, no asyncio event loop, no wall clock —
so two runs with the same seed take byte-identical decision paths.

The trampoline is what lets the REAL ``LoadBalancer.handle``
coroutine run here unmodified: the twin's transport overrides make
every ``await`` inside the request path terminate in either a plain
coroutine (runs inline, e.g. ``request.read()``) or a
:class:`SimFuture` resolved by a later kernel event (a modeled
replica's next token). ``Kernel.spawn`` drives the coroutine with
``send``/``throw`` until it completes — a ~40-line deterministic
substitute for asyncio.
"""
from __future__ import annotations

import heapq
from typing import Any, Callable, List, Optional, Tuple

from skypilot_tpu.utils import vclock


class SimFuture:
    """Minimal awaitable resolved by a kernel callback. Awaiting a
    pending future suspends the coroutine (yields the future to the
    trampoline); awaiting a resolved one continues inline — which is
    how a stream consumer drains an already-buffered burst of token
    lines without bouncing through the heap."""

    __slots__ = ('_done', '_value', '_exc', '_callbacks', '_cancel')

    def __init__(self) -> None:
        self._done = False
        self._value: Any = None
        self._exc: Optional[BaseException] = None
        self._callbacks: List[Callable[['SimFuture'], None]] = []
        # Set by Kernel.spawn on the future it returns: abandons the
        # driven coroutine (a process crash severing its connections).
        self._cancel: Optional[Callable[[], None]] = None

    def cancel(self) -> None:
        """Abandon the spawned coroutine this future tracks (no-op on
        plain futures and on already-finished ones). The crash seam of
        the kill-anywhere sweep: a killed LB's in-flight request
        coroutines stop mid-await exactly where the process died."""
        if self._cancel is not None and not self._done:
            self._cancel()

    def done(self) -> bool:
        return self._done

    def set_result(self, value: Any = None) -> None:
        if self._done:
            raise RuntimeError('SimFuture already resolved')
        self._done = True
        self._value = value
        self._fire()

    def set_exception(self, exc: BaseException) -> None:
        if self._done:
            raise RuntimeError('SimFuture already resolved')
        self._done = True
        self._exc = exc
        self._fire()

    def _fire(self) -> None:
        callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            cb(self)

    def add_done_callback(self,
                          cb: Callable[['SimFuture'], None]) -> None:
        if self._done:
            cb(self)
        else:
            self._callbacks.append(cb)

    def result(self) -> Any:
        if not self._done:
            raise RuntimeError('SimFuture pending')
        if self._exc is not None:
            raise self._exc
        return self._value

    def __await__(self):
        if not self._done:
            yield self
        if self._exc is not None:
            raise self._exc
        return self._value


class Kernel:
    """The event heap + virtual clock + trampoline."""

    def __init__(self, start: float = 0.0) -> None:
        self.clock = vclock.VirtualClock(start)
        self._heap: List[Tuple[float, int, Callable, tuple]] = []
        self._seq = 0
        self.events_run = 0

    @property
    def now(self) -> float:
        return self.clock.time()

    # ---- scheduling ------------------------------------------------------
    def call_at(self, t: float, fn: Callable, *args: Any) -> None:
        """Run ``fn(*args)`` at virtual time ``t`` (clamped to now —
        the past is not schedulable). Ties execute in scheduling
        order."""
        self._seq += 1
        heapq.heappush(self._heap,
                       (max(t, self.now), self._seq, fn, args))

    def call_later(self, delay: float, fn: Callable,
                   *args: Any) -> None:
        self.call_at(self.now + max(0.0, delay), fn, *args)

    def every(self, interval: float, fn: Callable[[], Any], *,
              start: float = 0.0, until: Optional[float] = None) -> None:
        """A fixed virtual cadence (control-loop ticks). ``fn`` runs at
        start, start+interval, ... while ``until`` allows."""
        def tick() -> None:
            fn()
            nxt = self.now + interval
            if until is None or nxt <= until:
                self.call_at(nxt, tick)
        self.call_at(start, tick)

    # ---- coroutines ------------------------------------------------------
    def create_future(self) -> SimFuture:
        return SimFuture()

    def spawn(self, coro) -> SimFuture:
        """Drive ``coro`` to completion across kernel events; the
        returned future resolves with its return value (or its
        exception — the twin inspects, never silently drops).
        ``result.cancel()`` abandons the coroutine: finally blocks run
        (GeneratorExit at the suspension point), later resolutions of
        futures it awaited are ignored, and ``result`` stays pending
        forever — the caller models the severed connection."""
        result = SimFuture()
        cancelled = [False]

        def cancel() -> None:
            if cancelled[0] or result._done:
                return
            cancelled[0] = True
            coro.close()

        result._cancel = cancel

        def advance(value: Any = None,
                    exc: Optional[BaseException] = None) -> None:
            if cancelled[0]:
                return
            try:
                if exc is not None:
                    awaited = coro.throw(exc)
                else:
                    awaited = coro.send(value)
            except StopIteration as s:
                result.set_result(s.value)
                return
            except BaseException as e:  # noqa: BLE001 — surfaced via future
                result.set_exception(e)
                return
            if not isinstance(awaited, SimFuture):
                result.set_exception(RuntimeError(
                    f'sim coroutine awaited a non-sim awaitable '
                    f'{awaited!r} — a transport seam is missing '
                    f'(asyncio primitives cannot run on the kernel)'))
                return
            awaited.add_done_callback(
                lambda f: advance(f._value, f._exc))

        advance()
        return result

    # ---- the loop --------------------------------------------------------
    def run(self, until: Optional[float] = None) -> None:
        """Execute events in (time, seq) order until the heap drains
        (or virtual ``until`` passes). Callback exceptions propagate —
        a crashed control loop must fail the replay loudly."""
        while self._heap:
            t, _, fn, args = self._heap[0]
            if until is not None and t > until:
                break
            heapq.heappop(self._heap)
            self.clock.advance_to(t)
            self.events_run += 1
            fn(*args)

    def pending(self) -> int:
        return len(self._heap)
