"""The digital twin orchestrator: one scenario → one deterministic
replay → one report.

Wiring (all REAL control-plane code, only the edges virtualized):

- a scratch ``SKY_TPU_HOME`` holds the run's serve state DB (fresh per
  run, so sqlite AUTOINCREMENT ids — which appear in the decision
  log — are identical across same-seed runs);
- the kernel's :class:`~skypilot_tpu.utils.vclock.VirtualClock` is
  installed process-wide for the replay, so every ``vclock`` read in
  ``serve/`` observes virtual time;
- the REAL :class:`ServeController` ticks at the scenario cadence
  (launch/terminate through the REAL ``ReplicaManager`` over the
  virtual cloud), the REAL LB syncs/flushes at its cadences, and
  every trace event becomes a REAL ``LoadBalancer.handle`` coroutine
  on the kernel trampoline;
- the decision log records every launch (with placement), terminate,
  drain, preemption notice, reclaim kill, autoscaler target change,
  and per-request outcome, stamped with virtual time + sequence.
  ``SimReport.decision_log_jsonl()`` is the byte-identity surface the
  determinism gate hashes.
"""
from __future__ import annotations

import json
import logging
import os
import random
import shutil
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional

import yaml

from skypilot_tpu.infer import sched as sched_lib
from skypilot_tpu.observability import integrity
from skypilot_tpu.serve import controller as controller_lib
from skypilot_tpu.serve import state as serve_state
from skypilot_tpu.serve.state import ReplicaStatus
from skypilot_tpu.sim import cloud as cloud_lib
from skypilot_tpu.sim import kernel as kernel_lib
from skypilot_tpu.sim import replica as replica_lib
from skypilot_tpu.sim import transport as transport_lib
from skypilot_tpu.sim.scenarios import Fault, KillSpec, Scenario
from skypilot_tpu.utils import common
from skypilot_tpu.utils import db as db_lib
from skypilot_tpu.utils import failpoints
from skypilot_tpu.utils import retry as retry_lib
from skypilot_tpu.utils import vclock

logger = logging.getLogger(__name__)


class SimReport:
    """Everything a gate asserts on."""

    def __init__(self, scenario: str, seed: int) -> None:
        self.scenario = scenario
        self.seed = seed
        self.decisions: List[Dict[str, Any]] = []
        self.records: List[Dict[str, Any]] = []
        self.lb_metrics: Dict[str, Any] = {}
        # VirtualCloud billing totals (market scenarios): what the
        # $-saved-at-SLO gate compares across runs.
        self.cost: Dict[str, Any] = {}
        # KV prefix tier rollup (disagg scenarios): fleet-wide
        # submit/warm/transfer/failure counters from the modeled
        # replicas — the hit-rate and fallback gates assert on these.
        self.kv: Dict[str, Any] = {}
        # End-of-replay control-plane convergence view (captured before
        # the scratch home is torn down): the crash gates compare a
        # killed run's final fleet against the unkilled baseline's.
        self.final_fleet: Dict[str, Any] = {}
        self.wall_s = 0.0
        self.events_run = 0

    # ---- rollups -------------------------------------------------------
    def _count(self, kind: str) -> int:
        return sum(1 for d in self.decisions if d['kind'] == kind)

    @property
    def launches(self) -> int:
        return self._count('launch')

    @property
    def drains(self) -> int:
        return self._count('drain')

    @property
    def reclaim_kills(self) -> int:
        return self._count('reclaim_kill')

    @property
    def preemption_notices(self) -> int:
        return self._count('preemption_notice')

    @property
    def scale_targets(self) -> List[int]:
        return [d['target'] for d in self.decisions
                if d['kind'] == 'scale_target']

    @property
    def completed(self) -> int:
        return sum(1 for r in self.records if r['completed'])

    @property
    def shed(self) -> int:
        return sum(1 for r in self.records if r['shed'])

    @property
    def resumed_requests(self) -> int:
        return sum(1 for r in self.records if r.get('resumed'))

    @property
    def crashes(self) -> int:
        return self._count('crash')

    @property
    def recoveries(self) -> List[Dict[str, Any]]:
        """The 'recover' decisions — one per controller restart, with
        the reconcile report rollup and the idempotence verdict."""
        return [d for d in self.decisions if d['kind'] == 'recover']

    @property
    def client_retries(self) -> int:
        """Streams severed by an LB kill and retried (with resume_from)
        against the restarted LB."""
        return sum(int(r.get('lb_retries') or 0) for r in self.records)

    @property
    def slo_alerts(self) -> List[Dict[str, Any]]:
        """Alert transitions from the REAL burn-rate evaluator
        (docs/observability.md "SLOs and alerting"); the fidelity
        gates assert on these."""
        return [d for d in self.decisions if d['kind'] == 'slo_alert']

    def slo_log_jsonl(self) -> str:
        """The alert decision log alone, one JSON line per
        transition — byte-identical across same-seed runs."""
        return '\n'.join(json.dumps(d, sort_keys=True)
                         for d in self.slo_alerts)

    @property
    def placements(self) -> List[Dict[str, Any]]:
        """The FleetPlacer's per-tick decisions (cost-optimized
        scenarios only; docs/cost.md)."""
        return [d for d in self.decisions if d['kind'] == 'place']

    def placement_log_jsonl(self) -> str:
        """The placer decision log alone — the cost gate's
        byte-identity surface (same seed ⇒ identical string)."""
        return '\n'.join(json.dumps(d, sort_keys=True)
                         for d in self.placements)

    @property
    def client_errors(self) -> List[Dict[str, Any]]:
        """Client-visible failures: anything that neither completed
        nor was an orderly admission shed (the zero-errors gates
        assert this list is empty)."""
        return [r for r in self.records
                if not r['completed'] and not r['shed']]

    def tenant_summary(self) -> Dict[str, Dict[str, Any]]:
        from tests.load_tests import loadgen
        return loadgen.tenant_summary(self.records)

    def decision_log_jsonl(self) -> str:
        """The byte-identity surface: same seed ⇒ identical string."""
        return '\n'.join(
            json.dumps(d, sort_keys=True) for d in self.decisions)

    def summary(self) -> Dict[str, Any]:
        return {
            'scenario': self.scenario, 'seed': self.seed,
            'virtual_events': self.events_run,
            'wall_s': round(self.wall_s, 3),
            'requests': len(self.records),
            'completed': self.completed, 'shed': self.shed,
            'client_errors': len(self.client_errors),
            'resumed_requests': self.resumed_requests,
            'launches': self.launches, 'drains': self.drains,
            'preemption_notices': self.preemption_notices,
            'reclaim_kills': self.reclaim_kills,
            'crashes': self.crashes,
            'client_retries': self.client_retries,
            'final_fleet': self.final_fleet,
            'scale_targets': self.scale_targets,
            'placements': len(self.placements),
            'cost': self.cost,
            'kv': self.kv,
            'fleet_prefix_hit_rate': self.lb_metrics.get(
                'fleet_prefix_hit_rate'),
            'cold_starts': self.lb_metrics.get('cold_starts_total'),
            'ready_replicas': self.lb_metrics.get('ready_replicas'),
            'lb_ttft_p50_s': self.lb_metrics.get('ttft_p50_s'),
            'lb_ttft_p99_s': self.lb_metrics.get('ttft_p99_s'),
        }


class _ClientCall:
    """One logical client request across LB crash-restarts: a severed
    leg's delivered tokens become the next leg's ``resume_from`` (the
    SDK-visible half of PR 5's resume splice)."""

    __slots__ = ('ev', 't0', 'resume', 'retries', 'req', 'fut')

    def __init__(self, ev, t0: float) -> None:
        self.ev = ev
        self.t0 = t0
        self.resume: List[int] = []
        self.retries = 0
        self.req: Optional[transport_lib.SimRequest] = None
        self.fut: Optional[kernel_lib.SimFuture] = None


class DigitalTwin:
    """One replay of one scenario at one seed. ``kill`` injects an
    extra :class:`KillSpec` on top of the scenario's own (the
    kill-anywhere sweep's per-boundary knob)."""

    SERVICE = 'twin'

    def __init__(self, scenario: Scenario, seed: int = 0, *,
                 keep_home: bool = False,
                 kill: Optional[KillSpec] = None) -> None:
        self.sc = scenario
        self.seed = seed
        self.keep_home = keep_home
        self.kernel = kernel_lib.Kernel()
        self.report = SimReport(scenario.name, seed)
        self._perf = self._make_perf()
        self._cloud: Optional[cloud_lib.VirtualCloud] = None
        self._lb: Optional[transport_lib.TwinLoadBalancer] = None
        self._controller = None
        self._executor: Optional[cloud_lib.SimExecutor] = None
        # Kill-anywhere machinery (docs/robustness.md "Crash safety").
        self.kills: List[KillSpec] = list(scenario.kills)
        if kill is not None:
            self.kills.append(kill)
        self._kills_fired: set = set()
        # Armed between a controller kill and its restart: the next
        # VirtualCloud crash-window gate tears the op on the stack
        # (slice created / drain done, DB not yet written).
        self._crash_armed = False
        # In-flight logical client calls (insertion-ordered — the kill
        # handler's severing order is deterministic) and legs parked
        # while the LB is dead.
        self._inflight_calls: Dict[int, _ClientCall] = {}
        self._pending_legs: List[_ClientCall] = []
        # Disagg role carving: launch-order-deterministic, so the
        # prefill/decode split is identical across same-seed runs.
        self._replicas_made = 0
        self._prefill_made = 0
        self._kv_stats: Dict[str, int] = {}
        # One-shot donor trap (the 'donor_reclaim' fault): the next
        # donor pull after arming gets its donor hard-killed
        # mid-transfer — the deterministic worst-case race the
        # recompute fallback exists for.
        self._donor_trap = False

    # ---- pieces --------------------------------------------------------
    def _make_perf(self) -> replica_lib.PerfModel:
        if self.sc.bench_json:
            perf = replica_lib.PerfModel.from_bench_json(
                self.sc.bench_json, scale=self.sc.perf_scale)
        else:
            perf = replica_lib.PerfModel.default(
                scale=self.sc.perf_scale)
        if self.sc.prefill_tokens_per_step is not None:
            perf.prefill_tokens_per_step = float(
                self.sc.prefill_tokens_per_step)
        return perf

    def _log(self, kind: str, **fields: Any) -> None:
        self.report.decisions.append(
            {'t': round(self.kernel.now, 6),
             'seq': len(self.report.decisions), 'kind': kind,
             **fields})
        # Kill-anywhere boundary injection: a KillSpec armed at this
        # decision's seq fires the virtual kill -9 the instant the
        # decision lands — if the decision was logged from inside a
        # cloud-facing op (launch/drain/terminate), the crash gate
        # tears that op at its real crash window before it can write
        # the DB.
        seq = len(self.report.decisions) - 1
        for i, k in enumerate(self.kills):
            if (k.at_seq is not None and k.at_seq == seq
                    and i not in self._kills_fired):
                self._kills_fired.add(i)
                self._kill(k.target, k.restart_delay_s)

    def _make_replica(self, url: str) -> replica_lib.ModelReplica:
        cfg = sched_lib.SchedulerConfig(
            max_queue_requests=self.sc.max_queue_requests,
            max_queue_tokens=self.sc.max_queue_tokens,
            tenant_weights=self.sc.tenant_weights)
        kw: Dict[str, Any] = {}
        if self.sc.kv_page:
            # Role carve by launch order: keep the prefill pool at
            # ``prefill_fraction`` of the fleet as launches accrue.
            self._replicas_made += 1
            role = 'mixed'
            if (self.sc.prefill_fraction > 0
                    and self._prefill_made < self.sc.prefill_fraction
                    * self._replicas_made):
                self._prefill_made += 1
                role = 'prefill'
            kw = {
                'role': role, 'kv_page': self.sc.kv_page,
                'kv_ttl_s': self.sc.kv_ttl_s,
                'kv_bytes_per_token': self.sc.kv_bytes_per_token,
                'kv_pull': self._kv_donor_model,
                'transfer_s': self._cloud.kv_transfer_s,
                'kv_stats': self._kv_stats,
                'on_kv_event': self._on_kv_transfer,
            }
        return replica_lib.ModelReplica(
            self.kernel, url, scheduler=self.sc.scheduler,
            sched_config=cfg, slots=self.sc.slots, perf=self._perf,
            **kw)

    def _kv_donor_model(self, url: str):
        """Donor resolver for modeled pulls: the donor's model while
        its slice is still alive (a reclaimed donor resolves to a
        dead model — the recompute-fallback path). An armed
        ``donor_reclaim`` trap reclaims the donor's slice halfway
        through the transfer floor — the pull was admitted against a
        live donor and completes against a dead one."""
        model = self._model_by_url(url)
        if self._donor_trap and model is not None and model.alive:
            cluster = next(
                (k for k in sorted(self._cloud.slices)
                 if self._cloud.slices[k].url == url
                 and self._cloud.slices[k].alive), None)
            if cluster is not None:
                self._donor_trap = False
                self.kernel.call_later(
                    self.sc.kv_transfer_floor_s * 0.5,
                    self._cloud.hard_kill, cluster)
        return model

    def _on_kv_transfer(self, **fields: Any) -> None:
        """Every modeled KV transfer outcome lands in the decision
        log (the byte-identity surface) — the disagg gates assert
        transfer and fallback counts from here too."""
        self._log('kv_transfer', **fields)

    def _model_by_url(self, url: str):
        s = self._cloud.by_url.get(url)
        return s.model if s is not None else None

    def _service_config(self) -> Dict[str, Any]:
        sc = self.sc
        floor = (sc.replicas if sc.min_replicas is None
                 else sc.min_replicas)
        policy: Dict[str, Any] = {'min_replicas': floor}
        if sc.max_replicas is not None:
            policy['max_replicas'] = sc.max_replicas
        if sc.queue_length_threshold is not None:
            policy['queue_length_threshold'] = sc.queue_length_threshold
        policy['upscale_delay_seconds'] = sc.upscale_delay_s
        policy['downscale_delay_seconds'] = sc.downscale_delay_s
        # Cost plane + scale-to-zero (docs/cost.md): the REAL spec
        # validation sees these — a scenario declaring min_replicas 0
        # without a wake policy fails exactly like a user task would.
        if sc.cost_optimized:
            policy['cost_optimized'] = True
            policy['relaunch_overhead_seconds'] = sc.relaunch_overhead_s
        if sc.wake_on_request:
            policy['wake_on_request'] = True
            policy['max_parked_requests'] = sc.max_parked_requests
        config = {
            'readiness_probe': {
                'path': '/health',
                'initial_delay_seconds': sc.initial_delay_s,
                'success_threshold': 1, 'failure_threshold': 3},
            'replica_policy': policy,
            'load_balancing_policy': sc.lb_policy,
        }
        if sc.slo is not None:
            config['slo'] = sc.slo
        return config

    # ---- traffic -------------------------------------------------------
    def _synthesize(self) -> list:
        if self.sc.trace_events is not None:
            # Recorded trace (docs/simulation.md): replay the
            # arrivals verbatim — the trace IS the workload, the seed
            # only drives service-side stochastics.
            return list(self.sc.trace_events)
        from tests.load_tests import loadgen
        return loadgen.synthesize(
            self.seed, self.sc.tenants,
            duration_s=max(0.0,
                           self.sc.duration_s - self.sc.traffic_start_s))

    def _fire_request(self, ev) -> None:
        self._start_leg(_ClientCall(ev, self.kernel.now))

    def _start_leg(self, call: _ClientCall) -> None:
        """Issue (or re-issue) one logical request against the current
        LB. With the LB dead — mid crash-restart — the leg parks and
        the restarted LB replays it, exactly like an SDK retry loop
        waiting out a connection refused."""
        if self._lb is None:
            self._pending_legs.append(call)
            return
        ev = call.ev
        payload: Dict[str, Any] = {
            'tokens': ev.tokens, 'max_new_tokens': ev.max_new_tokens,
            'stream': True, 'tenant': ev.tenant}
        if call.resume:
            # The client-side half of PR 5's resume splice: tokens the
            # dead LB already delivered seed resume_from, so the new
            # stream emits only the undelivered tail.
            payload['resume_from'] = list(call.resume)
        call.req = transport_lib.SimRequest(
            '/generate', json.dumps(payload).encode(),
            headers={common.TENANT_HEADER: ev.tenant})
        call.fut = self.kernel.spawn(self._lb.handle(call.req))
        self._inflight_calls[id(call)] = call
        call.fut.add_done_callback(
            lambda f, c=call: self._on_leg_done(c, f))

    def _on_leg_done(self, call: _ClientCall,
                     fut: kernel_lib.SimFuture) -> None:
        if self._inflight_calls.pop(id(call), None) is None:
            return   # severed by an LB kill; the retry leg owns it
        ev = call.ev
        rec: Dict[str, Any] = {
            'tenant': ev.tenant, 'shed': False, 'completed': False,
            'resumed': 0, 'tokens': 0, 'ttft': None,
            'queue_wait': None, 'steps_waited': None,
            'finish_reason': None, 'itls': [],
            'lb_retries': call.retries}
        try:
            resp = fut.result()
        except BaseException as e:  # noqa: BLE001 — a gate failure, kept loud
            rec['finish_reason'] = f'exception_{type(e).__name__}: {e}'
            self.report.records.append(rec)
            self._log('request', tenant=ev.tenant,
                      outcome=rec['finish_reason'])
            return
        if isinstance(resp, transport_lib.SimStreamResponse):
            done_line = None
            token_ids: List[int] = list(call.resume)
            for line in resp.lines():
                toks = line.get('tokens')
                if isinstance(toks, list):
                    token_ids.extend(toks)
                if line.get('done'):
                    done_line = line
                if 'error' in line:
                    rec['finish_reason'] = 'stream_error'
            rec['tokens'] = len(token_ids)
            if done_line is not None and rec['finish_reason'] is None:
                rec['completed'] = True
                # Bit-identity audit: whatever failovers, resumes, and
                # LB crash-retries happened on the way, the tokens the
                # client holds must equal the deterministic unkilled
                # continuation, full length — no loss, no dupes.
                rec['tokens_ok'] = (
                    token_ids == replica_lib.expected_continuation(
                        ev.tokens, ev.max_new_tokens))
                rec['finish_reason'] = done_line.get('finish_reason')
                rec['resumed'] = int(done_line.get('resumed') or 0)
                rec['queue_wait'] = done_line.get('queue_wait_s')
                rec['steps_waited'] = done_line.get('steps_waited')
            elif rec['finish_reason'] is None:
                rec['finish_reason'] = 'truncated'
        else:
            status = getattr(resp, 'status', None)
            if status in (429, 503):
                rec['shed'] = True
                rec['finish_reason'] = f'shed_{status}'
            else:
                rec['finish_reason'] = f'http_{status}'
        self.report.records.append(rec)
        extra = {'retries': call.retries} if call.retries else {}
        self._log('request', tenant=ev.tenant,
                  outcome=rec['finish_reason'],
                  tokens=rec['tokens'], resumed=rec['resumed'],
                  **extra)

    # ---- process kills (docs/robustness.md "Crash safety") -------------
    def _crash_gate(self, window: str) -> None:
        """Installed as the VirtualCloud's crash gate: when a
        controller kill just landed, tear the cloud-facing op on the
        stack at its real crash window (after the provider
        side-effect, before the manager's DB write)."""
        if self._crash_armed:
            self._crash_armed = False
            raise cloud_lib.SimCrashError(window)

    def _kill(self, target: str, restart_delay_s: float) -> None:
        if target == 'controller':
            self._kill_controller(restart_delay_s)
        elif target == 'lb':
            self._kill_lb(restart_delay_s)
        else:
            raise ValueError(f'unknown kill target {target!r}')

    def _kill_controller(self, restart_delay_s: float) -> None:
        if self._controller is None:
            return   # already dead (overlapping kills)
        self._controller = None
        # The thread pool dies with the process: queued launches and
        # teardowns never run; the one on the stack (if any) is torn
        # by the crash gate at its window.
        self._executor.kill()
        self._crash_armed = True
        self._log('crash', target='controller')
        self.kernel.call_later(restart_delay_s,
                               self._restart_controller)

    def _restart_controller(self) -> None:
        self._crash_armed = False
        self._executor = cloud_lib.SimExecutor(self.kernel)
        self._controller = controller_lib.ServeController(
            self.SERVICE, cloud=self._cloud, executor=self._executor,
            cost_catalog=getattr(self, '_cost_catalog', None))
        self._controller.place_hook = self._on_place
        # Startup reconciliation, run TWICE: the second pass must be a
        # no-op (the idempotence half of the acceptance gate — rolled
        # into every killed replay, not just the unit test).
        rep = self._controller.rm.reconcile(now=self.kernel.now)
        rep2 = self._controller.rm.reconcile(now=self.kernel.now)
        self._log('recover', target='controller',
                  adopted=len(rep['adopted']),
                  rolled_back=len(rep['rolled_back']),
                  resolved=len(rep['resolved']),
                  resumed_teardowns=len(rep['resumed_teardowns']),
                  second_pass_noop=not any(rep2.values()))

    def _kill_lb(self, restart_delay_s: float) -> None:
        if self._lb is None:
            return
        self._lb = None
        calls = list(self._inflight_calls.values())
        self._inflight_calls.clear()
        for call in calls:
            # The process died: its proxy coroutines stop mid-await
            # (finally blocks run, like sockets closing), and the
            # client keeps what was already flushed to it.
            call.fut.cancel()
            splice = call.req.splice if call.req is not None else None
            if splice is not None:
                call.resume.extend(int(t) for t in splice.delivered)
            call.retries += 1
            self._pending_legs.append(call)
        self._log('crash', target='lb', severed=len(calls))
        self.kernel.call_later(restart_delay_s, self._restart_lb)

    def _make_lb(self) -> transport_lib.TwinLoadBalancer:
        """Build the twin's LB (initial boot and crash-restarts take
        the identical path). When the scenario arms golden probes, the
        fixture is minted from the live sim oracle — the same mint
        ``make golden-refresh`` performs — so the LB's arm-time
        fingerprint gate runs for real."""
        sc = self.sc
        fixture = fingerprint = None
        if sc.probe_interval_s is not None:
            prompt = (2, 3, 5, 7)
            golden = replica_lib.expected_continuation(list(prompt), 4)
            fingerprint = replica_lib.oracle_fingerprint()
            fixture = integrity.GoldenFixture(
                model='sim', fingerprint=fingerprint,
                prompt_tokens=prompt, max_new_tokens=4,
                token_crc=integrity.token_crc(golden))
        lb = transport_lib.TwinLoadBalancer(
            self.SERVICE, sc.lb_policy, clock=self.kernel.clock,
            model_by_url=self._model_by_url, kernel=self.kernel,
            probe_fixture=fixture, probe_fingerprint=fingerprint,
            probe_interval_s=sc.probe_interval_s,
            fleet_routing=sc.fleet_routing)
        lb.sync_interval_s = sc.lb_sync_s
        lb.stats_flush_s = sc.stats_flush_s
        lb.slo_transition_hook = self._on_slo_transition
        lb.quarantine_hook = self._on_quarantine
        return lb

    def _on_quarantine(self, url: str, replica_id: int,
                       reason: str) -> None:
        """Every quarantine verdict lands in the decision log (the
        byte-identity surface): the sdc_storm gates assert count,
        latency, and the false-positive scenarios assert absence."""
        self._log('quarantine', url=url, replica_id=replica_id,
                  reason=reason)

    def _restart_lb(self) -> None:
        self._lb = self._make_lb()
        # The crash-restart rebuild under test: ready set, affinity
        # ring, and breaker state repopulated from serve_state before
        # the first retried leg lands.
        self.kernel.spawn(self._lb.bootstrap_from_state())
        self._breakers_open = set()
        self._log('lb_restart',
                  ready=len(self._lb.policy.ready_urls),
                  replayed=len(self._pending_legs))
        legs, self._pending_legs = self._pending_legs, []
        for call in legs:
            self._start_leg(call)

    # ---- faults --------------------------------------------------------
    def _apply_fault(self, fault: Fault) -> None:
        rng = random.Random(f'fault/{self.seed}/{fault.kind}/{fault.t}')
        cloud = self._cloud
        if fault.kind == 'reclaim_storm':
            victims = [s for s in cloud.live_slices() if s.is_spot]
            n = max(1, round(len(victims) * fault.frac))
            chosen = rng.sample(victims, min(n, len(victims)))
            self._log('storm', victims=len(chosen),
                      fleet=len(victims))
            for s in chosen:
                if rng.random() < fault.notice_frac:
                    cloud.reclaim(s.cluster_name,
                                  notice_lead_s=fault.notice_lead_s)
                else:
                    cloud.reclaim(s.cluster_name)
        elif fault.kind == 'donor_reclaim':
            # Targeted spot reclaim of the active KV donor, timed by
            # the trap to land mid-transfer (docs/serving.md
            # "Disaggregated prefill/decode") — makes the gate's
            # recompute-fallback assertion non-vacuous by
            # construction instead of by storm luck.
            self._donor_trap = True
            self._log('donor_trap_armed')
        elif fault.kind == 'zone_outage':
            cloud.zone_outage(fault.zone)
        elif fault.kind == 'brownout':
            live = cloud.live_slices()
            n = max(1, round(len(live) * fault.frac))
            chosen = rng.sample(live, min(n, len(live)))
            self._log('brownout', victims=len(chosen),
                      factor=fault.factor,
                      duration_s=fault.duration_s)
            for s in chosen:
                s.model.slow_factor = fault.factor
                self.kernel.call_later(
                    fault.duration_s,
                    lambda m=s.model: setattr(m, 'slow_factor', 1.0))
        elif fault.kind == 'wedge':
            live = cloud.live_slices()
            chosen = rng.sample(live, min(fault.count, len(live)))
            self._log('wedge', victims=[s.cluster_name for s in chosen],
                      duration_s=fault.duration_s)
            for s in chosen:
                s.model.wedged = True
                self.kernel.call_later(
                    fault.duration_s,
                    lambda m=s.model: setattr(m, 'wedged', False))
        elif fault.kind == 'sdc':
            # Silent data corruption (docs/robustness.md "Data
            # integrity"): poison healthy replicas — liveness probes
            # stay green; only the golden probes / sentinel self-
            # reports can see it. Never un-poisoned: detection and
            # replacement IS the recovery path under test.
            live = [s for s in cloud.live_slices()
                    if s.model.corrupt_flavor is None]
            chosen = rng.sample(live, min(fault.count, len(live)))
            self._log('sdc', flavor=fault.flavor,
                      victims=[s.cluster_name for s in chosen])
            for s in chosen:
                s.model.poison(fault.flavor)
        else:
            raise ValueError(f'unknown fault kind {fault.kind!r}')

    # ---- control loops -------------------------------------------------
    def _on_place(self, fields: Dict[str, Any]) -> None:
        """Every FleetPlacer plan lands in the decision log — the
        cost gate's byte-identity surface (docs/cost.md)."""
        self._log('place', **fields)

    def _on_slo_transition(self, tr: Dict[str, Any]) -> None:
        """Alert transitions from the REAL burn-rate evaluator land
        in the decision log (the byte-identity surface): the
        alert-fidelity gates assert firing/resolve times and the
        zero-false-positive scenarios assert absence."""
        self._log('slo_alert', objective=tr['objective'],
                  tier=tr['tier'], state=tr['state'],
                  burn_short=tr['burn_short'],
                  burn_long=tr['burn_long'])

    def _watch_breakers(self) -> None:
        """Log breaker state EDGES into the decision log (the
        breaker-flap gate asserts open ↦ re-closed; the REAL breaker
        decides, the twin only observes)."""
        if self._lb is None:
            return
        open_now = {u for u, s in self._lb.breaker.snapshot().items()
                    if s != retry_lib.STATE_CLOSED}
        prev = getattr(self, '_breakers_open', set())
        for url in sorted(open_now - prev):
            self._log('breaker_open', url=url)
        for url in sorted(prev - open_now):
            self._log('breaker_closed', url=url)
        self._breakers_open = open_now

    def _controller_tick(self) -> None:
        if self._controller is None:
            return   # dead between kill and restart
        before = self._controller.autoscaler.target_num_replicas
        try:
            self._controller.tick(now=self.kernel.now)
        except failpoints.FailpointError:
            # The serve.controller.crash failpoint at the tick
            # boundary, armed from the environment: becomes a virtual
            # process kill (the kill-anywhere seam composes with
            # env-driven chaos like every other failpoint mirror).
            self._kill('controller', restart_delay_s=30.0)
            return
        after = self._controller.autoscaler.target_num_replicas
        if after != before:
            self._log('scale_target', target=after)

    # ---- the replay ----------------------------------------------------
    def run(self) -> SimReport:
        home = tempfile.mkdtemp(prefix='sky-tpu-twin-')
        prev_home = os.environ.get(common.HOME_ENV_VAR)
        os.environ[common.HOME_ENV_VAR] = home
        t_wall = time.perf_counter()
        try:
            with vclock.installed(self.kernel.clock):
                self._setup()
                self.kernel.run()
                if self._lb is not None:
                    self.report.lb_metrics = self._lb.lb_metrics()
                if self._cloud is not None:
                    self.report.cost = self._cloud.billing()
                if self._kv_stats:
                    self.report.kv = dict(sorted(
                        self._kv_stats.items()))
                self.report.final_fleet = self._final_fleet()
        finally:
            if prev_home is None:
                os.environ.pop(common.HOME_ENV_VAR, None)
            else:
                os.environ[common.HOME_ENV_VAR] = prev_home
            if not self.keep_home:
                # Close the scratch DB's cached connection BEFORE the
                # rmtree — an open handle would pin the unlinked file's
                # disk space (and one fd per replay) until process exit.
                db_lib.evict_under(home)
                shutil.rmtree(home, ignore_errors=True)
        self.report.wall_s = time.perf_counter() - t_wall
        self.report.events_run = self.kernel.events_run
        return self.report

    def _final_fleet(self) -> Dict[str, Any]:
        """End-of-replay convergence view: the crash gates assert a
        killed-and-recovered run lands on the SAME fleet state as the
        unkilled baseline — same ready count, nothing stuck mid-
        transition, an empty intent journal."""
        rows = serve_state.get_replicas(self.SERVICE)
        statuses: Dict[str, int] = {}
        for r in rows:
            s = r['status'].value
            statuses[s] = statuses.get(s, 0) + 1
        transitional = (ReplicaStatus.PENDING, ReplicaStatus.PROVISIONING,
                        ReplicaStatus.STARTING, ReplicaStatus.DRAINING,
                        ReplicaStatus.SHUTTING_DOWN,
                        ReplicaStatus.QUARANTINED)
        record = serve_state.get_service(self.SERVICE)
        return {
            'service_status': (record['status'].value
                               if record is not None else None),
            'ready': statuses.get('READY', 0),
            'transitional': sum(statuses.get(s.value, 0)
                                for s in transitional),
            'open_intents': serve_state.count_open_intents(self.SERVICE),
            'statuses': statuses,
            # Provider-side truth: dead-but-uncleaned slices linger
            # here — a stranded carcass cleanup is invisible to the
            # replica table (PREEMPTED is terminal) but not to the
            # cloud.
            'cloud_slices': (len(self._cloud.slices)
                             if self._cloud is not None else None),
        }

    def _setup(self) -> None:
        sc = self.sc
        # The replay's state DB is scratch (fresh dir, deleted after):
        # skip fsync so 10k+ virtual-day commits don't buy durability
        # nobody needs. Production DBs never see this pragma.
        serve_state._db().conn.execute(  # noqa: SLF001
            'PRAGMA synchronous=OFF')
        task_yaml = yaml.safe_dump({
            'name': 'twin-svc', 'run': 'serve',
            'resources': {'use_spot': bool(sc.use_spot)}})
        ok = serve_state.add_service(
            self.SERVICE, json.dumps(self._service_config()), task_yaml,
            lb_port=0, lb_policy=sc.lb_policy)
        if not ok:
            raise RuntimeError('twin service row already exists — '
                               'scratch home is not scratch')
        market = dict(sc.market or {})
        self._cloud = cloud_lib.VirtualCloud(
            self.kernel, make_replica=self._make_replica,
            log=self._log,
            zones=sc.zones or (sorted(market) or None),
            provision_delay_s=sc.provision_delay_s, seed=self.seed,
            market=market, market_horizon_s=sc.duration_s,
            kv_link_gbps=sc.kv_link_gbps,
            kv_transfer_floor_s=sc.kv_transfer_floor_s)
        self._cloud.crash_gate = self._crash_gate
        # Cost-optimized scenarios run the REAL FleetPlacer against a
        # catalog built from the same market the cloud bills — per
        # replica-hour, accelerator-agnostic ('sim').
        self._cost_catalog = None
        if sc.cost_optimized:
            from skypilot_tpu.serve import costplane
            self._cost_catalog = costplane.FleetCatalog(entries=[
                costplane.ZoneEconomics(
                    accelerator='sim', region=region, zone=zone,
                    ondemand_price=float(econ['ondemand']),
                    spot_price=float(econ['spot']),
                    preemption_rate_per_hour=float(
                        econ.get('reclaim_per_hour') or 0.0))
                for (region, zone), econ in sorted(market.items())])
        self._executor = cloud_lib.SimExecutor(self.kernel)
        self._controller = controller_lib.ServeController(
            self.SERVICE, cloud=self._cloud, executor=self._executor,
            cost_catalog=self._cost_catalog)
        self._controller.place_hook = self._on_place
        self._lb = self._make_lb()
        # Control loops at their virtual cadences. The kernel's
        # trampoline drives the LB's REAL async bodies; every await
        # inside resolves inline (the twin's _offload) so each spawn
        # completes within its event.
        self.kernel.every(sc.controller_tick_s, self._controller_tick,
                          until=sc.duration_s)

        def check_lb_crash(fut: kernel_lib.SimFuture) -> None:
            # The serve.lb.crash failpoint fires at the top of the
            # REAL _sync_once; env-armed, it becomes a virtual LB
            # process kill here (same composition rule as the
            # lb.proxy mirrors).
            if isinstance(fut._exc,  # noqa: SLF001
                          failpoints.FailpointError):
                self._kill('lb', restart_delay_s=30.0)

        def lb_sync() -> None:
            if self._lb is None:
                return
            fut = self.kernel.spawn(self._lb._sync_once())  # noqa: SLF001
            fut.add_done_callback(check_lb_crash)
            self._watch_breakers()

        def stats_flush() -> None:
            if self._lb is not None:
                self.kernel.spawn(
                    self._lb._flush_stats_once())  # noqa: SLF001

        self.kernel.every(sc.lb_sync_s, lb_sync,
                          start=sc.lb_sync_s * 0.5,
                          until=sc.duration_s)
        self.kernel.every(sc.stats_flush_s, stats_flush,
                          start=sc.stats_flush_s * 0.7,
                          until=sc.duration_s)
        # Traffic.
        for ev in self._synthesize():
            self.kernel.call_at(sc.traffic_start_s + ev.t,
                                self._fire_request, ev)
        # Faults.
        for fault in sc.faults:
            self.kernel.call_at(fault.t, self._apply_fault, fault)
        # Scheduled process kills (crash scenarios; seq-armed kills
        # fire from _log instead).
        for i, k in enumerate(self.kills):
            if k.at_t is not None:
                def fire(idx=i, spec=k) -> None:
                    if idx not in self._kills_fired:
                        self._kills_fired.add(idx)
                        self._kill(spec.target, spec.restart_delay_s)
                self.kernel.call_at(k.at_t, fire)
