"""The kill-anywhere crash-consistency sweep (docs/robustness.md
"Crash safety").

One seeded storm replay runs unkilled to produce the baseline decision
log and final fleet state. Then, for EVERY control-plane decision
boundary in that log — every launch, drain, terminate, preemption
notice, reclaim kill, storm, and autoscaler move — the same scenario
replays with a virtual ``kill -9`` of the controller (and, in a second
pass, the LB) injected exactly at that boundary, followed by a
restart. A kill armed at a cloud-facing decision tears the operation
at its real crash window (slice created / drain done, DB not yet
written) via the VirtualCloud crash gate.

Each killed replay must prove the whole crash-safety contract at once:

- **zero client-visible errors** — streams severed by the dead LB are
  retried with ``resume_from`` and every completed stream's tokens are
  bit-identical to the unkilled continuation;
- **convergence** — the recovered control plane lands on the SAME
  final fleet state as the baseline (same ready count, nothing stuck
  mid-transition, an empty intent journal);
- **idempotent recovery** — the restarted controller runs startup
  reconciliation twice and the second pass is a no-op.

Request-outcome decisions are not kill boundaries: the control plane's
crash windows are its own mutations, and killing it after client
stream #217 vs #218 exercises the identical recovery path (the
mid-stream cases are covered by the LB-target sweep severing whatever
is in flight at each control boundary).

``run_crash_sweep`` is the ``make sim-crash-sweep`` / tier-1 entry;
its ``log`` string (every killed run's decision log, concatenated) is
the byte-identity surface the determinism gate hashes.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

from skypilot_tpu.sim.scenarios import KillSpec, Scenario
from skypilot_tpu.sim.twin import DigitalTwin, SimReport

# Decision kinds that are control-plane mutations — the kill
# boundaries. Everything else in the log (per-request outcomes,
# breaker-edge observations) observes the control plane rather than
# mutating it.
CONTROL_KINDS = frozenset((
    'launch', 'terminate', 'drain', 'preemption_notice',
    'reclaim_kill', 'storm', 'zone_outage', 'scale_target',
    'brownout', 'wedge'))


def control_boundaries(report: SimReport) -> List[int]:
    """Decision-log seqs of every control-plane mutation."""
    return [d['seq'] for d in report.decisions
            if d['kind'] in CONTROL_KINDS]


def check_run(report: SimReport, baseline: SimReport) -> List[str]:
    """The per-killed-run acceptance checks; returns human-readable
    violations (empty = the run passed)."""
    problems: List[str] = []
    if report.client_errors:
        problems.append(
            f'{len(report.client_errors)} client-visible error(s); '
            f'first: {report.client_errors[0]}')
    bad_tokens = [r for r in report.records
                  if r['completed'] and not r.get('tokens_ok')]
    if bad_tokens:
        problems.append(
            f'{len(bad_tokens)} completed stream(s) diverged from the '
            f'unkilled continuation; first: {bad_tokens[0]}')
    ff, bf = report.final_fleet, baseline.final_fleet
    if ff.get('ready') != bf.get('ready'):
        problems.append(
            f"final ready count {ff.get('ready')} != baseline "
            f"{bf.get('ready')}")
    if ff.get('transitional'):
        problems.append(
            f"{ff['transitional']} replica(s) stuck mid-transition: "
            f"{ff.get('statuses')}")
    if ff.get('open_intents'):
        problems.append(
            f"{ff['open_intents']} intent(s) still open — recovery "
            f'left journal entries behind')
    if ff.get('cloud_slices') != bf.get('cloud_slices'):
        problems.append(
            f"provider holds {ff.get('cloud_slices')} slice(s) vs "
            f"baseline {bf.get('cloud_slices')} — a carcass leaked "
            f'(or a teardown over-fired)')
    for rec in report.recoveries:
        if not rec.get('second_pass_noop'):
            problems.append(
                f'reconciliation was not idempotent at t={rec["t"]}: '
                f'{rec}')
    return problems


def run_crash_sweep(factory: Callable[[], Scenario], *, seed: int = 3,
                    targets: Sequence[str] = ('controller', 'lb'),
                    restart_delay_s: float = 30.0,
                    stride: int = 1,
                    on_progress: Optional[Callable[[str], None]] = None
                    ) -> Dict[str, Any]:
    """Sweep kills across every control boundary (``stride`` thins the
    boundary list for quick local runs; tier-1 uses 1). Returns::

        {'baseline': SimReport, 'boundaries': [...], 'runs': [...],
         'failures': [...], 'log': '<concatenated decision logs>'}

    ``failures`` empty means the kill-anywhere gate holds; ``log`` is
    byte-identical across same-seed sweeps (the determinism gate).
    """
    baseline = DigitalTwin(factory(), seed=seed).run()
    base_problems = check_run(baseline, baseline)
    if base_problems:
        raise AssertionError(
            f'baseline replay is not clean, the sweep would prove '
            f'nothing: {base_problems}')
    boundaries = control_boundaries(baseline)[::max(1, stride)]
    if not boundaries:
        raise AssertionError('baseline log has no control-plane '
                             'decisions — wrong scenario?')
    runs: List[Dict[str, Any]] = []
    failures: List[Dict[str, Any]] = []
    logs: List[str] = [baseline.decision_log_jsonl()]
    for target in targets:
        for seq in boundaries:
            spec = KillSpec(target=target, at_seq=seq,
                            restart_delay_s=restart_delay_s)
            report = DigitalTwin(factory(), seed=seed,
                                 kill=spec).run()
            logs.append(report.decision_log_jsonl())
            problems = check_run(report, baseline)
            row = {'target': target, 'at_seq': seq,
                   'crashes': report.crashes,
                   'requests': len(report.records),
                   'completed': report.completed,
                   'client_retries': report.client_retries,
                   'problems': problems}
            runs.append(row)
            if problems:
                failures.append(row)
            if on_progress is not None:
                on_progress(
                    f'kill {target}@{seq}: '
                    f'{"FAIL " + str(problems) if problems else "ok"}')
    return {
        'baseline': baseline,
        'boundaries': boundaries,
        'runs': runs,
        'failures': failures,
        'log': '\n'.join(logs),
    }
