"""Fleet-scale digital twin: deterministic virtual-time chaos for the
REAL control plane (docs/robustness.md "Digital twin").

FoundationDB-style deterministic simulation instead of wall-clock
chaos: a discrete-event kernel owns a seeded virtual clock
(``utils/vclock``) and an in-process virtual transport, and drives the
REAL ``LoadBalancer`` (policies, breakers, resume splicing, shed
routing), the REAL ``ServeController`` tick + autoscalers, the REAL
``ReplicaManager`` lifecycle state machine, and the REAL
``infer/sched`` admission code (fcfs/EDF/wfq quotas) — against modeled
replicas parameterized by measured TTFT/ITL curves from the bench
JSONs. A 24h diurnal trace at 1000 modeled replicas, with spot-reclaim
storms and tenant bursts, replays in seconds of tier-1 wall clock;
the same seed produces a byte-identical decision log.

Layout:

- ``kernel``: the event heap, virtual clock, and the coroutine
  trampoline that drives the LB's real ``async def handle`` without an
  asyncio loop.
- ``replica``: modeled replicas — a REAL scheduler instance fronting
  virtual decode slots whose step time follows the bench ITL curves.
- ``cloud``: the ``CloudAdapter`` implementation (virtual provisioner,
  probes, preemption notices, drains) + the deterministic executor the
  replica manager's thread pool is swapped for.
- ``transport``: the LB subclass whose only overrides are the
  transport seams (proxy attempts, metrics fetch, DB offload).
- ``twin``: the orchestrator — wires state DB, controller, LB, trace
  and fault schedule into one run; emits the decision log + report.
- ``scenarios``: the scenario library (flash crowd, reclaim storm,
  regional failover, brownout, breaker flap) and its gates.
"""
from skypilot_tpu.sim.crash import run_crash_sweep
from skypilot_tpu.sim.scenarios import (SCENARIOS, KillSpec, Scenario,
                                        breaker_flap,
                                        crash_controller_mid_storm,
                                        crash_lb_mid_stream,
                                        crash_sweep, disagg_fleet,
                                        flash_crowd,
                                        fleet_storm_24h,
                                        incident_page_storm,
                                        reclaim_storm,
                                        regional_failover, sdc_storm,
                                        slow_brownout, wfq_fleet)
from skypilot_tpu.sim.twin import DigitalTwin, SimReport

__all__ = ['DigitalTwin', 'KillSpec', 'SCENARIOS', 'Scenario',
           'SimReport', 'breaker_flap', 'crash_controller_mid_storm',
           'crash_lb_mid_stream', 'crash_sweep', 'disagg_fleet',
           'flash_crowd', 'fleet_storm_24h', 'incident_page_storm',
           'reclaim_storm',
           'regional_failover', 'run_crash_sweep', 'sdc_storm',
           'slow_brownout', 'wfq_fleet']
