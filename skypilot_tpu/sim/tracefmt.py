"""Versioned trace/scenario schema shared by loadgen, the flight
recorder, and the digital twin (docs/simulation.md).

One JSONL file format for everything that replays through the twin:

- **synthetic traces** (``tests/load_tests/loadgen.py``): full request
  records with explicit token ids — the byte-exact replay surface the
  determinism gates compare;
- **exported incidents** (``skypilot_tpu/observability/incident.py``):
  request records SCRUBBED to lengths + a prefix-cohort hash (no
  prompt content leaves the fleet) plus a fault timeline inferred
  from the LB's evidence rings.

Line 1 is the header: ``{"sky_tpu_trace": 2, "schema_version": 2,
"kind": ..., "truncated": ..., ...meta}``. Every further line is a
typed record — ``{"type": "request", ...}``, ``{"type": "fault",
...}`` or ``{"type": "kill", ...}``. All writes are
``sort_keys=True`` so a load→save round trip is byte-identical (the
regression property the compat tests pin).

Version policy, loud by construction:

- ``schema_version`` 2 is current; a file claiming a NEWER version
  raises (never a silent partial parse of a format we do not know);
- version-less v1 loadgen headers (``{"sky_tpu_trace": 1, ...}``)
  keep loading through the compat reader;
- anything else — a foreign JSONL, a non-JSON first line, an unknown
  record type — raises ``ValueError`` naming the file and the
  offending line instead of yielding an empty trace.

Scrubbed records carry ``prompt_tokens`` (a length), ``cohort`` (a
one-way hash of the leading token block) and ``prefix_tokens``
instead of token ids; :func:`materialize_tokens` re-mints
deterministic ids at load time — same cohort ⇒ same leading block, so
the prefix-cache/affinity structure of the original traffic survives
the scrub while the content does not.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import random
from typing import Any, Dict, List, Optional, Tuple

SCHEMA_VERSION = 2
MAGIC = 'sky_tpu_trace'
# Header keys owned by the format itself; everything else round-trips
# through ``Trace.meta``.
_HEADER_KEYS = (MAGIC, 'schema_version', 'kind', 'truncated')
# Cohort keys hash this many leading token ids — long enough to
# separate real prefix cohorts, short enough that two prompts sharing
# a system preamble land in the same cohort.
COHORT_LEAD = 16
_RECORD_TYPES = ('request', 'fault', 'kill')


@dataclasses.dataclass
class TraceEvent:
    """One request arrival (canonical home; ``loadgen.TraceEvent`` is
    an alias). ``t`` is seconds from trace start."""

    t: float
    tenant: str
    tokens: List[int]        # prompt token ids
    max_new_tokens: int
    cohort: Optional[str] = None          # shared-prefix cohort label
    disconnect_after: Optional[int] = None  # hang up after N tokens
    deadline_s: Optional[float] = None    # per-request budget

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> 'TraceEvent':
        return cls(t=float(d['t']), tenant=str(d['tenant']),
                   tokens=[int(x) for x in d['tokens']],
                   max_new_tokens=int(d['max_new_tokens']),
                   cohort=d.get('cohort'),
                   disconnect_after=d.get('disconnect_after'),
                   deadline_s=d.get('deadline_s'))


@dataclasses.dataclass
class Trace:
    """A loaded trace: replayable arrivals + the fault timeline."""

    events: List[TraceEvent]
    # Fault-timeline records (plain dicts): ``{'kind': 'reclaim_storm'
    # , 't': ..., 'frac': ...}`` rows matching ``scenarios.Fault``
    # fields, plus ``{'type': 'kill', 'target': ..., 't': ...}``
    # control-plane crash records.
    faults: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)
    kills: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)
    kind: str = 'trace'          # 'trace' | 'incident'
    truncated: bool = False      # evidence rings wrapped before export
    schema_version: int = SCHEMA_VERSION
    # Raw request records as stored (scrubbed incidents keep outcome /
    # output_tokens here; ``events`` holds the replayable view).
    requests: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)


def cohort_key(tokens: List[int], lead: int = COHORT_LEAD) -> str:
    """One-way prefix-cohort hash of a prompt's leading token block:
    stable across exports, carries no content (12 hex chars of a
    keyed blake2s)."""
    head = json.dumps([int(t) for t in tokens[:lead]]).encode()
    return hashlib.blake2s(head, digest_size=6).hexdigest()


def materialize_tokens(prompt_tokens: int, cohort: Optional[str],
                       prefix_tokens: int, index: int) -> List[int]:
    """Deterministic token ids for a scrubbed request: the cohort
    hash seeds the shared leading block (same cohort ⇒ same prefix —
    the affinity/prefix-cache structure survives), a per-record seed
    mints the tail. Ids stay in loadgen's [2, 201] vocab-safe
    range."""
    n = max(1, int(prompt_tokens))
    shared = min(max(0, int(prefix_tokens)), n) if cohort else 0
    ids: List[int] = []
    if shared:
        rng = random.Random(f'sky-tpu-cohort/{cohort}')
        ids.extend(2 + rng.randrange(200) for _ in range(shared))
    rng = random.Random(f'sky-tpu-tail/{cohort}/{index}')
    ids.extend(2 + rng.randrange(200) for _ in range(n - len(ids)))
    return ids


def request_record(ev: TraceEvent) -> Dict[str, Any]:
    """A full (token-carrying) request record for a synthetic
    trace."""
    return {'type': 'request', **ev.to_json()}


def scrub_event(ev: TraceEvent) -> Dict[str, Any]:
    """The privacy projection: lengths + cohort hash, no token
    ids."""
    return {
        'type': 'request', 't': ev.t, 'tenant': ev.tenant,
        'prompt_tokens': len(ev.tokens),
        'max_new_tokens': ev.max_new_tokens,
        'cohort': ev.cohort or cohort_key(ev.tokens),
        'prefix_tokens': min(COHORT_LEAD, len(ev.tokens)),
        'deadline_s': ev.deadline_s,
    }


def _event_from_record(rec: Dict[str, Any], index: int,
                       path: str) -> TraceEvent:
    if 'tokens' in rec:
        return TraceEvent.from_json(rec)
    # Scrubbed record: re-mint deterministic ids.
    try:
        tokens = materialize_tokens(
            int(rec['prompt_tokens']), rec.get('cohort'),
            int(rec.get('prefix_tokens') or 0), index)
        return TraceEvent(
            t=float(rec['t']), tenant=str(rec['tenant']),
            tokens=tokens,
            max_new_tokens=int(rec.get('max_new_tokens') or 1),
            cohort=rec.get('cohort'),
            disconnect_after=rec.get('disconnect_after'),
            deadline_s=rec.get('deadline_s'))
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(
            f'{path}: malformed request record #{index}: {e}')


def save(trace: Trace, path: str) -> str:
    """Write a v{SCHEMA_VERSION} trace file. Deterministic: sorted
    keys, records in list order — save(load(p)) is byte-identical to
    p for any v2 file."""
    header = {MAGIC: SCHEMA_VERSION,
              'schema_version': trace.schema_version,
              'kind': trace.kind, 'truncated': bool(trace.truncated),
              **{k: v for k, v in trace.meta.items()
                 if k not in _HEADER_KEYS}}
    with open(path, 'w', encoding='utf-8') as f:
        f.write(json.dumps(header, sort_keys=True) + '\n')
        requests = trace.requests or [request_record(ev)
                                      for ev in trace.events]
        for rec in requests:
            f.write(json.dumps({'type': 'request', **rec},
                               sort_keys=True) + '\n')
        for rec in trace.faults:
            f.write(json.dumps({'type': 'fault', **rec},
                               sort_keys=True) + '\n')
        for rec in trace.kills:
            f.write(json.dumps({'type': 'kill', **rec},
                               sort_keys=True) + '\n')
    return path


def save_events(events: List[TraceEvent], path: str,
                meta: Optional[Dict[str, Any]] = None) -> str:
    """Loadgen-shaped save: a list of events + free-form meta."""
    return save(Trace(events=list(events), meta=dict(meta or {})),
                path)


def _parse_header(line: str, path: str) -> Dict[str, Any]:
    try:
        header = json.loads(line)
    except ValueError:
        raise ValueError(f'{path}: not a sky-tpu trace file '
                         f'(first line is not JSON)')
    if not isinstance(header, dict) or MAGIC not in header:
        raise ValueError(f'{path}: not a sky-tpu trace file '
                         f'(missing {MAGIC!r} header)')
    return header


def load(path: str) -> Trace:
    """Load any trace file version this build knows; LOUD on anything
    else (an unknown newer schema, a foreign JSONL, a malformed
    record) — a partial parse presented as an empty trace is how
    replay gates go silently vacuous."""
    with open(path, encoding='utf-8') as f:
        header = _parse_header(f.readline(), path)
        version = header.get(MAGIC)
        if version == 1:
            return _load_v1(f, header, path)
        if version != SCHEMA_VERSION:
            raise ValueError(
                f'{path}: trace schema version {version!r} is not '
                f'supported by this build (reads v1 and '
                f'v{SCHEMA_VERSION}); re-export the trace or upgrade')
        declared = header.get('schema_version')
        if declared != SCHEMA_VERSION:
            raise ValueError(
                f'{path}: header schema_version {declared!r} '
                f'disagrees with {MAGIC}={version}')
        trace = Trace(
            events=[], kind=str(header.get('kind') or 'trace'),
            truncated=bool(header.get('truncated')),
            schema_version=SCHEMA_VERSION,
            meta={k: v for k, v in header.items()
                  if k not in _HEADER_KEYS})
        for i, line in enumerate(f, start=2):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                raise ValueError(f'{path}:{i}: malformed JSON record')
            if not isinstance(rec, dict):
                raise ValueError(f'{path}:{i}: record is not an '
                                 f'object')
            kind = rec.pop('type', None)
            if kind == 'request':
                trace.requests.append(rec)
                trace.events.append(_event_from_record(
                    rec, len(trace.events), path))
            elif kind == 'fault':
                trace.faults.append(rec)
            elif kind == 'kill':
                trace.kills.append(rec)
            else:
                raise ValueError(
                    f'{path}:{i}: unknown record type {kind!r} '
                    f'(knows {list(_RECORD_TYPES)})')
        return trace


def _load_v1(f, header: Dict[str, Any], path: str) -> Trace:
    """Compat reader for version-less loadgen files: a ``{"
    sky_tpu_trace": 1}`` header followed by bare event lines."""
    events: List[TraceEvent] = []
    for i, line in enumerate(f, start=2):
        if not line.strip():
            continue
        try:
            events.append(TraceEvent.from_json(json.loads(line)))
        except (ValueError, KeyError, TypeError) as e:
            raise ValueError(f'{path}:{i}: malformed v1 trace '
                             f'event: {e}')
    return Trace(events=events, schema_version=1,
                 meta={k: v for k, v in header.items()
                       if k != MAGIC})


def load_events(path: str
                ) -> Tuple[List[TraceEvent], Dict[str, Any]]:
    """Loadgen-shaped load: (events, header-meta)."""
    trace = load(path)
    return trace.events, {MAGIC: trace.schema_version, **trace.meta}
