"""``python -m skypilot_tpu.sim`` — run a digital-twin scenario.

The ``make sim-smoke`` entry: replays a scenario, prints the summary
and gate-relevant rollups, exits non-zero on client-visible errors or
a determinism violation (``--verify-determinism`` replays twice and
compares decision logs byte for byte).
"""
from __future__ import annotations

import argparse
import json
import logging
import sys

from skypilot_tpu.sim import SCENARIOS, DigitalTwin


def main() -> int:
    parser = argparse.ArgumentParser(
        description='fleet digital twin (docs/robustness.md)')
    parser.add_argument('--scenario', default='reclaim_storm',
                        choices=sorted(SCENARIOS))
    parser.add_argument('--seed', type=int, default=1)
    parser.add_argument('--replicas', type=int, default=None,
                        help='override the scenario fleet size')
    parser.add_argument('--verify-determinism', action='store_true',
                        help='replay twice, compare decision logs')
    parser.add_argument('--json', dest='json_out', default=None,
                        help='write the full report JSON here')
    args = parser.parse_args()
    logging.basicConfig(level=logging.ERROR)

    kwargs = {}
    if args.replicas is not None:
        kwargs['replicas'] = args.replicas
    scenario = SCENARIOS[args.scenario](**kwargs)
    report = DigitalTwin(scenario, seed=args.seed).run()
    summary = report.summary()
    print(json.dumps(summary, indent=1))

    rc = 0
    if report.client_errors:
        print(f'FAIL: {len(report.client_errors)} client-visible '
              f'error(s); first: {report.client_errors[0]}',
              file=sys.stderr)
        rc = 1
    if args.verify_determinism:
        again = DigitalTwin(SCENARIOS[args.scenario](**kwargs),
                            seed=args.seed).run()
        if (again.decision_log_jsonl()
                != report.decision_log_jsonl()):
            print('FAIL: same seed produced a different decision log',
                  file=sys.stderr)
            rc = 1
        else:
            print(f'determinism: OK '
                  f'({len(report.decisions)} decisions identical)')
    if args.json_out:
        with open(args.json_out, 'w', encoding='utf-8') as f:
            json.dump({'summary': summary,
                       'decisions': report.decisions}, f, indent=1)
    return rc


if __name__ == '__main__':
    sys.exit(main())
