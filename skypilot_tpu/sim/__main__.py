"""``python -m skypilot_tpu.sim`` — run a digital-twin scenario.

The ``make sim-smoke`` entry: replays a scenario, prints the summary
and gate-relevant rollups, exits non-zero on client-visible errors or
a determinism violation (``--verify-determinism`` replays twice and
compares decision logs byte for byte).
"""
from __future__ import annotations

import argparse
import json
import logging
import sys

from skypilot_tpu.sim import SCENARIOS, DigitalTwin, run_crash_sweep


def _run_crash_sweep(args, parser) -> int:
    """The kill-anywhere gate from the command line
    (``make sim-crash-sweep``): sweep controller and LB kills across
    every control-plane decision boundary of a storm replay (the
    ``crash_sweep`` scenario unless --scenario picks another kill-free
    one); with --verify-determinism, sweep twice and compare the
    concatenated decision logs byte for byte."""
    kwargs = {}
    if args.replicas is not None:
        kwargs['replicas'] = args.replicas
    # --scenario composes: any scenario can be swept, as long as it
    # does not embed its own kills (the baseline must be unkilled).
    # None default distinguishes "unset" from an explicit choice.
    name = args.scenario or 'crash_sweep'

    def factory():
        return SCENARIOS[name](**kwargs)

    if factory().kills:
        parser.error(f'--crash-sweep needs a kill-free base scenario; '
                     f'{name!r} embeds its own kills')
    sweep = run_crash_sweep(factory, seed=args.seed,
                            on_progress=print)
    summary = {
        'scenario': name, 'seed': args.seed,
        'boundaries': len(sweep['boundaries']),
        'runs': len(sweep['runs']),
        'failures': len(sweep['failures']),
    }
    print(json.dumps(summary, indent=1))
    if args.json_out:
        with open(args.json_out, 'w', encoding='utf-8') as f:
            json.dump({'summary': summary, 'runs': sweep['runs']},
                      f, indent=1)
    rc = 0
    if sweep['failures']:
        print(f"FAIL: {len(sweep['failures'])} killed replay(s) "
              f"violated the crash-safety gate; first: "
              f"{sweep['failures'][0]}", file=sys.stderr)
        rc = 1
    if args.verify_determinism:
        again = run_crash_sweep(factory, seed=args.seed)
        if again['log'] != sweep['log']:
            print('FAIL: same-seed crash sweeps produced different '
                  'decision logs', file=sys.stderr)
            rc = 1
        else:
            print('determinism: OK (sweep decision logs identical)')
    return rc


def main() -> int:
    parser = argparse.ArgumentParser(
        description='fleet digital twin (docs/robustness.md)')
    # Default None so --crash-sweep can tell an explicit scenario from
    # an unset one (its default base differs: crash_sweep).
    parser.add_argument('--scenario', default=None,
                        choices=sorted(SCENARIOS))
    parser.add_argument('--seed', type=int, default=1)
    parser.add_argument('--replicas', type=int, default=None,
                        help='override the scenario fleet size')
    parser.add_argument('--verify-determinism', action='store_true',
                        help='replay twice, compare decision logs')
    parser.add_argument('--crash-sweep', action='store_true',
                        help='run the kill-anywhere crash-consistency '
                             'sweep instead of a single replay')
    parser.add_argument('--json', dest='json_out', default=None,
                        help='write the full report JSON here')
    args = parser.parse_args()
    logging.basicConfig(level=logging.ERROR)

    if args.crash_sweep:
        return _run_crash_sweep(args, parser)

    kwargs = {}
    if args.replicas is not None:
        kwargs['replicas'] = args.replicas
    scenario = SCENARIOS[args.scenario or 'reclaim_storm'](**kwargs)
    report = DigitalTwin(scenario, seed=args.seed).run()
    summary = report.summary()
    print(json.dumps(summary, indent=1))

    rc = 0
    if report.client_errors:
        print(f'FAIL: {len(report.client_errors)} client-visible '
              f'error(s); first: {report.client_errors[0]}',
              file=sys.stderr)
        rc = 1
    if args.verify_determinism:
        again = DigitalTwin(SCENARIOS[args.scenario](**kwargs),
                            seed=args.seed).run()
        if (again.decision_log_jsonl()
                != report.decision_log_jsonl()):
            print('FAIL: same seed produced a different decision log',
                  file=sys.stderr)
            rc = 1
        else:
            print(f'determinism: OK '
                  f'({len(report.decisions)} decisions identical)')
    if args.json_out:
        with open(args.json_out, 'w', encoding='utf-8') as f:
            json.dump({'summary': summary,
                       'decisions': report.decisions}, f, indent=1)
    return rc


if __name__ == '__main__':
    sys.exit(main())
