"""TPU slice topology as a first-class object.

This is the central TPU-first design decision of the framework: where the
reference bolts TPU metadata onto GPU-shaped resources via string flags
(``accelerator_args['tpu_vm']``, reference sky/clouds/gcp.py:564-577 and
catalog grouping gcp_catalog.py:486-566), here every accelerator request
resolves to a :class:`TpuSlice` that *derives* host count, chips-per-host,
ICI torus dimensions, and the per-host `jax.distributed` wiring from the
slice name. The provisioner gang-allocates `slice.num_hosts` VMs atomically
(the slice *is* the gang — no Ray placement group needed), and the runtime
emits coordinator/process-id env from the same object.

Naming convention (mirrors GCP accelerator types):
  - ``v2-8 / v3-8``      : suffix counts TensorCores (2 cores/chip)
  - ``v4-N / v5p-N``     : suffix counts TensorCores (2 cores/chip, megacore)
  - ``v5e-N / v6e-N``    : suffix counts chips directly
Accepts an optional ``tpu-`` prefix (``tpu-v5e-8``).
"""
from __future__ import annotations

import dataclasses
import re
from typing import Dict, Optional, Tuple

from skypilot_tpu import exceptions

# Per-generation hardware constants.
#   chips_per_host: chips attached to one host VM in *multi-host* slices.
#   max_chips_single_host: largest slice still served by a single host VM.
#   ici_dims: 2 for a 2D torus (v2/v3/v5e/v6e), 3 for a 3D torus (v4/v5p).
#   hbm/flops: per-chip, for the optimizer's time model and bench reporting.
@dataclasses.dataclass(frozen=True)
class TpuGeneration:
    name: str
    cores_per_chip: int
    suffix_counts_cores: bool
    chips_per_host: int
    max_chips_single_host: int
    ici_dims: int
    hbm_gib: float
    bf16_tflops: float
    # Per-chip ICI bandwidth (GB/s, one direction, all links) — drives the
    # collective-time estimates in the optimizer.
    ici_gbps: float


TPU_GENERATIONS: Dict[str, TpuGeneration] = {
    'v2': TpuGeneration('v2', 2, True, 4, 4, 2, 8, 46, 62),
    'v3': TpuGeneration('v3', 2, True, 4, 4, 2, 16, 123, 112),
    'v4': TpuGeneration('v4', 2, True, 4, 4, 3, 32, 275, 268),
    'v5e': TpuGeneration('v5e', 1, False, 4, 8, 2, 16, 197, 186),
    'v5p': TpuGeneration('v5p', 2, True, 4, 4, 3, 95, 459, 537),
    'v6e': TpuGeneration('v6e', 1, False, 4, 8, 2, 32, 918, 448),
}

_TPU_NAME_RE = re.compile(r'^(?:tpu-)?(v\d+[ep]?(?:litepod)?)-(\d+)$')
_GEN_ALIASES = {'v5litepod': 'v5e', 'v5lite': 'v5e'}


def _torus_dims(chips: int, ndims: int) -> Tuple[int, ...]:
    """Factor `chips` into a near-cubic/near-square torus shape.

    Real slices have fixed catalogued topologies (e.g. v5p-64 → 2x4x4); this
    produces the same shapes for power-of-two sizes, which is what the
    catalog contains.
    """
    if chips == 1:
        return (1,) * ndims
    dims = [1] * ndims
    remaining = chips
    # Greedily split factors largest-first onto the smallest dimension.
    factors = []
    n = remaining
    for p in (2, 3, 5, 7):
        while n % p == 0:
            factors.append(p)
            n //= p
    if n > 1:
        factors.append(n)
    for f in sorted(factors, reverse=True):
        dims[dims.index(min(dims))] *= f
    return tuple(sorted(dims))


@dataclasses.dataclass(frozen=True)
class TpuSlice:
    """A fully-resolved TPU slice request.

    Everything the provisioner and runtime need: ``num_hosts`` VMs to
    gang-allocate, ``chips_per_host`` for per-host device expectations,
    ``ici_topology`` for mesh construction, and the accelerator_type string
    for the TPU API (``tpu.googleapis.com`` — reference
    sky/provision/gcp/instance_utils.py:1222-1226 shows the API shape).
    """
    generation: str           # 'v5e', 'v5p', ...
    num_chips: int
    num_hosts: int
    chips_per_host: int
    num_cores: int
    ici_topology: Tuple[int, ...]   # physical torus dims, e.g. (2, 4, 4)
    hbm_gib_per_chip: float
    bf16_tflops_per_chip: float
    ici_gbps: float

    @property
    def name(self) -> str:
        gen = TPU_GENERATIONS[self.generation]
        suffix = self.num_cores if gen.suffix_counts_cores else self.num_chips
        return f'{self.generation}-{suffix}'

    @property
    def accelerator_type(self) -> str:
        """GCP TPU API acceleratorType string."""
        if self.generation == 'v5e':
            return f'v5litepod-{self.num_chips}'
        return self.name

    @property
    def is_multi_host(self) -> bool:
        return self.num_hosts > 1

    @property
    def is_pod(self) -> bool:
        return self.is_multi_host

    @property
    def total_hbm_gib(self) -> float:
        return self.hbm_gib_per_chip * self.num_chips

    @property
    def total_bf16_tflops(self) -> float:
        return self.bf16_tflops_per_chip * self.num_chips

    @property
    def topology_str(self) -> str:
        return 'x'.join(str(d) for d in self.ici_topology)

    def host_bounds(self) -> Tuple[int, ...]:
        """How hosts tile the torus (TPU_HOST_BOUNDS-style metadata).

        A host owns a contiguous near-square block (2x2(x1) for the standard
        4-chip hosts), so the per-host block's prime factors are spread as
        evenly as possible across the trailing torus dimensions rather than
        consuming one whole dimension.
        """
        bounds = list(self.ici_topology)
        block = [1] * len(bounds)
        n = self.chips_per_host
        factors = []
        d = 2
        while d * d <= n:
            while n % d == 0:
                factors.append(d)
                n //= d
            d += 1
        if n > 1:
            factors.append(n)
        for f in sorted(factors, reverse=True):
            cands = [i for i in range(len(bounds))
                     if bounds[i] % (block[i] * f) == 0]
            if not cands:
                break
            # Smallest current block wins; ties prefer trailing dims.
            i = min(cands, key=lambda i: (block[i], -i))
            block[i] *= f
        return tuple(b // blk for b, blk in zip(bounds, block))

    def devices_per_process(self) -> int:
        """Local device count each `jax.distributed` process sees."""
        return self.chips_per_host

    def __str__(self) -> str:
        return (f'{self.name} ({self.num_chips} chips, {self.num_hosts} '
                f'host{"s" if self.num_hosts > 1 else ""}, '
                f'topo {self.topology_str})')


def parse_tpu(name: str) -> Optional[TpuSlice]:
    """Parse ``[tpu-]v5e-8``-style names; None if not a TPU accelerator."""
    m = _TPU_NAME_RE.match(name.strip().lower())
    if m is None:
        return None
    gen_name, count = m.group(1), int(m.group(2))
    gen_name = _GEN_ALIASES.get(gen_name, gen_name)
    gen = TPU_GENERATIONS.get(gen_name)
    if gen is None:
        raise exceptions.InvalidResourcesError(
            f'Unknown TPU generation in accelerator {name!r}. Known: '
            f'{sorted(TPU_GENERATIONS)}')
    if count <= 0:
        raise exceptions.InvalidResourcesError(
            f'Invalid TPU size in {name!r}')
    if gen.suffix_counts_cores:
        if count % gen.cores_per_chip != 0:
            raise exceptions.InvalidResourcesError(
                f'{name!r}: core count must be a multiple of '
                f'{gen.cores_per_chip}')
        num_chips = count // gen.cores_per_chip
    else:
        num_chips = count
    num_cores = num_chips * gen.cores_per_chip
    def _unit(chips: int) -> str:
        # Error messages speak the user's units (cores for v2-v4/v5p names).
        if gen.suffix_counts_cores:
            return f'{gen.name}-{chips * gen.cores_per_chip}'
        return f'{gen.name}-{chips}'

    if num_chips <= gen.max_chips_single_host:
        if num_chips & (num_chips - 1) != 0:
            valid = [_unit(c) for c in (1, 2, 4, 8)
                     if c <= gen.max_chips_single_host]
            raise exceptions.InvalidResourcesError(
                f'{name!r}: single-host {gen.name} slices must have a '
                f'power-of-two chip count; valid single-host sizes: '
                f'{", ".join(valid)}')
        num_hosts, chips_per_host = 1, num_chips
    else:
        if gen.ici_dims == 2 and num_chips & (num_chips - 1) != 0:
            # 2D-torus generations (v2/v3/v5e/v6e) are catalogued only at
            # power-of-two sizes; 3D generations (v4/v5p) support
            # rectangular topologies like 2x2x6 (v5p-48).
            raise exceptions.InvalidResourcesError(
                f'{name!r}: multi-host {gen.name} slices must have a '
                f'power-of-two chip count (e.g. {_unit(16)}, {_unit(32)})')
        if num_chips % gen.chips_per_host != 0:
            raise exceptions.InvalidResourcesError(
                f'{name!r}: multi-host slice must be a multiple of '
                f'{gen.chips_per_host} chips ({_unit(gen.chips_per_host)} '
                f'increments)')
        chips_per_host = gen.chips_per_host
        num_hosts = num_chips // chips_per_host
    return TpuSlice(
        generation=gen.name,
        num_chips=num_chips,
        num_hosts=num_hosts,
        chips_per_host=chips_per_host,
        num_cores=num_cores,
        ici_topology=_torus_dims(num_chips, gen.ici_dims),
        hbm_gib_per_chip=gen.hbm_gib,
        bf16_tflops_per_chip=gen.bf16_tflops,
        ici_gbps=gen.ici_gbps,
    )


def is_tpu(accelerator_name: str) -> bool:
    return _TPU_NAME_RE.match(accelerator_name.strip().lower()) is not None
