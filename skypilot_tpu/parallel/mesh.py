"""Device mesh construction for TPU slices.

Axes convention (the scaling-book recipe: pick a mesh, annotate shardings,
let XLA insert collectives):

- ``dp``  : pure data parallel (replicated params) — outermost, rides DCN
            across slices.
- ``fsdp``: data parallel with sharded params/optimizer (ZeRO-3-style via
            NamedSharding) — rides ICI.
- ``tp``  : tensor parallel (megatron-style column/row sharding) —
            innermost, highest-bandwidth ICI dimension.

``mesh_from_slice`` maps a :class:`~skypilot_tpu.topology.TpuSlice`'s
physical torus onto these logical axes so tp stays within a host's chips
where possible.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from skypilot_tpu import topology

AXES = ('dp', 'fsdp', 'tp')


def make_mesh(dp: int = 1, fsdp: int = 1, tp: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    want = dp * fsdp * tp
    if want != len(devices):
        raise ValueError(
            f'mesh {dp}x{fsdp}x{tp}={want} != {len(devices)} devices')
    arr = np.array(devices).reshape(dp, fsdp, tp)
    return Mesh(arr, AXES)


def auto_mesh(n_devices: Optional[int] = None, *,
              tp: Optional[int] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Reasonable default: all-FSDP, with optional tp factor.

    FSDP-dominant is the right default on TPU pods (ICI makes per-layer
    all-gathers cheap; pure dp wastes HBM on replicas).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devices)
    devices = devices[:n]
    tp = tp or 1
    if n % tp != 0:
        raise ValueError(f'tp={tp} does not divide {n} devices')
    return make_mesh(dp=1, fsdp=n // tp, tp=tp, devices=devices)


def mesh_from_slice(s: topology.TpuSlice, *,
                    tp: Optional[int] = None,
                    dp: int = 1,
                    devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Mesh for a whole slice. Default tp = chips_per_host (tensor parallel
    within a host's chips — lowest-latency ICI), fsdp = the rest."""
    if tp is None:
        tp = min(s.chips_per_host, s.num_chips)
    total = s.num_chips
    if total % (tp * dp) != 0:
        raise ValueError(f'dp={dp} * tp={tp} must divide {total} chips')
    return make_mesh(dp=dp, fsdp=total // (tp * dp), tp=tp,
                     devices=devices)


def make_multislice_mesh(num_slices: int, *,
                         fsdp: Optional[int] = None, tp: int = 1,
                         devices: Optional[Sequence[jax.Device]] = None
                         ) -> Mesh:
    """Mesh for a DCN-connected multislice job (MEGASCALE wiring).

    Logical layout follows the standard multislice recipe: the ``dp`` axis
    spans slices (gradient all-reduce rides DCN, the only traffic that
    crosses slice boundaries), while ``fsdp``/``tp`` stay within each
    slice's ICI. Devices must be ordered slice-major — jax returns exactly
    that order under MEGASCALE (process ids are slice-major, see
    runtime/distributed_env.make_env), and the CPU dryrun emulates it by
    construction.
    """
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) % num_slices != 0:
        raise ValueError(
            f'{len(devices)} devices do not split into {num_slices} slices')
    per_slice = len(devices) // num_slices
    if fsdp is None:
        if per_slice % tp != 0:
            raise ValueError(f'tp={tp} must divide {per_slice} '
                             f'devices/slice')
        fsdp = per_slice // tp
    if fsdp * tp != per_slice:
        raise ValueError(
            f'fsdp={fsdp} * tp={tp} != {per_slice} devices per slice')
    return make_mesh(dp=num_slices, fsdp=fsdp, tp=tp, devices=devices)
