"""Sharding rules: PartitionSpecs for model params, optimizer state, data.

Megatron-style TP composed with ZeRO-3-style FSDP, expressed as
NamedShardings (XLA inserts the all-gathers/reduce-scatters):

- attention qkv projections: column-parallel (heads over ``tp``), fsdp on
  the input dim.
- attention output / MLP down: row-parallel (``tp`` on input dim).
- MLP gate/up: column-parallel.
- embed: vocab over ``tp`` (vocab-parallel embedding), model dim over
  ``fsdp``; lm_head the transpose.
- Optimizer state inherits its parameter's sharding (ZeRO-3).
- Batch data: sharded over (``dp``, ``fsdp``) jointly — fsdp is also a data
  axis.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

LLAMA_PARAM_SPECS: Dict[str, Any] = {
    'embed': P('tp', 'fsdp'),
    'layers': {
        'attn_norm': P(None, None),
        'wq': P(None, 'fsdp', 'tp'),
        'wk': P(None, 'fsdp', 'tp'),
        'wv': P(None, 'fsdp', 'tp'),
        'wo': P(None, 'tp', 'fsdp'),
        'mlp_norm': P(None, None),
        'w_gate': P(None, 'fsdp', 'tp'),
        'w_up': P(None, 'fsdp', 'tp'),
        'w_down': P(None, 'tp', 'fsdp'),
    },
    'final_norm': P(None),
    'lm_head': P('fsdp', 'tp'),
}

BATCH_SPEC = P(('dp', 'fsdp'), None)           # [batch, seq]


def param_shardings(mesh: Mesh, params: Any) -> Any:
    """NamedShardings matching the params pytree (LLAMA_PARAM_SPECS
    broadcast over identical tree structure).

    Int8-quantized trees (ops/quant.py QuantArray) are handled too:
    the ``q`` field shards like the original weight; ``scale`` drops
    the contraction axis it was reduced over (-2 for matmul weights,
    -1 for the per-row embedding table) from the weight's spec — this
    is what lets an int8 70B shard over a tp mesh."""
    specs = LLAMA_PARAM_SPECS

    def to_sharding(path, leaf):
        node = specs
        keys = [p.key if hasattr(p, 'key') else
                getattr(p, 'name', None) or p.idx for p in path]
        consumed = 0
        for key in keys:
            if isinstance(node, dict):
                node = node[key]
                consumed += 1
            else:
                break
        rest = keys[consumed:]
        if not rest:
            return NamedSharding(mesh, node)
        [field] = rest                      # QuantArray member
        if field == 'q':
            return NamedSharding(mesh, node)
        assert field == 'scale', field
        parts = list(node) + [None] * (len(leaf.shape) + 1 - len(node))
        if keys[0] == 'embed':
            spec = P(*parts[:1])            # per-row: [vocab]
        else:
            spec = P(*(parts[:-2] + parts[-1:]))   # drop the in axis
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(to_sharding, params)


def opt_state_shardings(mesh: Mesh, opt_state: Any, params: Any) -> Any:
    """Optimizer state shards like its parameter (ZeRO-3). Non-pytree-of-
    params leaves (step counters etc.) are replicated."""
    p_shard = param_shardings(mesh, params)
    flat_params, _ = jax.tree_util.tree_flatten(params)
    flat_shards, _ = jax.tree_util.tree_flatten(p_shard)
    shard_by_shape = {}
    for p, s in zip(flat_params, flat_shards):
        shard_by_shape.setdefault((p.shape, p.dtype), s)

    def to_sharding(leaf):
        key = (getattr(leaf, 'shape', ()), getattr(leaf, 'dtype', None))
        if key in shard_by_shape:
            return shard_by_shape[key]
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(to_sharding, opt_state)


def shard_pytree(tree: Any, shardings: Any) -> Any:
    """Place a host pytree onto the mesh with the given shardings."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), tree, shardings)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, BATCH_SPEC)
