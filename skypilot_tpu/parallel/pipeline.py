"""Pipeline parallelism: GPipe-style microbatch pipeline over a mesh axis.

The reference has no model parallelism of its own (SURVEY.md §2.8 —
delegated to torchrun/DeepSpeed in example YAMLs); this is the TPU-native
construction: stages are layer groups sharded over the ``pp`` mesh axis,
activations flow stage-to-stage via ``lax.ppermute`` inside ``shard_map``,
and the schedule is a single ``lax.scan`` over M + P - 1 ticks (the
pipeline bubble). **The backward pipeline comes from AD**: ppermute's
transpose is the reverse permute, so ``jax.grad`` of this forward IS the
reverse-schedule backward — no hand-written schedule.

Composes with the other axes: params stay fsdp/tp-sharded inside a stage;
``pp`` only partitions the layer axis.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from skypilot_tpu.models import llama
from skypilot_tpu.ops import norms
from skypilot_tpu.ops import rope as rope_lib


def pipeline_stages(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                    local_params: Any, microbatches: jnp.ndarray,
                    axis_name: str = 'pp') -> jnp.ndarray:
    """Run microbatches through all pipeline stages. CALL INSIDE shard_map.

    stage_fn(local_params, x) -> y: this stage's compute (same shape).
    microbatches: [M, ...] — every stage sees the full microbatch list;
    stage 0 injects them, later stages consume ppermuted activations.
    Returns [M, ...] stage outputs — valid on the LAST stage, zeros
    elsewhere (psum over ``axis_name`` broadcasts, since others are 0).
    """
    num_stages = jax.lax.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    T = M + num_stages - 1
    shift = [(i, i + 1) for i in range(num_stages - 1)]

    def tick(carry, t):
        state, outputs = carry
        mb = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, M - 1), 0, keepdims=False)
        x = jnp.where(stage == 0, mb, state)
        y = stage_fn(local_params, x)
        out_idx = t - (num_stages - 1)
        ci = jnp.clip(out_idx, 0, M - 1)
        valid = ((stage == num_stages - 1) & (out_idx >= 0)
                 & (out_idx < M))
        prev = jax.lax.dynamic_index_in_dim(outputs, ci, 0,
                                            keepdims=False)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, jnp.where(valid, y, prev), ci, 0)
        state = jax.lax.ppermute(y, axis_name, shift) \
            if num_stages > 1 else y
        return (state, outputs), None

    state0 = jnp.zeros_like(microbatches[0])
    out0 = jnp.zeros_like(microbatches)
    (_, outputs), _ = jax.lax.scan(tick, (state0, out0),
                                   jnp.arange(T))
    return outputs


def _llama_stage(config: llama.LlamaConfig, local_layers: Any,
                 x: jnp.ndarray, cos: jnp.ndarray,
                 sin: jnp.ndarray) -> jnp.ndarray:
    """One stage = scan over this stage's contiguous layer group."""
    def body(h, layer):
        fn = llama._layer  # noqa: SLF001 — same model family
        if config.remat:
            fn = jax.checkpoint(fn, static_argnums=(0,))
        return fn(config, h, layer, cos, sin, None), None
    x, _ = jax.lax.scan(body, x, local_layers)
    return x


def llama_pp_loss_fn(config: llama.LlamaConfig, mesh: Mesh,
                     num_microbatches: int,
                     dp_axis: Optional[str] = 'dp',
                     pp_axis: str = 'pp') -> Callable:
    """Build loss(params, tokens, targets) pipelined over ``pp_axis``.

    Layer-stacked params are split over stages (n_layers % pp == 0);
    embed/head/norms are computed on every stage (replicated compute —
    negligible next to the layer stack). Batch shards over ``dp_axis``.
    """
    pp = mesh.shape[pp_axis]
    if config.n_layers % pp != 0:
        raise ValueError(f'n_layers={config.n_layers} not divisible by '
                         f'pp={pp}')
    has_dp = dp_axis is not None and dp_axis in mesh.shape
    batch_spec = P(dp_axis) if has_dp else P()

    layer_specs = jax.tree_util.tree_map(
        lambda _: P(pp_axis), llama.LLAMA_LAYER_TREE)
    param_specs = {
        'embed': P(), 'layers': layer_specs, 'final_norm': P(),
        'lm_head': P(),
    }

    def inner(params, tokens, targets):
        cos, sin = rope_lib.rope_frequencies(config.head_dim,
                                             config.max_seq_len,
                                             config.rope_theta)
        b = tokens.shape[0]
        if b % num_microbatches != 0:
            raise ValueError(f'per-dp batch {b} not divisible by '
                             f'M={num_microbatches}')
        x = params['embed'][tokens]                 # [b, s, d]
        mbs = x.reshape(num_microbatches, b // num_microbatches,
                        *x.shape[1:])
        stage_fn = functools.partial(_llama_stage, config)
        outputs = pipeline_stages(
            lambda lp, h: stage_fn(lp, h, cos, sin),
            params['layers'], mbs, axis_name=pp_axis)
        # Valid only on the last stage; zeros elsewhere → psum broadcasts.
        outputs = jax.lax.psum(outputs, pp_axis)
        h = outputs.reshape(b, *outputs.shape[2:])
        h = norms.rms_norm(h, params['final_norm'], config.norm_eps)
        logits = (h @ params['lm_head']).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None],
                                   axis=-1)[..., 0]
        loss = jnp.mean(nll)
        if has_dp:
            loss = jax.lax.pmean(loss, dp_axis)
        return loss

    from skypilot_tpu.parallel import shard_map
    return shard_map(
        inner, mesh=mesh,
        in_specs=(param_specs, batch_spec, batch_spec),
        out_specs=P(),
        check_rep=False)
