"""Parallelism: device meshes, sharding rules, collectives.

The TPU-native replacement for everything the reference delegates to
torchrun/NCCL (SURVEY.md §2.8): DP/FSDP/TP via `jax.sharding` +
NamedSharding over a Mesh; SP via ring attention (`ops/ring_attention.py`);
XLA emits the collectives over ICI/DCN.
"""

try:                                    # jax >= 0.8
    import inspect as _inspect

    from jax import shard_map as _shard_map
    _HAS_CHECK_VMA = 'check_vma' in _inspect.signature(
        _shard_map).parameters

    def shard_map(f, *args, check_rep=None, **kwargs):
        """jax.shard_map with the old check_rep spelling accepted."""
        if check_rep is not None:
            if _HAS_CHECK_VMA:
                kwargs.setdefault('check_vma', check_rep)
            else:
                kwargs.setdefault('check_rep', check_rep)
        return _shard_map(f, *args, **kwargs)
except ImportError:                     # older jax
    from jax.experimental.shard_map import shard_map  # noqa: F401
