"""Parallelism: device meshes, sharding rules, collectives.

The TPU-native replacement for everything the reference delegates to
torchrun/NCCL (SURVEY.md §2.8): DP/FSDP/TP via `jax.sharding` +
NamedSharding over a Mesh; SP via ring attention (`ops/ring_attention.py`);
XLA emits the collectives over ICI/DCN.
"""
