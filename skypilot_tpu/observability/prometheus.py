"""Prometheus text exposition for the serving tier.

The LB's ``/-/metrics`` and each replica's ``/metrics`` are JSON by
design (they feed `serve status` and the TTFT bench directly); this
module is the exposition wrapper both grow behind
``?format=prometheus`` so a scrape-based stack ingests the same
numbers without a JSON exporter sidecar.

Exposed families are an **explicit, curated literal map** — never a
mechanical flatten — for two reasons: exposition names are a public
API (dashboards break when they drift), and `sky-tpu lint`
(SKY-REGISTRY) cross-checks every ``sky_tpu_*`` family named here
against docs/observability.md's "Prometheus exposition" catalog, both
directions. Add a family => add a catalog row.

Label values are client-controlled (tenant ids ride
``X-SkyTpu-Tenant``): every label is passed through the span store's
:func:`~skypilot_tpu.observability.store.sanitize_label` rule so a
hostile id cannot corrupt the exposition format (quotes, newlines,
unbounded length).
"""
from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Tuple

from skypilot_tpu.observability import store as store_lib


def lb_exposition() -> Dict[str, Tuple[str, str]]:
    """Scalar LB ``lb_metrics()`` keys -> (family, type). Counters
    are monotonic LB edge counters; gauges are point-in-time."""
    return {
        'requests_total': ('sky_tpu_lb_requests_total', 'counter'),
        'requests_failed': ('sky_tpu_lb_requests_failed', 'counter'),
        'requests_no_replica': (
            'sky_tpu_lb_requests_no_replica', 'counter'),
        'requests_retried': (
            'sky_tpu_lb_requests_retried', 'counter'),
        'requests_resumed': (
            'sky_tpu_lb_requests_resumed', 'counter'),
        'requests_shed': ('sky_tpu_lb_requests_shed', 'counter'),
        'ready_replicas': ('sky_tpu_lb_ready_replicas', 'gauge'),
        'engine_queue_depth': (
            'sky_tpu_lb_engine_queue_depth', 'gauge'),
        'ttft_p50_s': ('sky_tpu_lb_ttft_p50_seconds', 'gauge'),
        'ttft_p90_s': ('sky_tpu_lb_ttft_p90_seconds', 'gauge'),
        'ttft_p99_s': ('sky_tpu_lb_ttft_p99_seconds', 'gauge'),
        'itl_p50_s': ('sky_tpu_lb_itl_p50_seconds', 'gauge'),
        'itl_p99_s': ('sky_tpu_lb_itl_p99_seconds', 'gauge'),
        'engine_tokens_per_step': (
            'sky_tpu_lb_engine_tokens_per_step', 'gauge'),
        'engine_tokens_per_sec_w': (
            'sky_tpu_lb_engine_tokens_per_sec', 'gauge'),
        'prefix_hit_rate_w': (
            'sky_tpu_lb_prefix_hit_rate', 'gauge'),
        'history_window_s': (
            'sky_tpu_lb_history_window_seconds', 'gauge'),
        'slo_alerts_firing': (
            'sky_tpu_lb_slo_alerts_firing', 'gauge'),
        'slo_burn': ('sky_tpu_lb_slo_burn', 'gauge'),
        # Cost plane (docs/cost.md).
        'fleet_cost_per_hour': (
            'sky_tpu_lb_fleet_cost_per_hour', 'gauge'),
        'cost_per_1k_good_tokens': (
            'sky_tpu_lb_cost_per_1k_good_tokens', 'gauge'),
        'spot_fraction': ('sky_tpu_lb_spot_fraction', 'gauge'),
        'cost_catalog_stale': (
            'sky_tpu_lb_cost_catalog_stale', 'gauge'),
        # Scale to zero (docs/cost.md "Scale to zero").
        'parked_requests': ('sky_tpu_lb_parked_requests', 'gauge'),
        'cold_starts_total': (
            'sky_tpu_lb_cold_starts_total', 'counter'),
        'cold_start_p50_s': (
            'sky_tpu_lb_cold_start_p50_seconds', 'gauge'),
        # Data-integrity plane (docs/robustness.md "Data integrity").
        'replicas_quarantined': (
            'sky_tpu_lb_replicas_quarantined', 'counter'),
        'probe_failures_total': (
            'sky_tpu_lb_probe_failures_total', 'counter'),
        'probe_interval_s': (
            'sky_tpu_lb_probe_interval_seconds', 'gauge'),
        # Disaggregated prefill/decode (docs/serving.md).
        'kv_transfers_total': (
            'sky_tpu_lb_kv_transfers_total', 'counter'),
        'kv_transfer_bytes': (
            'sky_tpu_lb_kv_transfer_bytes', 'counter'),
        'kv_transfer_failures': (
            'sky_tpu_lb_kv_transfer_failures', 'counter'),
        'kv_transfer_p99_s': (
            'sky_tpu_lb_kv_transfer_p99_seconds', 'gauge'),
        'fleet_prefix_hit_rate': (
            'sky_tpu_lb_fleet_prefix_hit_rate', 'gauge'),
        'fleet_prefix_pages': (
            'sky_tpu_lb_fleet_prefix_pages', 'gauge'),
    }


def replica_exposition() -> Dict[str, Tuple[str, str]]:
    """Scalar replica ``/metrics`` keys -> (family, type)."""
    return {
        'decode_steps': ('sky_tpu_engine_decode_steps', 'counter'),
        'decode_tokens': ('sky_tpu_engine_decode_tokens', 'counter'),
        'decode_tokens_per_sec': (
            'sky_tpu_engine_decode_tokens_per_sec', 'gauge'),
        'num_waiting': ('sky_tpu_engine_num_waiting', 'gauge'),
        'num_active': ('sky_tpu_engine_num_active', 'gauge'),
        'queued_tokens': ('sky_tpu_engine_queued_tokens', 'gauge'),
        'tokens_per_step': (
            'sky_tpu_engine_tokens_per_step', 'gauge'),
        'tokens_in_flight': (
            'sky_tpu_engine_tokens_in_flight', 'gauge'),
        'ttft_p50_s': ('sky_tpu_engine_ttft_p50_seconds', 'gauge'),
        'queue_wait_p50_ms': (
            'sky_tpu_engine_queue_wait_p50_ms', 'gauge'),
        'queue_wait_p99_ms': (
            'sky_tpu_engine_queue_wait_p99_ms', 'gauge'),
        'requests_abandoned': (
            'sky_tpu_engine_requests_abandoned', 'counter'),
        'requests_expired': (
            'sky_tpu_engine_requests_expired', 'counter'),
        'requests_cancelled': (
            'sky_tpu_engine_requests_cancelled', 'counter'),
        'requests_shed': ('sky_tpu_server_requests_shed', 'counter'),
        'server_inflight': ('sky_tpu_server_inflight', 'gauge'),
        'draining': ('sky_tpu_server_draining', 'gauge'),
        'prefill_tokens': (
            'sky_tpu_engine_prefill_tokens', 'counter'),
        'fused_steps': ('sky_tpu_engine_fused_steps', 'counter'),
        'decode_stall_steps': (
            'sky_tpu_engine_decode_stall_steps', 'counter'),
        'spec_steps': ('sky_tpu_engine_spec_steps', 'counter'),
        'spec_drafted_tokens': (
            'sky_tpu_engine_spec_drafted_tokens', 'counter'),
        'spec_accepted_tokens': (
            'sky_tpu_engine_spec_accepted_tokens', 'counter'),
        'spec_accept_rate': (
            'sky_tpu_engine_spec_accept_rate', 'gauge'),
        'accepted_len_mean': (
            'sky_tpu_engine_accepted_len_mean', 'gauge'),
        'pages_total': ('sky_tpu_engine_pages_total', 'gauge'),
        'pages_free': ('sky_tpu_engine_pages_free', 'gauge'),
        'preemptions': ('sky_tpu_engine_preemptions', 'counter'),
        'prefix_hit_rate': (
            'sky_tpu_engine_prefix_hit_rate', 'gauge'),
        'prefix_cached_pages': (
            'sky_tpu_engine_prefix_cached_pages', 'gauge'),
        'prefix_evictions': (
            'sky_tpu_engine_prefix_evictions', 'counter'),
        'stepline_steps': (
            'sky_tpu_engine_stepline_steps', 'counter'),
        'stepline_dumps': (
            'sky_tpu_engine_stepline_dumps', 'counter'),
        # Data-integrity plane (docs/robustness.md "Data integrity");
        # the string-valued ``integrity`` state renders as a labeled
        # state-set, not a scalar.
        'sdc_events_total': (
            'sky_tpu_engine_sdc_events_total', 'counter'),
        # Disaggregated prefill/decode (docs/serving.md).
        'kv_transfers_total': (
            'sky_tpu_engine_kv_transfers_total', 'counter'),
        'kv_transfer_bytes': (
            'sky_tpu_engine_kv_transfer_bytes', 'counter'),
        'kv_transfer_failures': (
            'sky_tpu_engine_kv_transfer_failures', 'counter'),
        'kv_transfer_p99_s': (
            'sky_tpu_engine_kv_transfer_p99_seconds', 'gauge'),
        'prefix_indexed_pages': (
            'sky_tpu_engine_prefix_indexed_pages', 'gauge'),
    }


def label_families() -> Dict[str, Tuple[str, str]]:
    """Labeled families (not scalar-key derived): logical name ->
    (family, type). The logical names pick the renderer branch; the
    family strings are what SKY-REGISTRY cross-checks."""
    return {
        'lb_tenant_requests_total': (
            'sky_tpu_lb_tenant_requests_total', 'counter'),
        'lb_tenant_requests_shed': (
            'sky_tpu_lb_tenant_requests_shed', 'counter'),
        'lb_tenant_requests_failed': (
            'sky_tpu_lb_tenant_requests_failed', 'counter'),
        'lb_tenant_ttft_p99': (
            'sky_tpu_lb_tenant_ttft_p99_seconds', 'gauge'),
        'lb_replica_queue_depth': (
            'sky_tpu_lb_replica_queue_depth', 'gauge'),
        'lb_breaker_state': ('sky_tpu_lb_breaker_state', 'gauge'),
        'lb_draining_replicas': (
            'sky_tpu_lb_draining_replicas', 'gauge'),
        'lb_quarantined_replicas': (
            'sky_tpu_lb_quarantined_replicas', 'gauge'),
        'engine_integrity': (
            'sky_tpu_engine_integrity_state', 'gauge'),
        'slo_burn_rate': ('sky_tpu_lb_slo_burn_rate', 'gauge'),
        'slo_budget': (
            'sky_tpu_lb_slo_error_budget_remaining', 'gauge'),
        'slo_firing': ('sky_tpu_lb_slo_alert_firing', 'gauge'),
        'engine_tenant_queue_depth': (
            'sky_tpu_engine_tenant_queue_depth', 'gauge'),
        'engine_tenant_decode_tokens': (
            'sky_tpu_engine_tenant_decode_tokens', 'counter'),
        'engine_tenant_requests_shed': (
            'sky_tpu_engine_tenant_requests_shed', 'counter'),
        'engine_tenant_ttft_p99': (
            'sky_tpu_engine_tenant_ttft_p99_seconds', 'gauge'),
    }


def _labels(pairs: Mapping[str, Any]) -> str:
    inner = ','.join(
        f'{k}="{store_lib.sanitize_label(v)}"'
        for k, v in sorted(pairs.items()))
    return '{' + inner + '}'


class _Doc:
    """Accumulates exposition samples grouped by family: the text
    format requires ALL lines of one family to form a single
    contiguous group under its # TYPE header, but the renderers
    iterate entity-major (per tenant, per replica, per objective) —
    so samples collect per family here and emit family-major, in
    first-add order, at ``text()`` time."""

    def __init__(self) -> None:
        # family -> (type, {label-suffix: value}); dicts preserve
        # insertion order, so families (and samples within one)
        # render in the order renderers add them.
        self._families: Dict[str, Tuple[str, Dict[str, Any]]] = {}

    def add(self, family: str, mtype: str, value: Any,
            labels: Optional[Mapping[str, Any]] = None) -> None:
        if value is None:
            return
        if isinstance(value, bool):
            value = int(value)
        if not isinstance(value, (int, float)):
            return
        group = self._families.get(family)
        if group is None:
            group = self._families[family] = (mtype, {})
        suffix = _labels(labels) if labels else ''
        # Post-sanitization label COLLISIONS (two tenant ids mapping
        # to one label value) must not emit duplicate series — a
        # scrape containing duplicates is rejected wholesale, a
        # client-triggerable observability outage. Counters fold by
        # sum (the collided series' true total); gauges keep the
        # first sample.
        if suffix in group[1]:
            if mtype == 'counter':
                group[1][suffix] += value
        else:
            group[1][suffix] = value

    def text(self) -> str:
        lines: List[str] = []
        for family, (mtype, samples) in self._families.items():
            lines.append(f'# TYPE {family} {mtype}')
            lines.extend(f'{family}{suffix} {value}'
                         for suffix, value in samples.items())
        return '\n'.join(lines) + '\n'


def _emit_scalars(doc: _Doc, metrics: Mapping[str, Any],
                  exposition: Dict[str, Tuple[str, str]]) -> None:
    for key, (family, mtype) in exposition.items():
        doc.add(family, mtype, metrics.get(key))


def render_lb(metrics: Dict[str, Any]) -> str:
    """The serve LB's ``lb_metrics()`` as Prometheus text."""
    doc = _Doc()
    fams = label_families()
    _emit_scalars(doc, metrics, lb_exposition())
    fam, t = fams['lb_draining_replicas']
    doc.add(fam, t, len(metrics.get('draining') or ()))
    fam, t = fams['lb_quarantined_replicas']
    doc.add(fam, t, len(metrics.get('quarantined') or ()))
    for tenant, row in sorted(
            (metrics.get('tenants') or {}).items()):
        labels = {'tenant': tenant}
        fam, t = fams['lb_tenant_requests_total']
        doc.add(fam, t, row.get('requests_total'), labels)
        fam, t = fams['lb_tenant_requests_shed']
        doc.add(fam, t, row.get('requests_shed'), labels)
        fam, t = fams['lb_tenant_requests_failed']
        doc.add(fam, t, row.get('requests_failed'), labels)
        fam, t = fams['lb_tenant_ttft_p99']
        doc.add(fam, t, row.get('ttft_p99_s'), labels)
    for url, depth in sorted(
            (metrics.get('replica_queue_depth') or {}).items()):
        fam, t = fams['lb_replica_queue_depth']
        doc.add(fam, t, depth, {'replica': url})
    for url, state in sorted((metrics.get('breaker') or {}).items()):
        # One series per (replica, state), value 1 for the active
        # state — the standard state-set encoding.
        fam, t = fams['lb_breaker_state']
        doc.add(fam, t, 1, {'replica': url, 'state': state})
    for key, row in sorted((metrics.get('slo') or {}).items()):
        labels = {'objective': key}
        fam, t = fams['slo_budget']
        doc.add(fam, t, row.get('error_budget_remaining'), labels)
        for tier in ('page', 'ticket'):
            for window in ('short', 'long'):
                fam, t = fams['slo_burn_rate']
                doc.add(fam, t, row.get(f'{tier}_burn_{window}'),
                        {**labels, 'tier': tier, 'window': window})
            fam, t = fams['slo_firing']
            doc.add(fam, t, row.get(f'{tier}_firing'),
                    {**labels, 'tier': tier})
    return doc.text()


def render_replica(metrics: Dict[str, Any]) -> str:
    """An inference replica's ``/metrics`` JSON as Prometheus text
    (EnginePool tiers stay JSON-only; the pool-level rollup is what
    the fleet scrape wants)."""
    doc = _Doc()
    fams = label_families()
    _emit_scalars(doc, metrics, replica_exposition())
    integ = metrics.get('integrity')
    if isinstance(integ, str):
        # State-set encoding (the breaker-state rule): one series per
        # state, value 1 for the active one — a string never survives
        # _Doc.add as a scalar.
        fam, t = fams['engine_integrity']
        doc.add(fam, t, 1, {'state': integ})
    for tenant, row in sorted(
            (metrics.get('tenants') or {}).items()):
        if not isinstance(row, dict):
            continue
        labels = {'tenant': tenant}
        fam, t = fams['engine_tenant_queue_depth']
        doc.add(fam, t, row.get('queue_depth'), labels)
        fam, t = fams['engine_tenant_decode_tokens']
        doc.add(fam, t, row.get('decode_tokens'), labels)
        fam, t = fams['engine_tenant_requests_shed']
        doc.add(fam, t, row.get('requests_shed'), labels)
        fam, t = fams['engine_tenant_ttft_p99']
        doc.add(fam, t, row.get('ttft_p99_s'), labels)
    return doc.text()
