"""Data-integrity plane: golden probes, SDC verdicts, quarantine.

The serving tier survives replicas that are *dead* (breakers),
*slow* (burn-rate alerting + the brownout gates), and control planes
that are *killed* (the intent journal) — this module is the layer for
replicas that are **wrong**: silent data corruption (a bad HBM bank,
a flaky chip, a desynced lockstep host) that serves divergent tokens
while every liveness probe reads healthy. The repo's greedy
bit-determinism invariant makes byte-exact integrity checking
uniquely cheap: a correct replica's greedy continuation of a fixed
prompt is a known constant, so "is this replica wrong?" is one tiny
``/generate`` round trip and a CRC compare.

Three detectors feed one quarantine state machine
(docs/robustness.md "Data integrity"):

- **On-device SDC sentinel** (``infer/engine.py``): a
  ``jnp.isfinite`` reduction over each step's logits rides the
  existing readback pair as one extra int32 row — no extra transfer,
  no new compiled programs. A NaN/inf hit marks the engine
  ``integrity_suspect``; ``/health`` flips to 503 ``"corrupt"`` and
  ``/generate`` sheds with a ``"quarantined"`` reason body.
- **Golden-probe canaries** (``serve/load_balancer.py``): the LB
  periodically replays a versioned golden prompt (this module's
  fixtures) against each READY replica through the normal
  ``/generate`` path and compares the delivered token ids' CRC
  against the fixture. Mismatch or a corrupt self-report =>
  ``ReplicaStatus.QUARANTINED`` (status + intent in one txn —
  crash-safe) => drain-and-replace, with in-flight streams re-issued
  via the resume splice. Probe traffic is invisible to tenant
  ledgers, SLO windows and wfq quotas; a probe *transport* failure
  counts integrity (``probe_failures_total``), never availability.
- **Multihost desync detection** (``infer/multihost.py``): each
  lockstep tick all-gathers a per-host output digest; any mismatch
  fails the slice loudly (watchdog exit => relaunch) instead of
  streaming diverged tokens.

Golden fixtures are keyed by the model+tokenizer identity and carry
the oracle **fingerprint** they were minted against. Arming probes
validates the fingerprint first (:func:`check_fixture`): a stale
golden fails loudly at arm time — the alternative failure mode is
every healthy replica "failing" the probe, i.e. a fleet-wide
quarantine storm. ``make golden-refresh`` re-mints the fixtures.
"""
from __future__ import annotations

import dataclasses
import json
import os
import zlib
from typing import Any, Dict, Optional, Sequence

# Bumped when the fixture JSON schema changes (not when a model's
# golden continuation changes — that is the fingerprint's job).
GOLDEN_VERSION = 1

# The tenant id probe requests ride under. Reserved: the LB refuses
# to ledger it, the SLO evaluator never ingests it, and a leading
# underscore keeps it out of any real tenant namespace.
PROBE_TENANT = '_probe'


class StaleGoldenError(Exception):
    """The golden fixture was minted against a different oracle
    (model/tokenizer/sim-oracle version) than the one now serving.
    Raised at probe-ARM time on purpose: armed anyway, every healthy
    replica would fail the probe and the fleet would quarantine
    itself."""


def token_crc(tokens: Sequence[int]) -> int:
    """Stable digest of a delivered token-id sequence (zlib.crc32
    over the canonical JSON — never builtin ``hash``, which is
    per-process salted)."""
    return zlib.crc32(json.dumps([int(t) for t in tokens]).encode())


@dataclasses.dataclass(frozen=True)
class GoldenFixture:
    """One versioned golden probe: a tiny fixed greedy prompt and the
    CRC of its known-correct continuation."""
    model: str           # model+tokenizer identity key (e.g. 'sim')
    fingerprint: str     # oracle identity the golden was minted for
    prompt_tokens: tuple
    max_new_tokens: int
    token_crc: int
    version: int = GOLDEN_VERSION

    def payload(self) -> Dict[str, Any]:
        """The probe's ``/generate`` body — the NORMAL serving path
        (greedy, streaming), so the probe exercises exactly what
        tenants ride."""
        return {'tokens': list(self.prompt_tokens),
                'max_new_tokens': int(self.max_new_tokens),
                'temperature': 0.0, 'stream': True,
                'tenant': PROBE_TENANT}


def fixtures_path() -> str:
    """The in-tree fixture store (``make golden-refresh`` rewrites
    it); ``SKY_TPU_GOLDEN_FIXTURES`` points deployments elsewhere."""
    return (os.environ.get('SKY_TPU_GOLDEN_FIXTURES')
            or os.path.join(os.path.dirname(__file__),
                            'golden_probes.json'))


def load_fixture(model: str,
                 path: Optional[str] = None) -> GoldenFixture:
    """Load the golden fixture for ``model``. Raises
    :class:`StaleGoldenError` on a missing/unreadable store, an
    unknown model, or a fixture-schema version mismatch — arming
    probes without a trustworthy golden is the quarantine-storm
    failure mode this loud path exists to prevent."""
    p = path or fixtures_path()
    try:
        with open(p, encoding='utf-8') as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise StaleGoldenError(
            f'golden fixture store {p!r} unreadable: {e}; run '
            f'`make golden-refresh`') from e
    if int(doc.get('version') or 0) != GOLDEN_VERSION:
        raise StaleGoldenError(
            f'golden fixture store {p!r} is schema v'
            f'{doc.get("version")}, expected v{GOLDEN_VERSION}; run '
            f'`make golden-refresh`')
    row = (doc.get('fixtures') or {}).get(model)
    if row is None:
        raise StaleGoldenError(
            f'no golden fixture for model {model!r} in {p!r}; run '
            f'`make golden-refresh`')
    return GoldenFixture(
        model=model, fingerprint=str(row['fingerprint']),
        prompt_tokens=tuple(int(t) for t in row['prompt_tokens']),
        max_new_tokens=int(row['max_new_tokens']),
        token_crc=int(row['token_crc']))


def check_fixture(fixture: GoldenFixture,
                  current_fingerprint: str) -> GoldenFixture:
    """The probe-ARM gate: the fixture must have been minted against
    the oracle now serving. Returns the fixture for chaining."""
    if fixture.fingerprint != current_fingerprint:
        raise StaleGoldenError(
            f'golden fixture for {fixture.model!r} was minted for '
            f'oracle {fixture.fingerprint!r} but the serving oracle '
            f'is {current_fingerprint!r} — refusing to arm probes '
            f'(a stale golden reads as a fleet-wide quarantine '
            f'storm); run `make golden-refresh`')
    return fixture


def refresh_golden(path: Optional[str] = None) -> Dict[str, Any]:
    """``make golden-refresh``: re-mint the fixture store from the
    oracles available in-tree. Today that is the digital twin's sim
    oracle (real model fixtures are minted at deploy time against
    the served checkpoint by the same schema); the prompt is
    deliberately TINY — a handful of tokens — so a probe costs a few
    decode steps and rides admission like any small request."""
    from skypilot_tpu.sim import replica as replica_lib
    prompt = (2, 3, 5, 7)
    n = 4
    golden = replica_lib.expected_continuation(list(prompt), n)
    doc = {
        'version': GOLDEN_VERSION,
        'fixtures': {
            'sim': {
                'fingerprint': replica_lib.oracle_fingerprint(),
                'prompt_tokens': list(prompt),
                'max_new_tokens': n,
                'token_crc': token_crc(golden),
            },
        },
    }
    p = path or fixtures_path()
    with open(p, 'w', encoding='utf-8') as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write('\n')
    return doc


def _smoke() -> int:
    """``make integrity-smoke``: replay the ``sdc_storm`` scenario in
    the digital twin and prove the whole plane end to end — every
    poisoned replica detected and QUARANTINED within the probe
    budget, replaced by the autoscaler, zero wrong tokens in any
    completed client stream — then replay the brownout scenario with
    probes armed and prove zero false quarantines (slow is NOT
    corrupt). Exit 0 = the data-integrity plane works end to end."""
    import dataclasses as dc
    import logging

    from skypilot_tpu.sim import DigitalTwin, sdc_storm, slow_brownout

    logging.disable(logging.WARNING)
    try:
        sc = sdc_storm()
        report = DigitalTwin(sc, seed=3).run()
        poisoned = sum(f.count for f in sc.faults if f.kind == 'sdc')
        quarantines = [d for d in report.decisions
                       if d['kind'] == 'quarantine']
        if len(quarantines) != poisoned:
            print(f'integrity-smoke: {poisoned} replicas poisoned '
                  f'but {len(quarantines)} quarantined: '
                  f'{quarantines}')
            return 1
        budget_s = 3 * (sc.probe_interval_s or 0) + 3 * sc.lb_sync_s
        for fault in (f for f in sc.faults if f.kind == 'sdc'):
            hits = [q for q in quarantines
                    if fault.t <= q['t'] <= fault.t + budget_s]
            if not hits:
                print(f'integrity-smoke: the {fault.flavor} fault at '
                      f't={fault.t} was not quarantined within '
                      f'{budget_s:.0f}s (3 probe rounds)')
                return 1
        bad = [r for r in report.records
               if r['completed'] and not r['tokens_ok']]
        if bad:
            print(f'integrity-smoke: {len(bad)} completed stream(s) '
                  f'delivered wrong tokens; first: {bad[0]}')
            return 1
        fleet = report.final_fleet or {}
        if (fleet.get('ready') or 0) < sc.replicas:
            print(f'integrity-smoke: fleet never healed — '
                  f'{fleet.get("ready")} ready < {sc.replicas}: '
                  f'{fleet}')
            return 1
        # Slow is NOT corrupt: the brownout replay with probes armed
        # must produce ZERO quarantines (the probe rides admission
        # and tolerates latency; only wrong bytes quarantine).
        brown = dc.replace(slow_brownout(),
                           probe_interval_s=sc.probe_interval_s)
        brown_report = DigitalTwin(brown, seed=3).run()
        false_q = [d for d in brown_report.decisions
                   if d['kind'] == 'quarantine']
        if false_q:
            print(f'integrity-smoke: brownout replay produced false '
                  f'quarantines: {false_q}')
            return 1
        if brown_report.client_errors:
            print(f'integrity-smoke: brownout replay had client '
                  f'errors: {brown_report.client_errors[:3]}')
            return 1
    finally:
        logging.disable(logging.NOTSET)
    print('integrity-smoke OK:', json.dumps({
        'poisoned': poisoned,
        'quarantined': len(quarantines),
        'resumed': report.lb_metrics['requests_resumed'],
        'completed': report.completed,
        'brownout_quarantines': 0}))
    return 0


if __name__ == '__main__':
    import sys

    # `python -m` runs this file as `__main__` — a second module
    # object. Delegate to the canonical package import (the stepline
    # rule) so module globals are the ones the LB uses.
    from skypilot_tpu.observability import integrity as _canonical
    if '--refresh' in sys.argv:
        doc = _canonical.refresh_golden()
        print('golden-refresh OK:', json.dumps(sorted(
            doc['fixtures'])))
        sys.exit(0)
    sys.exit(_canonical._smoke())
