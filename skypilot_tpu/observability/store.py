"""Span store: sqlite-backed persistence for shipped spans.

Same shape as ``server/requests_store.py`` over ``utils/db.py``: one
logical store (``traces.db`` under the state dir, or a pg schema when
``SKY_TPU_DB_URL`` is set), plain accessors, no ORM. ``ingest()`` is
the single write path — every shipped batch lands here, feeds the
``sky_tpu_span_duration_seconds{op,hop}`` Prometheus series, and
triggers the size-capped GC so a busy control plane cannot grow the
trace DB without bound.
"""
from __future__ import annotations

import json
import os
import re
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import common
from skypilot_tpu.utils import db as db_util

# Whole-trace GC cap (rows). Oldest traces are dropped first; a trace is
# never half-deleted (a broken parent chain renders as orphans).
MAX_SPANS_ENV = 'SKY_TPU_TRACE_MAX_SPANS'
DEFAULT_MAX_SPANS = 100_000
# Age-based retention: whole traces whose NEWEST span is older than
# this many seconds are dropped at GC time, regardless of the row
# count — a long-lived replica under the size cap must not keep
# week-old flight-recorder rings around. 0/unset disables the TTL;
# both caps compose (age first, then size).
TTL_ENV = 'SKY_TPU_TRACE_TTL_S'

_SCHEMA = """
CREATE TABLE IF NOT EXISTS spans (
    trace_id TEXT,
    span_id TEXT,
    parent_id TEXT,
    name TEXT,
    hop TEXT,
    start_ts REAL,
    dur_s REAL,
    status TEXT,
    attrs_json TEXT,
    request_id TEXT
);
CREATE INDEX IF NOT EXISTS idx_spans_trace ON spans (trace_id);
CREATE INDEX IF NOT EXISTS idx_spans_request ON spans (request_id);
CREATE INDEX IF NOT EXISTS idx_spans_start ON spans (start_ts);
"""


class SpanStore:
    def __init__(self, db_path: Optional[str] = None):
        self.db_path = db_path or os.path.join(common.base_dir(),
                                               'traces.db')

    @property
    def _conn(self):
        return db_util.get_db(self.db_path, _SCHEMA).conn

    def add_spans(self, spans: List[Dict[str, Any]]) -> int:
        rows = []
        for s in spans:
            attrs = s.get('attrs') or {}
            if not isinstance(attrs, dict):
                attrs = {}
            attrs_json = json.dumps(attrs, default=str)
            # Attr payloads are caller-controlled (and the collector
            # endpoint is unauthenticated): bound bytes per span so the
            # store's GC row cap is also, in effect, a byte cap.
            if len(attrs_json) > 8192:
                attrs_json = json.dumps(
                    {'_truncated': True,
                     'request_id': attrs.get('request_id')})
            rows.append((
                str(s['trace_id'])[:64], str(s['span_id'])[:64],
                (str(s['parent_id'])[:64]
                 if s.get('parent_id') is not None else None),
                str(s.get('name', ''))[:256], str(s.get('hop', ''))[:64],
                float(s.get('start', 0.0)), float(s.get('dur_s', 0.0)),
                str(s.get('status', 'ok'))[:128], attrs_json,
                (str(attrs['request_id'])[:64]
                 if attrs.get('request_id') is not None else None),
            ))
        if not rows:
            return 0
        self._conn.executemany(
            'INSERT INTO spans (trace_id, span_id, parent_id, name, hop,'
            ' start_ts, dur_s, status, attrs_json, request_id) '
            'VALUES (?,?,?,?,?,?,?,?,?,?)', rows)
        self._conn.commit()
        return len(rows)

    @staticmethod
    def _row_to_span(row) -> Dict[str, Any]:
        d = dict(row)
        d['attrs'] = json.loads(d.pop('attrs_json') or '{}')
        d['start'] = d.pop('start_ts')
        return d

    def get_trace(self, trace_id: str) -> List[Dict[str, Any]]:
        rows = self._conn.execute(
            'SELECT * FROM spans WHERE trace_id=? ORDER BY start_ts',
            (trace_id,)).fetchall()
        return [self._row_to_span(r) for r in rows]

    def trace_id_for_request(self, request_id: str) -> Optional[str]:
        row = self._conn.execute(
            'SELECT trace_id FROM spans WHERE request_id=? '
            'ORDER BY start_ts LIMIT 1', (request_id,)).fetchone()
        return row['trace_id'] if row else None

    def trace_for_request(self, request_id: str) -> List[Dict[str, Any]]:
        trace_id = self.trace_id_for_request(request_id)
        if trace_id is None:
            return []
        return self.get_trace(trace_id)

    def trace_ids_for_request(self, request_id: str) -> List[str]:
        """Every trace containing the request, newest first. A request
        can appear in both its ordinary propagated-span trace and one
        or more flight-recorder dumps (``stepline-*``); callers that
        want a specific kind filter on the trace-id prefix."""
        rows = self._conn.execute(
            'SELECT trace_id, MAX(start_ts) AS newest FROM spans '
            'WHERE request_id=? GROUP BY trace_id '
            'ORDER BY newest DESC', (request_id,)).fetchall()
        return [r['trace_id'] for r in rows]

    def list_traces(self, limit: int = 50,
                    trace_id_prefix: Optional[str] = None,
                    ) -> List[Dict[str, Any]]:
        """Most-recent-first trace summaries (for `sky-tpu trace` with
        no argument / the API listing). ``trace_id_prefix`` filters
        SERVER-side (``stepline-`` for flight-recorder dumps) — a
        post-filtered page would lose dumps behind ``limit`` newer
        ordinary traces on a busy store."""
        where = ''
        args: tuple = ()
        if trace_id_prefix:
            esc = (trace_id_prefix.replace('\\', '\\\\')
                   .replace('%', '\\%').replace('_', '\\_'))
            where = "WHERE trace_id LIKE ? ESCAPE '\\' "
            args = (esc + '%',)
        rows = self._conn.execute(
            'SELECT trace_id, MIN(start_ts) AS start_ts,'
            ' COUNT(*) AS n_spans, MAX(request_id) AS request_id '
            'FROM spans ' + where + 'GROUP BY trace_id '
            'ORDER BY start_ts DESC LIMIT ?',
            args + (limit,)).fetchall()
        out = []
        for r in rows:
            d = dict(r)
            root = self._conn.execute(
                'SELECT name FROM spans WHERE trace_id=? AND '
                'parent_id IS NULL ORDER BY start_ts LIMIT 1',
                (d['trace_id'],)).fetchone()
            d['root'] = root['name'] if root else None
            out.append(d)
        return out

    def count(self) -> int:
        return self._conn.execute(
            'SELECT COUNT(*) AS n FROM spans').fetchone()['n']

    def gc(self, max_spans: Optional[int] = None,
           ttl_s: Optional[float] = None) -> int:
        """Drop whole traces past the age TTL (``SKY_TPU_TRACE_TTL_S``;
        a trace's age is its NEWEST span), then oldest whole traces
        until the row count fits the size cap. The two caps compose:
        age first — so the size pass only ever sees live-window traces
        — then size. Returns total rows deleted.

        Set-based: one aggregate scan picks the victim traces, one
        DELETE drops them — a per-trace loop would re-COUNT the full
        table thousands of times when small SDK traces pushed it over
        cap."""
        if max_spans is None:
            max_spans = int(os.environ.get(MAX_SPANS_ENV,
                                           DEFAULT_MAX_SPANS))
        if ttl_s is None:
            try:
                ttl_s = float(os.environ.get(TTL_ENV, '0') or 0)
            except ValueError:
                ttl_s = 0.0
        deleted = 0
        if ttl_s and ttl_s > 0:
            cutoff = time.time() - ttl_s
            # Single statement with ONE bound variable: a populated
            # store's first TTL pass can expire tens of thousands of
            # small traces, and an IN (?,?,...) victim list would
            # blow sqlite's bound-variable limit and fail ingest.
            cur = self._conn.execute(
                'DELETE FROM spans WHERE trace_id IN ('
                'SELECT trace_id FROM spans GROUP BY trace_id '
                'HAVING MAX(start_ts) < ?)', (cutoff,))
            self._conn.commit()
            deleted += cur.rowcount
        excess = self.count() - max_spans
        if excess <= 0:
            return deleted
        rows = self._conn.execute(
            'SELECT trace_id, COUNT(*) AS n FROM spans '
            'GROUP BY trace_id ORDER BY MIN(start_ts)').fetchall()
        victims = []
        for r in rows:
            if excess <= 0:
                break
            victims.append(r['trace_id'])
            excess -= r['n']
        if not victims:
            return deleted
        marks = ','.join('?' for _ in victims)
        cur = self._conn.execute(
            f'DELETE FROM spans WHERE trace_id IN ({marks})',
            tuple(victims))
        self._conn.commit()
        return deleted + cur.rowcount


_ingest_count = 0

# Spans can arrive over the auth-exempt collector endpoint, and
# serving-tier labels (tenant ids) are client-controlled: label
# values fed to Prometheus must not be able to corrupt the exposition
# format (quotes/newlines) or carry unbounded payloads. This is THE
# canonical sanitization rule — observability/prometheus.py reuses it
# for every serving-exposition label.
_LABEL_RE = re.compile(r'[^A-Za-z0-9_.:/\-]')


def sanitize_label(value: Any) -> str:
    return _LABEL_RE.sub('_', str(value))[:64]


_label = sanitize_label


def ingest(spans: List[Dict[str, Any]],
           store: Optional[SpanStore] = None) -> int:
    """The one write path for shipped spans: persist, feed the metrics
    registry, and GC occasionally. Used directly by the API server's
    sink and by its POST /api/traces handler."""
    global _ingest_count
    if not spans:
        return 0
    store = store or SpanStore()
    n = store.add_spans(spans)
    from skypilot_tpu.server import metrics as metrics_lib
    for s in spans:
        try:
            metrics_lib.observe_span(_label(s.get('name', '')),
                                     _label(s.get('hop', '')),
                                     float(s.get('dur_s', 0.0)))
        except Exception:  # noqa: BLE001 — telemetry must not throw
            pass
    _ingest_count += 1
    # Amortized GC: the cap check is a COUNT(*) — cheap, but not free on
    # every batch.
    if _ingest_count % 20 == 0:
        store.gc()
    return n
