"""Trace surfacing: span-tree text rendering + Perfetto export.

``render_tree`` drives ``sky-tpu trace <request_id>`` — an indented
tree with per-hop latency so a slow launch reads as "provision took
41s of the 44s total, and 39s of that was wait_healthy on the agent".

``to_perfetto`` emits Chrome-trace JSON in the SAME event shape as
``utils/timeline.py`` ('X' complete events, microsecond timestamps),
so a process's local timeline events (intra-process profiling) merge
into one file with the propagated spans and nest visually under them
in Perfetto / chrome://tracing.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional


def build_tree(spans: List[Dict[str, Any]]
               ) -> List[Dict[str, Any]]:
    """Parent-link the flat span list into a forest (roots returned,
    children attached as ``span['children']``, sorted by start time).
    Spans whose parent never arrived (a hop's ship was dropped —
    fail-open tracing guarantees only best effort) become roots rather
    than vanishing."""
    by_id = {s['span_id']: dict(s, children=[]) for s in spans}
    roots: List[Dict[str, Any]] = []
    for s in by_id.values():
        parent = by_id.get(s.get('parent_id') or '')
        if parent is not None and parent is not s:
            parent['children'].append(s)
        else:
            roots.append(s)
    def _sort(nodes):
        nodes.sort(key=lambda n: n.get('start') or 0.0)
        for n in nodes:
            _sort(n['children'])
    _sort(roots)
    return roots


def _fmt_dur(dur_s: float) -> str:
    if dur_s >= 1.0:
        return f'{dur_s:.2f}s'
    return f'{dur_s * 1000:.1f}ms'


def render_tree(spans: List[Dict[str, Any]]) -> str:
    """ASCII span tree with per-hop latency and status."""
    if not spans:
        return '(no spans)'
    roots = build_tree(spans)
    trace_id = spans[0].get('trace_id', '?')
    lines = [f'trace {trace_id} — {len(spans)} spans']

    def walk(node: Dict[str, Any], prefix: str, last: bool) -> None:
        branch = '└─ ' if last else '├─ '
        status = node.get('status') or 'ok'
        flag = '' if status == 'ok' else f'  [{status}]'
        attrs = node.get('attrs') or {}
        extra = ''
        if attrs:
            short = {k: v for k, v in sorted(attrs.items())
                     if k != 'request_id'}
            if short:
                kv = ', '.join(f'{k}={v}' for k, v in short.items())
                extra = f'  ({kv})'
        lines.append(
            f'{prefix}{branch}{node.get("name", "?")} '
            f'[{node.get("hop", "?")}] '
            f'{_fmt_dur(float(node.get("dur_s") or 0.0))}{flag}{extra}')
        children = node['children']
        child_prefix = prefix + ('   ' if last else '│  ')
        for i, c in enumerate(children):
            walk(c, child_prefix, i == len(children) - 1)

    for i, r in enumerate(roots):
        walk(r, '', i == len(roots) - 1)
    return '\n'.join(lines)


def to_perfetto(spans: List[Dict[str, Any]],
                extra_events: Optional[List[Dict[str, Any]]] = None
                ) -> Dict[str, Any]:
    """Chrome trace JSON. Each hop becomes a pid row (named via
    process_name metadata); spans become 'X' events whose ts/dur are in
    microseconds of wall time, so cross-hop spans line up on one clock.
    ``extra_events`` takes raw ``utils/timeline.py`` events (already in
    this format) and merges them verbatim."""
    hops = []
    events: List[Dict[str, Any]] = []
    for s in spans:
        hop = s.get('hop') or '?'
        if hop not in hops:
            hops.append(hop)
        ev = {
            'name': s.get('name', '?'),
            'ph': 'X',
            'ts': float(s.get('start') or 0.0) * 1e6,
            'dur': float(s.get('dur_s') or 0.0) * 1e6,
            'pid': hops.index(hop) + 1,
            'tid': 1,
            'args': {
                'trace_id': s.get('trace_id'),
                'span_id': s.get('span_id'),
                'parent_id': s.get('parent_id'),
                'status': s.get('status'),
                **(s.get('attrs') or {}),
            },
        }
        events.append(ev)
    meta = [
        {'name': 'process_name', 'ph': 'M', 'pid': i + 1, 'tid': 1,
         'args': {'name': hop}} for i, hop in enumerate(hops)
    ]
    if extra_events:
        events.extend(extra_events)
    return {'traceEvents': meta + events, 'displayTimeUnit': 'ms'}
