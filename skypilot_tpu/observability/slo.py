"""Fleet SLO engine: declarative objectives + multi-window
multi-burn-rate alerting over the serving tier's live signals.

Everything before this module *exported* signals (per-tenant TTFT and
queue-wait, LB edge counters, the PR 12 fleet history rings); nothing
*interpreted* them — there was no machine-checkable answer to "is the
fleet meeting its latency objective for tenant X right now?". This
module adds the interpretation layer:

- :class:`SloObjective` — a declarative objective over one service
  level indicator (SLI): TTFT p99, ITL p99, request availability,
  shed rate, or replica responsiveness; fleet-wide or scoped to one
  tenant; loaded from the service spec's ``slo:`` section (validated
  at ``serve up`` time) or the ``SKY_TPU_LB_SLO`` env override.
- :class:`SloEvaluator` — the SRE-workbook multi-window multi-burn
  evaluator: each SLI is a time-bucketed good/bad event series; an
  alert **tier** fires when the burn rate (error rate over the
  window, divided by the objective's error budget ``1 - target``)
  exceeds the tier's threshold on BOTH its short and long window.
  Two shipped tiers: **page** (5m/1h at burn 14.4 — burning a 30-day
  budget in ~2 days) and **ticket** (30m/6h at burn 6). The long
  window proves the burn is sustained; the short window clears the
  alert promptly after recovery.

The evaluator is clock-free by construction: every entry point takes
``now`` explicitly, so the SAME code runs on the production wall
clock (the LB passes its injected ``vclock`` reads) and inside the
digital twin's virtual time — which is what makes alert FIDELITY
provable: ``tests/sim/test_slo_alerts.py`` replays incident and
brownout scenarios and asserts the page tier fires within a bounded
number of virtual minutes, clears after recovery, and stays silent
on degraded-but-within-SLO fleets, with the alert decision log
byte-identical per seed.

Wiring (docs/observability.md "SLOs and alerting"): the serve LB
drives :meth:`SloEvaluator.evaluate` from its existing sync tick,
feeds latency samples from its TTFT/ITL stopwatches, outcome counters
by delta, and replica freshness from the PR 12 history-ring staleness
rule (a hung replica counts BAD instead of silently masking a fleet
burn). Surfaces: alert/budget gauges in ``lb_metrics()``, the
``/-/alerts`` endpoint, Prometheus exposition
(``observability/prometheus.py``), a page-tier firing edge triggers a
``stepline.fleet_dump`` flight-recorder capture, and the max page
burn is flushed to the state DB as the autoscaler's ``slo_burn``
scale-up input.
"""
from __future__ import annotations

import collections
import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions

# Supported SLI metrics. Latency metrics classify each sample against
# ``threshold_s`` (the objective "p99 TTFT <= threshold" IS the SLI
# "fraction of requests faster than threshold >= target"); the
# counter metrics classify request outcomes; ``replica_availability``
# classifies per-sync-tick replica responsiveness (the PR 12
# freshest-ring staleness rule).
LATENCY_METRICS = ('ttft_p99', 'itl_p99')
COUNTER_METRICS = ('availability', 'shed_rate')
REPLICA_METRICS = ('replica_availability',)
METRICS = LATENCY_METRICS + COUNTER_METRICS + REPLICA_METRICS

# Bucket width of the good/bad event series. Finer than the shortest
# window by >10x so window sums are sharp at tick cadence.
DEFAULT_BUCKET_S = 15.0
# A window with fewer total events returns burn 0.0 — two bad events
# out of three must not page anyone (the sparse-sample rule).
DEFAULT_MIN_SAMPLES = 12
# Error-budget accounting horizon (the "remaining budget" gauge; a
# 30-day horizon is meaningless inside a replay, so it is a knob).
DEFAULT_BUDGET_WINDOW_S = 24 * 3600.0
# Env override for a stand-alone LB without a service spec.
SLO_ENV = 'SKY_TPU_LB_SLO'


@dataclasses.dataclass(frozen=True)
class BurnTier:
    """One alert tier: fires when burn >= ``burn`` on BOTH windows."""
    tier: str
    short_s: float
    long_s: float
    burn: float


# The SRE-workbook defaults: page = fast burn (14.4x eats a 30-day
# budget in ~2 days), ticket = slow burn worth a work-hours look.
PAGE = BurnTier('page', 300.0, 3600.0, 14.4)
TICKET = BurnTier('ticket', 1800.0, 21600.0, 6.0)
DEFAULT_TIERS = (PAGE, TICKET)


@dataclasses.dataclass(frozen=True)
class SloObjective:
    """One declarative objective. ``target`` is the good-event
    fraction (0.99 = "99% of events good", error budget 1%);
    ``tenant`` scopes the SLI to one tenant's events (None =
    fleet-wide); ``threshold_s`` classifies latency samples."""
    metric: str
    target: float = 0.99
    threshold_s: Optional[float] = None
    tenant: Optional[str] = None
    name: str = ''

    @property
    def key(self) -> str:
        if self.name:
            return self.name
        if self.tenant:
            return f'{self.metric}:{self.tenant}'
        return self.metric

    def to_config(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {'metric': self.metric,
                               'target': self.target}
        if self.threshold_s is not None:
            out['threshold_s'] = self.threshold_s
        if self.tenant is not None:
            out['tenant'] = self.tenant
        if self.name:
            out['name'] = self.name
        return out


def objectives_from_spec(config: Any) -> List[SloObjective]:
    """Parse + validate the ``slo:`` list of a service spec (also the
    ``SKY_TPU_LB_SLO`` env JSON). Raises ``InvalidTaskError`` on a bad
    entry so `serve up` rejects a misconfigured objective instead of
    the LB silently evaluating garbage."""
    if config is None:
        return []
    if not isinstance(config, (list, tuple)):
        raise exceptions.InvalidTaskError(
            f'service slo must be a list of objectives, got '
            f'{type(config).__name__}')
    out: List[SloObjective] = []
    seen: set = set()
    for i, entry in enumerate(config):
        if not isinstance(entry, dict):
            raise exceptions.InvalidTaskError(
                f'slo[{i}] must be a mapping, got '
                f'{type(entry).__name__}')
        unknown = set(entry) - {'metric', 'target', 'threshold_s',
                                'tenant', 'name'}
        if unknown:
            raise exceptions.InvalidTaskError(
                f'slo[{i}]: unknown fields {sorted(unknown)}')
        metric = str(entry.get('metric') or '')
        if metric not in METRICS:
            raise exceptions.InvalidTaskError(
                f'slo[{i}]: unknown metric {metric!r}; choose from '
                f'{list(METRICS)}')
        try:
            target = float(entry.get('target', 0.99))
        except (TypeError, ValueError):
            raise exceptions.InvalidTaskError(
                f'slo[{i}]: target must be a number') from None
        if not 0.0 < target < 1.0:
            raise exceptions.InvalidTaskError(
                f'slo[{i}]: target must be in (0, 1), got {target}')
        threshold = entry.get('threshold_s')
        if metric in LATENCY_METRICS:
            try:
                threshold = float(threshold)
            except (TypeError, ValueError):
                raise exceptions.InvalidTaskError(
                    f'slo[{i}]: {metric} requires a positive '
                    f'threshold_s') from None
            if threshold <= 0:
                raise exceptions.InvalidTaskError(
                    f'slo[{i}]: threshold_s must be > 0')
        elif threshold is not None:
            raise exceptions.InvalidTaskError(
                f'slo[{i}]: threshold_s only applies to latency '
                f'metrics ({list(LATENCY_METRICS)})')
        tenant = entry.get('tenant')
        if tenant is not None:
            tenant = str(tenant)
            if metric in REPLICA_METRICS:
                raise exceptions.InvalidTaskError(
                    f'slo[{i}]: {metric} is fleet-wide only')
        obj = SloObjective(metric=metric, target=target,
                           threshold_s=threshold, tenant=tenant,
                           name=str(entry.get('name') or ''))
        if obj.key in seen:
            raise exceptions.InvalidTaskError(
                f'slo[{i}]: duplicate objective key {obj.key!r} '
                f'(set a distinct name)')
        seen.add(obj.key)
        out.append(obj)
    return out


class _Series:
    """Time-bucketed good/bad event counts: O(1) append into the
    newest bucket, bounded deque so the ring wraps (oldest buckets
    drop) instead of growing without bound. Not thread-safe — the
    owning evaluator serializes (same contract as the stepline
    rings)."""

    __slots__ = ('width', 'buckets')

    def __init__(self, width_s: float, keep_s: float) -> None:
        self.width = max(1.0, float(width_s))
        self.buckets: collections.deque = collections.deque(
            maxlen=int(keep_s / self.width) + 2)

    def add(self, now: float, good: int = 0, bad: int = 0) -> None:
        idx = int(now // self.width)
        if self.buckets and self.buckets[-1][0] >= idx:
            # Same bucket (or a stale stamp — never with vclock, but
            # fold rather than rewind: the series is append-only).
            cell = self.buckets[-1][1]
            cell[0] += good
            cell[1] += bad
        else:
            self.buckets.append((idx, [good, bad]))

    def window(self, now: float, window_s: float) -> Tuple[int, int]:
        """(good, bad) totals over ``[now - window_s, now]``."""
        cutoff = now - window_s
        good = bad = 0
        for idx, (g, b) in reversed(self.buckets):
            if (idx + 1) * self.width <= cutoff:
                break
            good += g
            bad += b
        return good, bad


class SloEvaluator:
    """The burn-rate evaluator: per-objective event series, tiered
    alert state, budget gauges, and an append-only transition log
    (the byte-identity surface the twin gates hash).

    Clock-free: every method takes ``now``; the caller (the LB)
    passes its injected clock's reads, so production and the digital
    twin run the identical code path. Single-context by contract —
    every field is owner-confined (``_GUARDED_BY``): the LB touches
    it only from its event loop, unit tests from one thread.
    """

    _GUARDED_BY = {
        '_series': 'owner',
        '_last_counters': 'owner',
        '_last_tenants': 'owner',
        '_firing': 'owner',
        '_firing_since': 'owner',
        '_transitions': 'owner',
        '_seq': 'owner',
    }

    def __init__(self, objectives: List[SloObjective], *,
                 tiers: Tuple[BurnTier, ...] = DEFAULT_TIERS,
                 bucket_s: float = DEFAULT_BUCKET_S,
                 min_samples: int = DEFAULT_MIN_SAMPLES,
                 budget_window_s: float = DEFAULT_BUDGET_WINDOW_S
                 ) -> None:
        self.objectives = list(objectives)
        self.tiers = tuple(tiers)
        self.min_samples = max(1, int(min_samples))
        self.budget_window_s = float(budget_window_s)
        keep_s = max([t.long_s for t in self.tiers]
                     + [self.budget_window_s])
        self._series: Dict[str, _Series] = {
            obj.key: _Series(bucket_s, keep_s)
            for obj in self.objectives}
        # Counter baselines for delta ingestion (first ingest is the
        # baseline, not a burst of phantom events).
        self._last_counters: Optional[Dict[str, int]] = None
        self._last_tenants: Dict[str, Tuple[int, int, int, int]] = {}
        # (objective key, tier) -> firing? + since-when, and the
        # append-only transition log.
        self._firing: Dict[Tuple[str, str], bool] = {}
        self._firing_since: Dict[Tuple[str, str], float] = {}
        self._transitions: collections.deque = collections.deque(
            maxlen=4096)
        self._seq = 0

    # -- event ingestion ---------------------------------------------------
    def note_latency(self, kind: str, value_s: float,
                     tenant: Optional[str], now: float) -> None:
        """One latency sample (``kind`` 'ttft' or 'itl'), classified
        against every matching latency objective's threshold."""
        metric = f'{kind}_p99'
        for obj in self.objectives:
            if obj.metric != metric:
                continue
            if obj.tenant is not None and obj.tenant != tenant:
                continue
            ok = value_s <= (obj.threshold_s or 0.0)
            self._series[obj.key].add(now, good=int(ok),
                                      bad=int(not ok))

    @staticmethod
    def _tenant_row(row: Any) -> Tuple[int, int, int, int]:
        """(total, shed, failed, no_replica), padded so an older
        3-field writer still ingests."""
        vals = tuple(int(v) for v in row)[:4]
        return vals + (0,) * (4 - len(vals))

    def ingest_counters(self, counters: Dict[str, Any],
                        now: float) -> None:
        """Outcome counters by DELTA (the LB passes its monotonic
        edge counters each sync tick): ``total`` / ``failed`` /
        ``no_replica`` / ``shed``, plus per-tenant
        ``tenants: {t: (total, shed, failed, no_replica)}``."""
        cur = {k: int(counters.get(k) or 0)
               for k in ('total', 'failed', 'no_replica', 'shed')}
        tenants: Dict[str, Tuple[int, int, int, int]] = {
            str(t): self._tenant_row(row)
            for t, row in (counters.get('tenants') or {}).items()}
        prev, self._last_counters = self._last_counters, cur
        prev_tenants, self._last_tenants = self._last_tenants, tenants
        if prev is None:
            return   # baseline tick
        d = {k: max(0, cur[k] - prev[k]) for k in cur}
        dt = {}
        for t, row in tenants.items():
            p = prev_tenants.get(t, (0, 0, 0, 0))
            dt[t] = tuple(max(0, a - b) for a, b in zip(row, p))
        for obj in self.objectives:
            if obj.metric == 'availability':
                if obj.tenant is None:
                    bad = d['failed'] + d['no_replica']
                    total = d['total']
                else:
                    t_total, _, t_failed, t_norep = dt.get(
                        obj.tenant, (0, 0, 0, 0))
                    # An empty ready set is BAD for the tenant too —
                    # the all-replicas-lost outage must burn this
                    # objective, not read as 100% good.
                    bad, total = t_failed + t_norep, t_total
            elif obj.metric == 'shed_rate':
                if obj.tenant is None:
                    bad, total = d['shed'], d['total']
                else:
                    t_total, t_shed, _, _ = dt.get(obj.tenant,
                                                   (0, 0, 0, 0))
                    bad, total = t_shed, t_total
            else:
                continue
            # `total` counts request ARRIVALS; failures/sheds land at
            # completion, routinely a later tick for long streams.
            # Bad events are therefore ingested in full even when this
            # tick saw fewer (or zero) new arrivals — clamping bad to
            # the arrival delta would read an outage of in-flight
            # traffic as 100% good.
            good = max(0, total - bad)
            if good or bad:
                self._series[obj.key].add(now, good=good, bad=bad)

    def note_replica_freshness(self, fresh: int, stale: int,
                               now: float) -> None:
        """Per-sync-tick replica responsiveness, classified by the
        PR 12 freshest-ring staleness rule at the LB: a ready replica
        whose metrics ring has frozen counts as a BAD event — a hung
        replica must not silently mask a fleet-wide burn by simply
        not reporting."""
        for obj in self.objectives:
            if obj.metric != 'replica_availability':
                continue
            if fresh or stale:
                self._series[obj.key].add(now, good=fresh, bad=stale)

    # -- burn math ---------------------------------------------------------
    def burn_rate(self, obj: SloObjective, window_s: float,
                  now: float) -> float:
        """Error rate over the window divided by the error budget
        (``1 - target``). 0.0 below ``min_samples`` — sparse windows
        must not page anyone."""
        good, bad = self._series[obj.key].window(now, window_s)
        total = good + bad
        if total < self.min_samples:
            return 0.0
        return (bad / total) / (1.0 - obj.target)

    def budget_remaining(self, obj: SloObjective,
                         now: float) -> float:
        """Fraction of the error budget left over the accounting
        window, clamped to [0, 1]. 1.0 with no traffic (an idle
        service has spent nothing)."""
        good, bad = self._series[obj.key].window(
            now, self.budget_window_s)
        total = good + bad
        if not total:
            return 1.0
        consumed = (bad / total) / (1.0 - obj.target)
        return max(0.0, min(1.0, 1.0 - consumed))

    # -- evaluation --------------------------------------------------------
    def evaluate(self, now: float) -> List[Dict[str, Any]]:
        """One evaluation pass (the LB calls this each sync tick):
        recompute every (objective, tier) burn pair, flip alert
        states, and return the transitions this pass produced. A tier
        fires when BOTH windows breach its burn threshold; it
        resolves the moment the short window recovers (the long
        window alone holding the breach means the incident is over
        but the budget is still scorched — ticket territory, not a
        live page)."""
        transitions: List[Dict[str, Any]] = []
        for obj in self.objectives:
            for tier in self.tiers:
                burn_short = self.burn_rate(obj, tier.short_s, now)
                burn_long = self.burn_rate(obj, tier.long_s, now)
                firing = (burn_short >= tier.burn
                          and burn_long >= tier.burn)
                key = (obj.key, tier.tier)
                if firing == self._firing.get(key, False):
                    continue
                self._firing[key] = firing
                if firing:
                    self._firing_since[key] = now
                else:
                    self._firing_since.pop(key, None)
                record = {
                    't': round(now, 6), 'seq': self._seq,
                    'objective': obj.key, 'tier': tier.tier,
                    'state': 'firing' if firing else 'resolved',
                    'burn_short': round(burn_short, 3),
                    'burn_long': round(burn_long, 3),
                }
                self._seq += 1
                self._transitions.append(record)
                transitions.append(record)
        return transitions

    def disarm(self, now: float) -> List[Dict[str, Any]]:
        """Resolve every firing alert (the evaluator is being
        replaced — a config change mid-incident must not leave
        dangling 'firing' edges in the decision log; a still-ongoing
        burn re-fires cleanly on the successor). Returns the
        synthetic transitions, shaped exactly like evaluate()'s."""
        transitions: List[Dict[str, Any]] = []
        for key, tier in self.firing():
            self._firing[(key, tier)] = False
            self._firing_since.pop((key, tier), None)
            record = {
                't': round(now, 6), 'seq': self._seq,
                'objective': key, 'tier': tier, 'state': 'resolved',
                'burn_short': 0.0, 'burn_long': 0.0,
            }
            self._seq += 1
            self._transitions.append(record)
            transitions.append(record)
        return transitions

    # -- surfaces ----------------------------------------------------------
    def firing(self, tier: Optional[str] = None
               ) -> List[Tuple[str, str]]:
        """Currently-firing (objective key, tier) pairs."""
        return sorted(k for k, v in self._firing.items()
                      if v and (tier is None or k[1] == tier))

    def page_burn(self, now: float) -> float:
        """The autoscaler's ``slo_burn`` scale-up input: the max over
        objectives of the page tier's effective burn (min of the two
        windows — the same AND the alert condition applies), so the
        signal crosses ``PAGE.burn`` exactly when a page fires."""
        best = 0.0
        for obj in self.objectives:
            b = min(self.burn_rate(obj, PAGE.short_s, now),
                    self.burn_rate(obj, PAGE.long_s, now))
            best = max(best, b)
        return round(best, 3)

    def gauges(self, now: float) -> Dict[str, Dict[str, Any]]:
        """Per-objective gauge rows for ``lb_metrics()['slo']``."""
        out: Dict[str, Dict[str, Any]] = {}
        for obj in self.objectives:
            row: Dict[str, Any] = {
                'metric': obj.metric, 'target': obj.target,
                'tenant': obj.tenant,
                'threshold_s': obj.threshold_s,
                'error_budget_remaining': round(
                    self.budget_remaining(obj, now), 4),
            }
            for tier in self.tiers:
                row[f'{tier.tier}_burn_short'] = round(
                    self.burn_rate(obj, tier.short_s, now), 3)
                row[f'{tier.tier}_burn_long'] = round(
                    self.burn_rate(obj, tier.long_s, now), 3)
                row[f'{tier.tier}_firing'] = bool(
                    self._firing.get((obj.key, tier.tier), False))
            out[obj.key] = row
        return out

    def snapshot(self, now: float) -> Dict[str, Any]:
        """The ``/-/alerts`` payload: objectives with live gauges,
        the firing set, and the transition-log tail."""
        firing = [{'objective': k, 'tier': tier,
                   'since_t': round(
                       self._firing_since.get((k, tier), now), 6)}
                  for k, tier in self.firing()]
        return {
            'enabled': True,
            'tiers': [dataclasses.asdict(t) for t in self.tiers],
            'objectives': self.gauges(now),
            'firing': firing,
            'transitions': list(self._transitions)[-64:],
        }

    def decision_log_jsonl(self) -> str:
        """Alert transitions as one JSON line each — the
        byte-identity surface (same seed => identical string in the
        twin gates)."""
        return '\n'.join(json.dumps(t, sort_keys=True)
                         for t in self._transitions)


def _smoke() -> int:
    """``make slo-smoke``: replay the reclaim-storm scenario in the
    digital twin with a TTFT objective armed and prove the alert
    round trip end to end — the page tier fires after the storm,
    clears after recovery, and the firing edge produced a
    flight-recorder fleet dump in the span store. Exit 0 = the SLO
    engine works end to end."""
    import logging
    import os
    import tempfile

    from skypilot_tpu.observability import stepline as stepline_lib
    from skypilot_tpu.observability import store as store_lib
    from skypilot_tpu.sim import DigitalTwin, reclaim_storm

    logging.disable(logging.WARNING)
    # Sized so the page tier provably crosses: losing 3 of 4 replicas
    # halves the service rate below the offered load, and replacement
    # provisioning (~4-5 virtual minutes — readiness follows the
    # probe, so provision time IS the recovery time) keeps the burn
    # going long enough for the LONG page window to breach — the
    # multi-window rule needs a sustained incident, not a blip.
    sc = reclaim_storm(replicas=4, duration_s=1800.0,
                       storm_frac=0.75, rps=8.0)
    sc.provision_delay_s = (240.0, 300.0)
    sc.slo = [{'metric': 'ttft_p99', 'threshold_s': 2.0,
               'target': 0.99},
              {'metric': 'availability', 'target': 0.999}]
    with tempfile.TemporaryDirectory() as tmp:
        store = store_lib.SpanStore(
            db_path=os.path.join(tmp, 'slo-smoke-traces.db'))
        stepline_lib.set_dump_store(store)
        try:
            report = DigitalTwin(sc, seed=3).run()
        finally:
            stepline_lib.set_dump_store(None)
            logging.disable(logging.NOTSET)
    alerts = [d for d in report.decisions
              if d['kind'] == 'slo_alert']
    pages = [a for a in alerts if a['tier'] == 'page']
    fired = [a for a in pages if a['state'] == 'firing']
    resolved = [a for a in pages if a['state'] == 'resolved']
    if not fired:
        print('slo-smoke: the storm never fired the page alert')
        return 1
    if not resolved or resolved[-1]['t'] <= fired[0]['t']:
        print('slo-smoke: the page alert never cleared after '
              'recovery')
        return 1
    avail = [a for a in alerts if a['objective'] == 'availability']
    if avail:
        print(f'slo-smoke: availability alert fired on a zero-error '
              f'storm (false positive): {avail[:2]}')
        return 1
    dumps = [t for t in store.list_traces(
                 limit=200, trace_id_prefix='stepline-fleet')]
    slo_dumps = []
    for t in dumps:
        spans = store.get_trace(t['trace_id'])
        root = next((s for s in spans
                     if s['name'] == 'stepline.fleet_dump'), None)
        if root and root['attrs'].get('trigger') == 'slo_page':
            slo_dumps.append(t['trace_id'])
    if not slo_dumps:
        print('slo-smoke: no slo_page fleet dump in the span store')
        return 1
    if report.client_errors:
        print(f'slo-smoke: {len(report.client_errors)} client-visible '
              f'error(s) in the replay; first: '
              f'{report.client_errors[0]}')
        return 1
    print('slo-smoke OK:', json.dumps({
        'page_fired_t': fired[0]['t'],
        'page_resolved_t': resolved[-1]['t'],
        'transitions': len(alerts),
        'fleet_dumps': len(slo_dumps)}))
    return 0


if __name__ == '__main__':
    import sys

    # `python -m` runs this file as `__main__` — a second module
    # object. Delegate to the canonical package import (the stepline
    # rule) so module globals are the ones the LB uses.
    from skypilot_tpu.observability import slo as _canonical
    sys.exit(_canonical._smoke())
