"""Engine flight recorder: step-level timelines + anomaly dumps.

The serving dashboard answers "how slow is it"; nothing before this
module answered "*why was that step slow*". The flight recorder is an
always-on, low-overhead ring of per-engine-step records (one compact
:class:`StepRecord` per worked step — kind, dispatch/drain/readback
wall shares, batch/chunk sizes, speculation accept counts, page
pressure, queue depth per tenant) plus a per-request timeline ring
(submit → first_dispatch → first_token → done, with resume / cancel /
shed events), both appended by the engine step loop under the
engine's ``_lock``.

Three export paths:

- **Perfetto**: :func:`to_perfetto` renders a snapshot as
  Chrome-trace JSON — one track per step-loop stage (dispatch /
  drain / readback / host) and one per request — mergeable with the
  PR 1 propagated spans (``render.to_perfetto``'s event shape, pids
  offset so the hops never collide), stitched by ``request_id``.
- **Anomaly dumps**: a TTFT-SLO breach, preemption, ``cache_full``
  finish, admission shed, or LB breaker-open snapshots the ring into
  the PR 1 sqlite span store (one ``stepline.dump`` root span, one
  child span per step / request event, the triggering event tagged)
  — a black box you read *after* the incident with
  ``sky-tpu profile``. Writes happen on a background thread, never
  under the engine lock, rate-limited per trigger kind.
- **Fleet history**: the serve LB keeps a bounded per-replica history
  ring of the gauges its sync tick already fetches (queue depth,
  tokens_per_step, accept rate, prefix hit rate) — surfaced as
  ``/-/metrics/history`` and as windowed-rate gauges; the signal
  shape the ROADMAP autoscaler and digital twin consume.

Determinism contract: the recorder reads clocks and counters only —
it never influences scheduling, sampling, or page decisions, so
greedy outputs are bit-identical recorder on vs off (gated with the
fused/pipeline/spec golden tests).
"""
from __future__ import annotations

import collections
import dataclasses
import os
import threading
import time
from typing import Any, Dict, List, Optional

# Ring capacities (records, not bytes). A step record is ~15 scalars;
# 1024 of them cover minutes of steady-state decode — enough context
# around any anomaly without growing replica RSS measurably.
CAP_ENV = 'SKY_TPU_STEPLINE_CAP'
DEFAULT_CAP = 1024
# Minimum seconds between two dumps of the SAME trigger kind: a
# preemption storm must not turn the span store into a write
# amplifier (each dump is O(ring) rows). 0 disables the limit.
DUMP_INTERVAL_ENV = 'SKY_TPU_STEPLINE_DUMP_INTERVAL_S'
DEFAULT_DUMP_INTERVAL_S = 30.0

TRIGGERS = ('ttft_slo', 'preemption', 'cache_full', 'admission_shed',
            'breaker_open', 'slo_page')

# Step-loop stage keys, in the order they run inside one step. 'host'
# is the remainder (scheduling, page accounting, drafting).
STAGES = ('dispatch', 'drain', 'readback', 'host')


def default_cap() -> int:
    try:
        return max(8, int(os.environ.get(CAP_ENV, DEFAULT_CAP)))
    except ValueError:
        return DEFAULT_CAP


def dump_interval_s() -> float:
    try:
        return max(0.0, float(os.environ.get(
            DUMP_INTERVAL_ENV, DEFAULT_DUMP_INTERVAL_S)))
    except ValueError:
        return DEFAULT_DUMP_INTERVAL_S


@dataclasses.dataclass
class StepRecord:
    """One engine step, compactly. All times are wall seconds; the
    stage shares are DISJOINT: ``dispatch_s`` (device program
    launches), ``drain_s`` (consume bookkeeping while catching host
    state up), ``readback_s`` (blocked on the device→host pair copy),
    and host = ``dur_s`` minus the three."""
    __slots__ = ('idx', 't', 'dur_s', 'kind', 'dispatch_s', 'drain_s',
                 'readback_s', 'batch', 'chunk_tokens', 'prefilling',
                 'spec_drafted', 'spec_accepted', 'pages_free',
                 'prefix_evictions', 'preemptions', 'queue_depth',
                 'tenant_depths')
    idx: int                 # monotonic step index (survives wrap)
    t: float                 # wall-clock step start
    dur_s: float
    kind: str                # prefill | decode | mixed | verify | free
    dispatch_s: float
    drain_s: float
    readback_s: float
    batch: int               # decoding slots in the dispatch
    chunk_tokens: int        # prefill tokens dispatched this step
    prefilling: int          # slots mid-prefill after the step
    spec_drafted: int        # draft tokens consumed this step
    spec_accepted: int
    pages_free: int          # -1 on dense engines
    prefix_evictions: int    # cumulative (deltas = per-step evictions)
    preemptions: int         # cumulative
    queue_depth: int
    tenant_depths: Optional[Dict[str, int]]   # None when single-tenant

    def host_s(self) -> float:
        return max(0.0, self.dur_s - self.dispatch_s - self.drain_s
                   - self.readback_s)

    def as_dict(self) -> Dict[str, Any]:
        d = {k: getattr(self, k) for k in self.__slots__}
        d['host_s'] = self.host_s()
        return d


class Ring:
    """Fixed-capacity ring buffer: O(1) append, oldest-first
    ``snapshot``, and a monotonic ``total`` so wraparound is
    observable (record ``idx`` continuity is testable). NOT
    thread-safe by itself — the owner (the engine) serializes access
    under its own lock."""

    __slots__ = ('_buf', '_cap', 'total')

    def __init__(self, cap: int) -> None:
        self._cap = max(1, int(cap))
        self._buf: List[Any] = [None] * self._cap
        self.total = 0

    def __len__(self) -> int:
        return min(self.total, self._cap)

    @property
    def cap(self) -> int:
        return self._cap

    def append(self, item: Any) -> None:
        self._buf[self.total % self._cap] = item
        self.total += 1

    def snapshot(self) -> List[Any]:
        n = len(self)
        start = self.total - n
        return [self._buf[i % self._cap]
                for i in range(start, self.total)]


class StepRecorder:
    """The engine-side recorder: a step ring + a request-event ring +
    per-trigger dump rate limiting. Every method is called under the
    owning engine's ``_lock`` (the recorder owns no lock; same
    contract as the scheduler)."""

    def __init__(self, cap: Optional[int] = None,
                 min_dump_interval_s: Optional[float] = None) -> None:
        cap = cap if cap is not None else default_cap()
        self.steps = Ring(cap)
        # Requests produce ~4 events each; give them a wider window so
        # the request timeline spans the same wall interval as steps.
        self.events = Ring(cap * 4)
        self.dumps = 0
        self._min_dump_s = (min_dump_interval_s
                            if min_dump_interval_s is not None
                            else dump_interval_s())
        self._last_dump: Dict[str, float] = {}

    # -- recording (holds: engine _lock) -----------------------------------
    def note_step(self, rec: StepRecord) -> None:
        self.steps.append(rec)

    def note_event(self, request_id: int, tenant: str, event: str,
                   t: float, **detail: Any) -> None:
        ev = {'request_id': request_id, 'tenant': tenant,
              'event': event, 't': t}
        if detail:
            ev.update(detail)
        self.events.append(ev)

    def should_dump(self, trigger: str, now: float) -> bool:
        """Per-trigger rate limit: at most one dump per kind per
        ``min_dump_interval_s`` (the span store is sqlite; a
        preemption storm must not DoS it). ``dumps`` counts rate-
        limit passes, i.e. dumps TRIGGERED — the handoff queue is
        bounded and the store write fail-open, so completion is not
        guaranteed (metric semantics documented accordingly)."""
        last = self._last_dump.get(trigger)
        if last is not None and self._min_dump_s > 0 \
                and now - last < self._min_dump_s:
            return False
        self._last_dump[trigger] = now
        self.dumps += 1
        return True

    # -- export ------------------------------------------------------------
    def raw(self) -> Dict[str, Any]:
        """O(n) POINTER copy of both rings (oldest first) — the only
        part that needs the owner's lock. Records and event dicts are
        write-once after append, so sharing the references is safe;
        render with :func:`render_snapshot` OUTSIDE the lock."""
        return {
            'cap': self.steps.cap,
            'steps_total': self.steps.total,
            'events_total': self.events.total,
            'dumps': self.dumps,
            'steps_raw': self.steps.snapshot(),
            'events': self.events.snapshot(),
        }


def render_snapshot(raw: Dict[str, Any]) -> Dict[str, Any]:
    """Expand a ``StepRecorder.raw()`` copy into the JSON-able
    snapshot shape (per-record dict building — thousands of dicts for
    a full ring — deliberately OUTSIDE any lock: a 1 Hz
    /debug/stepline poll must not stall the step loop for the
    build)."""
    out = dict(raw)
    out['steps'] = [r.as_dict() for r in out.pop('steps_raw')]
    return out


def summarize(recs: List[StepRecord]) -> Dict[str, Any]:
    """Aggregate step-time breakdown over a snapshot of step records:
    total and fractional share per stage — the recorder-derived
    decomposition ``bench_ttft`` stamps into the TTFT json. Runs on a
    COPY, so callers can (and do) compute it outside any lock."""
    tot = {s: 0.0 for s in STAGES}
    kinds: Dict[str, int] = {}
    dur = 0.0
    for r in recs:
        tot['dispatch'] += r.dispatch_s
        tot['drain'] += r.drain_s
        tot['readback'] += r.readback_s
        tot['host'] += r.host_s()
        dur += r.dur_s
        kinds[r.kind] = kinds.get(r.kind, 0) + 1
    out: Dict[str, Any] = {
        'steps': len(recs),
        'step_kinds': kinds,
        'step_time_s': round(dur, 6),
        'step_mean_ms': (round(dur / len(recs) * 1e3, 4)
                         if recs else None),
    }
    for s in STAGES:
        out[f'{s}_s'] = round(tot[s], 6)
        out[f'{s}_share'] = (round(tot[s] / dur, 4) if dur
                             else None)
    return out


# ---- Perfetto export -----------------------------------------------------
# Stepline tracks use pids far above render.to_perfetto's hop pids
# (which start at 1), so a merged document never collides.
_PID_STEPS = 1000
_PID_REQUESTS = 1001
_STAGE_TIDS = {s: i + 1 for i, s in enumerate(STAGES)}


def stepline_events(snapshot: Dict[str, Any]
                    ) -> List[Dict[str, Any]]:
    """The snapshot as raw Chrome-trace events (including the track
    metadata), suitable for ``render.to_perfetto``'s
    ``extra_events`` — the stitch path that merges the recorder with
    a request's PR 1 propagated spans."""
    events: List[Dict[str, Any]] = [
        {'name': 'process_name', 'ph': 'M', 'pid': _PID_STEPS,
         'tid': 1, 'args': {'name': 'engine-step'}},
        {'name': 'process_name', 'ph': 'M', 'pid': _PID_REQUESTS,
         'tid': 1, 'args': {'name': 'requests'}},
    ]
    for s, tid in _STAGE_TIDS.items():
        events.append({'name': 'thread_name', 'ph': 'M',
                       'pid': _PID_STEPS, 'tid': tid,
                       'args': {'name': s}})
    for rec in snapshot.get('steps', ()):
        # Stages laid out sequentially inside the step's wall
        # interval: dispatch, drain, readback, then host remainder —
        # an approximation of interleaving, exact in total.
        t = rec['t']
        spans = (('dispatch', rec['dispatch_s']),
                 ('drain', rec['drain_s']),
                 ('readback', rec['readback_s']),
                 ('host', rec.get('host_s', 0.0)))
        for stage, dur in spans:
            if dur <= 0.0:
                continue
            events.append({
                'name': f"step.{rec['kind']}",
                'ph': 'X', 'ts': t * 1e6, 'dur': dur * 1e6,
                'pid': _PID_STEPS, 'tid': _STAGE_TIDS[stage],
                'args': {'step': rec['idx'], 'stage': stage,
                         'batch': rec['batch'],
                         'chunk_tokens': rec['chunk_tokens'],
                         'queue_depth': rec['queue_depth']},
            })
            t += dur
    # Request tracks: one tid per request_id; lifecycle phases become
    # 'X' slices bounded by the recorded events, everything else an
    # instant.
    # Lifecycle phase boundaries keyed by FIRST occurrence (each
    # fires once per request); repeatable events (preemption, resume,
    # shed, ...) are NOT folded into this map — every occurrence in
    # the ring gets its own instant below, so a request preempted
    # twice shows two instants, same as the span-store dump path.
    by_req: Dict[int, Dict[str, Any]] = {}
    for ev in snapshot.get('events', ()):
        by_req.setdefault(ev['request_id'], {}).setdefault(
            ev['event'], ev)
    for rid, evs in by_req.items():
        tid = (rid % 100000) + 1
        phases = (('queue_wait', 'submit', 'first_dispatch'),
                  ('prefill', 'first_dispatch', 'first_token'),
                  ('decode', 'first_token', 'done'))
        for name, a, b in phases:
            if a in evs and b in evs and evs[b]['t'] >= evs[a]['t']:
                events.append({
                    'name': f'req.{name}', 'ph': 'X',
                    'ts': evs[a]['t'] * 1e6,
                    'dur': (evs[b]['t'] - evs[a]['t']) * 1e6,
                    'pid': _PID_REQUESTS, 'tid': tid,
                    'args': {'request_id': rid,
                             'tenant': evs[a].get('tenant')}})
    for ev in snapshot.get('events', ()):
        if ev['event'] in ('submit', 'first_dispatch',
                           'first_token', 'done'):
            continue
        events.append({
            'name': f"req.{ev['event']}", 'ph': 'i',
            'ts': ev['t'] * 1e6, 's': 't',
            'pid': _PID_REQUESTS,
            'tid': (ev['request_id'] % 100000) + 1,
            'args': {k: v for k, v in ev.items() if k != 't'}})
    return events


def to_perfetto(snapshot: Dict[str, Any],
                spans: Optional[List[Dict[str, Any]]] = None
                ) -> Dict[str, Any]:
    """Chrome-trace JSON of a recorder snapshot; with ``spans`` (PR 1
    propagated spans of the same request/replica) the two merge into
    one document, stitched on the wall clock + request_id."""
    events = stepline_events(snapshot)
    if spans:
        from skypilot_tpu.observability import render
        return render.to_perfetto(spans, extra_events=events)
    return {'traceEvents': events, 'displayTimeUnit': 'ms'}


def validate_perfetto(doc: Any) -> List[str]:
    """Schema check for an exported trace (``[]`` = valid): the
    contract ui.perfetto.dev / chrome://tracing require. Shared by
    the tests and ``make profile-smoke``."""
    errs: List[str] = []
    if not isinstance(doc, dict):
        return ['document is not an object']
    events = doc.get('traceEvents')
    if not isinstance(events, list):
        return ["missing 'traceEvents' list"]
    if not events:
        errs.append('traceEvents is empty')
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            errs.append(f'event {i} is not an object')
            continue
        for key in ('name', 'ph', 'pid', 'tid'):
            if key not in ev:
                errs.append(f'event {i} missing {key!r}')
        ph = ev.get('ph')
        if ph not in ('X', 'M', 'i', 'B', 'E'):
            errs.append(f'event {i} has unknown phase {ph!r}')
        if ph == 'X':
            if not isinstance(ev.get('ts'), (int, float)):
                errs.append(f'event {i} missing numeric ts')
            if not isinstance(ev.get('dur'), (int, float)) \
                    or ev.get('dur', -1) < 0:
                errs.append(f'event {i} missing non-negative dur')
    return errs


# ---- anomaly dumps into the span store -----------------------------------

def dump_spans(trigger: str, detail: Dict[str, Any],
               snapshot: Dict[str, Any],
               trace_id: Optional[str] = None
               ) -> List[Dict[str, Any]]:
    """Encode one ring snapshot as PR 1 span-store rows: a
    ``stepline.dump`` root carrying the trigger tag, a child span per
    step record, a child per request event (carrying its
    ``request_id`` so ``sky-tpu profile <request_id>`` finds the
    dump), and one ``stepline.trigger`` span for the anomaly
    itself."""
    if trace_id is None:
        trace_id = 'stepline-' + os.urandom(12).hex()
    now = time.time()
    root_id = os.urandom(8).hex()
    steps = snapshot.get('steps', [])
    events = snapshot.get('events', [])
    start = min([r['t'] for r in steps]
                + [e['t'] for e in events] + [now])
    spans: List[Dict[str, Any]] = [{
        'trace_id': trace_id, 'span_id': root_id, 'parent_id': None,
        'name': 'stepline.dump', 'hop': 'stepline',
        'start': start, 'dur_s': max(0.0, now - start),
        'status': 'ok',
        'attrs': {'trigger': trigger, 'steps': len(steps),
                  'events': len(events),
                  # Monotonic ring totals ride along so an exporter
                  # can tell how much history wrapped off the rings
                  # before the dump (no-silent-caps: a truncated
                  # incident must say so; docs/simulation.md).
                  'steps_total': int(snapshot.get('steps_total')
                                     or len(steps)),
                  'events_total': int(snapshot.get('events_total')
                                      or len(events)),
                  'request_id': detail.get('request_id'), **detail},
    }, {
        'trace_id': trace_id, 'span_id': os.urandom(8).hex(),
        'parent_id': root_id,
        'name': 'stepline.trigger', 'hop': 'stepline',
        'start': detail.get('t', now), 'dur_s': 0.0,
        'status': f'anomaly:{trigger}',
        'attrs': {'trigger': trigger, **detail},
    }]
    for rec in steps:
        spans.append({
            'trace_id': trace_id, 'span_id': os.urandom(8).hex(),
            'parent_id': root_id,
            'name': f"step.{rec['kind']}", 'hop': 'stepline',
            'start': rec['t'], 'dur_s': rec['dur_s'], 'status': 'ok',
            'attrs': {k: v for k, v in rec.items()
                      if k not in ('t', 'dur_s', 'kind')
                      and v is not None},
        })
    for ev in events:
        spans.append({
            'trace_id': trace_id, 'span_id': os.urandom(8).hex(),
            'parent_id': root_id,
            'name': f"req.{ev['event']}", 'hop': 'stepline',
            'start': ev['t'], 'dur_s': 0.0, 'status': 'ok',
            'attrs': {k: v for k, v in ev.items() if k != 't'},
        })
    return spans


def fleet_history_spans(trigger: str, detail: Dict[str, Any],
                        history: Dict[str, List[Dict[str, Any]]],
                        *,
                        request_events: List[Dict[str, Any]] = (),
                        request_events_total: int = 0,
                        fleet_events: List[Dict[str, Any]] = (),
                        fleet_events_total: int = 0
                        ) -> List[Dict[str, Any]]:
    """The LB-tier analog of :func:`dump_spans`: one span per
    retained per-replica history sample (``breaker_open`` is the
    trigger that snapshots the fleet), plus the LB's incident-replay
    evidence rings (docs/simulation.md) — one ``fleet.request`` span
    per retained scrubbed request record and one ``fleet.event`` span
    per retained fleet event (replica joins/losses, breaker edges,
    quarantines, SLO transitions). The root carries the monotonic
    ring totals so an exporter can report how many records wrapped
    off before the dump (no-silent-caps)."""
    trace_id = 'stepline-fleet-' + os.urandom(10).hex()
    now = time.time()
    root_id = os.urandom(8).hex()
    spans: List[Dict[str, Any]] = [{
        'trace_id': trace_id, 'span_id': root_id, 'parent_id': None,
        'name': 'stepline.fleet_dump', 'hop': 'serve-lb',
        'start': now, 'dur_s': 0.0, 'status': f'anomaly:{trigger}',
        'attrs': {'trigger': trigger,
                  'replicas': sorted(history),
                  'request_events': len(request_events),
                  'request_events_total': int(request_events_total
                                              or len(request_events)),
                  'fleet_events': len(fleet_events),
                  'fleet_events_total': int(fleet_events_total
                                            or len(fleet_events)),
                  **detail},
    }]
    for url, rows in history.items():
        for row in rows:
            spans.append({
                'trace_id': trace_id, 'span_id': os.urandom(8).hex(),
                'parent_id': root_id,
                'name': 'fleet.sample', 'hop': 'serve-lb',
                'start': row.get('t', now), 'dur_s': 0.0,
                'status': 'ok',
                'attrs': {'replica': url,
                          **{k: v for k, v in row.items()
                             if k != 't'}},
            })
    for name, rows in (('fleet.request', request_events),
                       ('fleet.event', fleet_events)):
        for row in rows:
            spans.append({
                'trace_id': trace_id, 'span_id': os.urandom(8).hex(),
                'parent_id': root_id,
                'name': name, 'hop': 'serve-lb',
                'start': row.get('t', now), 'dur_s': 0.0,
                'status': 'ok',
                'attrs': {k: v for k, v in row.items() if k != 't'},
            })
    return spans


# Background dump writer: the trigger fires on the engine thread (or
# an HTTP submit thread) — sqlite writes must happen elsewhere, and
# never while any engine lock is held. Bounded queue, fail-open.
_dump_q: collections.deque = collections.deque(maxlen=64)
_dump_cv = threading.Condition()
_writer_started = False
_inflight_writes = 0
_store = None            # test/ops injection (SpanStore-compatible)


def set_dump_store(store: Any) -> None:
    """Inject the span store dumps land in (tests point this at a
    tmp-path store; None restores the default resolution)."""
    global _store
    _store = store


def _resolve_store():
    if _store is not None:
        return _store
    from skypilot_tpu.observability import store as store_lib
    return store_lib.SpanStore()


def write_dump_sync(spans: List[Dict[str, Any]]) -> Optional[str]:
    """Synchronous dump write (the LB's ``asyncio.to_thread`` path
    and ``profile-smoke``). Returns the dump's trace_id, or None on
    failure — fail-open like every observability write."""
    try:
        store = _resolve_store()
        store.add_spans(spans)
        store.gc()
        return spans[0]['trace_id'] if spans else None
    except Exception:  # noqa: BLE001 — telemetry must never throw
        return None


def enqueue_dump(spans: Any) -> None:
    """Queue a dump for the background writer: a span list, or a
    zero-arg callable producing one — the engine hands a thunk so the
    O(ring) span rendering runs on the writer thread, not the step
    loop. Drops oldest beyond the bound (an anomaly storm degrades to
    fewer dumps, never to a blocked engine)."""
    with _dump_cv:
        _dump_q.append(spans)
        _ensure_writer()
        _dump_cv.notify_all()


def _ensure_writer() -> None:
    global _writer_started
    if _writer_started:
        return
    _writer_started = True

    def loop() -> None:
        global _inflight_writes
        while True:
            with _dump_cv:
                while not _dump_q:
                    # Bounded wait (not an idle poll: the enqueue
                    # notifies; the timeout only re-arms the wait).
                    _dump_cv.wait(timeout=60.0)
                spans = _dump_q.popleft()
                _inflight_writes += 1
            try:
                if callable(spans):
                    try:
                        spans = spans()
                    except Exception:  # noqa: BLE001 — fail-open
                        spans = []
                write_dump_sync(spans)
            finally:
                with _dump_cv:
                    _inflight_writes -= 1
                    _dump_cv.notify_all()

    threading.Thread(target=loop, daemon=True,
                     name='stepline-dump-writer').start()


def flush_dumps(timeout_s: float = 5.0) -> bool:
    """Block until every queued dump has been written (tests and the
    smoke target; the serving path never calls this)."""
    deadline = time.monotonic() + timeout_s
    with _dump_cv:
        while _dump_q or _inflight_writes:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            _dump_cv.wait(remaining)
    return True


# ---- profile-smoke -------------------------------------------------------

def _smoke() -> int:
    """``make profile-smoke``: run a tiny in-process workload with
    the recorder on, force an anomaly dump, and validate both the
    live Perfetto export and the dump round-trip through the span
    store. Exit code 0 = the flight recorder works end to end."""
    import json
    import tempfile

    import jax

    from skypilot_tpu.infer import engine as engine_lib
    from skypilot_tpu.models import llama
    from skypilot_tpu.observability import store as store_lib

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    eng = engine_lib.InferenceEngine(
        cfg, params,
        engine_lib.EngineConfig(
            n_slots=2, max_seq_len=128, prefill_buckets=(16, 32),
            prefill_chunk=32,
            # Any TTFT breaches a zero SLO: guarantees one dump.
            ttft_slo_s=0.0))
    with tempfile.TemporaryDirectory() as tmp:
        store = store_lib.SpanStore(
            db_path=os.path.join(tmp, 'smoke-traces.db'))
        set_dump_store(store)
        try:
            eng.generate([[7, 8, 9], [11] * 40], max_new_tokens=8)
            snap = eng.stepline_snapshot()
            doc = to_perfetto(snap)
            errs = validate_perfetto(doc)
            if errs:
                print('profile-smoke: live export INVALID:', errs)
                return 1
            if not snap['steps']:
                print('profile-smoke: recorder captured no steps')
                return 1
            if not flush_dumps(10.0):
                print('profile-smoke: dump writer did not drain')
                return 1
            traces = store.list_traces()
            dump = next((t for t in traces
                         if str(t.get('trace_id', ''))
                         .startswith('stepline-')), None)
            if dump is None:
                print('profile-smoke: no anomaly dump in the store')
                return 1
            spans = store.get_trace(dump['trace_id'])
            from skypilot_tpu.observability import render
            errs = validate_perfetto(render.to_perfetto(spans))
            if errs:
                print('profile-smoke: dump export INVALID:', errs)
                return 1
            if not any(s['name'] == 'stepline.trigger'
                       for s in spans):
                print('profile-smoke: dump lacks the trigger span')
                return 1
            summ = eng.stepline_summary()
            print('profile-smoke OK:',
                  json.dumps({'steps': summ['steps'],
                              'step_mean_ms': summ['step_mean_ms'],
                              'dump_spans': len(spans),
                              'dump_trace': dump['trace_id']}))
            return 0
        finally:
            set_dump_store(None)


if __name__ == '__main__':
    import sys

    # `python -m` runs this file as `__main__` — a SECOND module
    # object. Delegate to the canonical package import so the smoke's
    # set_dump_store hits the same globals the engine's dump path
    # uses.
    from skypilot_tpu.observability import stepline as _canonical
    sys.exit(_canonical._smoke())
