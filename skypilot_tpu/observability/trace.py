"""Trace context + span recorder (the wire-crossing half of tracing).

Contract (mirrors the Dapper/W3C trace-context model):

- A **trace** is one logical request's tree of **spans**; every span
  carries ``(trace_id, span_id, parent_id)``. Context rides between
  processes as a W3C-style ``traceparent`` string
  (``00-<32 hex>-<16 hex>-01``) in three channels: the ``traceparent``
  HTTP header (SDK→server, server→agent, LB→replica), the
  ``SKY_TPU_TRACEPARENT`` env var (parent → child process, e.g. agent →
  job ranks), and the ``_traceparent`` request-payload field (API
  server → its detached request worker, via the persisted request row).
- **Zero overhead when disabled**: ``SKY_TPU_TRACE`` unset means
  ``traced`` returns the original function at decoration time,
  ``span()`` yields without allocating, and ``inject_headers`` is a
  no-op. Nothing is buffered, nothing is shipped.
- **Fail-open**: recording and shipping must never fail a request.
  Every ship path swallows errors; the buffer is size-capped and drops
  (never blocks) when full.

Finished spans buffer in-process and ship on ``flush()`` (driven by a
background shipper thread and atexit — never synchronously from the
recording thread, which may be an event loop): to a collector URL when
one is resolvable
(``SKY_TPU_TRACE_COLLECTOR``, then ``SKY_TPU_API_SERVER``, then the
local ``api_server.json``), else straight into the local span store.
The API server short-circuits by installing a local sink
(``set_sink``), so its own spans never loop through HTTP.
"""
from __future__ import annotations

import atexit
import contextlib
import contextvars
import functools
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

ENV_VAR = 'SKY_TPU_TRACE'
CTX_ENV_VAR = 'SKY_TPU_TRACEPARENT'
COLLECTOR_ENV_VAR = 'SKY_TPU_TRACE_COLLECTOR'
# Collector URL as reachable FROM provisioned cluster hosts (the API
# server's VPC/ingress address) — stamped into agent_config.json so
# remote agents can ship their spans home.
AGENT_COLLECTOR_ENV_VAR = 'SKY_TPU_TRACE_AGENT_COLLECTOR'
PAYLOAD_KEY = '_traceparent'
HEADER = 'traceparent'

# Buffer cap: a hot instrumented loop (engine.step) must not grow RAM
# without bound if shipping stalls; drops are counted, not silent.
_MAX_BUFFER = 10_000

_TRACEPARENT_RE = re.compile(
    r'^00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$')

_current: contextvars.ContextVar[Optional['SpanContext']] = (
    contextvars.ContextVar('sky_tpu_trace_ctx', default=None))

_buffer: List[Dict[str, Any]] = []
_buffer_lock = threading.Lock()
_dropped = 0
_flush_registered = False
_sink: Optional[Callable[[List[Dict[str, Any]]], Any]] = None
_hop: Optional[str] = None
# Background shipper: spans must never be flushed synchronously from
# the recording thread — span closure happens on aiohttp event loops
# (the API server's admission span, the LB's proxy span), and a flush
# is sqlite or HTTP I/O. A daemon thread drains the buffer instead.
_SHIP_INTERVAL_S = 0.3
_shipper_started = False
_shipper_lock = threading.Lock()


class SpanContext:
    """(trace_id, span_id) pair — the propagated identity of a span."""

    __slots__ = ('trace_id', 'span_id')

    def __init__(self, trace_id: str, span_id: str) -> None:
        self.trace_id = trace_id
        self.span_id = span_id

    def traceparent(self) -> str:
        return f'00-{self.trace_id}-{self.span_id}-01'

    def __repr__(self) -> str:
        return f'SpanContext({self.traceparent()})'


def enabled() -> bool:
    return bool(os.environ.get(ENV_VAR))


def set_hop(name: str) -> None:
    """Name this process's hop ('server', 'worker', 'agent', ...); spans
    record it so per-hop latency is separable. Defaults to 'client'."""
    global _hop
    _hop = name


def get_hop() -> str:
    return _hop or os.environ.get('SKY_TPU_TRACE_HOP') or 'client'


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def parse_traceparent(value: Optional[str]) -> Optional[SpanContext]:
    """Parse a traceparent string; malformed input yields None (a bad
    header must never fail the request carrying it)."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value.strip())
    if not m:
        return None
    return SpanContext(m.group(1), m.group(2))


def current() -> Optional[SpanContext]:
    """The active span context: contextvar first (same process), then
    the env-var handoff a parent process may have left."""
    ctx = _current.get()
    if ctx is None:
        ctx = parse_traceparent(os.environ.get(CTX_ENV_VAR))
    return ctx


def current_traceparent() -> Optional[str]:
    ctx = current()
    return ctx.traceparent() if ctx else None


@contextlib.contextmanager
def use_context(ctx: Optional[SpanContext]):
    """Run a block under an explicit parent context (cross-thread /
    cross-process handoff: the worker re-parents to the server's span,
    the agent's job runner to the submit span)."""
    token = _current.set(ctx)
    try:
        yield
    finally:
        _current.reset(token)


def context_from(traceparent: Optional[str]):
    return use_context(parse_traceparent(traceparent))


def bind(fn: Callable) -> Callable:
    """Capture the current context into a callable about to run on
    another thread (executors do not inherit contextvars)."""
    if not enabled():
        return fn
    ctx = current()

    @functools.wraps(fn)
    def inner(*a, **kw):
        with use_context(ctx):
            return fn(*a, **kw)

    return inner


def inject_headers(headers: Dict[str, str]) -> Dict[str, str]:
    """Add the traceparent header for an outbound hop. Mutates and
    returns ``headers``; skipped entirely when tracing is off."""
    if enabled():
        tp = current_traceparent()
        if tp:
            headers[HEADER] = tp
    return headers


def inject_payload(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Stamp the context into a request payload (server → worker: the
    worker re-reads the persisted row, not our memory)."""
    if enabled():
        tp = current_traceparent()
        if tp:
            payload[PAYLOAD_KEY] = tp
    return payload


def child_env(env: Dict[str, str]) -> Dict[str, str]:
    """Stamp the context into a child process environment."""
    if enabled():
        tp = current_traceparent()
        if tp:
            env[CTX_ENV_VAR] = tp
    return env


def agent_trace_config() -> Dict[str, Any]:
    """Keys a provisioner merges into agent_config.json so tracing
    reaches REAL (remote) agent hosts, where the provisioner's env does
    not: `trace_enabled`, plus `trace_collector` when the operator set
    SKY_TPU_TRACE_AGENT_COLLECTOR (the API server URL as reachable
    from the cluster). Empty when tracing is off."""
    if not enabled():
        return {}
    cfg: Dict[str, Any] = {'trace_enabled': True}
    collector = os.environ.get(AGENT_COLLECTOR_ENV_VAR)
    if collector:
        cfg['trace_collector'] = collector
    return cfg


class _SpanHandle:
    """Yielded by ``span()`` so the body can attach attributes that are
    only known mid-span (e.g. the request_id minted inside)."""

    __slots__ = ('ctx', 'attrs')

    def __init__(self, ctx: SpanContext, attrs: Dict[str, Any]) -> None:
        self.ctx = ctx
        self.attrs = attrs

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value


@contextlib.contextmanager
def span(name: str, *, hop: Optional[str] = None,
         min_dur_s: float = 0.0, **attrs: Any):
    """Record one span around a block. No-op (yields None) when tracing
    is disabled. ``min_dur_s`` drops sub-threshold spans — for hot loops
    (engine.step) where only outliers are interesting."""
    if not enabled():
        yield None
        return
    parent = current()
    ctx = SpanContext(parent.trace_id if parent else _new_id(16),
                      _new_id(8))
    handle = _SpanHandle(ctx, dict(attrs))
    token = _current.set(ctx)
    t0 = time.time()
    status = 'ok'
    try:
        yield handle
    except BaseException as e:
        status = f'error:{type(e).__name__}'
        raise
    finally:
        _current.reset(token)
        dur = time.time() - t0
        if dur >= min_dur_s:
            record_span(
                name=name, trace_id=ctx.trace_id, span_id=ctx.span_id,
                parent_id=parent.span_id if parent else None,
                start=t0, dur_s=dur, status=status,
                hop=hop or get_hop(), attrs=handle.attrs)


def traced(fn: Callable = None, *, name: Optional[str] = None,
           hop: Optional[str] = None,
           min_dur_s: float = 0.0) -> Callable:
    """Decorator form. Gated at decoration time (same zero-cost default
    as ``timeline.event``): with ``SKY_TPU_TRACE`` unset the original
    function is returned unchanged — no wrapper, no per-call check."""

    def wrap(f: Callable) -> Callable:
        if not enabled():
            return f
        label = name or f'{f.__module__}.{f.__qualname__}'

        @functools.wraps(f)
        def inner(*a, **kw):
            with span(label, hop=hop, min_dur_s=min_dur_s):
                return f(*a, **kw)

        return inner

    return wrap(fn) if fn is not None else wrap


def record_span(*, name: str, trace_id: str, span_id: str,
                parent_id: Optional[str], start: float, dur_s: float,
                status: str, hop: str,
                attrs: Optional[Dict[str, Any]] = None) -> None:
    global _flush_registered, _dropped
    s = {
        'trace_id': trace_id, 'span_id': span_id,
        'parent_id': parent_id, 'name': name, 'hop': hop,
        'start': start, 'dur_s': dur_s, 'status': status,
        'attrs': attrs or {},
    }
    with _buffer_lock:
        if len(_buffer) >= _MAX_BUFFER:
            _dropped += 1
            return
        _buffer.append(s)
        if not _flush_registered:
            atexit.register(flush)
            _flush_registered = True
    _ensure_shipper()


def _ensure_shipper() -> None:
    global _shipper_started
    if _shipper_started:
        return
    with _shipper_lock:
        if _shipper_started:
            return
        _shipper_started = True

        def loop() -> None:
            while True:
                time.sleep(_SHIP_INTERVAL_S)
                try:
                    flush()
                except Exception:  # noqa: BLE001 — fail-open
                    pass

        threading.Thread(target=loop, daemon=True,
                         name='trace-shipper').start()


def set_sink(sink: Optional[Callable[[List[Dict[str, Any]]], Any]]
             ) -> None:
    """Install a local sink (the API server: spans go straight into the
    store + metrics instead of over HTTP to itself)."""
    global _sink
    _sink = sink


def _resolve_collector() -> Optional[str]:
    url = (os.environ.get(COLLECTOR_ENV_VAR) or
           os.environ.get('SKY_TPU_API_SERVER'))
    if url:
        return url.rstrip('/')
    # Config-declared API endpoint (the SDK's own fallback chain).
    try:
        from skypilot_tpu import config as config_lib
        url = config_lib.get_nested(('api_server', 'endpoint'))
        if url:
            return url.rstrip('/')
    except Exception:  # noqa: BLE001 — config layer unavailable
        pass
    # Same host as a running API server? Its startup file names the URL.
    try:
        import json

        from skypilot_tpu.utils import common
        path = os.path.join(common.base_dir(), 'api_server.json')
        with open(path, encoding='utf-8') as f:
            return json.load(f)['url'].rstrip('/')
    except Exception:  # noqa: BLE001 — no server around: ship locally
        return None


def flush() -> int:
    """Ship buffered spans. Best-effort, fail-open: a collector POST
    failure falls back to the local store; a store failure drops. Never
    raises. Returns the number of spans handed off."""
    with _buffer_lock:
        if not _buffer:
            return 0
        spans, _buffer[:] = list(_buffer), []
    if _sink is not None:
        try:
            _sink(spans)
        except Exception:  # noqa: BLE001 — fail-open
            pass
        return len(spans)
    collector = _resolve_collector()
    if collector:
        try:
            import requests

            # Lazy import: retry.py imports this module at its top
            # level, so the dependency must only run at call time.
            from skypilot_tpu.utils import retry as retry_lib

            def _post() -> None:
                r = requests.post(f'{collector}/api/traces',
                                  json={'spans': spans}, timeout=3)
                r.raise_for_status()

            # Two quick tries, then fall back to the local store —
            # shipping is fail-open and must never stall the caller.
            retry_lib.Retrier(
                'trace.ship', max_attempts=2, base_delay_s=0.1,
                max_delay_s=0.5,
                transient=(requests.RequestException,)).call(_post)
            return len(spans)
        except Exception:  # noqa: BLE001 — fall through to local store
            pass
    try:
        from skypilot_tpu.observability import store as store_lib
        store_lib.ingest(spans)
    except Exception:  # noqa: BLE001 — fail-open
        pass
    return len(spans)


def _reset_for_tests() -> None:
    """Drop all module state (buffered spans, sink, hop)."""
    global _dropped, _sink, _hop
    with _buffer_lock:
        _buffer[:] = []
        _dropped = 0
    _sink = None
    _hop = None


def buffered() -> Tuple[int, int]:
    """(buffered, dropped) counts — introspection for tests/debugging."""
    with _buffer_lock:
        return len(_buffer), _dropped
