"""Incident converter: flight-recorder dump → twin scenario
(docs/simulation.md "Incident lifecycle").

This is the piece that closes the PR 12 ↔ PR 13 loop: every anomaly
dump the LB's flight recorder writes (``breaker_open``, ``slo_page``,
``quarantine`` fleet dumps; engine stepline dumps) already carries the
two evidence rings — scrubbed request arrivals and control-plane
fleet events. :func:`trace_from_spans` reconstructs a replayable
:class:`~skypilot_tpu.sim.tracefmt.Trace` from them:

- the **arrival process** (per-tenant rate, prompt/output shape,
  prefix-cohort mix, deadlines) is re-derived from the request ring —
  the recorded window itself is usually far too short to sustain a
  multi-minute burn-rate alert, so replay synthesizes full-duration
  traffic from the reconstructed tenant specs while the raw (scrubbed)
  window records ride along as evidence;
- the **fault timeline** is inferred from the fleet-event ring:
  ``replica_lost`` clusters become a reclaim storm, ``breaker_open``
  edges a wedge, ``quarantine`` verdicts an SDC injection,
  ``controller_recovered`` deltas a controller kill — each with
  inter-event spacing preserved;
- the **expected anomaly class** (the ordered page-tier alert
  transitions the LB recorded before dumping) lands in ``meta`` so
  :func:`verify_replay` can gate "the replay reproduces the incident".

No prompt content crosses this boundary: the LB ring records are
scrubbed at capture (lengths + one-way cohort hashes), so an exported
incident file is safe to commit as a permanent regression gate in
``tests/sim/incidents/``.

``python -m skypilot_tpu.observability.incident`` is the
``make incident-smoke`` entry: storm → page dump → export → replay →
assert the page reproduces.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu.sim import tracefmt

# Root span names that mark a flight-recorder dump in the span store.
ROOT_NAMES = ('stepline.fleet_dump', 'stepline.dump')
# replica_lost events within this window collapse into ONE reclaim
# storm (a storm's victims drop over a few sync ticks, not one).
_STORM_CLUSTER_S = 60.0
# Replay margin added past the recorded fault→dump span so the outage
# persists long enough for the burn windows to re-fire.
_HOLD_MARGIN_S = 600.0


def list_dumps(store) -> List[Dict[str, Any]]:
    """Flight-recorder dumps in the span store, newest first:
    ``{'dump_id', 'root', 'trigger', 'start', 'n_spans'}``."""
    out = []
    for tr in store.list_traces(limit=200,
                                trace_id_prefix='stepline-'):
        if tr.get('root') not in ROOT_NAMES:
            continue
        spans = store.get_trace(tr['trace_id'])
        root = _root_span(spans)
        out.append({
            'dump_id': tr['trace_id'], 'root': tr['root'],
            'trigger': (root or {}).get('attrs', {}).get('trigger'),
            'start': tr.get('start_ts'), 'n_spans': tr['n_spans'],
        })
    return out


def find_dump(store, dump_id: str) -> List[Dict[str, Any]]:
    """Spans for a dump by exact id or unique prefix; raises
    ``ValueError`` (never an empty trace) when the id is unknown or
    ambiguous — the loud-failure rule."""
    spans = store.get_trace(dump_id)
    if spans:
        return spans
    matches = [d for d in list_dumps(store)
               if d['dump_id'].startswith(dump_id)]
    if not matches:
        raise ValueError(f'no flight-recorder dump matches '
                         f'{dump_id!r} (see `sky-tpu incident list`)')
    if len(matches) > 1:
        raise ValueError(
            f'{dump_id!r} is ambiguous: matches '
            f'{[m["dump_id"] for m in matches]}')
    return store.get_trace(matches[0]['dump_id'])


def _root_span(spans: List[Dict[str, Any]]
               ) -> Optional[Dict[str, Any]]:
    for s in spans:
        if s.get('parent_id') is None and s.get('name') in ROOT_NAMES:
            return s
    return None


def _children(spans: List[Dict[str, Any]], name: str
              ) -> List[Dict[str, Any]]:
    """Deterministically ordered child spans: span ids are random, so
    order by (virtual time, canonical attrs) — two exports of the
    same dump must produce byte-identical traces."""
    rows = [s for s in spans if s.get('name') == name]
    rows.sort(key=lambda s: (s.get('start') or 0.0,
                             json.dumps(s.get('attrs') or {},
                                        sort_keys=True)))
    return rows


def _rel(t: Any, t0: float) -> float:
    return round(max(0.0, float(t or t0) - t0), 6)


def _mean(xs: List[float], default: float = 0.0) -> float:
    return (sum(xs) / len(xs)) if xs else default


def _tenant_specs(requests: List[Dict[str, Any]],
                  window_s: float) -> Dict[str, Dict[str, Any]]:
    """Reconstruct loadgen tenant specs from the recorded window: the
    arrival PROCESS (rate, shape, cohort mix), not the literal
    arrivals — replay synthesizes full-duration traffic from these."""
    by_tenant: Dict[str, List[Dict[str, Any]]] = {}
    for r in requests:
        by_tenant.setdefault(str(r.get('tenant') or 'default'),
                             []).append(r)
    specs: Dict[str, Dict[str, Any]] = {}
    for name in sorted(by_tenant):
        rows = by_tenant[name]
        prompts = [int(r.get('prompt_tokens') or 1) for r in rows]
        max_new = [int(r['max_new_tokens']) for r in rows
                   if r.get('max_new_tokens')]
        cohorts = [r.get('cohort') for r in rows if r.get('cohort')]
        shared = [c for c in cohorts if cohorts.count(c) >= 2]
        deadlines = [float(r['deadline_s']) for r in rows
                     if r.get('deadline_s')]
        disconnects = sum(1 for r in rows
                          if r.get('outcome') == 'disconnect')
        spec: Dict[str, Any] = {
            'rps': round(max(0.1, len(rows) / max(1.0, window_s)), 4),
            'prompt_mean': max(1, round(_mean(prompts, 1.0))),
            'prompt_max': max(prompts) if prompts else 1,
            'max_new': max(1, round(_mean(max_new, 16.0))),
        }
        if shared:
            spec['shared_prefix_frac'] = round(
                len(shared) / len(rows), 4)
            spec['prefix_tokens'] = tracefmt.COHORT_LEAD
        if deadlines and len(deadlines) >= len(rows) // 2:
            spec['deadline_s'] = round(_mean(deadlines), 3)
        if disconnects:
            spec['disconnect_frac'] = round(
                disconnects / len(rows), 4)
        specs[name] = spec
    return specs


def _infer_faults(fleet_events: List[Tuple[float, Dict[str, Any]]],
                  n_replicas: int, probe_interval_s: Optional[float]
                  ) -> Tuple[List[Dict[str, Any]],
                             List[Dict[str, Any]],
                             List[Dict[str, Any]]]:
    """Fault timeline from the fleet-event ring. Returns (faults,
    kills, alert transitions); times are relative to the ring's t0."""
    faults: List[Dict[str, Any]] = []
    kills: List[Dict[str, Any]] = []
    alerts: List[Dict[str, Any]] = []
    lost: List[float] = []
    breaker: List[Tuple[float, Dict[str, Any]]] = []
    quarantine: List[Tuple[float, Dict[str, Any]]] = []
    for t, ev in fleet_events:
        kind = ev.get('kind')
        if kind == 'replica_lost':
            lost.append(t)
        elif kind == 'breaker_open':
            breaker.append((t, ev))
        elif kind == 'quarantine':
            quarantine.append((t, ev))
        elif kind == 'controller_recovered':
            # The recovery is when the LB NOTICED; the crash preceded
            # it by at most a reload cadence — close enough for a
            # what-if replay.
            kills.append({'target': 'controller',
                          't': round(max(0.0, t - 30.0), 6)})
        elif kind == 'slo_alert':
            alerts.append({'t': t, 'objective': ev.get('objective'),
                           'tier': ev.get('tier'),
                           'state': ev.get('state')})
    # replica_lost clusters → reclaim storms (inter-cluster spacing
    # preserved; within a cluster the loss count sets the storm
    # fraction).
    lost.sort()
    i = 0
    while i < len(lost):
        j = i
        while (j + 1 < len(lost)
               and lost[j + 1] - lost[i] <= _STORM_CLUSTER_S):
            j += 1
        n = j - i + 1
        frac = min(0.9, max(0.1, n / max(1, n_replicas)))
        faults.append({'kind': 'reclaim_storm',
                       't': round(lost[i], 6),
                       'frac': round(frac, 4), 'notice_frac': 0.5})
        i = j + 1
    if breaker:
        urls = sorted({str(ev.get('replica')) for _, ev in breaker})
        faults.append({'kind': 'wedge',
                       't': round(max(0.0, breaker[0][0] - 15.0), 6),
                       'count': len(urls)})
    if quarantine:
        urls = sorted({str(ev.get('replica'))
                       for _, ev in quarantine})
        lead = probe_interval_s or 20.0
        faults.append({
            'kind': 'sdc', 'flavor': 'token_flip',
            't': round(max(0.0, quarantine[0][0] - lead), 6),
            'count': len(urls)})
    faults.sort(key=lambda f: (f['t'], f['kind']))
    return faults, kills, alerts


def trace_from_spans(spans: List[Dict[str, Any]]) -> tracefmt.Trace:
    """Pure conversion: dump spans → versioned incident trace.
    Deterministic — same spans in, byte-identical trace out (the
    double-export gate)."""
    root = _root_span(spans)
    if root is None:
        raise ValueError(
            'not a flight-recorder dump: no '
            f'{"/".join(ROOT_NAMES)} root span in the trace')
    attrs = root.get('attrs') or {}
    if root['name'] == 'stepline.dump':
        return _trace_from_engine_dump(root, spans)
    samples = _children(spans, 'fleet.sample')
    req_spans = _children(spans, 'fleet.request')
    ev_spans = _children(spans, 'fleet.event')
    # NOTE: the root span's `start` is WALL time (the one clock the
    # twin does not virtualize); every child carries ring time. The
    # timeline anchors on the EVIDENCE rings, never the root.
    ring_ts = ([s['start'] for s in req_spans]
               + [s['start'] for s in ev_spans])
    t0 = min(ring_ts) if ring_ts else 0.0
    dump_t = max(ring_ts) if ring_ts else t0
    requests = []
    for s in req_spans:
        requests.append({'t': _rel(s['start'], t0),
                         **(s.get('attrs') or {})})
    # The arrival RATE comes from the request ring's own span — the
    # ring holds the most recent N arrivals, a much shorter window
    # than the fleet-event timeline (dividing by the global window
    # would under-estimate rps by the ratio of the two).
    req_ts = [s['start'] for s in req_spans]
    window_s = (max(1.0, max(req_ts) - min(req_ts))
                if len(req_ts) >= 2 else 1.0)
    fleet_events = [(_rel(s['start'], t0), s.get('attrs') or {})
                    for s in ev_spans]
    # Initial fleet size: the dump's history only covers replicas
    # ALIVE at dump time (the sync tick prunes departed rings), so
    # reconstruct survivors + losses − replacements from the event
    # ring.
    at_dump = set(attrs.get('replicas') or ())
    # Walk the membership edges to the PEAK concurrent fleet: a
    # replica whose first edge is `lost` predates the window, one
    # whose first edge is `ready` joined inside it, and a replica
    # with no edges at all was simply there the whole time.  (A plain
    # union over-counts churned replacements; survivors-plus-losses
    # under-counts a fleet that ramped inside the window.)
    first_edge: Dict[str, str] = {}
    for _, ev in fleet_events:
        kind = ev.get('kind')
        if kind in ('replica_ready', 'replica_lost'):
            first_edge.setdefault(str(ev.get('replica')), kind)
    fleet = {u for u, k in first_edge.items() if k == 'replica_lost'}
    fleet |= at_dump - set(first_edge)
    peak = len(fleet)
    for _, ev in fleet_events:
        kind, u = ev.get('kind'), str(ev.get('replica'))
        if kind == 'replica_ready':
            fleet.add(u)
        elif kind == 'replica_lost':
            fleet.discard(u)
        peak = max(peak, len(fleet))
    n_replicas = max(1, peak)
    probe_interval = attrs.get('probe_interval_s')
    # Cold-start shape: when the ring shows replicas becoming READY
    # around the recorded arrivals (traffic racing provisioning), the
    # replay must recreate that ordering — record each ready edge as
    # an offset from the first recorded arrival.
    ready_offsets = sorted(
        round(s['start'] - min(req_ts), 6) for s in ev_spans
        if (s.get('attrs') or {}).get('kind') == 'replica_ready'
    ) if req_ts else []
    faults, kills, alerts = _infer_faults(
        fleet_events, n_replicas, probe_interval)
    # No-silent-caps: a ring that wrapped before the dump yields a
    # PARTIAL incident — say so in the header, and say how much fell
    # off.
    dropped_req = max(0, int(attrs.get('request_events_total') or 0)
                      - len(req_spans))
    dropped_fleet = max(0, int(attrs.get('fleet_events_total') or 0)
                        - len(ev_spans))
    page_firing = []
    for a in alerts:
        if (a['tier'] == 'page' and a['state'] == 'firing'
                and a['objective'] not in page_firing):
            page_firing.append(a['objective'])
    first_fault_t = min([f['t'] for f in faults]
                        + [k['t'] for k in kills] + [0.0])
    meta: Dict[str, Any] = {
        'trigger': attrs.get('trigger'),
        'dump_id': root.get('trace_id'),
        'replicas': n_replicas,
        'lb_policy': attrs.get('lb_policy'),
        'sync_interval_s': attrs.get('sync_interval_s'),
        'probe_interval_s': probe_interval,
        'slo': attrs.get('slo_cfg') or [],
        'window_s': round(window_s, 6),
        'tenants': _tenant_specs(requests, window_s),
        'expected_page_firing': page_firing,
        'expected_alert_transitions': [
            [a['objective'], a['tier'], a['state']] for a in alerts],
        # How long past the first fault the outage must persist in
        # replay for the recorded anomaly to re-fire.
        'hold_outage_s': round(
            max(0.0, dump_t - t0 - first_fault_t) + _HOLD_MARGIN_S, 6),
        'ready_offsets_s': ready_offsets[:32],
        'dropped_request_events': dropped_req,
        'dropped_fleet_events': dropped_fleet,
    }
    for key in ('objectives', 'replicas_open',
                'replicas_quarantined'):
        if attrs.get(key) is not None:
            meta[key] = attrs[key]
    return tracefmt.Trace(
        events=[], requests=requests, faults=faults, kills=kills,
        meta=meta, kind='incident',
        truncated=bool(dropped_req or dropped_fleet))


def _trace_from_engine_dump(root: Dict[str, Any],
                            spans: List[Dict[str, Any]]
                            ) -> tracefmt.Trace:
    """Engine stepline dump (``stepline.dump``): per-request
    ``req.<event>`` child spans instead of LB ring records — group by
    request_id into scrubbed arrival records. No fleet-event ring
    here, so the fault timeline is empty (the trigger detail rides in
    meta)."""
    attrs = root.get('attrs') or {}
    by_req: Dict[str, Dict[str, Any]] = {}
    t_min: Optional[float] = None
    for s in spans:
        name = s.get('name') or ''
        if not name.startswith('req.'):
            continue
        a = s.get('attrs') or {}
        rid = str(a.get('request_id') or s.get('request_id') or '')
        if not rid:
            continue
        rec = by_req.setdefault(rid, {'outcome': None})
        t = float(s.get('start') or 0.0)
        t_min = t if t_min is None else min(t_min, t)
        event = name[len('req.'):]
        if event == 'submit':
            rec['t_abs'] = t
            rec['tenant'] = a.get('tenant')
            rec['prompt_tokens'] = int(a.get('prompt_tokens') or 1)
        elif event == 'done':
            rec['output_tokens'] = a.get('tokens')
            rec['outcome'] = ('completed'
                              if a.get('finish_reason') != 'error'
                              else 'failed')
    t0 = t_min or 0.0
    requests = []
    for rid in sorted(by_req):
        rec = by_req[rid]
        if 't_abs' not in rec:
            continue   # ring wrapped between submit and done
        requests.append({
            't': _rel(rec.pop('t_abs'), t0),
            'tenant': rec.get('tenant') or 'default',
            'prompt_tokens': rec.get('prompt_tokens') or 1,
            'max_new_tokens': rec.get('output_tokens'),
            'cohort': None,
            'outcome': rec.get('outcome'),
            'output_tokens': rec.get('output_tokens'),
        })
    dropped = max(
        0, int(attrs.get('events_total') or 0)
        - sum(1 for s in spans
              if (s.get('name') or '').startswith('req.')))
    meta = {'trigger': attrs.get('trigger'),
            'dump_id': root.get('trace_id'),
            'window_s': 0.0, 'tenants': {},
            'expected_page_firing': [],
            'expected_alert_transitions': [],
            'hold_outage_s': 0.0,
            'dropped_request_events': dropped,
            'dropped_fleet_events': 0}
    return tracefmt.Trace(events=[], requests=requests, faults=[],
                          kills=[], meta=meta, kind='incident',
                          truncated=bool(dropped))


def export(store, dump_id: str, path: str) -> tracefmt.Trace:
    """dump → incident trace file. Returns the trace (callers report
    ``trace.truncated`` / dropped counts — the no-silent-caps
    surface)."""
    trace = trace_from_spans(find_dump(store, dump_id))
    tracefmt.save(trace, path)
    return trace


def replay(trace: tracefmt.Trace, seed: int = 0):
    """Run the incident through the twin; returns the SimReport."""
    from skypilot_tpu.sim import twin as twin_lib
    from skypilot_tpu.sim import whatif
    sc = whatif.incident_scenario(trace)
    return twin_lib.DigitalTwin(sc, seed=seed).run()


def verify_replay(trace: tracefmt.Trace, report) -> List[str]:
    """The reproduction gate: does the replay show the same anomaly
    CLASS the dump recorded? Returns human-readable problems (empty =
    reproduced)."""
    problems: List[str] = []
    replay_page: List[str] = []
    for d in report.slo_alerts:
        if (d.get('tier') == 'page' and d.get('state') == 'firing'
                and d['objective'] not in replay_page):
            replay_page.append(d['objective'])
    recorded = list(trace.meta.get('expected_page_firing') or [])
    for obj in recorded:
        if obj not in replay_page:
            problems.append(
                f'recorded page alert {obj!r} did not fire in '
                f'replay (replay fired {replay_page or "none"})')
    if recorded:
        prefix = [o for o in replay_page if o in recorded]
        if prefix != recorded:
            problems.append(
                f'page-alert ORDER diverged: recorded {recorded}, '
                f'replay {prefix}')
    trigger = trace.meta.get('trigger')
    if trigger == 'slo_page' and not replay_page:
        problems.append('slo_page incident: no page-tier alert '
                        'fired in replay')
    if trigger == 'breaker_open' and not any(
            d['kind'] == 'breaker_open' for d in report.decisions):
        problems.append('breaker_open incident: no breaker opened '
                        'in replay')
    if trigger == 'quarantine' and not any(
            d['kind'] == 'quarantine' for d in report.decisions):
        problems.append('quarantine incident: no replica was '
                        'quarantined in replay')
    shed_rec = sum(1 for r in trace.requests
                   if r.get('outcome') == 'shed')
    if (trace.requests
            and shed_rec / len(trace.requests) > 0.05
            and report.shed == 0):
        problems.append(
            f'recorded window shed {shed_rec}/{len(trace.requests)} '
            f'requests but the replay shed none')
    return problems


def _smoke() -> int:
    """``make incident-smoke``: grow an SLO-page incident in the
    twin, export it from the dump store, replay the export, and
    assert the page alert reproduces — the full lifecycle in one
    process, < 60s."""
    import tempfile

    from skypilot_tpu.observability import stepline as stepline_lib
    from skypilot_tpu.observability import store as store_lib
    from skypilot_tpu.sim import scenarios
    from skypilot_tpu.sim import twin as twin_lib

    sc = scenarios.incident_page_storm(replicas=4,
                                       duration_s=1500.0)
    with tempfile.TemporaryDirectory() as tmp:
        store = store_lib.SpanStore(f'{tmp}/spans.db')
        prev = stepline_lib._store  # noqa: SLF001 — smoke injection
        stepline_lib.set_dump_store(store)
        try:
            twin_lib.DigitalTwin(sc, seed=3).run()
        finally:
            stepline_lib.set_dump_store(prev)
        dumps = [d for d in list_dumps(store)
                 if d['trigger'] == 'slo_page']
        assert dumps, 'storm replay wrote no slo_page fleet dump'
        path = f'{tmp}/incident.jsonl'
        trace = export(store, dumps[0]['dump_id'], path)
        assert trace.meta['expected_page_firing'], (
            'exported incident recorded no page-tier firing')
        loaded = tracefmt.load(path)
        report = replay(loaded, seed=3)
        problems = verify_replay(loaded, report)
        assert not problems, f'replay did not reproduce: {problems}'
        print(json.dumps({
            'incident_smoke': 'ok',
            'dump_id': dumps[0]['dump_id'],
            'recorded_page_firing':
                trace.meta['expected_page_firing'],
            'replayed_requests': len(report.records),
            'truncated': trace.truncated,
        }, indent=2, sort_keys=True))
    return 0


if __name__ == '__main__':
    raise SystemExit(_smoke())
