"""End-to-end distributed tracing for the control plane.

A Dapper-style propagated-trace subsystem for the multi-hop
orchestrator (CLI/SDK → API server → request worker → on-cluster agent
→ job runtime, plus the jobs controller and the serve LB → replica
path). ``utils/timeline.py`` records Chrome-trace events *per process*;
this package adds the piece timeline cannot provide — a trace context
that crosses the wire, so a TTFT or recovery regression is attributable
to a hop instead of "the box was noisy".

Layout:

- ``trace``  — trace context (W3C-traceparent-style), span recording,
  cross-process propagation (HTTP header / env var / request payload),
  and best-effort span shipping. Zero overhead when ``SKY_TPU_TRACE``
  is unset; every ship path is fail-open.
- ``store``  — sqlite-backed span store (``utils/db.py`` pattern, like
  ``server/requests_store.py``) with size-capped GC, plus ``ingest()``,
  the single write path that also feeds the Prometheus
  ``sky_tpu_span_duration_seconds{op,hop}`` series.
- ``render`` — span-tree text rendering for ``sky-tpu trace`` and
  Perfetto/Chrome-trace JSON export (same event shape as
  ``utils/timeline.py``, so local intra-process events merge in).
"""
from skypilot_tpu.observability import trace  # noqa: F401
