"""Logging agent base classes (reference ``sky/logs/agent.py``:
``LoggingAgent`` with get_setup_command/get_credential_file_mounts,
``FluentbitAgent`` rendering a fluent-bit config that tails the per-job
log files).

TPU-native wiring: when the global config carries ``logs.store``, the
backend appends the agent's setup command to cluster setup, so every
host of a slice ships its job logs (all ranks — per-rank log files are
first-class here, unlike the GPU reference's single driver log).
"""
from __future__ import annotations

import abc
import shlex
from typing import Any, Dict, Optional

from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions

LOGGING_CONFIG_DIR = '/opt/sky_tpu/logging'
# Agent job logs: <cluster_dir>/job_logs/<job_id>/rank<i>_<phase>.log
# on real hosts (runtime/agent.py h_submit log_dir layout).
JOB_LOG_GLOB = '/opt/sky_tpu/cluster/job_logs/*/*.log'


class LoggingAgent(abc.ABC):
    """Reference sky/logs/agent.py:12."""

    @abc.abstractmethod
    def get_setup_command(self, cluster_name: str) -> str:
        ...

    @abc.abstractmethod
    def get_credential_file_mounts(self) -> Dict[str, str]:
        ...


class FluentbitAgent(LoggingAgent):
    """Fluent-bit install + config scaffolding (reference :31)."""

    def get_setup_command(self, cluster_name: str) -> str:
        install = (
            'if ! command -v fluent-bit >/dev/null 2>&1 && '
            '[ ! -f /opt/fluent-bit/bin/fluent-bit ]; then '
            'curl -fsSL '
            'https://raw.githubusercontent.com/fluent/fluent-bit/master/'
            'install.sh | sh; fi')
        cfg = self.fluentbit_config(cluster_name)
        cfg_path = f'{LOGGING_CONFIG_DIR}/fluentbit.yaml'
        configure = (
            f'sudo mkdir -p {LOGGING_CONFIG_DIR} && '
            f'sudo chmod a+rwx {LOGGING_CONFIG_DIR} && '
            f'echo {shlex.quote(cfg)} > {cfg_path}')
        start = (
            'nohup $(command -v fluent-bit || '
            'echo /opt/fluent-bit/bin/fluent-bit) '
            f'-c {cfg_path} > {LOGGING_CONFIG_DIR}/agent.log 2>&1 &')
        return f'({install}) && {configure} && ({start})'

    def fluentbit_config(self, cluster_name: str) -> str:
        import yaml
        cfg = {
            'pipeline': {
                'inputs': [{
                    'name': 'tail',
                    'path': JOB_LOG_GLOB,
                    'path_key': 'log_path',
                    'refresh_interval': 5,
                }],
                'outputs': [self.fluentbit_output_config(cluster_name)],
            },
        }
        return yaml.safe_dump(cfg, sort_keys=False)

    @abc.abstractmethod
    def fluentbit_output_config(self,
                                cluster_name: str) -> Dict[str, Any]:
        ...


def get_logging_agent() -> Optional[LoggingAgent]:
    """The configured agent, or None (reference resolves logs.store the
    same way)."""
    store = config_lib.get_nested(('logs', 'store'))
    if store is None:
        return None
    store_cfg = config_lib.get_nested(('logs', store), {}) or {}
    if store == 'gcp':
        from skypilot_tpu.logs.gcp import GCPLoggingAgent
        return GCPLoggingAgent(store_cfg)
    if store == 'aws':
        from skypilot_tpu.logs.aws import CloudwatchLoggingAgent
        return CloudwatchLoggingAgent(store_cfg)
    raise exceptions.InvalidTaskError(
        f'Unknown logs.store {store!r}; supported: gcp, aws')
