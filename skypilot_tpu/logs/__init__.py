"""Cluster-log shipping agents (reference ``sky/logs/``: fluentbit-based
agents for GCP Cloud Logging / AWS CloudWatch, wired into cluster setup
when ``logs.store`` is configured)."""
from skypilot_tpu.logs.agent import (FluentbitAgent, LoggingAgent,
                                     get_logging_agent)
from skypilot_tpu.logs.aws import CloudwatchLoggingAgent
from skypilot_tpu.logs.gcp import GCPLoggingAgent

__all__ = ['CloudwatchLoggingAgent', 'FluentbitAgent', 'GCPLoggingAgent',
           'LoggingAgent', 'get_logging_agent']
