"""AWS CloudWatch agent (reference ``sky/logs/aws.py``) — relevant when
jobs ship logs cross-cloud (e.g. a team standardized on CloudWatch)."""
from __future__ import annotations

from typing import Any, Dict

from skypilot_tpu.logs.agent import FluentbitAgent


class CloudwatchLoggingAgent(FluentbitAgent):
    def __init__(self, config: Dict[str, Any]):
        self.region = config.get('region', 'us-east-1')
        self.log_group = config.get('log_group_name', 'sky-tpu-logs')
        self.credentials_file = config.get('credentials_file')

    def fluentbit_output_config(self,
                                cluster_name: str) -> Dict[str, Any]:
        return {
            'name': 'cloudwatch_logs',
            'match': '*',
            'region': self.region,
            'log_group_name': self.log_group,
            'log_stream_prefix': f'{cluster_name}-',
            'auto_create_group': 'true',
        }

    def get_credential_file_mounts(self) -> Dict[str, str]:
        if not self.credentials_file:
            return {}
        return {'~/.aws/credentials': self.credentials_file}
