"""GCP Cloud Logging agent (reference ``sky/logs/gcp.py``:
``GCPLoggingAgent`` at :38, stackdriver fluent-bit output at :19)."""
from __future__ import annotations

from typing import Any, Dict

from skypilot_tpu.logs.agent import FluentbitAgent


class GCPLoggingAgent(FluentbitAgent):
    """Ships job logs to Cloud Logging via fluent-bit's stackdriver
    output. On TPU VMs the metadata-server credentials just work; off
    GCP, ``credentials_file`` points at a service-account key."""

    def __init__(self, config: Dict[str, Any]):
        self.project_id = config.get('project_id')
        self.credentials_file = config.get('credentials_file')
        self.additional_labels = dict(config.get('labels') or {})

    def fluentbit_output_config(self,
                                cluster_name: str) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            'name': 'stackdriver',
            'match': '*',
            'resource': 'global',
            'labels': ','.join(
                f'{k}={v}' for k, v in {
                    'sky_tpu_cluster': cluster_name,
                    **self.additional_labels,
                }.items()),
        }
        if self.project_id:
            out['export_to_project_id'] = self.project_id
        if self.credentials_file:
            out['google_service_credentials'] = (
                '/opt/sky_tpu/logging/gcp-credentials.json')
        return out

    def get_credential_file_mounts(self) -> Dict[str, str]:
        if not self.credentials_file:
            return {}   # TPU VM metadata credentials
        return {'/opt/sky_tpu/logging/gcp-credentials.json':
                self.credentials_file}
