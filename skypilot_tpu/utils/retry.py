"""The one retry/backoff policy (plus the serve LB's circuit breaker).

Before this module, every hop hand-rolled its own loop: the SDK retried
GETs with bare exponential sleep, ``AgentClient`` did not retry at all,
and the serve LB turned any pre-stream connection error into a 502.
``Retrier`` replaces all of them with a single policy:

- exponential backoff with **full jitter** (AWS-style: the delay is
  uniform in [0, min(cap, base * 2^attempt)] — synchronized clients
  hammering a recovering agent is exactly the thundering herd a gang
  restart produces);
- an **overall deadline** in addition to the attempt cap, so callers on
  a budget (the LB, provisioning) bound wall clock, not just tries;
- **transient vs fatal classification** by exception type — fatal wins
  when both match, and anything matching neither propagates immediately
  (an unknown error is not license to hammer);
- an optional **server-supplied backoff floor** (``retry_after``): when
  the failed call carries a ``Retry-After`` the server computed (the
  serve stack's queue-drain estimate on 429/503), the jittered delay is
  raised to at least that value — the server knows its backlog better
  than our exponential guess, and ignoring it turns a polite shed into
  a hammer;
- every retry is recorded as a zero-duration span on the active trace
  (``retry.<name>``), so `sky-tpu trace` shows *where* a request's
  latency went to backoff.

``CircuitBreaker`` is the replica-level complement used by the serve
load balancer: consecutive pre-stream failures trip a replica OPEN
(never selected); after a cooldown it goes HALF_OPEN and admits exactly
one probe request — success closes it, failure re-opens and restarts
the cooldown.
"""
from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple, Type

from skypilot_tpu.observability import trace as trace_lib

# Default transient set: connection-shaped trouble. requests exceptions
# subclass OSError via ConnectionError only sometimes, so adopters pass
# their own tuple when the transport is requests.
DEFAULT_TRANSIENT: Tuple[Type[BaseException], ...] = (
    ConnectionError, TimeoutError, OSError)

# Ceiling on an honored server-supplied Retry-After: the serve stack
# clamps its own queue-drain estimates to [1, 60] s, and a client must
# not sleep unboundedly on a hostile/buggy header.
RETRY_AFTER_CAP_S = 60.0


def _record_retry_event(name: str, attempt: int, delay_s: float,
                        exc: BaseException) -> None:
    if not trace_lib.enabled():
        return
    try:
        parent = trace_lib.current()
        trace_lib.record_span(
            name=f'retry.{name}',
            trace_id=(parent.trace_id if parent
                      else os.urandom(16).hex()),
            span_id=os.urandom(8).hex(),
            parent_id=parent.span_id if parent else None,
            start=time.time(), dur_s=0.0,
            status=f'retry:{type(exc).__name__}',
            hop=trace_lib.get_hop(),
            attrs={'attempt': attempt, 'delay_s': round(delay_s, 4),
                   'error': str(exc)[:200]})
    except Exception:  # noqa: BLE001 — observability must not fail calls
        pass


class Retrier:
    """Call a function under the shared retry policy.

    ``transient`` exceptions are retried while attempts and the deadline
    allow; ``fatal`` exceptions (checked first) and anything matching
    neither propagate immediately. ``retry_on`` gives callers a
    predicate escape hatch (e.g. "HTTPError but only 5xx").
    """

    def __init__(self, name: str, *,
                 max_attempts: int = 4,
                 base_delay_s: float = 0.2,
                 max_delay_s: float = 10.0,
                 deadline_s: Optional[float] = None,
                 transient: Tuple[Type[BaseException], ...] =
                 DEFAULT_TRANSIENT,
                 fatal: Tuple[Type[BaseException], ...] = (),
                 retry_on: Optional[
                     Callable[[BaseException], bool]] = None,
                 retry_after: Optional[
                     Callable[[BaseException], Optional[float]]] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Callable[[], float] = random.random) -> None:
        if max_attempts < 1:
            raise ValueError('max_attempts must be >= 1')
        self.name = name
        self.max_attempts = max_attempts
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.deadline_s = deadline_s
        self.transient = transient
        self.fatal = fatal
        self.retry_on = retry_on
        # Private name on purpose: a field called `retry_after` would
        # collide with the engine schedulers' lock-annotated
        # Scheduler.retry_after in the lint's duck dispatch.
        self._retry_after = retry_after
        self._sleep = sleep
        self._rng = rng

    def _classify_transient(self, exc: BaseException) -> bool:
        if isinstance(exc, self.fatal):
            return False
        if self.retry_on is not None and self.retry_on(exc):
            return True
        return isinstance(exc, self.transient)

    def backoff_s(self, attempt: int) -> float:
        """Full-jitter delay before retry number ``attempt`` (1-based)."""
        cap = min(self.max_delay_s,
                  self.base_delay_s * (2 ** (attempt - 1)))
        return self._rng() * cap

    def _floor_s(self, exc: BaseException) -> Optional[float]:
        """Server-supplied backoff floor for this failure, if any —
        extraction errors never fail the retry loop."""
        if self._retry_after is None:
            return None
        try:
            floor = self._retry_after(exc)
        except Exception:  # noqa: BLE001 — a bad header is no floor
            return None
        if floor is None or floor <= 0:
            return None
        return min(float(floor), RETRY_AFTER_CAP_S)

    def call(self, fn: Callable[..., Any], *args: Any,
             **kwargs: Any) -> Any:
        deadline = (time.monotonic() + self.deadline_s
                    if self.deadline_s is not None else None)
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — classified below
                if not self._classify_transient(e):
                    raise
                if attempt >= self.max_attempts:
                    raise
                delay = self.backoff_s(attempt)
                # The server's Retry-After (serve-stack queue-drain
                # estimate) is a FLOOR on the jittered delay, never a
                # cap — but the overall deadline still wins below.
                floor = self._floor_s(e)
                if floor is not None:
                    delay = max(delay, floor)
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise
                    delay = min(delay, remaining)
                _record_retry_event(self.name, attempt, delay, e)
                self._sleep(delay)


# ---------------------------------------------------------------------------
# Circuit breaker (per-key; the LB keys by replica URL).

STATE_CLOSED = 'closed'
STATE_OPEN = 'open'
STATE_HALF_OPEN = 'half-open'


class _Breaker:
    __slots__ = ('failures', 'opened_at', 'probing')

    def __init__(self) -> None:
        self.failures = 0
        self.opened_at: Optional[float] = None
        self.probing = False


class CircuitBreaker:
    """Consecutive-failure breaker over a dynamic key set.

    closed --[threshold consecutive failures]--> open
    open   --[cooldown elapsed]--> half-open (one probe admitted)
    half-open --success--> closed | --failure--> open (cooldown restarts)

    Keys never seen (or pruned) are closed. Thread-safe; ``allows`` is
    the hot-path call and is one dict lookup for closed keys.
    """

    def __init__(self, *, failure_threshold: int = 3,
                 cooldown_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if failure_threshold < 1:
            raise ValueError('failure_threshold must be >= 1')
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._breakers: Dict[str, _Breaker] = {}

    # The per-replica breaker map is read from the LB event loop and
    # (in tests / sync callers) plain threads — every access goes
    # through the lock; `_get` is lock-free itself because the
    # interprocedural pass proves all its callers hold it (SKY-LOCK).
    _GUARDED_BY = {
        '_breakers': '_lock',
    }

    def _get(self, key: str) -> _Breaker:
        b = self._breakers.get(key)
        if b is None:
            b = self._breakers[key] = _Breaker()
        return b

    def state(self, key: str) -> str:
        with self._lock:
            b = self._breakers.get(key)
            if b is None or b.opened_at is None:
                return STATE_CLOSED
            if self._clock() - b.opened_at >= self.cooldown_s:
                return STATE_HALF_OPEN
            return STATE_OPEN

    def allows(self, key: str) -> bool:
        """May a request be sent to ``key`` right now? In HALF_OPEN only
        the first caller gets True (the probe); others wait."""
        with self._lock:
            b = self._breakers.get(key)
            if b is None or b.opened_at is None:
                return True
            if self._clock() - b.opened_at < self.cooldown_s:
                return False
            if b.probing:
                return False
            b.probing = True
            return True

    def record_success(self, key: str) -> None:
        with self._lock:
            b = self._breakers.get(key)
            if b is None:
                return
            b.failures = 0
            b.opened_at = None
            b.probing = False

    def release(self, key: str) -> None:
        """Give back an admitted half-open probe slot WITHOUT recording
        an outcome — for attempts that died of causes unrelated to the
        replica (e.g. the client disconnected). Without this, a probe
        that never reports back would blacklist the key forever."""
        with self._lock:
            b = self._breakers.get(key)
            if b is not None:
                b.probing = False

    def record_failure(self, key: str) -> None:
        with self._lock:
            b = self._get(key)
            b.failures += 1
            if b.opened_at is not None:
                # Failed half-open probe (or a straggler request that
                # was in flight when the breaker tripped): re-open and
                # restart the cooldown.
                b.opened_at = self._clock()
                b.probing = False
            elif b.failures >= self.failure_threshold:
                b.opened_at = self._clock()
                b.probing = False

    def prune(self, live_keys) -> None:
        """Drop state for keys no longer in the live set (dead replicas
        must not pin breaker state forever)."""
        live = set(live_keys)
        with self._lock:
            for k in list(self._breakers):
                if k not in live:
                    del self._breakers[k]

    def snapshot(self) -> Dict[str, str]:
        # Key snapshot under the lock (SKY-LOCK): prune() deletes
        # entries concurrently, and the declared contract is that
        # _breakers is only touched under _lock. state() re-locks per
        # key — the RLock-free double hop is fine, a pruned key just
        # reads CLOSED.
        with self._lock:
            keys = list(self._breakers)
        return {k: self.state(k) for k in keys}
