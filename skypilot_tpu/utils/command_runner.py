"""Command runners: run commands / sync files on cluster hosts.

Counterpart of the reference's ``sky/utils/command_runner.py`` (base :329,
``SSHCommandRunner`` :875 with ControlMaster + rsync,
``LocalProcessCommandRunner`` :1690). The TPU backend prefers the on-host
agent for *execution* (SSH-free, SURVEY.md §7 hard-parts note); runners are
used for file *sync* and as the SSH fallback for debugging.
"""
from __future__ import annotations

import os
import shlex
import shutil
import subprocess
from typing import List, Optional, Tuple

from skypilot_tpu import exceptions

_SSH_OPTS = [
    '-o', 'StrictHostKeyChecking=no',
    '-o', 'UserKnownHostsFile=/dev/null',
    '-o', 'ConnectTimeout=10',
    '-o', 'ControlMaster=auto',
    '-o', 'ControlPath=~/.sky_tpu/ssh_control/%C',
    '-o', 'ControlPersist=120s',
]


class CommandRunner:
    """Run a command on one host and rsync files to it."""

    def run(self, cmd: str, *, timeout: Optional[float] = None,
            check: bool = False) -> Tuple[int, str, str]:
        raise NotImplementedError

    def rsync(self, src: str, dst: str, *, up: bool = True) -> None:
        raise NotImplementedError

    def _check(self, rc: int, cmd: str, stderr: str, check: bool) -> None:
        if check and rc != 0:
            raise exceptions.CommandError(rc, cmd, stderr)


class LocalProcessCommandRunner(CommandRunner):
    """Runs on this machine, rooted at a host dir (fake-slice hosts)."""

    def __init__(self, host_dir: str):
        self.host_dir = host_dir
        os.makedirs(os.path.join(host_dir, 'workdir'), exist_ok=True)

    def run(self, cmd: str, *, timeout: Optional[float] = None,
            check: bool = False) -> Tuple[int, str, str]:
        proc = subprocess.run(
            cmd, shell=True, cwd=os.path.join(self.host_dir, 'workdir'),
            capture_output=True, text=True, timeout=timeout)
        self._check(proc.returncode, cmd, proc.stderr, check)
        return proc.returncode, proc.stdout, proc.stderr

    def rsync(self, src: str, dst: str, *, up: bool = True) -> None:
        """`dst` is interpreted relative to the host dir (absolute remote
        paths map into the host's sandbox)."""
        target = os.path.join(self.host_dir, dst.lstrip('/'))
        if not up:
            src, target = target, src
        src = os.path.expanduser(src)
        if os.path.isdir(src):
            # Trailing-slash rsync semantics: copy contents into target.
            copy_contents = src.endswith('/')
            os.makedirs(target if copy_contents
                        else os.path.dirname(target) or '.', exist_ok=True)
            dest = target if copy_contents else target
            shutil.copytree(src, dest, dirs_exist_ok=True)
        else:
            os.makedirs(os.path.dirname(target) or '.', exist_ok=True)
            shutil.copy2(src, target)


class SSHCommandRunner(CommandRunner):
    """SSH/rsync to a real host (reference :875). Used for TPU VMs when the
    agent path is unavailable and for file sync."""

    def __init__(self, ip: str, user: str = 'root',
                 key_path: Optional[str] = None, port: int = 22,
                 password: Optional[str] = None):
        self.ip = ip
        self.user = user
        self.key_path = key_path
        self.port = port
        self.password = password
        if password and shutil.which('sshpass') is None:
            raise exceptions.CommandError(
                1, 'sshpass', 'password auth requires sshpass on PATH; '
                'install it or use identity_file instead')
        os.makedirs(os.path.expanduser('~/.sky_tpu/ssh_control'),
                    exist_ok=True)

    def _auth_prefix(self) -> List[str]:
        # -e reads the password from $SSHPASS (see _env): a -p argument
        # would expose it to every local user via /proc/*/cmdline.
        return ['sshpass', '-e'] if self.password else []

    def _env(self) -> Optional[dict]:
        if not self.password:
            return None
        return {**os.environ, 'SSHPASS': self.password}

    def _ssh_base(self) -> List[str]:
        cmd = self._auth_prefix() + ['ssh', *_SSH_OPTS, '-p',
                                     str(self.port)]
        if self.key_path:
            cmd += ['-i', os.path.expanduser(self.key_path)]
        if not self.password:
            # Fail fast instead of prompting when key auth is rejected.
            cmd += ['-o', 'BatchMode=yes']
        cmd.append(f'{self.user}@{self.ip}')
        return cmd

    def run(self, cmd: str, *, timeout: Optional[float] = None,
            check: bool = False) -> Tuple[int, str, str]:
        full = self._ssh_base() + [f'bash -lc {shlex.quote(cmd)}']
        try:
            proc = subprocess.run(full, capture_output=True, text=True,
                                  timeout=timeout, env=self._env())
        except subprocess.TimeoutExpired:
            # A hung handshake must look like a failed command (rc 124,
            # GNU timeout convention), not a raw TimeoutExpired that
            # escapes the provisioner's failover error handling.
            rc, err = 124, f'ssh to {self.ip} timed out after {timeout}s'
            self._check(rc, cmd, err, check)
            return rc, '', err
        self._check(proc.returncode, cmd, proc.stderr, check)
        return proc.returncode, proc.stdout, proc.stderr

    def rsync(self, src: str, dst: str, *, up: bool = True) -> None:
        ssh_cmd = ' '.join(['ssh', *_SSH_OPTS, '-p', str(self.port)] +
                           (['-i', self.key_path] if self.key_path else []))
        remote = f'{self.user}@{self.ip}:{dst}'
        pair = [src, remote] if up else [remote, src]
        proc = subprocess.run(
            self._auth_prefix() +
            ['rsync', '-az', '--delete', '-e', ssh_cmd, *pair],
            capture_output=True, text=True, env=self._env())
        if proc.returncode != 0:
            raise exceptions.CommandError(proc.returncode,
                                          f'rsync {src} {dst}', proc.stderr)


class KubectlCommandRunner(CommandRunner):
    """kubectl exec/cp to a pod (reference KubernetesCommandRunner,
    command_runner.py:1410). Pods have no sshd; the k8s transport is the
    API server."""

    def __init__(self, pod: str, *, namespace: str = 'default',
                 context: Optional[str] = None,
                 container: Optional[str] = None):
        self.pod = pod
        self.namespace = namespace
        self.context = context
        self.container = container

    def _base(self) -> List[str]:
        cmd = ['kubectl']
        if self.context:
            cmd += ['--context', self.context]
        cmd += ['-n', self.namespace]
        return cmd

    def run(self, cmd: str, *, timeout: Optional[float] = None,
            check: bool = False) -> Tuple[int, str, str]:
        full = self._base() + ['exec', self.pod]
        if self.container:
            full += ['-c', self.container]
        full += ['--', '/bin/bash', '-c', cmd]
        try:
            proc = subprocess.run(full, capture_output=True, text=True,
                                  timeout=timeout, input='')
        except FileNotFoundError:
            self._check(127, cmd, 'kubectl not found on PATH', check)
            return 127, '', 'kubectl not found on PATH'
        except subprocess.TimeoutExpired:
            err = f'kubectl exec to {self.pod} timed out after {timeout}s'
            self._check(124, cmd, err, check)
            return 124, '', err
        self._check(proc.returncode, cmd, proc.stderr, check)
        return proc.returncode, proc.stdout, proc.stderr

    def _expand_home(self, path: str) -> str:
        """kubectl cp / quoted mkdir never expand ~ (unlike ssh)."""
        if not path.startswith('~'):
            return path
        if not hasattr(self, '_home'):
            _, out, _ = self.run('echo $HOME', check=True, timeout=30)
            self._home = out.strip() or '/root'
        return self._home + path[1:].lstrip('/')  \
            if path == '~' else path.replace('~', self._home, 1)

    def rsync(self, src: str, dst: str, *, up: bool = True) -> None:
        """kubectl cp (no rsync delta, but the same contract)."""
        if up:
            dst = self._expand_home(dst)
        else:
            src = self._expand_home(src)
        if up:
            # Parent must exist, but NOT dst itself: kubectl cp nests
            # the source under an existing destination directory.
            parent = os.path.dirname(dst.rstrip('/')) or '/'
            self.run(f'mkdir -p {shlex.quote(parent)} && '
                     f'rm -rf {shlex.quote(dst.rstrip("/"))}',
                     check=True, timeout=60)
            pair = [src.rstrip('/'),
                    f'{self.namespace}/{self.pod}:{dst.rstrip("/")}']
        else:
            pair = [f'{self.namespace}/{self.pod}:{src}', dst]
        full = self._base() + ['cp', *pair]
        if self.container:
            full += ['-c', self.container]
        proc = subprocess.run(full, capture_output=True, text=True,
                              input='')
        if proc.returncode != 0:
            raise exceptions.CommandError(
                proc.returncode, f'kubectl cp {src} {dst}', proc.stderr)
