"""Per-cluster file locks (reference sky/utils/locks.py).

The engine's planner-under-lock discipline (reference
sky/execution.py:469-487): every state-mutating operation on a cluster takes
its lock so concurrent launches/downs serialize.
"""
from __future__ import annotations

import contextlib
import os
from typing import Iterator

import filelock

from skypilot_tpu.utils import common


def _lock_path(name: str) -> str:
    d = os.path.join(common.base_dir(), 'locks')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f'{name}.lock')


@contextlib.contextmanager
def cluster_lock(cluster_name: str,
                 timeout: float = 60.0) -> Iterator[None]:
    lock = filelock.FileLock(_lock_path(f'cluster_{cluster_name}'),
                             timeout=timeout)
    with lock:
        yield
