"""Per-cluster file locks (reference sky/utils/locks.py).

The engine's planner-under-lock discipline (reference
sky/execution.py:469-487): every state-mutating operation on a cluster takes
its lock so concurrent launches/downs serialize.
"""
from __future__ import annotations

import contextlib
import os
from typing import Iterator

import filelock

from skypilot_tpu.utils import common


def _lock_path(name: str) -> str:
    d = os.path.join(common.base_dir(), 'locks')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f'{name}.lock')


@contextlib.contextmanager
def cluster_lock(cluster_name: str,
                 timeout: float = 60.0) -> Iterator[None]:
    lock = filelock.FileLock(_lock_path(f'cluster_{cluster_name}'),
                             timeout=timeout)
    with lock:
        yield


# One FileLock instance per path: distinct instances on the same path
# conflict even within a process (flock is per-open-file), so nested
# named_lock() calls (workspace CRUD -> config.update_global) would
# deadlock. A shared instance is reentrant and still serializes threads.
_named_locks: dict = {}
_named_locks_guard = __import__('threading').Lock()


@contextlib.contextmanager
def named_lock(name: str, timeout: float = 60.0) -> Iterator[None]:
    """General-purpose cross-process lock (config writes, etc.)."""
    path = _lock_path(name)
    with _named_locks_guard:
        lock = _named_locks.get(path)
        if lock is None:
            lock = filelock.FileLock(path, timeout=timeout)
            _named_locks[path] = lock
    with lock:
        yield
