"""Per-cluster file locks (reference sky/utils/locks.py).

The engine's planner-under-lock discipline (reference
sky/execution.py:469-487): every state-mutating operation on a cluster takes
its lock so concurrent launches/downs serialize.
"""
from __future__ import annotations

import contextlib
import os
from typing import Iterator

import filelock

from skypilot_tpu.utils import common


def _lock_path(name: str) -> str:
    d = os.path.join(common.base_dir(), 'locks')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f'{name}.lock')


@contextlib.contextmanager
def cluster_lock(cluster_name: str,
                 timeout: float = 60.0) -> Iterator[None]:
    lock = filelock.FileLock(_lock_path(f'cluster_{cluster_name}'),
                             timeout=timeout)
    with lock:
        yield


# One FileLock instance per path: distinct instances on the same path
# conflict even within a process (flock is per-open-file), so nested
# named_lock() calls (workspace CRUD -> config.update_global) would
# deadlock. A shared instance is reentrant and still serializes threads.
_named_locks: dict = {}
_named_locks_guard = __import__('threading').Lock()


@contextlib.contextmanager
def named_lock(name: str, timeout: float = 60.0) -> Iterator[None]:
    """General-purpose cross-process lock (config writes, etc.)."""
    path = _lock_path(name)
    with _named_locks_guard:
        lock = _named_locks.get(path)
        if lock is None:
            lock = filelock.FileLock(path, timeout=timeout)
            _named_locks[path] = lock
    with lock:
        yield


# The bench-owns-the-chip lock lives at a FIXED machine-wide path, NOT
# under SKY_TPU_HOME: benches and the test suite run with different
# (per-test, per-run) homes, and the whole point is that they contend
# on the one physical accelerator.
CHIP_LOCK_ENV = 'SKY_TPU_CHIP_LOCK'


def chip_lock_path() -> str:
    import tempfile
    return (os.environ.get(CHIP_LOCK_ENV) or
            os.path.join(tempfile.gettempdir(), 'sky_tpu_chip0.lock'))


def acquire_chip_lock(tag: str, timeout: float = 3600.0
                      ) -> filelock.FileLock:
    """Blocking chip-lock acquisition for benches: logs the wait and
    holds until process exit (flock dies with the process)."""
    import sys
    lock = chip_lock(timeout=timeout)
    print(f'[{tag}] acquiring chip lock {chip_lock_path()}',
          file=sys.stderr)
    lock.acquire()
    return lock


def chip_lock(timeout: float = -1) -> filelock.FileLock:
    """Machine-wide accelerator ownership (VERDICT r5 weak #2: perf
    artifacts were produced while the test suite burned the box).

    Benches (bench.py / bench_ttft.py) hold it for their measured
    section with a long blocking timeout; the test session try-acquires
    it at startup (tests/conftest.py) so a bench launched mid-suite
    waits instead of measuring noise. flock-backed, so a crashed
    holder's lock dies with its process.
    """
    return filelock.FileLock(chip_lock_path(), timeout=timeout)
