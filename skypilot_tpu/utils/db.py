"""Shared sqlite access: thread-local connections, WAL, schema bootstrap.

One copy of the pattern every state store uses (control-plane clusters DB,
managed-jobs DB, serve DB, API request store — reference keeps these
separate too: global_user_state / jobs/state / serve_state / requests).
Connections are per-(path, thread); WAL gives multi-process safety with
the per-cluster file locks providing read-modify-write discipline.
"""
from __future__ import annotations

import os
import sqlite3
import threading
from typing import Dict, Tuple

_local = threading.local()
_GLOBAL_LOCK = threading.Lock()


class Db:
    """Thread-local sqlite connections to one database file."""

    def __init__(self, path: str, schema: str):
        self.path = path
        self.schema = schema

    @property
    def conn(self) -> sqlite3.Connection:
        cache: Dict[str, sqlite3.Connection] = getattr(
            _local, 'conns', None) or {}
        if not hasattr(_local, 'conns'):
            _local.conns = cache
        conn = cache.get(self.path)
        if conn is None:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            conn = sqlite3.connect(self.path, timeout=30.0)
            conn.execute('PRAGMA journal_mode=WAL')
            conn.executescript(self.schema)
            conn.row_factory = sqlite3.Row
            cache[self.path] = conn
        return conn


_instances: Dict[Tuple[str, int], Db] = {}


def get_db(path: str, schema: str) -> Db:
    """Process-wide Db registry keyed by absolute path."""
    key = (os.path.abspath(path), hash(schema))
    with _GLOBAL_LOCK:
        if key not in _instances:
            _instances[key] = Db(path, schema)
        return _instances[key]
