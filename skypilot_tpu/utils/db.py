"""Shared state-store access: sqlite by default, postgres by DSN.

One copy of the pattern every state store uses (control-plane clusters DB,
managed-jobs DB, serve DB, API request store — reference keeps these
separate too: global_user_state / jobs/state / serve_state / requests).

Engine selection (reference global_user_state runs on SQLAlchemy with
sqlite or postgres; here the same choice is made without the ORM):

- default: per-store sqlite file. Connections are per-(path, thread);
  WAL gives multi-process safety with the per-cluster file locks
  providing read-modify-write discipline.
- ``SKY_TPU_DB_URL=postgresql://user:pw@host/db`` (or config ``db.url``):
  every store lands in that one database, each in its own pg *schema*
  named after the store file (``state``, ``server_requests``, ...), so a
  multi-user API server deployment gets transactional shared state.

Store code is written once against the sqlite dialect; the postgres
connection adapter translates statements (placeholders, AUTOINCREMENT,
PRAGMA, INSERT OR REPLACE) at execute time. The translation layer is unit
tested against a fake DBAPI driver — a real postgres needs psycopg2 or
pg8000 on the server's PATH (not bundled).
"""
from __future__ import annotations

import os
import re
import sqlite3
import threading
from typing import Any, Dict, List, Optional, Tuple

_local = threading.local()
_GLOBAL_LOCK = threading.Lock()


_cfg_url_cache: List[Optional[str]] = []   # [] = not yet resolved


def db_url() -> Optional[str]:
    """The configured shared-database DSN, if any.

    Called on every `.conn` access, so: env lookup (cheap, and lets tests
    flip engines per-test) first; the config fallback is resolved once
    per process.
    """
    url = os.environ.get('SKY_TPU_DB_URL')
    if url:
        return url
    if not _cfg_url_cache:
        try:
            from skypilot_tpu import config as config_lib
            _cfg_url_cache.append(config_lib.get_nested(('db', 'url')))
        except Exception:  # noqa: BLE001 — config not importable yet:
            return None    # retry next call rather than caching None
    return _cfg_url_cache[0]


def _is_postgres(url: Optional[str]) -> bool:
    return bool(url) and url.split('://', 1)[0] in ('postgres',
                                                    'postgresql')


# --------------------------------------------------------------------------
# sqlite-dialect → postgres translation
# --------------------------------------------------------------------------
def translate_schema(schema: str) -> List[str]:
    """Translate a sqlite CREATE script into postgres statements."""
    out = []
    for stmt in schema.split(';'):
        stmt = stmt.strip()
        if not stmt or stmt.upper().startswith('PRAGMA'):
            continue
        stmt = re.sub(r'INTEGER\s+PRIMARY\s+KEY\s+AUTOINCREMENT',
                      'BIGSERIAL PRIMARY KEY', stmt, flags=re.I)
        stmt = re.sub(r'\bREAL\b', 'DOUBLE PRECISION', stmt, flags=re.I)
        stmt = re.sub(r'\bBLOB\b', 'BYTEA', stmt, flags=re.I)
        out.append(stmt)
    return out


def translate_sql(sql: str) -> str:
    """Translate one sqlite-dialect statement for postgres."""
    if re.search(r'INSERT\s+OR\s+REPLACE', sql, flags=re.I):
        # No generic pg equivalent (needs a conflict target); store code
        # must use explicit ON CONFLICT ... DO UPDATE, which both engines
        # accept. Failing loud beats silently dropping replace semantics.
        raise ValueError(
            f'INSERT OR REPLACE is not portable to postgres; use '
            f'ON CONFLICT DO UPDATE: {sql!r}')
    if re.search(r'INSERT\s+OR\s+IGNORE\s+INTO', sql, flags=re.I):
        # Atomic get-or-create relies on conflicts being swallowed
        # (state.get_or_create_secret) — map to pg's equivalent.
        sql = re.sub(r'INSERT\s+OR\s+IGNORE\s+INTO', 'INSERT INTO', sql,
                     flags=re.I)
        sql = sql.rstrip().rstrip(';') + ' ON CONFLICT DO NOTHING'
    # `?` placeholders → `%s` (outside string literals; store SQL never
    # embeds literal question marks in strings).
    sql = sql.replace('?', '%s')
    return sql


class _DictRow(dict):
    """Row usable as both mapping and by dict(row) (sqlite3.Row parity)."""

    def keys(self):  # noqa: D102 — dict.keys already documented
        return super().keys()


class PostgresConnection:
    """sqlite3.Connection-shaped adapter over a DBAPI pg connection."""

    def __init__(self, raw, schema_name: str):
        self._raw = raw
        self.schema_name = schema_name

    def execute(self, sql: str, params: Tuple = ()):
        cur = self._raw.cursor()
        cur.execute(translate_sql(sql), tuple(params))
        return _PgCursor(cur)

    def executemany(self, sql: str, seq_of_params):
        cur = self._raw.cursor()
        cur.executemany(translate_sql(sql),
                        [tuple(p) for p in seq_of_params])
        return _PgCursor(cur)

    def executescript(self, script: str) -> None:
        cur = self._raw.cursor()
        for stmt in translate_schema(script):
            cur.execute(stmt)
        self._raw.commit()

    def commit(self) -> None:
        self._raw.commit()

    def close(self) -> None:
        self._raw.close()


class _PgCursor:
    def __init__(self, cur):
        self._cur = cur

    def _cols(self) -> List[str]:
        return [d[0] for d in self._cur.description or []]

    def fetchone(self) -> Optional[_DictRow]:
        row = self._cur.fetchone()
        if row is None:
            return None
        return _DictRow(zip(self._cols(), row))

    def fetchall(self) -> List[_DictRow]:
        cols = None
        out = []
        for row in self._cur.fetchall():
            if cols is None:
                cols = self._cols()
            out.append(_DictRow(zip(cols, row)))
        return out

    @property
    def lastrowid(self):
        return getattr(self._cur, 'lastrowid', None)

    @property
    def rowcount(self):
        return self._cur.rowcount


def _connect_postgres(url: str):
    """Import a driver and connect. Overridable in tests (fake driver)."""
    try:
        import psycopg2  # type: ignore
        return psycopg2.connect(url)
    except ImportError:
        pass
    try:
        import pg8000.dbapi  # type: ignore
    except ImportError as e:
        raise RuntimeError(
            f'SKY_TPU_DB_URL={url!r} needs a postgres driver; install '
            f'psycopg2 or pg8000 on the API server host') from e
    import urllib.parse as up
    p = up.urlparse(url)
    return pg8000.dbapi.connect(
        user=p.username or 'postgres', password=p.password,
        host=p.hostname or 'localhost', port=p.port or 5432,
        database=(p.path or '/postgres').lstrip('/'))


def _schema_name_for(path: str) -> str:
    base = os.path.splitext(os.path.basename(path))[0]
    return re.sub(r'[^a-z0-9_]', '_', base.lower()) or 'state'


class Db:
    """Thread-local connections to one logical store.

    `path` names the store: a sqlite file by default, or a pg schema
    within the shared database when a postgres DSN is configured.
    """

    def __init__(self, path: str, schema: str):
        self.path = path
        self.schema = schema

    @property
    def conn(self):
        # NOTE: `getattr(...) or {}` would drop the cache whenever the
        # dict is empty (every call would open a new connection, and an
        # INSERT's commit could land on a different connection).
        if not hasattr(_local, 'conns'):
            _local.conns = {}
        cache: Dict[str, Any] = _local.conns
        url = db_url()
        key = f'{url or "sqlite"}::{self.path}'
        conn = cache.get(key)
        if conn is None:
            if _is_postgres(url):
                conn = self._connect_pg(url)
            else:
                conn = self._connect_sqlite()
            cache[key] = conn
        return conn

    def _connect_sqlite(self) -> sqlite3.Connection:
        import time
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        conn = sqlite3.connect(self.path, timeout=30.0)
        # Switching journal mode needs a moment of exclusive access; on
        # a FRESH store two threads connecting simultaneously (e.g. a
        # job group's parallel launches both doing first-touch) can race
        # it to an immediate 'database is locked' that the busy timeout
        # does not cover. Retry briefly; if the other side won, the file
        # is already in WAL (mode is persistent) and proceeding is fine.
        for attempt in range(20):
            try:
                conn.execute('PRAGMA journal_mode=WAL')
                break
            except sqlite3.OperationalError:
                if attempt == 19:
                    break   # connection still works under the winner's mode
                time.sleep(0.05 * (attempt + 1))
        conn.executescript(self.schema)
        conn.row_factory = sqlite3.Row
        return conn

    def _connect_pg(self, url: str) -> PostgresConnection:
        raw = _connect_postgres(url)
        name = _schema_name_for(self.path)
        conn = PostgresConnection(raw, name)
        cur = raw.cursor()
        cur.execute(f'CREATE SCHEMA IF NOT EXISTS {name}')
        cur.execute(f'SET search_path TO {name}')
        raw.commit()
        conn.executescript(self.schema)
        return conn


_instances: Dict[Tuple[str, int], Db] = {}


def get_db(path: str, schema: str) -> Db:
    """Process-wide Db registry keyed by absolute path."""
    key = (os.path.abspath(path), hash(schema))
    with _GLOBAL_LOCK:
        if key not in _instances:
            _instances[key] = Db(path, schema)
        return _instances[key]


def evict_under(root: str) -> None:
    """Close and forget every cached handle for stores under ``root``.

    For callers that create a scratch state home (the digital twin's
    per-replay SKY_TPU_HOME) and delete it afterward: without eviction
    the unlinked sqlite file's disk space and fd stay pinned by the
    cached connection until process exit, one per replay. Only the
    calling thread's connections can be closed (they are thread-local);
    the process-wide registry entry is dropped too, so a later store at
    the same path starts fresh."""
    root = os.path.abspath(root) + os.sep
    with _GLOBAL_LOCK:
        for key in [k for k in _instances if k[0].startswith(root)]:
            del _instances[key]
    cache = getattr(_local, 'conns', None)
    if cache is not None:
        for key in list(cache):
            # rsplit: the key is '<url-or-sqlite>::<path>' and a
            # postgres URL may itself contain '::' (IPv6 literal) —
            # the path is always the last component.
            path = key.rsplit('::', 1)[1]
            if os.path.abspath(path).startswith(root):
                try:
                    cache.pop(key).close()
                except Exception:  # noqa: BLE001 — eviction is best-effort
                    pass


def ensure_columns(conn, migrations) -> None:
    """Apply add-column migrations to a live DB (CREATE IF NOT EXISTS
    does not evolve existing tables). `migrations` is a sequence of
    (table, column, ddl); each column is probed and, when missing, its
    DDL applied — losing the race to a concurrent migrator is fine
    (the other side created the identical column).
    """
    for table, col, ddl in migrations:
        try:
            conn.execute(f'SELECT {col} FROM {table} LIMIT 1')
            continue
        except Exception:  # noqa: BLE001 — old schema
            pass
        try:
            conn.rollback()
        except Exception:  # noqa: BLE001 — nothing open
            pass
        try:
            conn.execute(ddl)
            conn.commit()
        except Exception:  # noqa: BLE001 — concurrent migrator won
            try:
                conn.rollback()
            except Exception:  # noqa: BLE001
                pass
