"""Chrome trace-event recorder (opt-in profiling of the control plane).

Counterpart of the reference's ``sky/utils/timeline.py`` (enabled via
SKYPILOT_TIMELINE_FILE_PATH, :19-21; ``@timeline.event`` decorating
entrypoints like sky/execution.py:597). Same contract here:

    SKY_TPU_TIMELINE_FILE=/tmp/trace.json sky-tpu launch ...

then load the file in chrome://tracing or Perfetto. Events are complete
("X") trace events with thread/process ids, flushed on process exit.
Zero overhead when the env var is unset (decorator returns fn unchanged
at decoration time).
"""
from __future__ import annotations

import atexit
import functools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

ENV_VAR = 'SKY_TPU_TIMELINE_FILE'

_events: List[Dict[str, Any]] = []
_lock = threading.Lock()
_registered = False


def enabled() -> bool:
    return bool(os.environ.get(ENV_VAR))


def _ensure_flush_registered() -> None:
    global _registered
    if not _registered:
        atexit.register(save)
        _registered = True


def record(name: str, start_us: float, dur_us: float,
           args: Optional[Dict[str, Any]] = None) -> None:
    if not enabled():
        return
    _ensure_flush_registered()
    ev = {
        'name': name, 'ph': 'X', 'ts': start_us, 'dur': dur_us,
        'pid': os.getpid(), 'tid': threading.get_ident(),
    }
    if args:
        ev['args'] = args
    with _lock:
        _events.append(ev)


class Event:
    """Context manager form: ``with timeline.Event('provision'): ...``"""

    def __init__(self, name: str, **args: Any) -> None:
        self.name = name
        self.args = args or None
        self._t0 = 0.0

    def __enter__(self) -> 'Event':
        self._t0 = time.perf_counter_ns() / 1e3
        return self

    def __exit__(self, *exc) -> None:
        record(self.name, self._t0,
               time.perf_counter_ns() / 1e3 - self._t0, self.args)


def event(fn: Callable = None, *, name: Optional[str] = None) -> Callable:
    """Decorator: trace every call of fn. No-op unless enabled at
    decoration time (matching the reference's zero-cost default)."""
    def wrap(f: Callable) -> Callable:
        if not enabled():
            return f
        label = name or f'{f.__module__}.{f.__qualname__}'

        @functools.wraps(f)
        def inner(*a, **kw):
            with Event(label):
                return f(*a, **kw)
        return inner

    return wrap(fn) if fn is not None else wrap


def save(path: Optional[str] = None) -> Optional[str]:
    """Write accumulated events as a Chrome trace JSON; returns path."""
    path = path or os.environ.get(ENV_VAR)
    if not path:
        return None
    with _lock:
        events = list(_events)
    if not events:
        return None
    with open(path, 'w', encoding='utf-8') as f:
        json.dump({'traceEvents': events, 'displayTimeUnit': 'ms'}, f)
    return path
