"""Cluster TLS: self-signed certs with fingerprint pinning.

The reference encrypts its control channel by riding gRPC over an SSH
tunnel (reference sky/backends/cloud_vm_ray_backend.py:2288-2320). This
framework's agent plane is HTTP on the VPC, so the equivalent hardening
is TLS at the agent socket: each cluster gets one self-signed cert,
generated at provision time next to the bearer token, delivered to every
host inside agent_config.json (the same secret-bearing channel the token
already rides), and **pinned by SHA-256 fingerprint** on the client side
— no CA, no hostname checks, no trust store to manage. A MITM on the VPC
can no longer read the bearer token off the wire, and cannot present its
own cert without breaking the pin.

The serve load balancer reuses the server half for user-plane HTTPS
(reference sky/serve/load_balancer.py:274-286 TLSCredential), there with
operator-supplied cert/key files instead of a generated pair.
"""
from __future__ import annotations

import datetime
import functools
import hashlib
import os
import ssl
import tempfile
from typing import Optional, Tuple

import requests
import requests.adapters

CERT_FILE = 'agent_cert.pem'
KEY_FILE = 'agent_key.pem'


def generate_cluster_cert(common_name: str,
                          valid_days: int = 3650
                          ) -> Tuple[str, str, str]:
    """One self-signed cert per cluster.

    Returns (cert_pem, key_pem, sha256_fingerprint_hex). ECDSA P-256:
    small keys (the PEM travels inline in agent_config.json to every
    host) and fast handshakes on the agent's tiny HTTP exchanges.
    """
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name)
            .issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=valid_days))
            .add_extension(x509.SubjectAlternativeName(
                [x509.DNSName(common_name)]), critical=False)
            .sign(key, hashes.SHA256()))
    cert_pem = cert.public_bytes(serialization.Encoding.PEM).decode()
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption()).decode()
    return cert_pem, key_pem, fingerprint_of_pem(cert_pem)


def fingerprint_of_pem(cert_pem: Optional[str]) -> Optional[str]:
    """SHA-256 over the DER encoding, lowercase hex (no colons).
    None-tolerant: providers pass whatever their metadata holds, and a
    cluster provisioned before TLS simply has no pin."""
    if not cert_pem:
        return None
    der = ssl.PEM_cert_to_DER_cert(cert_pem)
    return hashlib.sha256(der).hexdigest()


def server_context(cert_pem: str, key_pem: str,
                   workdir: Optional[str] = None) -> ssl.SSLContext:
    """Server-side context from inline PEMs.

    load_cert_chain only takes paths, so the PEMs are materialized under
    `workdir` (0600) — on an agent host that is the cluster dir, which
    already holds the bearer token in agent_config.json.
    """
    if workdir is None:
        workdir = tempfile.mkdtemp(prefix='sky-tpu-tls-')
    cert_path = os.path.join(workdir, CERT_FILE)
    key_path = os.path.join(workdir, KEY_FILE)
    for path, pem in ((cert_path, cert_pem), (key_path, key_pem)):
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, 'w', encoding='utf-8') as f:
            f.write(pem)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_path, key_path)
    return ctx


def file_server_context(certfile: str, keyfile: str) -> ssl.SSLContext:
    """Server context from operator-supplied files (serve LB tls: block)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(os.path.expanduser(certfile),
                        os.path.expanduser(keyfile))
    return ctx


class _FingerprintAdapter(requests.adapters.HTTPAdapter):
    """requests transport that accepts exactly one pinned server cert.

    urllib3's assert_fingerprint replaces CA verification: the TLS
    handshake completes, then the peer cert's SHA-256 is compared to the
    pin and the connection is torn down on mismatch.
    """

    def __init__(self, fingerprint: str, **kwargs):
        self._fingerprint = fingerprint
        super().__init__(**kwargs)

    def init_poolmanager(self, *args, **kwargs):
        kwargs['assert_fingerprint'] = self._fingerprint
        kwargs['cert_reqs'] = 'CERT_NONE'
        return super().init_poolmanager(*args, **kwargs)

    def proxy_manager_for(self, proxy, **kwargs):
        # Proxied connections must carry the pin too, or an HTTPS_PROXY
        # env var silently downgrades the channel to unverified TLS —
        # the exact MITM this adapter exists to stop.
        kwargs['assert_fingerprint'] = self._fingerprint
        kwargs['cert_reqs'] = 'CERT_NONE'
        return super().proxy_manager_for(proxy, **kwargs)

    def send(self, request, *args, **kwargs):
        # requests re-applies its per-request `verify` onto the pool,
        # which would restore CA verification and reject the
        # self-signed cert before the fingerprint check ever ran. The
        # pin IS the verification; CA checks are forced off.
        kwargs['verify'] = False
        import urllib3.exceptions
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter(
                'ignore', urllib3.exceptions.InsecureRequestWarning)
            return super().send(request, *args, **kwargs)


class _RefuseAdapter(requests.adapters.BaseAdapter):
    """https transport for unpinned clients: always fails closed."""

    def send(self, request, **kwargs):  # noqa: D102
        raise requests.exceptions.SSLError(
            f'no pinned fingerprint for {request.url}; refusing '
            'unverified TLS to an agent')

    def close(self) -> None:
        pass


_REFUSE_ADAPTER = _RefuseAdapter()


@functools.lru_cache(maxsize=256)
def _pinned_adapter(fingerprint: str) -> _FingerprintAdapter:
    """One adapter (= one urllib3 keep-alive pool) per fingerprint.

    The adapter is the expensive, shareable part: urllib3 pools are
    thread-safe and survive an HTTPAdapter.close() (pools re-create on
    demand), so every client of a cluster shares one TLS-session pool
    without re-handshaking each probe tick.
    """
    return _FingerprintAdapter(fingerprint)


def pinned_session(fingerprint: Optional[str]) -> requests.Session:
    """A requests.Session whose https:// transport is fingerprint-pinned.

    With no fingerprint the session still works for http:// URLs and
    refuses https (no pin → no basis for trust: failing closed here is
    what makes the sniff-test meaningful).

    Returns a NEW lightweight Session per call, mounting the cached
    per-fingerprint adapter. Sessions are NOT thread-safe (cookie jar,
    per-request state) — the old one-cached-Session-per-fingerprint
    design handed the same Session to every AgentClient in the process,
    so concurrent monitor loops and request workers raced on it. The
    connection pool (the part worth sharing) lives in the adapter.
    """
    sess = requests.Session()
    # Agents live on the VPC/loopback: a corp HTTPS_PROXY from the
    # environment must never be interposed on the pinned channel.
    sess.trust_env = False
    if fingerprint:
        sess.mount('https://', _pinned_adapter(fingerprint))
    else:
        sess.mount('https://', _REFUSE_ADAPTER)
    return sess


def scheme_for(cert_pem: Optional[str]) -> str:
    """URL scheme for an agent endpoint given its cluster cert (one
    home for the https-iff-cert rule every provider applies)."""
    return 'https' if cert_pem else 'http'


_warned_no_cryptography = False


def ensure_cluster_cert(store: dict, cluster_name: str,
                        cert_key: str = 'agent_tls_cert',
                        key_key: str = 'agent_tls_key'
                        ) -> Tuple[Optional[str], Optional[str]]:
    """Get-or-mint the cluster TLS pair in `store` (a provider's
    provider_config or metadata dict). Reused across idempotent
    re-provisions — a rotation would invalidate the live agents' pin
    mid-flight. One home for the logic all five providers share.

    Gated on the optional ``cryptography`` dependency: without it the
    cluster provisions in pre-TLS mode (plain-HTTP agents + bearer
    token, the pervasive None-cert path) instead of failing the launch
    — logged loudly once, since it is a downgrade an operator should
    notice. A later re-provision with cryptography installed mints the
    pair and force-restarts the agents (the TLS upgrade path).
    """
    cert, key = store.get(cert_key), store.get(key_key)
    if not cert or not key:
        try:
            cert, key, _ = generate_cluster_cert(cluster_name)
        except ImportError:
            global _warned_no_cryptography
            if not _warned_no_cryptography:
                _warned_no_cryptography = True
                import logging
                logging.getLogger(__name__).warning(
                    "the 'cryptography' package is unavailable — "
                    'provisioning %s WITHOUT agent TLS (bearer-token '
                    'auth over plain HTTP). Install cryptography and '
                    're-provision to upgrade.', cluster_name)
            return None, None
        store[cert_key] = cert
        store[key_key] = key
    return cert, key


def aiohttp_ssl(fingerprint: Optional[str]):
    """ssl= argument for aiohttp requests to a pinned agent.

    aiohttp.Fingerprint disables cert verification and instead matches
    the peer cert digest — the async twin of _FingerprintAdapter.
    Returns None (library default: full verification) when no pin is
    given, for plain-http or public endpoints.
    """
    if not fingerprint:
        return None
    import aiohttp
    return aiohttp.Fingerprint(bytes.fromhex(fingerprint))
