"""Chained page-block prefix hashes — the fleet KV index key space.

The radix prefix cache (infer/prefix_cache.py) keys nodes on raw token
tuples; that is exact but unbounded on the wire. The fleet index at the
load balancer needs a COMPACT, order-preserving digest of "this replica
holds the first N pages of prompt P" that both sides can compute
independently: the replica from its radix tree, the LB from an incoming
request's token ids. A chained hash gives exactly that:

    h_0 = H(root_seed || tokens[0:page])
    h_i = H(h_{i-1}   || tokens[i*page:(i+1)*page])

so ``h_i`` commits to the ENTIRE prefix through page ``i``, not just
block ``i`` — two prompts share ``h_i`` iff they share the first
``(i+1)*page`` tokens (modulo 64-bit collision, whose worst case is one
wasted transfer attempt that degrades to recompute; correctness never
rides on the hash).

Deliberately hashlib-only (no jax, no numpy): serve/ imports this
without dragging the inference stack in, and the digital twin's modeled
replicas share the exact same key space as real engines.
"""
from __future__ import annotations

import hashlib
from typing import List, Sequence, Tuple

# 8-byte digests: the index holds ~thousands of entries per replica, so
# 64 bits keeps collision odds negligible while the on-wire summary
# stays compact (the whole point of hashing instead of shipping tokens).
_DIGEST_BYTES = 8
_ROOT_SEED = b'sky-tpu/kv-prefix/v1'


def _h(parent: bytes, block: Sequence[int]) -> bytes:
    d = hashlib.blake2b(digest_size=_DIGEST_BYTES)
    d.update(parent)
    d.update(','.join(str(int(t)) for t in block).encode())
    return d.digest()


def block_hash(parent: int, block: Sequence[int]) -> int:
    """One chain link: the digest committing to ``parent``'s prefix
    extended by ``block``. ``parent`` is 0 at the root."""
    seed = _ROOT_SEED if parent == 0 else int(parent).to_bytes(
        _DIGEST_BYTES, 'big')
    return int.from_bytes(_h(seed, block), 'big')


def chain_hashes(tokens: Sequence[int], page: int,
                 limit: int = -1) -> List[int]:
    """Chain digests for each FULL page of ``tokens``, capped at the
    last full page strictly before the prompt end — the same boundary
    rule as PrefixCache.match, so an LB-side chain lines up one-to-one
    with the radix path a replica would index.

    ``limit`` (when >= 0) caps the number of blocks hashed — the LB
    bounds per-request work with it.
    """
    n_full = (len(tokens) - 1) // page if tokens else 0
    if limit >= 0:
        n_full = min(n_full, limit)
    out: List[int] = []
    parent = 0
    for i in range(n_full):
        parent = block_hash(parent, tokens[i * page:(i + 1) * page])
        out.append(parent)
    return out


def fold_crc(hashes: Sequence[int]) -> int:
    """Order-independent checksum of an index's hash SET (XOR fold):
    the LB verifies a delta-maintained mirror against the replica's
    self-reported value and forces a full resync on mismatch."""
    acc = 0
    for h in hashes:
        acc ^= int(h)
    return acc


def build_snapshot(gen: int, crc: int, page: int,
                   journal: Sequence[Tuple[int, str, int]],
                   hashes, since_gen: int) -> dict:
    """The on-wire radix summary, delta-encoded when the (gen, op,
    hash) journal still covers ``since_gen`` — every op bumps the
    generation by exactly one, so coverage is checkable from the oldest
    retained entry alone. Falls back to the full (sorted — the wire
    must be deterministic) hash list on a cold or lapsed consumer."""
    snap: dict = {'gen': gen, 'crc': crc, 'page': page}
    if since_gen == gen:
        snap['delta'] = []
    elif (0 <= since_gen < gen and journal
          and journal[0][0] <= since_gen + 1):
        snap['delta'] = [[op, h] for g, op, h in journal
                         if g > since_gen]
    else:
        snap['full'] = sorted(hashes)
    return snap


def match_depth(chain: Sequence[int], held: 'set | frozenset') -> int:
    """Longest indexed prefix: how many leading links of ``chain`` are
    in ``held``. Chained hashes make the held set prefix-closed per
    donor, so the first miss ends the match."""
    depth = 0
    for h in chain:
        if h not in held:
            break
        depth += 1
    return depth
