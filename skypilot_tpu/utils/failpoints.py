"""Named, env-configurable fault-injection sites (failpoints).

The deterministic chaos seam for the recovery paths the paper's workload
model lives or dies by (whole-gang spot preemption, agent restarts,
replica death): production code declares *sites* —
``failpoints.hit('provision.create')`` — and an operator (or the chaos
test suite) arms them through one env var:

    SKY_TPU_FAILPOINTS='provision.create=error:0.5,agent.submit=delay:2,\
agent.health=error:1@3'

Spec grammar (comma-separated entries)::

    <site>=<action>[:<arg>[:<prob>]][@<count>]

    error[:p]            raise FailpointError with probability p (def. 1)
    delay:seconds[:p]    sleep `seconds` with probability p
    hang[:p]             sleep SKY_TPU_FAILPOINT_HANG_S (default 3600)

    @N                   fire-count budget: the site fires at most N
                         times, then goes inert (probability rolls that
                         do not fire don't consume budget)

Discipline (mirrors ``SKY_TPU_TRACE``): with the env var unset, ``hit``
is a single ``os.environ.get`` miss and an immediate return — no parsing,
no allocation, no lock. The spec is parsed once per distinct env value,
so tests may arm/disarm sites mid-process via monkeypatch.setenv. A
malformed spec raises ``FailpointSpecError`` loudly at first use:
failpoints are only ever set deliberately, and a typo silently injecting
nothing would invalidate the chaos run it was meant to drive.

Sites are just strings; the catalog of live sites is documented in
docs/robustness.md (kept in sync by the chaos suite).
"""
from __future__ import annotations

import asyncio
import os
import random
import threading
import time
from typing import Dict, Optional

ENV_VAR = 'SKY_TPU_FAILPOINTS'
HANG_ENV_VAR = 'SKY_TPU_FAILPOINT_HANG_S'

_ACTIONS = ('error', 'delay', 'hang')


class FailpointError(Exception):
    """The injected failure. Deliberately a plain Exception so each
    layer's *generic* transient-error handling must absorb it (the point
    of the exercise) — except where a site's contract says otherwise."""


class FailpointSpecError(ValueError):
    """SKY_TPU_FAILPOINTS could not be parsed."""


class _Site:
    __slots__ = ('name', 'action', 'arg', 'prob', 'budget', 'fired',
                 '_lock')

    def __init__(self, name: str, action: str, arg: float, prob: float,
                 budget: Optional[int]) -> None:
        self.name = name
        self.action = action
        self.arg = arg
        self.prob = prob
        self.budget = budget
        self.fired = 0
        self._lock = threading.Lock()

    def take(self) -> bool:
        """Decide (atomically w.r.t. the budget) whether this hit fires."""
        with self._lock:
            if self.budget is not None and self.fired >= self.budget:
                return False
            if self.prob >= 1.0:
                pass
            elif self.prob <= 0.0:
                return False
            elif random.random() >= self.prob:
                return False
            self.fired += 1
            return True


def _parse_float(token: str, what: str, entry: str) -> float:
    try:
        return float(token)
    except ValueError as e:
        raise FailpointSpecError(
            f'bad {ENV_VAR} entry {entry!r}: {what} {token!r} is not a '
            f'number') from e


def parse_specs(spec: str) -> Dict[str, _Site]:
    """Parse a SKY_TPU_FAILPOINTS value. Raises FailpointSpecError with
    the offending entry named on any malformation."""
    sites: Dict[str, _Site] = {}
    for entry in spec.split(','):
        entry = entry.strip()
        if not entry:
            continue
        site, sep, rhs = entry.partition('=')
        site = site.strip()
        if not sep or not site or not rhs:
            raise FailpointSpecError(
                f'bad {ENV_VAR} entry {entry!r}: expected '
                f'<site>=<action>[:<arg>[:<prob>]][@<count>]')
        rhs, at, count_s = rhs.partition('@')
        budget: Optional[int] = None
        if at:
            try:
                budget = int(count_s)
            except ValueError as e:
                raise FailpointSpecError(
                    f'bad {ENV_VAR} entry {entry!r}: fire-count '
                    f'{count_s!r} is not an integer') from e
            if budget < 0:
                raise FailpointSpecError(
                    f'bad {ENV_VAR} entry {entry!r}: fire-count must '
                    f'be >= 0')
        parts = rhs.split(':')
        action = parts[0].strip()
        if action not in _ACTIONS:
            raise FailpointSpecError(
                f'bad {ENV_VAR} entry {entry!r}: unknown action '
                f'{action!r}; choose from {list(_ACTIONS)}')
        arg = 0.0
        prob = 1.0
        if action == 'error':
            if len(parts) > 2:
                raise FailpointSpecError(
                    f'bad {ENV_VAR} entry {entry!r}: error takes at '
                    f'most one argument (probability)')
            if len(parts) == 2:
                prob = _parse_float(parts[1], 'probability', entry)
        elif action == 'delay':
            if len(parts) < 2 or len(parts) > 3:
                raise FailpointSpecError(
                    f'bad {ENV_VAR} entry {entry!r}: delay needs '
                    f'seconds (delay:<s>[:<prob>])')
            arg = _parse_float(parts[1], 'delay seconds', entry)
            if len(parts) == 3:
                prob = _parse_float(parts[2], 'probability', entry)
        else:   # hang
            if len(parts) > 2:
                raise FailpointSpecError(
                    f'bad {ENV_VAR} entry {entry!r}: hang takes at '
                    f'most one argument (probability)')
            if len(parts) == 2:
                prob = _parse_float(parts[1], 'probability', entry)
        if not 0.0 <= prob <= 1.0:
            raise FailpointSpecError(
                f'bad {ENV_VAR} entry {entry!r}: probability {prob} '
                f'outside [0, 1]')
        if arg < 0:
            raise FailpointSpecError(
                f'bad {ENV_VAR} entry {entry!r}: delay must be >= 0')
        sites[site] = _Site(site, action, arg, prob, budget)
    return sites


# Parsed-spec cache, keyed by the env value it was parsed from so a test
# re-arming SKY_TPU_FAILPOINTS mid-process takes effect on the next hit
# (and so fire-count state survives across hits of an unchanged spec).
_cached_env: Optional[str] = None
_sites: Dict[str, _Site] = {}
_load_lock = threading.Lock()


def _lookup(site: str) -> Optional[_Site]:
    global _cached_env, _sites
    env = os.environ.get(ENV_VAR)
    if env != _cached_env:
        with _load_lock:
            if env != _cached_env:
                _sites = parse_specs(env) if env else {}
                _cached_env = env
    fp = _sites.get(site)
    if fp is None or not fp.take():
        return None
    return fp


def _hang_s() -> float:
    return float(os.environ.get(HANG_ENV_VAR, '3600'))


def hit(site: str) -> None:
    """Evaluate failpoint ``site``. The production no-op: with
    SKY_TPU_FAILPOINTS unset this is one env-dict miss and a return."""
    if os.environ.get(ENV_VAR) is None:
        return
    fp = _lookup(site)
    if fp is None:
        return
    if fp.action == 'error':
        raise FailpointError(f'injected failure at failpoint {site!r}')
    time.sleep(fp.arg if fp.action == 'delay' else _hang_s())


async def hit_async(site: str) -> None:
    """``hit`` for event-loop code paths (agent handlers, the LB proxy):
    delay/hang park on asyncio.sleep instead of blocking the loop."""
    if os.environ.get(ENV_VAR) is None:
        return
    fp = _lookup(site)
    if fp is None:
        return
    if fp.action == 'error':
        raise FailpointError(f'injected failure at failpoint {site!r}')
    await asyncio.sleep(fp.arg if fp.action == 'delay' else _hang_s())


def fired(site: str) -> int:
    """How many times ``site`` has fired under the current spec
    (introspection for tests; 0 for unarmed sites)."""
    fp = _sites.get(site)
    return fp.fired if fp is not None else 0


def _reset_for_tests() -> None:
    global _cached_env, _sites
    with _load_lock:
        _cached_env = None
        _sites = {}
