"""Shared paths, enums, and small helpers."""
from __future__ import annotations

import enum
import os
import time
import uuid

HOME_ENV_VAR = 'SKY_TPU_HOME'
DEFAULT_API_PORT = 46580
# Per-request wall-clock budget in seconds, propagated serve LB →
# infer server → engine (docs/robustness.md "Zero-downtime serving"):
# the LB forwards the REMAINING budget on every retry/resume leg, the
# server turns it into an absolute deadline, and the engine cancels
# queued or decoding requests past it. Lives here (not in serve/ or
# infer/) so the LB never has to import the jax-heavy infer stack.
DEADLINE_HEADER = 'X-SkyTpu-Deadline-S'
# Multi-tenant identity on /generate, propagated serve LB → infer
# server → engine scheduler (docs/serving.md "Engine scheduler"): the
# unit of weighted fair queueing, per-tenant admission quotas, and the
# per-tenant metric breakdown. Absent header = the 'default' tenant.
# Same placement rationale as DEADLINE_HEADER.
TENANT_HEADER = 'X-SkyTpu-Tenant'
# Disaggregated prefill/decode (docs/serving.md): when the serve LB's
# fleet prefix index knows another replica holds a longer cached prefix
# of this prompt than the selected replica, it names that donor's URL
# here; the receiving server pulls the cached KV pages from the donor
# (/kv/export) before prefilling, so only the boundary is recomputed.
# Best-effort end to end — any pull failure degrades to plain
# recompute, never a client-visible error.
KV_DONOR_HEADER = 'X-SkyTpu-KV-Donor'


# Directories base_dir() has already created this process: the call
# sits on hot DB paths (every serve-state query resolves the root),
# and an unconditional os.makedirs per call is measurable at fleet
# scale (~1µs*4 syscalls x millions of state reads in the twin).
_made_dirs: set = set()


def base_dir() -> str:
    """Framework state root (~/.sky_tpu, overridable for tests)."""
    d = os.path.expanduser(os.environ.get(HOME_ENV_VAR, '~/.sky_tpu'))
    # isdir-guarded memo: one cheap stat instead of four makedirs
    # syscalls on the hot path, but a root deleted mid-process (test
    # cleanup, operator rm -rf) is still recreated — direct writers
    # like api_server.json depend on it.
    if d not in _made_dirs or not os.path.isdir(d):
        os.makedirs(d, exist_ok=True)
        _made_dirs.add(d)
    return d


def logs_dir() -> str:
    d = os.path.join(base_dir(), 'logs')
    os.makedirs(d, exist_ok=True)
    return d


def clusters_dir() -> str:
    d = os.path.join(base_dir(), 'clusters')
    os.makedirs(d, exist_ok=True)
    return d


class ClusterStatus(enum.Enum):
    """Lifecycle of a cluster (reference sky/utils/status_lib.py semantics)."""
    INIT = 'INIT'          # provisioning in progress or unknown
    UP = 'UP'              # all hosts running, runtime healthy
    STOPPED = 'STOPPED'    # hosts stopped, disk kept


class JobStatus(enum.Enum):
    """Per-cluster job queue states (reference sky/skylet/job_lib.py:156)."""
    INIT = 'INIT'
    PENDING = 'PENDING'
    SETTING_UP = 'SETTING_UP'
    RUNNING = 'RUNNING'
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'
    FAILED_SETUP = 'FAILED_SETUP'
    CANCELLED = 'CANCELLED'

    def is_terminal(self) -> bool:
        return self in (JobStatus.SUCCEEDED, JobStatus.FAILED,
                        JobStatus.FAILED_SETUP, JobStatus.CANCELLED)


def new_request_id() -> str:
    return uuid.uuid4().hex[:16]


def now() -> float:
    return time.time()


def readable_time_duration(seconds: float) -> str:
    seconds = int(seconds)
    if seconds < 60:
        return f'{seconds}s'
    if seconds < 3600:
        return f'{seconds // 60}m {seconds % 60}s'
    return f'{seconds // 3600}h {(seconds % 3600) // 60}m'


def free_port() -> int:
    """An ephemeral port that was free at probe time."""
    import socket
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def pid_alive(pid: int) -> bool:
    if not pid or pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except (ProcessLookupError, PermissionError):
        return False
    # A zombie still answers kill(pid, 0) but is dead. This matters for
    # crash detection (docs/robustness.md "Crash safety"): a kill -9'd
    # detached controller is orphaned onto pid 1, and in containers
    # whose init does not reap, the corpse lingers as Z forever — it
    # must read as crashed, or `serve status` reports a dead control
    # plane healthy and `serve down` waits on it. The comm field in
    # /proc/<pid>/stat may contain spaces/parens; the state letter is
    # the first field after the LAST ')'.
    try:
        with open(f'/proc/{pid}/stat', encoding='ascii',
                  errors='replace') as f:
            stat = f.read()
        return stat.rsplit(')', 1)[1].split()[0] != 'Z'
    except (OSError, IndexError):
        return True   # no procfs (macOS): keep the kill(0) verdict
