"""The injectable clock seam (the digital twin's enabling refactor).

Every control loop in ``serve/`` — the LB's replica sync and stats
flush, the controller tick, the autoscaler hysteresis windows, replica
provision/readiness timing, the serve-state gauge staleness checks —
reads time through this module instead of calling ``time.time()`` /
``time.monotonic()`` directly (SKY-ASYNC pins the discipline: a bare
wall-clock read in ``serve/`` fails lint, docs/static-analysis.md).

In production nothing changes: the installed clock is
:data:`SYSTEM`, a pass-through to the ``time`` module. The fleet
digital twin (``skypilot_tpu/sim/``, docs/robustness.md "Digital
twin") installs a :class:`VirtualClock` for the duration of a replay,
so a 24h diurnal trace against the REAL control-plane code advances in
discrete virtual steps and finishes in seconds — deterministically,
because no decision ever observes the machine's wall clock.

Two dials on one face:

- ``time()`` is the WALL clock: row timestamps, QPS windows, gauge
  staleness, hysteresis anchors.
- ``monotonic()`` is the INTERVAL clock: TTFT/ITL stopwatches, request
  deadlines, breaker cooldowns.

A virtual clock returns the same value for both (virtual time never
steps backward), which also closes the historical seam where
autoscalers used ``time.time()`` while the LB used
``time.monotonic()`` — both now route here.

Components should prefer an injected ``Clock`` parameter (defaulting
to :func:`get`) so tests can drive them directly; module-level helpers
(``serve/state.py``'s row stamps) read the process-global installation
via :func:`now` / :func:`monotonic`.
"""
from __future__ import annotations

import contextlib
import time as _time
from typing import Iterator


class Clock:
    """The system clock — and the interface a virtual clock implements."""

    def time(self) -> float:
        """Wall-clock seconds (``time.time`` semantics)."""
        return _time.time()

    def monotonic(self) -> float:
        """Interval seconds (``time.monotonic`` semantics)."""
        return _time.monotonic()


class VirtualClock(Clock):
    """A manually-advanced clock: ``time()`` and ``monotonic()`` both
    read one virtual instant. Advancing is the owner's job (the sim
    kernel advances it to each event's timestamp); it never moves on
    its own and never goes backward."""

    def __init__(self, start: float = 0.0) -> None:
        self._now = float(start)

    def time(self) -> float:
        return self._now

    def monotonic(self) -> float:
        return self._now

    def advance_to(self, t: float) -> None:
        if t < self._now:
            raise ValueError(
                f'virtual clock cannot rewind: {t} < {self._now}')
        self._now = t


SYSTEM = Clock()
_current: Clock = SYSTEM


def get() -> Clock:
    """The process-wide installed clock (SYSTEM unless a sim replay is
    running)."""
    return _current


def set_clock(clock: Clock) -> Clock:
    """Install ``clock`` process-wide; returns the previous one so the
    caller can restore it (prefer :func:`installed`)."""
    global _current
    prev = _current
    _current = clock
    return prev


@contextlib.contextmanager
def installed(clock: Clock) -> Iterator[Clock]:
    """Scoped install: the digital twin wraps a whole replay in this so
    an exploding scenario can never leak virtual time into the next
    test's serve components."""
    prev = set_clock(clock)
    try:
        yield clock
    finally:
        set_clock(prev)


def now() -> float:
    """Wall-clock read through the installed clock."""
    return _current.time()


def monotonic() -> float:
    """Interval read through the installed clock."""
    return _current.monotonic()
