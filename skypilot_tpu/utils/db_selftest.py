"""Real-database self-test for the control plane's DSN path.

Run as ``python -m skypilot_tpu.utils.db_selftest`` with
``SKY_TPU_DB_URL`` (or a DSN argument) pointing at postgres. The
packaged control plane runs this as an initContainer whenever a
``db-url`` secret is configured, so the sqlite→postgres dialect
translation in ``utils/db.py`` is proven against a REAL server before
the API server takes writes (round-3 verdict, weak #8: the CI fake
driver cannot catch server-side dialect rejections).

Exercises the translation's hot spots end-to-end: schema DDL,
placeholder style, upsert, RETURNING-free inserts, and the state
store's own table round trip.
"""
from __future__ import annotations

import os
import sys
import time


def run(dsn: str) -> None:
    os.environ['SKY_TPU_DB_URL'] = dsn
    from skypilot_tpu.utils import db as db_util
    probe = f'selftest_{int(time.time())}'
    schema = (f'CREATE TABLE IF NOT EXISTS {probe} ('
              'name TEXT PRIMARY KEY, value TEXT, n INTEGER DEFAULT 0)')
    db = db_util.get_db(f'{probe}.db', schema)
    conn = db.conn
    try:
        conn.execute(
            f'INSERT INTO {probe} (name, value, n) VALUES (?,?,?)',
            ('a', 'x', 1))
        # Upsert path (sqlite ON CONFLICT syntax must translate).
        conn.execute(
            f'INSERT INTO {probe} (name, value, n) VALUES (?,?,?) '
            f'ON CONFLICT(name) DO UPDATE SET value=excluded.value, '
            f'n=excluded.n',
            ('a', 'y', 2))
        conn.commit()
        row = conn.execute(
            f'SELECT value, n FROM {probe} WHERE name = ?',
            ('a',)).fetchone()
        assert row is not None and row['value'] == 'y' and \
            row['n'] == 2, row
        cur = conn.execute(
            f'UPDATE {probe} SET n = n + 1 WHERE name = ?', ('a',))
        assert cur.rowcount == 1
    finally:
        # This runs against the SHARED production DB: never leak the
        # probe table, even when an assertion above fails. On postgres
        # a failed statement aborts the transaction — roll back first
        # or the DROP itself raises and masks the real dialect error.
        for meth in ('rollback',):
            try:
                getattr(conn, meth)()
            except Exception:  # noqa: BLE001 — sqlite: nothing open
                pass
        conn.execute(f'DROP TABLE IF EXISTS {probe}')
        conn.commit()

    # The real state store against the same server.
    from skypilot_tpu import state
    from skypilot_tpu.utils import common
    name = f'selftest-cluster-{int(time.time())}'
    state.add_or_update_cluster(name, common.ClusterStatus.INIT)
    try:
        rec = state.get_cluster(name)
        assert rec is not None and rec['name'] == name
    finally:
        # A phantom INIT cluster in the shared table would show in
        # every user's status view.
        state.remove_cluster(name)
    assert state.get_cluster(name) is None
    print(f'db selftest OK against {dsn.split("@")[-1]}')


def main() -> None:
    dsn = (sys.argv[1] if len(sys.argv) > 1
           else os.environ.get('SKY_TPU_DB_URL', ''))
    if not dsn or not dsn.startswith(('postgres://', 'postgresql://')):
        print('db selftest skipped: no postgres SKY_TPU_DB_URL')
        return
    run(dsn)


if __name__ == '__main__':
    main()
