"""Multi-document YAML → Dag loaders.

Counterpart of the reference's ``sky/utils/dag_utils.py``
(``load_chain_dag_from_yaml`` at :139, ``load_job_group_from_yaml`` at
:420). Format: an optional header document carrying only ``name`` (and
optionally ``execution: serial|parallel``), followed by one document per
task. ``execution: parallel`` marks a *job group*: tasks are gang-placed
on common infra by ``Optimizer.optimize_job_group``.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import yaml

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import task as task_lib

_HEADER_FIELDS = {'name', 'execution'}


def _is_header(doc: Dict[str, Any], rest: List[Dict[str, Any]]) -> bool:
    """Is doc[0] the pipeline header (``name:`` / ``execution:`` only)?

    A first document whose keys are a subset of the header fields is the
    header — the reference's pipeline format (``name: my-pipeline`` as
    doc 0). That reading is only safe when the remaining documents are
    recognizably tasks; if EVERY document looks like a header, treating
    doc 0 as one would silently swallow a task, so the caller raises.
    """
    if not doc or not set(doc).issubset(_HEADER_FIELDS):
        return False
    if 'execution' in doc:  # not a task field — unambiguously a header
        return True
    if all(set(d).issubset(_HEADER_FIELDS) for d in rest):
        raise exceptions.InvalidTaskError(
            'Ambiguous multi-document YAML: every document has only '
            f'header fields ({sorted(_HEADER_FIELDS)}). Add a task field '
            "(e.g. 'run:') to task documents, or an 'execution:' field "
            'to the header.')
    return True


def load_dag_from_yaml_str(
        yaml_str: str,
        env_overrides: Optional[Dict[str, str]] = None) -> dag_lib.Dag:
    """Parse a (possibly multi-document) task YAML into a Dag.

    Single-document YAML gives a one-task Dag. Multi-document YAML gives a
    chain (``execution: serial`` / default) or a job group
    (``execution: parallel``).
    """
    docs = [d for d in yaml.safe_load_all(yaml_str) if d is not None]
    if not docs:
        docs = [{}]
    for d in docs:
        if not isinstance(d, dict):
            raise exceptions.InvalidTaskError(
                'Each YAML document must be a mapping, got '
                f'{type(d).__name__}')
    dag = dag_lib.Dag()
    execution = dag_lib.DagExecution.SERIAL
    if len(docs) > 1 and _is_header(docs[0], docs[1:]):
        header = docs.pop(0)
        dag.name = header.get('name')
        exec_str = header.get('execution', 'serial')
        try:
            execution = dag_lib.DagExecution(exec_str)
        except ValueError:
            raise exceptions.InvalidTaskError(
                f'Invalid execution mode {exec_str!r}; expected one of '
                f'{[e.value for e in dag_lib.DagExecution]}') from None
    prev: Optional[task_lib.Task] = None
    for doc in docs:
        t = task_lib.Task.from_yaml_config(doc, env_overrides)
        dag.add(t)
        if dag.name is None and len(docs) == 1:
            dag.name = t.name
        if prev is not None and execution is dag_lib.DagExecution.SERIAL:
            dag.add_edge(prev, t)
        prev = t
    dag.set_execution(execution)
    return dag


def load_dag_from_yaml(
        path: str,
        env_overrides: Optional[Dict[str, str]] = None) -> dag_lib.Dag:
    with open(os.path.expanduser(path), 'r', encoding='utf-8') as f:
        return load_dag_from_yaml_str(f.read(), env_overrides)


def dump_dag_to_yaml_str(dag: dag_lib.Dag) -> str:
    """Round-trip: serialize a chain/job-group Dag back to multi-doc YAML
    (reference dump_chain_dag_to_yaml_str)."""
    header: Dict[str, Any] = {'name': dag.name}
    if dag.execution is not None:
        header['execution'] = dag.execution.value
    configs: List[Dict[str, Any]] = [header]
    for t in dag.tasks:
        configs.append(t.to_yaml_config())
    return yaml.safe_dump_all(configs, sort_keys=False)
