"""Execution backend: provision → sync → setup → execute → teardown.

Counterpart of the reference's ``sky/backends/`` — the abstract ``Backend``
lifecycle (reference backend.py:30-152) and the sole real implementation
``CloudVmRayBackend`` (reference cloud_vm_ray_backend.py:2913, 6,366 LoC).
The TPU-native backend is radically smaller because the two hardest parts of
the reference are replaced by structure:

- Failover provisioning lives in ``provision/provisioner.py`` (the
  reference's ``RetryingVmProvisioner`` is inside the backend).
- There is no generated Ray driver program (reference
  task_codegen.py:301): execution is a single agent ``/submit`` call; the
  agent fans out to every slice host with `jax.distributed` env.
"""
from __future__ import annotations

import logging
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu import catalog
from skypilot_tpu import exceptions
from skypilot_tpu import provision
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import state
from skypilot_tpu import task as task_lib
from skypilot_tpu.provision import provisioner
from skypilot_tpu.provision.common import ClusterInfo
from skypilot_tpu.runtime import agent_client
from skypilot_tpu.utils import command_runner
from skypilot_tpu.utils import common

logger = logging.getLogger(__name__)


class Backend:
    """Abstract lifecycle (reference sky/backends/backend.py:30)."""

    def provision(self, task: task_lib.Task, cluster_name: str,
                  candidates: List[catalog.Candidate]) -> ClusterInfo:
        raise NotImplementedError

    def sync_workdir(self, info: ClusterInfo, workdir: str) -> None:
        raise NotImplementedError

    def sync_file_mounts(self, info: ClusterInfo,
                         file_mounts: Dict[str, str]) -> None:
        raise NotImplementedError

    def setup(self, info: ClusterInfo, task: task_lib.Task) -> None:
        raise NotImplementedError

    def execute(self, info: ClusterInfo, task: task_lib.Task,
                detach: bool = True, *,
                include_setup: bool = False) -> int:
        raise NotImplementedError

    def teardown(self, info: ClusterInfo, terminate: bool) -> None:
        raise NotImplementedError


class TpuVmBackend(Backend):
    """The TPU-slice backend (local fake slices + GCP TPU nodes)."""

    # ---- provision ------------------------------------------------------
    def provision(self, task: task_lib.Task, cluster_name: str,
                  candidates: List[catalog.Candidate]) -> ClusterInfo:
        # gcp-pd volumes are zonal and only attach at TPU-node create
        # (dataDisks): pin placement to the disks' zone and pass them in.
        data_disks: List[str] = []
        pd_zones = set()
        has_pvc = False
        for vol_name in task.volumes.values():
            rec = state.get_volume(vol_name)
            if rec is None:
                continue   # mount_volumes reports unknown names
            # Fail BEFORE provisioning a slice the volumes can't join.
            if (rec['status'] == 'IN_USE' and
                    rec['attached_to'] != cluster_name):
                raise exceptions.VolumeError(
                    f'Volume {vol_name!r} is attached to '
                    f'{rec["attached_to"]!r}; detach it before '
                    f'launching.')
            if rec['type'] == 'gcp-pd':
                data_disks.append(rec['name'])
                pd_zones.add(rec['zone'])
            elif rec['type'] == 'k8s-pvc':
                # PVCs bind inside one cluster: ride the data_disks
                # channel into render_slice's persistentVolumeClaim
                # mounts.
                data_disks.append(rec['name'])
                has_pvc = True
                candidates = [c for c in candidates
                              if c.cloud == 'kubernetes']
                if not candidates:
                    raise exceptions.ResourcesUnavailableError(
                        f'k8s-pvc volume {rec["name"]!r} requires a '
                        f'kubernetes placement.')
        if pd_zones:
            # data_disks semantics are PROVIDER-SPECIFIC (PD names on
            # gcp, PVC claim names on k8s) — a pd-carrying task must
            # never reach another provisioner, or the names would be
            # misinterpreted (e.g. rendered as nonexistent PVCs).
            if has_pvc:
                raise exceptions.InvalidTaskError(
                    'gcp-pd and k8s-pvc volumes cannot be mixed in one '
                    'task (they pin to different clouds)')
            if len(pd_zones) > 1:
                raise exceptions.InvalidTaskError(
                    f'gcp-pd volumes of one task must share a zone; '
                    f'got {sorted(pd_zones)}')
            (pd_zone,) = pd_zones
            candidates = [c for c in candidates
                          if c.cloud == 'gcp' and c.zone == pd_zone]
            if not candidates:
                raise exceptions.ResourcesUnavailableError(
                    f'No gcp placement in zone {pd_zone} (required by '
                    f'gcp-pd volumes {data_disks}).')
        state.add_or_update_cluster(
            cluster_name, common.ClusterStatus.INIT,
            resources_config=task.resources.to_yaml_config(),
            task_yaml=task.to_yaml())
        state.add_cluster_event(cluster_name, 'PROVISION',
                                f'trying {len(candidates)} placements')
        try:
            info, cand = provisioner.provision_with_retries(
                cluster_name, task.resources, candidates,
                data_disks=data_disks)
        except exceptions.ResourcesUnavailableError as e:
            state.add_cluster_event(cluster_name, 'PROVISION_FAILED', str(e))
            state.remove_cluster(cluster_name)
            raise
        state.add_or_update_cluster(
            cluster_name, common.ClusterStatus.UP,
            cluster_info=info.to_dict())
        state.add_cluster_event(
            cluster_name, 'PROVISIONED',
            f'{cand} ({info.num_hosts} hosts)')
        self._setup_logging_agent(info)
        return info

    def _setup_logging_agent(self, info: ClusterInfo) -> None:
        """Install the configured log-shipping agent on every host
        (reference wires sky/logs agents into cluster setup). Non-fatal:
        a logging outage must not fail a launch."""
        from skypilot_tpu import logs as logs_lib
        try:
            agent = logs_lib.get_logging_agent()
        except exceptions.SkyTpuError as e:
            logger.warning('logging agent config invalid: %s', e)
            return
        if agent is None or 'cluster_dir' in info.provider_config:
            return   # not configured / local fake slice has no sudo env
        try:
            runners = self._runners(info)
            for dst, src in agent.get_credential_file_mounts().items():
                for runner in runners:
                    # Parent dirs like /opt/sky_tpu/logging are created
                    # by the setup command, which runs AFTER this rsync
                    # — create them (writably) first.
                    parent = os.path.dirname(dst) or '/'
                    runner.run(f'sudo mkdir -p {parent} && '
                               f'sudo chmod a+rwx {parent} || '
                               f'mkdir -p {parent}', check=True,
                               timeout=60)
                    runner.rsync(os.path.expanduser(src), dst)
            client = self._client(info)
            result = client.exec_sync(
                agent.get_setup_command(info.cluster_name))
            if any(rc != 0 for rc in result['returncodes']):
                raise exceptions.CommandError(
                    max(result['returncodes']), 'logging agent setup',
                    str(result['tails']))
            state.add_cluster_event(info.cluster_name,
                                    'LOGGING_AGENT_SETUP',
                                    type(agent).__name__)
        except Exception as e:  # noqa: BLE001 — non-fatal by contract:
            # agent HTTP errors (requests.*) included, a log-shipping
            # outage must not fail the launch.
            logger.warning('logging agent setup failed on %s: %s',
                           info.cluster_name, e)
            state.add_cluster_event(info.cluster_name,
                                    'LOGGING_AGENT_FAILED', str(e))

    # ---- file sync ------------------------------------------------------
    def _runners(self, info: ClusterInfo
                 ) -> List[command_runner.CommandRunner]:
        # Process-simulated hosts (local cloud, process-mode ssh pools)
        # carry a cluster_dir; pods go through kubectl; real hosts are
        # reached over SSH.
        if 'cluster_dir' in info.provider_config:
            cdir = info.provider_config['cluster_dir']
            return [command_runner.LocalProcessCommandRunner(
                os.path.join(cdir, f'host{i}'))
                for i in range(info.num_hosts)]
        if info.cloud == 'kubernetes':
            return [command_runner.KubectlCommandRunner(
                h.host_id,
                namespace=info.provider_config.get('namespace',
                                                   'default'),
                context=info.provider_config.get('context'))
                for h in info.hosts]
        ssh_user = info.provider_config.get('ssh_user', 'sky')
        password = info.provider_config.get('ssh_password')
        key = info.provider_config.get('ssh_key')
        if key is None and not password:
            key = '~/.sky_tpu/keys/sky-key'
        return [command_runner.SSHCommandRunner(
            h.external_ip or h.internal_ip, user=ssh_user, key_path=key,
            password=password)
            for h in info.hosts]

    def _remote_workdir(self, info: ClusterInfo) -> str:
        """The directory jobs run in — must match the agent's _rank_cwd.

        Local fake slices: relative to each host sandbox. Real hosts: the
        agent's cluster dir (gcp instance.py AGENT_CLUSTER_DIR).
        """
        if 'cluster_dir' in info.provider_config:
            return 'workdir/'
        return '/opt/sky_tpu/cluster/workdir/'

    def sync_workdir(self, info: ClusterInfo, workdir: str) -> None:
        """Rsync the user's workdir to every host (reference
        sync_workdir, backend.py:93)."""
        src = os.path.expanduser(workdir)
        if not src.endswith('/'):
            src += '/'
        dst = self._remote_workdir(info)
        for runner in self._runners(info):
            runner.rsync(src, dst)

    def sync_file_mounts(self, info: ClusterInfo,
                         file_mounts: Dict[str, str]) -> None:
        from skypilot_tpu.data import storage as storage_lib
        for dst, src in file_mounts.items():
            if storage_lib.is_bucket_url(src):
                # Bucket-backed sources (gs/s3/r2/azure/file) are mounted
                # by data/storage.py via the agent on every host.
                storage_lib.mount_on_cluster(info, dst, src)
                continue
            for runner in self._runners(info):
                runner.rsync(os.path.expanduser(src), dst)

    def mount_volumes(self, info: ClusterInfo,
                      task: task_lib.Task) -> None:
        """Attach + mount each task volume on every host (reference
        volumes are mounted during file-mount sync)."""
        if not task.volumes:
            return
        from skypilot_tpu.volumes import core as volumes_core
        client = self._client(info)
        for mount_path, vol_name in task.volumes.items():
            rec = volumes_core.attach(vol_name, info.cluster_name)
            vol = volumes_core.to_volume(rec)
            result = client.exec_sync(vol.mount_command(mount_path))
            rcs = result['returncodes']
            if any(rc != 0 for rc in rcs):
                raise exceptions.CommandError(
                    max(rcs), f'mount volume {vol_name}',
                    str(result['tails']))
            state.add_cluster_event(
                info.cluster_name, 'VOLUME_MOUNTED',
                f'{vol_name} at {mount_path}')

    # ---- setup / execute -------------------------------------------------
    def _client(self, info: ClusterInfo) -> agent_client.AgentClient:
        url = info.head.agent_url
        if not url:
            raise exceptions.ClusterNotUpError(
                f'{info.cluster_name}: no agent URL (cluster stopped?)')
        return agent_client.AgentClient.for_info(info)

    def setup(self, info: ClusterInfo, task: task_lib.Task) -> None:
        if not task.setup:
            return
        client = self._client(info)
        result = client.exec_sync(task.setup,
                                  envs={**task.envs, **task.secrets})
        rcs = result['returncodes']
        if any(rc != 0 for rc in rcs):
            tails = '\n'.join(f'--- host {r} ---\n{t}'
                              for r, t in result['tails'].items())
            raise exceptions.CommandError(
                max(rcs), 'setup', f'setup failed on hosts '
                f'{[i for i, rc in enumerate(rcs) if rc]}:\n{tails}')

    def execute(self, info: ClusterInfo, task: task_lib.Task,
                detach: bool = True, *,
                include_setup: bool = False) -> int:
        """Submit the run command as a job; the agent gangs it across all
        hosts of the slice.

        include_setup submits task.setup as the job's setup phase too —
        the pool-job path uses it (workers are provisioned once, so the
        launch-time SETUP stage never saw this task); the normal launch
        flow leaves it False because Stage.SETUP already ran it.
        """
        if not task.run:
            logger.info('Task has no run command; nothing to execute.')
            return -1
        client = self._client(info)
        job_id = client.submit(
            name=task.name or 'job',
            run=task.run,
            setup=(task.setup if include_setup else None),
            envs={**task.envs, **task.secrets})
        state.update_last_use(info.cluster_name, f'exec job {job_id}')
        return job_id

    def tail_logs(self, info: ClusterInfo, job_id: int,
                  *, follow: bool = True, rank: int = 0):
        yield from self._client(info).tail_logs(job_id, follow=follow,
                                                rank=rank)

    def wait_job(self, info: ClusterInfo, job_id: int,
                 timeout: float = 3600.0) -> common.JobStatus:
        return self._client(info).wait_job(job_id, timeout)

    # ---- teardown -------------------------------------------------------
    def teardown(self, info: ClusterInfo, terminate: bool) -> None:
        if terminate:
            provision.terminate_instances(info.cloud, info.cluster_name,
                                          info.provider_config)
            # Volumes release only AFTER a successful terminate — a
            # failed delete must not let another cluster claim a disk
            # that is still attached. Stop keeps them attached (the
            # stopped cluster still owns its disks/data).
            from skypilot_tpu.volumes import core as volumes_core
            volumes_core.detach_all(info.cluster_name)
            state.remove_cluster(info.cluster_name)
            state.add_cluster_event(info.cluster_name, 'TERMINATED', 'down')
        else:
            provision.stop_instances(info.cloud, info.cluster_name,
                                     info.provider_config)
            state.set_cluster_status(info.cluster_name,
                                     common.ClusterStatus.STOPPED)
            state.add_cluster_event(info.cluster_name, 'STOPPED', 'stop')

    def set_autostop(self, info: ClusterInfo, idle_minutes: int,
                     down: bool) -> None:
        self._client(info).set_autostop(idle_minutes, down)
        state.set_cluster_autostop(info.cluster_name, idle_minutes, down)
