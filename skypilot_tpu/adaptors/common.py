"""LazyImport: defer SDK imports until first use.

Same role as the reference's ``sky/adaptors/common.py:10`` LazyImport;
re-designed minimally — a module proxy that imports on first attribute
access and raises a hint-carrying ImportError if the SDK is missing.
"""
from __future__ import annotations

import importlib
import threading
from typing import Any, Optional


class LazyImport:
    """Proxy for a module imported on first attribute access."""

    def __init__(self, module_name: str,
                 install_hint: Optional[str] = None) -> None:
        self._module_name = module_name
        self._install_hint = install_hint
        self._module: Any = None
        self._lock = threading.Lock()

    def _load(self) -> Any:
        if self._module is None:
            with self._lock:
                if self._module is None:
                    try:
                        self._module = importlib.import_module(
                            self._module_name)
                    except ImportError as e:
                        hint = self._install_hint or str(e)
                        raise ImportError(
                            f'Failed to import {self._module_name!r}: '
                            f'{hint}') from e
        return self._module

    def available(self) -> bool:
        """True if the underlying module can be imported."""
        try:
            self._load()
            return True
        except ImportError:
            return False

    def __getattr__(self, name: str) -> Any:
        return getattr(self._load(), name)
