"""Lazy adaptors for optional/heavy third-party SDKs.

Counterpart of the reference's ``sky/adaptors/`` (LazyImport at
``sky/adaptors/common.py:10-24``): cloud SDKs are imported on first
attribute access so the framework imports fast and works where a given
SDK is absent — callers get a clear, actionable ImportError only when
they actually touch the missing SDK.
"""
from skypilot_tpu.adaptors.common import LazyImport

# The TPU cloud's storage SDK (present in the standard image).
gcs_storage = LazyImport(
    'google.cloud.storage',
    install_hint='google-cloud-storage is required for GCS bucket '
    'operations (pip install google-cloud-storage)')

# Optional elsewhere.
boto3 = LazyImport(
    'boto3',
    install_hint='boto3 is required for S3/R2 bucket SDK operations '
    '(pip install boto3); the `aws` CLI is used as a fallback when '
    'available')
azure_blob = LazyImport(
    'azure.storage.blob',
    install_hint='azure-storage-blob is required for Azure Blob '
    'operations (pip install azure-storage-blob)')
gcsfs = LazyImport('gcsfs',
                   install_hint='gcsfs is required for fsspec-style GCS '
                   'access (pip install gcsfs)')
