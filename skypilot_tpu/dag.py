"""Task DAG container (reference sky/dag.py: ``Dag`` at :26, ``is_chain``
at :159, thread-local ``_DagContext`` at :202).

The optimizer consumes this: chain DAGs get the DP solver, general DAGs the
exhaustive/greedy solver (reference uses ILP via pulp; pulp is not available
here so the general case is solved exactly for small DAGs — see
``skypilot_tpu/optimizer.py``).
"""
from __future__ import annotations

import enum
import threading
from typing import Dict, List, Optional, Set

from skypilot_tpu import task as task_lib


class DagExecution(enum.Enum):
    """How a multi-task DAG executes (reference sky/dag.py:12).

    SERIAL: tasks run one after another, in topological order.
    PARALLEL: a *job group* — tasks run simultaneously and must be
    gang-placed on the same infra (cloud + region); on TPU this means
    slices carved out of the same region so DCN between them is local.
    """
    SERIAL = 'serial'
    PARALLEL = 'parallel'


class Dag:
    """A directed acyclic graph of Tasks."""

    def __init__(self, name: Optional[str] = None):
        self.name = name
        self.tasks: List[task_lib.Task] = []
        self._edges: Dict[int, Set[int]] = {}  # task index -> child indices
        # None means DEFAULT (serial); set_execution(PARALLEL) marks a
        # job group (reference sky/dag.py:91 is_job_group).
        self.execution: Optional[DagExecution] = None

    # ---- construction ----------------------------------------------------
    def add(self, t: task_lib.Task) -> 'Dag':
        if t not in self.tasks:
            self.tasks.append(t)
            self._edges.setdefault(self.tasks.index(t), set())
        return self

    def add_edge(self, parent: task_lib.Task, child: task_lib.Task) -> None:
        self.add(parent)
        self.add(child)
        pi, ci = self.tasks.index(parent), self.tasks.index(child)
        self._edges.setdefault(pi, set()).add(ci)
        if self._has_cycle():
            self._edges[pi].discard(ci)
            raise ValueError('Adding this edge would create a cycle')

    def remove(self, t: task_lib.Task) -> None:
        idx = self.tasks.index(t)
        self.tasks.pop(idx)
        new_edges: Dict[int, Set[int]] = {}
        for p, children in self._edges.items():
            if p == idx:
                continue
            np_ = p - 1 if p > idx else p
            new_edges[np_] = {c - 1 if c > idx else c
                              for c in children if c != idx}
        self._edges = new_edges

    # ---- queries ---------------------------------------------------------
    def __len__(self) -> int:
        return len(self.tasks)

    def __iter__(self):
        return iter(self.tasks)

    def children(self, t: task_lib.Task) -> List[task_lib.Task]:
        return [self.tasks[c] for c in self._edges.get(
            self.tasks.index(t), set())]

    def parents(self, t: task_lib.Task) -> List[task_lib.Task]:
        idx = self.tasks.index(t)
        return [self.tasks[p] for p, cs in self._edges.items() if idx in cs]

    def set_execution(self, execution: DagExecution) -> None:
        self.execution = execution

    def is_job_group(self) -> bool:
        """True when tasks run in parallel as one gang (reference
        sky/dag.py:91): they must be co-placed on common infra."""
        return self.execution is DagExecution.PARALLEL

    def is_chain(self) -> bool:
        """True for a *connected* linear chain: every degree <= 1, exactly
        one source and one sink (reference sky/dag.py:159 has the same
        single-source/single-sink requirement; without it two disconnected
        tasks would be mis-routed to the chain DP solver)."""
        if len(self.tasks) <= 1:
            return True
        out_deg = {i: len(self._edges.get(i, set()))
                   for i in range(len(self.tasks))}
        in_deg: Dict[int, int] = {i: 0 for i in range(len(self.tasks))}
        for cs in self._edges.values():
            for c in cs:
                in_deg[c] += 1
        return (all(d <= 1 for d in out_deg.values()) and
                all(d <= 1 for d in in_deg.values()) and
                sum(1 for d in out_deg.values() if d == 0) == 1 and
                sum(1 for d in in_deg.values() if d == 0) == 1)

    def topological_order(self) -> List[task_lib.Task]:
        in_deg: Dict[int, int] = {i: 0 for i in range(len(self.tasks))}
        for cs in self._edges.values():
            for c in cs:
                in_deg[c] += 1
        ready = [i for i, d in in_deg.items() if d == 0]
        order: List[int] = []
        while ready:
            i = ready.pop(0)
            order.append(i)
            for c in sorted(self._edges.get(i, set())):
                in_deg[c] -= 1
                if in_deg[c] == 0:
                    ready.append(c)
        if len(order) != len(self.tasks):
            raise ValueError('DAG has a cycle')
        return [self.tasks[i] for i in order]

    def _has_cycle(self) -> bool:
        try:
            self.topological_order()
            return False
        except ValueError:
            return True

    def __repr__(self) -> str:
        return f'Dag({self.name or "<unnamed>"}, {len(self.tasks)} tasks)'


class _DagContext(threading.local):
    """Thread-local `with Dag()` support (reference sky/dag.py:202)."""

    def __init__(self) -> None:
        super().__init__()
        self._stack: List[Dag] = []

    def push(self, dag: Dag) -> None:
        self._stack.append(dag)

    def pop(self) -> Dag:
        return self._stack.pop()

    def current(self) -> Optional[Dag]:
        return self._stack[-1] if self._stack else None


_dag_context = _DagContext()


def get_current_dag() -> Optional[Dag]:
    return _dag_context.current()


def _dag_enter(self: Dag) -> Dag:
    _dag_context.push(self)
    return self


def _dag_exit(self: Dag, *_args) -> None:
    _dag_context.pop()


Dag.__enter__ = _dag_enter  # type: ignore[attr-defined]
Dag.__exit__ = _dag_exit  # type: ignore[attr-defined]
