"""User management + RBAC (reference ``sky/users/``: rbac.py roles and
blocklists, permission.py enforcement, token_service.py service-account
tokens)."""
from skypilot_tpu.users.core import (create_token, delete_user, get_user,
                                     list_tokens, list_users, revoke_token,
                                     update_role)
from skypilot_tpu.users.rbac import RoleName, check_permission

__all__ = [
    'RoleName', 'check_permission', 'create_token', 'delete_user',
    'get_user', 'list_tokens', 'list_users', 'revoke_token', 'update_role',
]
