"""Role-based access control (reference ``sky/users/rbac.py``: RoleName
at :49, config-driven role permissions at :63, default-user blocklist).

Enforcement model matches the reference: roles carry a *blocklist* of
(path, method) rules; everything not blocked is allowed. Admins have an
empty blocklist. The server's auth middleware calls
``check_permission(role, path, method)`` per request.
"""
from __future__ import annotations

import enum
import fnmatch
from typing import Dict, List

from skypilot_tpu import config


class RoleName(str, enum.Enum):
    ADMIN = 'admin'
    USER = 'user'


# Mutating control-plane surfaces a plain user cannot touch (reference
# _DEFAULT_USER_BLOCKLIST: workspace config updates, user role changes).
_DEFAULT_USER_BLOCKLIST: List[Dict[str, str]] = [
    {'path': '/users.role', 'method': 'POST'},
    {'path': '/users.delete', 'method': 'POST'},
    {'path': '/users.token_revoke', 'method': 'POST'},
    {'path': '/workspaces.create', 'method': 'POST'},
    {'path': '/workspaces.update', 'method': 'POST'},
    {'path': '/workspaces.delete', 'method': 'POST'},
]


def get_supported_roles() -> List[str]:
    return [r.value for r in RoleName]


def get_default_role() -> str:
    """Role assigned to users on first sight (reference rbac.py:58;
    default admin keeps single-user deployments frictionless)."""
    return config.get_nested(('rbac', 'default_role'),
                             RoleName.ADMIN.value)


def get_role_permissions() -> Dict[str, Dict[str, List[Dict[str, str]]]]:
    """Blocklist per role, overridable from config ``rbac.roles``."""
    roles: Dict[str, Dict[str, List[Dict[str, str]]]] = {
        RoleName.ADMIN.value: {'blocklist': []},
        RoleName.USER.value: {'blocklist': list(_DEFAULT_USER_BLOCKLIST)},
    }
    for role, spec in (config.get_nested(('rbac', 'roles'), {}) or {}).items():
        role = role.lower()
        if role not in roles:
            continue
        blocklist = (spec or {}).get('permissions', {}).get('blocklist')
        if blocklist is not None:
            roles[role] = {'blocklist': list(blocklist)}
    return roles


def check_permission(role: str, path: str, method: str) -> bool:
    """True when `role` may call `method path`. Unknown roles get the
    most-restricted (user) blocklist."""
    perms = get_role_permissions()
    spec = perms.get(role, perms[RoleName.USER.value])
    for rule in spec['blocklist']:
        if (fnmatch.fnmatch(path, rule['path']) and
                method.upper() == rule.get('method', 'POST').upper()):
            return False
    return True
