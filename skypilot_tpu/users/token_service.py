"""Service-account bearer tokens (reference ``sky/users/token_service.py``:
JWT-style tokens signed with a DB-persisted secret, token records with
revocation + last-used tracking).

PyJWT is not a baked-in dependency, so tokens are stdlib HMAC-SHA256:
``sky_<token_id>_<base64url(payload)>_<hex sig>``. The payload carries
(token_id, user_id, exp); the DB row carries a *hash* of the full token
so a leaked DB does not leak usable credentials (same property the
reference gets from storing only token hashes).
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import json
import secrets as pysecrets
import time
from typing import Any, Dict, Optional

from skypilot_tpu import state

_SECRET_KEY = 'token_signing_secret'
TOKEN_PREFIX = 'sky'


def _secret() -> bytes:
    return state.get_or_create_secret(
        _SECRET_KEY, lambda: pysecrets.token_hex(32)).encode()


def _sign(msg: bytes) -> str:
    return hmac.new(_secret(), msg, hashlib.sha256).hexdigest()


def token_hash(token: str) -> str:
    return hashlib.sha256(token.encode()).hexdigest()


def create_token(name: str, user_id: str,
                 expires_in_s: Optional[float] = None) -> str:
    """Mint a token. The cleartext is returned exactly once."""
    token_id = pysecrets.token_hex(8)
    expires_at = time.time() + expires_in_s if expires_in_s else None
    payload = {'tid': token_id, 'uid': user_id, 'exp': expires_at}
    body = base64.urlsafe_b64encode(
        json.dumps(payload, separators=(',', ':')).encode()).decode()
    sig = _sign(body.encode())
    token = f'{TOKEN_PREFIX}_{token_id}_{body}_{sig}'
    state.add_token(token_id, name, user_id, token_hash(token), expires_at)
    return token


def verify_token(token: str) -> Optional[Dict[str, Any]]:
    """Payload dict if the token is valid, unrevoked and unexpired."""
    # base64url bodies may themselves contain '_': split off the hex sig
    # from the right, then prefix/tid (both '_'-free) from the left.
    head, _, sig = token.rpartition('_')
    parts = head.split('_', 2)
    if not sig or len(parts) != 3 or parts[0] != TOKEN_PREFIX:
        return None
    _, token_id, body = parts
    if not hmac.compare_digest(sig, _sign(body.encode())):
        return None
    try:
        payload = json.loads(base64.urlsafe_b64decode(body))
    except (ValueError, UnicodeDecodeError):
        return None
    record = state.get_token(token_id)
    if record is None or record['revoked']:
        return None
    if not hmac.compare_digest(record['token_hash'], token_hash(token)):
        return None
    exp = payload.get('exp')
    if exp is not None and time.time() > exp:
        return None
    state.touch_token(token_id)
    return payload
