"""User-management server ops (reference ``sky/users/server.py`` endpoints
backed by global_user_state user rows)."""
from __future__ import annotations

import getpass
import hashlib
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import state
from skypilot_tpu.users import rbac
from skypilot_tpu.users import token_service


def current_user_id() -> str:
    """Stable id for the local OS user (reference hashes the username the
    same way for its default identity)."""
    name = getpass.getuser()
    return hashlib.md5(name.encode()).hexdigest()[:8]


def ensure_user(user_id: Optional[str] = None,
                name: Optional[str] = None) -> Dict[str, Any]:
    """Get-or-create, assigning the default role on first sight."""
    user_id = user_id or current_user_id()
    user = state.get_user(user_id)
    if user is None:
        state.add_or_update_user(user_id, name or getpass.getuser(),
                                 rbac.get_default_role())
        user = state.get_user(user_id)
    return user


def get_user(user_id: str) -> Optional[Dict[str, Any]]:
    return state.get_user(user_id)


def list_users() -> List[Dict[str, Any]]:
    return state.get_users()


def update_role(user_id: str, role: str) -> None:
    if role not in rbac.get_supported_roles():
        raise exceptions.InvalidTaskError(
            f'Unknown role {role!r}; supported: '
            f'{rbac.get_supported_roles()}')
    if state.get_user(user_id) is None:
        raise exceptions.UserNotFoundError(f'No such user: {user_id}')
    state.set_user_role(user_id, role)


def delete_user(user_id: str) -> None:
    if state.get_user(user_id) is None:
        raise exceptions.UserNotFoundError(f'No such user: {user_id}')
    state.delete_user(user_id)


def create_token(name: str, user_id: Optional[str] = None,
                 expires_in_s: Optional[float] = None,
                 caller: Optional[Dict[str, Any]] = None) -> str:
    """Mint a token.

    ``user_id=None`` means "for the calling identity" (auto-created on
    first sight). An explicit user_id must already exist — auto-creating
    it would hand out default-role (often admin) credentials — and a
    non-admin ``caller`` may only mint tokens for itself (privilege
    escalation otherwise: a user-role caller minting an admin's token).
    """
    if user_id is None:
        # Self-service: the authenticated caller's identity, else the
        # local OS user (direct/loopback mode).
        if caller is not None and caller.get('id'):
            user = state.get_user(caller['id'])
            if user is None:
                raise exceptions.UserNotFoundError(
                    f'Caller {caller["id"]!r} has no user record.')
        else:
            user = ensure_user()
    else:
        user = state.get_user(user_id)
        if user is None:
            raise exceptions.UserNotFoundError(
                f'No such user: {user_id} (tokens are only minted for '
                f'existing users)')
        if (caller is not None and
                caller.get('role') != rbac.RoleName.ADMIN.value and
                caller.get('id') != user['id']):
            raise exceptions.PermissionDeniedError(
                f'Role {caller.get("role")!r} may only mint tokens for '
                f'itself, not for user {user["id"]!r}.')
    return token_service.create_token(name, user['id'], expires_in_s)


def list_tokens(user_id: Optional[str] = None) -> List[Dict[str, Any]]:
    rows = state.get_tokens(user_id)
    for r in rows:
        r.pop('token_hash', None)   # never expose even hashes
    return rows


def revoke_token(token_id: str) -> None:
    if state.get_token(token_id) is None:
        raise exceptions.UserNotFoundError(f'No such token: {token_id}')
    state.revoke_token(token_id)


def authenticate(token: str) -> Optional[Dict[str, Any]]:
    """Resolve a bearer token to its user record (with role)."""
    payload = token_service.verify_token(token)
    if payload is None:
        return None
    return state.get_user(payload['uid'])
