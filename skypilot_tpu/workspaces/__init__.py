"""Multi-workspace config scoping (reference ``sky/workspaces/``)."""
from skypilot_tpu.workspaces.core import (accessible_workspaces,
                                          active_workspace,
                                          check_workspace_permission,
                                          create_workspace,
                                          delete_workspace, get_workspaces,
                                          update_workspace)

__all__ = [
    'accessible_workspaces', 'active_workspace',
    'check_workspace_permission', 'create_workspace', 'delete_workspace',
    'get_workspaces', 'update_workspace',
]
