"""Workspace CRUD + access control (reference ``sky/workspaces/core.py``:
get_workspaces :67, create :416, update :358, delete :465,
check_workspace_permission :641).

A workspace is a named section of the global config that scopes clusters
and can pin per-cloud settings (e.g. a GCP project per team). Clusters are
tagged with the active workspace at launch; `status` filters by it. A
workspace with ``private: true`` is visible only to ``allowed_users``
(and admins).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import config
from skypilot_tpu import exceptions
from skypilot_tpu import state
from skypilot_tpu.users import rbac
from skypilot_tpu.utils import locks

DEFAULT_WORKSPACE = 'default'
ACTIVE_ENV_VAR = 'SKY_TPU_WORKSPACE'


def get_workspaces() -> Dict[str, Any]:
    """All configured workspaces; `default` always exists."""
    ws = config.get_nested(('workspaces',), {}) or {}
    if DEFAULT_WORKSPACE not in ws:
        ws = {DEFAULT_WORKSPACE: {}, **ws}
    return ws


def active_workspace() -> str:
    """Env override > config ``active_workspace`` > default."""
    import os
    env = os.environ.get(ACTIVE_ENV_VAR)
    if env:
        return env
    return config.get_nested(('active_workspace',), DEFAULT_WORKSPACE)


def _validate_name(name: str) -> None:
    if not name or not name.replace('-', '').replace('_', '').isalnum():
        raise exceptions.WorkspaceError(
            f'Invalid workspace name {name!r}: alphanumeric, - and _ only.')


def _validate_config(name: str, ws_config: Dict[str, Any]) -> None:
    if not isinstance(ws_config, dict):
        raise exceptions.WorkspaceError(
            f'Workspace {name!r} config must be a mapping.')
    allowed = {'private', 'allowed_users', 'gcp', 'clouds', 'description'}
    unknown = set(ws_config) - allowed
    if unknown:
        raise exceptions.WorkspaceError(
            f'Unknown workspace fields {sorted(unknown)}; '
            f'allowed: {sorted(allowed)}')


def create_workspace(name: str,
                     ws_config: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    _validate_name(name)
    ws_config = ws_config or {}
    _validate_config(name, ws_config)
    # Lock spans the read-modify-write: a concurrent create must not be
    # dropped by this one's wholesale rewrite (POSIX locks are
    # per-process, so update_global's nested acquire is safe).
    with locks.named_lock('global_config'):
        config.reload()
        workspaces = get_workspaces()
        if name in workspaces and name != DEFAULT_WORKSPACE:
            raise exceptions.WorkspaceError(
                f'Workspace {name!r} already exists.')
        workspaces[name] = ws_config
        config.update_global({'workspaces': workspaces},
                             replace_keys=('workspaces',))
    return workspaces


def update_workspace(name: str,
                     ws_config: Dict[str, Any]) -> Dict[str, Any]:
    _validate_config(name, ws_config)
    with locks.named_lock('global_config'):
        config.reload()
        workspaces = get_workspaces()
        if name not in workspaces:
            raise exceptions.WorkspaceError(f'No such workspace: {name!r}')
        workspaces[name] = ws_config
        config.update_global({'workspaces': workspaces},
                             replace_keys=('workspaces',))
    return workspaces


def delete_workspace(name: str) -> Dict[str, Any]:
    if name == DEFAULT_WORKSPACE:
        raise exceptions.WorkspaceError(
            'The default workspace cannot be deleted.')
    with locks.named_lock('global_config'):
        config.reload()
        workspaces = get_workspaces()
        if name not in workspaces:
            raise exceptions.WorkspaceError(f'No such workspace: {name!r}')
        # Active clusters pin their workspace (reference delete_workspace
        # refuses while clusters reference it).
        in_use = [c['name'] for c in state.get_clusters()
                  if c.get('workspace') == name]
        if in_use:
            raise exceptions.WorkspaceError(
                f'Workspace {name!r} still has clusters: {in_use}. '
                f'Down them first.')
        del workspaces[name]
        config.update_global({'workspaces': workspaces},
                             replace_keys=('workspaces',))
    return workspaces


def is_workspace_private(ws_config: Dict[str, Any]) -> bool:
    return bool((ws_config or {}).get('private', False))


def check_workspace_permission(user: Optional[Dict[str, Any]],
                               workspace: str) -> None:
    """Raise unless `user` may use `workspace` (reference :641)."""
    ws_config = get_workspaces().get(workspace)
    if ws_config is None:
        raise exceptions.WorkspaceError(f'No such workspace: {workspace!r}')
    if not is_workspace_private(ws_config):
        return
    if user is None:
        raise exceptions.PermissionDeniedError(
            f'Workspace {workspace!r} is private; authentication required.')
    if user.get('role') == rbac.RoleName.ADMIN.value:
        return
    allowed = ws_config.get('allowed_users', []) or []
    if user.get('id') in allowed or user.get('name') in allowed:
        return
    raise exceptions.PermissionDeniedError(
        f'User {user.get("name")!r} is not in workspace '
        f'{workspace!r} allowed_users.')


def accessible_workspaces(user: Optional[Dict[str, Any]]
                          ) -> List[str]:
    out = []
    for name in get_workspaces():
        try:
            check_workspace_permission(user, name)
            out.append(name)
        except (exceptions.PermissionDeniedError, exceptions.WorkspaceError):
            continue
    return out
