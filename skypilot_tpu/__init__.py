"""skypilot_tpu: a TPU-native AI-workload orchestrator.

A brand-new framework with the capabilities of SkyPilot (the reference),
re-designed TPU-first: multi-host TPU slices are atomic, gang-scheduled
resources; the runtime wires `jax.distributed` process groups over ICI/DCN
instead of Ray placement groups + NCCL; serving targets continuous-batched
JAX LLM inference; and the bundled model/ops/parallel layers provide the
Llama-family training and inference stack the examples run.

Public API (mirrors the reference's `sky.*` surface, reference
sky/client/sdk.py):

    import skypilot_tpu as sky
    task = sky.Task.from_yaml('examples/minimal.yaml')
    sky.launch(task, cluster_name='dev')
    sky.status()
    sky.down('dev')
"""
from typing import TYPE_CHECKING

__version__ = '0.1.0'

from skypilot_tpu import exceptions
from skypilot_tpu.dag import Dag
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task
from skypilot_tpu.topology import TpuSlice, parse_tpu

if TYPE_CHECKING:
    pass


def __getattr__(name: str):
    # Engine entrypoints are imported lazily to keep `import skypilot_tpu`
    # light (no jax import, no sqlite open) — same motivation as the
    # reference's LazyImport adaptors (reference sky/adaptors/common.py:10).
    _engine_api = {
        'launch', 'exec', 'status', 'stop', 'start', 'down', 'autostop',
        'queue', 'cancel', 'tail_logs', 'cost_report', 'optimize',
    }
    import importlib
    try:
        if name in _engine_api:
            core = importlib.import_module('skypilot_tpu.core')
            return getattr(core, name)
        if name in ('jobs', 'serve'):
            # importlib, not from-import: a from-import falls back to this
            # very __getattr__ and recurses when the submodule is missing.
            return importlib.import_module(f'skypilot_tpu.{name}')
    except ImportError as e:
        # Keep hasattr()/getattr(default) semantics intact.
        raise AttributeError(
            f'module {__name__!r} attribute {name!r} unavailable: {e}'
        ) from e
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')
