"""Typed exception hierarchy for skypilot_tpu.

Counterpart of the reference's ``sky/exceptions.py`` (745 LoC): the important
design element preserved is ``ResourcesUnavailableError.failover_history`` —
the provisioner's failover loop appends each failed attempt so callers (and
the managed-jobs recovery strategies) can reason about *why* placement failed.
"""
from __future__ import annotations

from typing import List, Optional


class SkyTpuError(Exception):
    """Base class for all framework errors."""


class ResourcesUnavailableError(SkyTpuError):
    """No cloud/region/zone could satisfy the resource request.

    Carries the full failover history (one entry per failed attempt) like the
    reference's ``sky.exceptions.ResourcesUnavailableError`` (used by
    ``RetryingVmProvisioner``, reference cloud_vm_ray_backend.py:1661).
    """

    def __init__(self, message: str,
                 failover_history: Optional[List[Exception]] = None):
        super().__init__(message)
        self.failover_history: List[Exception] = failover_history or []

    def with_failover_history(
            self, history: List[Exception]) -> 'ResourcesUnavailableError':
        self.failover_history = history
        return self


class ResourcesMismatchError(SkyTpuError):
    """Requested resources cannot run on the target cluster."""


class InvalidTaskError(SkyTpuError):
    """Malformed task spec (YAML or programmatic)."""


class InvalidResourcesError(SkyTpuError):
    """Malformed or unsatisfiable resources spec."""


class ClusterNotUpError(SkyTpuError):
    """Operation requires an UP cluster."""


class ClusterDoesNotExist(SkyTpuError):
    """Named cluster not found in the state store."""


class ClusterOwnerIdentityMismatchError(SkyTpuError):
    """Cluster belongs to a different user/identity."""


class ProvisionError(SkyTpuError):
    """A single provisioning attempt failed (retryable via failover)."""

    def __init__(self, message: str, *, retryable: bool = True,
                 blocked_region: Optional[str] = None,
                 blocked_zone: Optional[str] = None):
        super().__init__(message)
        self.retryable = retryable
        self.blocked_region = blocked_region
        self.blocked_zone = blocked_zone


class ProvisionTimeoutError(ProvisionError):
    """Slice did not become ready in time (e.g. TPU QUEUED/PROVISIONING)."""


class QuotaExceededError(ProvisionError):
    """Out of quota in a region — block the whole region on failover."""

    def __init__(self, message: str, **kwargs):
        super().__init__(message, **kwargs)
        self.retryable = True


class CapacityError(ProvisionError):
    """Stockout / no capacity in a zone — block the zone on failover."""


class CommandError(SkyTpuError):
    """A remote/local command exited non-zero."""

    def __init__(self, returncode: int, command: str, error_msg: str = '',
                 detailed_reason: str = ''):
        self.returncode = returncode
        self.command = command
        self.error_msg = error_msg
        self.detailed_reason = detailed_reason
        super().__init__(
            f'Command failed with return code {returncode}: {command}\n'
            f'{error_msg}')


class JobNotFoundError(SkyTpuError):
    """Job id not present in a cluster's job queue."""


class ManagedJobReachedMaxRetriesError(SkyTpuError):
    """Managed job exhausted its recovery budget."""


class ManagedJobStatusError(SkyTpuError):
    """Managed job is in a state that does not permit the operation."""


class ServeUserTerminatedError(SkyTpuError):
    """Service was torn down by the user while an operation was in flight."""


class RequestCancelled(SkyTpuError):
    """An async API request was cancelled by the client."""


class ApiServerConnectionError(SkyTpuError):
    """Client could not reach the API server."""

    def __init__(self, server_url: str):
        super().__init__(
            f'Could not connect to API server at {server_url}. '
            'Start one with `sky-tpu api start`.')


class StorageError(SkyTpuError):
    """Object-store/storage mount failure."""


class CheckpointError(SkyTpuError):
    """Checkpoint save/restore failure."""


class NoCloudAccessError(SkyTpuError):
    """No cloud credentials are available for the requested operation."""


class AuthenticationError(SkyTpuError):
    """SSH key generation / credential setup failure."""


class UserNotFoundError(SkyTpuError):
    """Unknown user or token id (reference users/server.py 404s)."""


class PermissionDeniedError(SkyTpuError):
    """RBAC blocked the request (reference permission.py enforcement)."""


class WorkspaceError(SkyTpuError):
    """Workspace validation/permission failure (reference workspaces/core)."""


class VolumeError(SkyTpuError):
    """Volume lifecycle failure (reference volumes/server/core.py)."""


class VolumeNotFoundError(VolumeError):
    """Unknown volume name."""


class UnknownOpError(SkyTpuError):
    """API request named an op that does not exist (HTTP 404)."""


class OpUnavailableError(SkyTpuError):
    """API op exists but its subsystem is not importable (HTTP 501)."""
