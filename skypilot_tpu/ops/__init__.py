"""TPU-native neural net ops: fused-friendly primitives + Pallas kernels."""
