"""Normalization ops.

RMSNorm in plain jnp: XLA fuses the reduction + rescale into neighbouring
ops on TPU; a Pallas kernel buys nothing here (bandwidth-bound elementwise,
already fused), so the idiomatic-TPU choice is to leave it to the compiler.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray,
             eps: float = 1e-5) -> jnp.ndarray:
    """RMSNorm (Llama-style, no bias). Computes the variance in fp32
    regardless of input dtype — required for bf16 stability."""
    orig_dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    normed = x32 * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(orig_dtype)
