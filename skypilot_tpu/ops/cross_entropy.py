"""Vocab-chunked cross-entropy with a custom VJP.

The naive path materializes fp32 logits [tokens, vocab] (1.6 GB on the
1B bench), then log-softmax walks that tensor several more times, and
autodiff stores/rebuilds it for the backward — all HBM traffic, no
MXU work. This version streams the vocabulary in chunks with an online
logsumexp (the flash-attention trick applied to the loss):

- forward: one [T, C] fp32 buffer per chunk; accumulates (max, sumexp,
  target-logit) — never more than T*C live.
- backward: recomputes each chunk's logits (one extra logits matmul —
  MXU flops are cheap; the avoided HBM round trips are not), forms
  P - onehot per chunk, and feeds the SAME dX / dW matmuls autodiff
  would run.

Numerics match the dense fp32 log-softmax to float32 tolerance (tested
against the dense oracle in test_ops.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def chunked_cross_entropy(x: jnp.ndarray, w: jnp.ndarray,
                          targets: jnp.ndarray,
                          num_chunks: int = 8) -> jnp.ndarray:
    """Per-token NLL of ``softmax(x @ w)`` at ``targets``.

    x: [T, d] (compute dtype); w: [d, V]; targets: [T] int32.
    Returns [T] fp32. V must divide by num_chunks.
    """
    nll, _ = _ce_fwd_impl(x, w, targets, num_chunks)
    return nll


def _chunk(w: jnp.ndarray, i: jnp.ndarray, c: int) -> jnp.ndarray:
    return jax.lax.dynamic_slice(w, (0, i * c), (w.shape[0], c))


def _ce_fwd_impl(x, w, targets, num_chunks):
    t = x.shape[0]
    v = w.shape[1]
    assert v % num_chunks == 0, (v, num_chunks)
    c = v // num_chunks

    def body(carry, i):
        m, l, tl = carry
        logits = (x @ _chunk(w, i, c)).astype(jnp.float32)   # [T, C]
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        in_chunk = (targets >= i * c) & (targets < (i + 1) * c)
        idx = jnp.clip(targets - i * c, 0, c - 1)
        picked = jnp.take_along_axis(logits, idx[:, None], axis=1)[:, 0]
        tl = tl + jnp.where(in_chunk, picked, 0.0)
        return (m_new, l, tl), None

    init = (jnp.full((t,), -jnp.inf, jnp.float32),
            jnp.zeros((t,), jnp.float32),
            jnp.zeros((t,), jnp.float32))
    (m, l, tl), _ = jax.lax.scan(body, init,
                                 jnp.arange(num_chunks, dtype=jnp.int32))
    lse = m + jnp.log(l)
    return lse - tl, lse


def _ce_fwd(x, w, targets, num_chunks):
    # (nondiff_argnums args reach the fwd rule at their ORIGINAL
    # positions; only the bwd rule gets them as leading args.)
    nll, lse = _ce_fwd_impl(x, w, targets, num_chunks)
    return nll, (x, w, targets, lse)


def _ce_bwd(num_chunks, res, g):
    x, w, targets, lse = res
    d = x.shape[1]
    v = w.shape[1]
    c = v // num_chunks
    gx32 = g.astype(jnp.float32)

    def body(dx, i):
        wc = _chunk(w, i, c)
        logits = (x @ wc).astype(jnp.float32)                # [T, C]
        p = jnp.exp(logits - lse[:, None])                   # softmax
        in_chunk = (targets >= i * c) & (targets < (i + 1) * c)
        idx = jnp.clip(targets - i * c, 0, c - 1)
        onehot = (jax.nn.one_hot(idx, c, dtype=jnp.float32) *
                  in_chunk[:, None].astype(jnp.float32))
        dlogits = ((p - onehot) * gx32[:, None]).astype(x.dtype)
        dx = dx + dlogits @ wc.T                             # [T, d]
        dwc = x.T @ dlogits                                  # [d, C]
        return dx, dwc

    dx0 = jnp.zeros(x.shape, x.dtype)
    dx, dw_chunks = jax.lax.scan(
        body, dx0, jnp.arange(num_chunks, dtype=jnp.int32))
    # [nc, d, C] -> [d, V]
    dw = jnp.transpose(dw_chunks, (1, 0, 2)).reshape(d, v)
    return dx.astype(x.dtype), dw.astype(w.dtype), None


chunked_cross_entropy.defvjp(_ce_fwd, _ce_bwd)
