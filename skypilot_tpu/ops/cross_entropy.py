"""Vocab-chunked cross-entropy with a custom VJP.

The naive path materializes fp32 logits [tokens, vocab] (1.6 GB on the
1B bench), then log-softmax walks that tensor several more times, and
autodiff stores/rebuilds it for the backward — all HBM traffic, no
MXU work. This version streams the vocabulary in chunks with an online
logsumexp (the flash-attention trick applied to the loss):

- forward: one [T, C] fp32 buffer per chunk; accumulates (max, sumexp,
  target-logit) — never more than T*C live.
- backward: recomputes each chunk's logits (one extra logits matmul —
  MXU flops are cheap; the avoided HBM round trips are not), forms
  P - onehot per chunk, and feeds the SAME dX / dW matmuls autodiff
  would run.

Numerics match the dense fp32 log-softmax to float32 tolerance (tested
against the dense oracle in test_ops.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def chunked_cross_entropy(x: jnp.ndarray, w: jnp.ndarray,
                          targets: jnp.ndarray,
                          num_chunks: int = 8) -> jnp.ndarray:
    """Per-token NLL of ``softmax(x @ w)`` at ``targets``.

    x: [T, d] (compute dtype); w: [d, V]; targets: [T] int32.
    Returns [T] fp32. V must divide by num_chunks.
    """
    nll, _ = _ce_fwd_impl(x, w, targets, num_chunks)
    return nll


def _chunk(w: jnp.ndarray, i: jnp.ndarray, c: int) -> jnp.ndarray:
    return jax.lax.dynamic_slice(w, (0, i * c), (w.shape[0], c))


def _ce_fwd_impl(x, w, targets, num_chunks):
    t = x.shape[0]
    v = w.shape[1]
    assert v % num_chunks == 0, (v, num_chunks)
    c = v // num_chunks

    def body(carry, i):
        m, l, tl = carry
        logits = (x @ _chunk(w, i, c)).astype(jnp.float32)   # [T, C]
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        in_chunk = (targets >= i * c) & (targets < (i + 1) * c)
        idx = jnp.clip(targets - i * c, 0, c - 1)
        picked = jnp.take_along_axis(logits, idx[:, None], axis=1)[:, 0]
        tl = tl + jnp.where(in_chunk, picked, 0.0)
        return (m_new, l, tl), None

    init = (jnp.full((t,), -jnp.inf, jnp.float32),
            jnp.zeros((t,), jnp.float32),
            jnp.zeros((t,), jnp.float32))
    (m, l, tl), _ = jax.lax.scan(body, init,
                                 jnp.arange(num_chunks, dtype=jnp.int32))
    lse = m + jnp.log(l)
    return lse - tl, lse


def _ce_fwd(x, w, targets, num_chunks):
    # (nondiff_argnums args reach the fwd rule at their ORIGINAL
    # positions; only the bwd rule gets them as leading args.)
    nll, lse = _ce_fwd_impl(x, w, targets, num_chunks)
    return nll, (x, w, targets, lse)


def _ce_bwd(num_chunks, res, g):
    x, w, targets, lse = res
    d = x.shape[1]
    v = w.shape[1]
    c = v // num_chunks
    gx32 = g.astype(jnp.float32)

    def body(dx, i):
        wc = _chunk(w, i, c)
        logits = (x @ wc).astype(jnp.float32)                # [T, C]
        p = jnp.exp(logits - lse[:, None])                   # softmax
        in_chunk = (targets >= i * c) & (targets < (i + 1) * c)
        idx = jnp.clip(targets - i * c, 0, c - 1)
        onehot = (jax.nn.one_hot(idx, c, dtype=jnp.float32) *
                  in_chunk[:, None].astype(jnp.float32))
        dlogits = ((p - onehot) * gx32[:, None]).astype(x.dtype)
        dx = dx + dlogits @ wc.T                             # [T, d]
        dwc = x.T @ dlogits                                  # [d, C]
        return dx, dwc

    dx0 = jnp.zeros(x.shape, x.dtype)
    dx, dw_chunks = jax.lax.scan(
        body, dx0, jnp.arange(num_chunks, dtype=jnp.int32))
    # [nc, d, C] -> [d, V]
    dw = jnp.transpose(dw_chunks, (1, 0, 2)).reshape(d, v)
    return dx.astype(x.dtype), dw.astype(w.dtype), None


chunked_cross_entropy.defvjp(_ce_fwd, _ce_bwd)


# ---------------------------------------------------------------------------
# Fused Pallas cross-entropy: logits never leave VMEM.
# ---------------------------------------------------------------------------
# The chunked path above kills the [T, V] materialization but still
# dispatches one XLA matmul per vocab chunk and round-trips each chunk's
# fp32 logits through HBM. The fused FORWARD moves the loss into Pallas:
# each grid step computes one [bt, bv] logits tile ON THE MXU, consumes
# it (online logsumexp + target pick) while it is still in VMEM, and
# throws it away — HBM traffic is just x + W, instead of the dense
# path's 4+ passes over [T, V] fp32 (measured ~25 ms of the 1B bench
# forward at 32k vocab). The BACKWARD stays in XLA with exactly one
# logits recompute — see _fused_bwd_rule's docstring for why the
# fully-Pallas two-kernel backward measured slower.
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Vocab size above which the fused backward switches from the one-shot
# fp32 recompute to the chunked scan (fp32 [T, V] logits alone exceed
# 6 GB at Llama-3's 128k vocab). Module-level so tests can lower it.
ONE_SHOT_BWD_MAX_VOCAB = 65536


def _ce_fwd_kernel(x_ref, w_ref, t_ref, nll_ref, lse_ref,
                   m_ref, l_ref, tl_ref, *, bv: int, n_v: int):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)
        tl_ref[...] = jnp.zeros_like(tl_ref)

    x = x_ref[...]
    w = w_ref[...]
    logits = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # [bt, bv]
    m_prev = m_ref[...]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1,
                                        keepdims=True))
    l_ref[...] = (l_ref[...] * jnp.exp(m_prev - m_new)
                  + jnp.sum(jnp.exp(logits - m_new), axis=-1,
                            keepdims=True))
    m_ref[...] = m_new
    cols = vi * bv + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    is_t = cols == t_ref[...]                        # [bt, 1] broadcast
    tl_ref[...] = tl_ref[...] + jnp.sum(
        jnp.where(is_t, logits, 0.0), axis=-1, keepdims=True)

    @pl.when(vi == n_v - 1)
    def _finalize():
        lse = m_ref[...] + jnp.log(jnp.maximum(l_ref[...], 1e-30))
        nll_ref[...] = lse - tl_ref[...]
        lse_ref[...] = lse


def _fused_dims(t, v, block_t, block_v):
    assert t % block_t == 0, (t, block_t)
    assert v % block_v == 0, (v, block_v)
    return t // block_t, v // block_v


def _fused_fwd(x, w, targets, block_t, block_v, interpret):
    t, d = x.shape
    v = w.shape[1]
    n_t, n_v = _fused_dims(t, v, block_t, block_v)
    t2 = targets.astype(jnp.int32).reshape(t, 1)
    kernel = functools.partial(_ce_fwd_kernel, bv=block_v, n_v=n_v)
    nll, lse = pl.pallas_call(
        kernel,
        grid=(n_t, n_v),
        in_specs=[
            pl.BlockSpec((block_t, d), lambda ti, vi: (ti, 0)),
            pl.BlockSpec((d, block_v), lambda ti, vi: (0, vi)),
            pl.BlockSpec((block_t, 1), lambda ti, vi: (ti, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_t, 1), lambda ti, vi: (ti, 0)),
            pl.BlockSpec((block_t, 1), lambda ti, vi: (ti, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, 1), jnp.float32),
            jax.ShapeDtypeStruct((t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_t, 1), jnp.float32),
            pltpu.VMEM((block_t, 1), jnp.float32),
            pltpu.VMEM((block_t, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, w, t2)
    return nll[:, 0], lse


def _auto_block(n: int, want: int, floor: int = 8) -> int:
    """Largest power-of-two-ish tile <= want that divides n (Llama-3's
    128256 vocab divides 256, not 512)."""
    b = want
    while b > floor and n % b:
        b //= 2
    if n % b:
        import math
        b = math.gcd(b, n)
    return max(b, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_cross_entropy(x, w, targets, block_t, block_v, interpret):
    nll, _ = _fused_fwd(x, w, targets, block_t, block_v, interpret)
    return nll


def fused_cross_entropy(x: jnp.ndarray, w: jnp.ndarray,
                        targets: jnp.ndarray,
                        block_t: 'Optional[int]' = None,
                        block_v: 'Optional[int]' = None,
                        interpret: 'Optional[bool]' = None
                        ) -> jnp.ndarray:
    """Per-token NLL of ``softmax(x @ w)`` at ``targets``, fused
    forward (logits tiles never leave VMEM) + single-recompute XLA
    backward.

    x: [T, d]; w: [d, V]; targets: [T] int32 -> [T] fp32. Tile sizes
    default to the largest divisors of T / V up to 512. `interpret`
    defaults to True off-TPU.
    """
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    bt = block_t or _auto_block(x.shape[0], 512)
    bv = block_v or _auto_block(w.shape[1], 512, floor=128)
    return _fused_cross_entropy(x, w, targets, bt, bv, interpret)


def _fused_fwd_rule(x, w, targets, block_t, block_v, interpret):
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    nll, lse = _fused_fwd(x, w, targets, block_t, block_v, interpret)
    return nll, (x, w, targets, lse)


def _fused_bwd_rule(block_t, block_v, interpret, res, g):
    """Backward in plain XLA, recomputing the logits ONCE.

    A fully-Pallas backward (dx kernel + dW kernel, each recomputing
    its logits tile — the flash-attention decomposition) was built and
    MEASURED SLOWER on the 1B bench: CE's cost IS the matmul, so two
    recomputes (4 total matmul units vs autodiff's 3) overwhelm the
    HBM passes they save — d=1536's flops/byte ratio keeps that true
    at every vocab size. The winning split: Pallas forward (logits
    tiles never leave VMEM — that pass was ~60% softmax/materialization
    overhead) + one XLA recompute feeding both grad matmuls through a
    bf16 P (one materialized [T, V] round trip, half the fp32 bytes,
    and exactly the dX/dW matmuls autodiff would run).
    """
    del block_t, block_v, interpret
    x, w, targets, lse = res
    t = x.shape[0]
    v = w.shape[1]
    if v <= ONE_SHOT_BWD_MAX_VOCAB:
        # One-shot recompute: a single fp32 [T, V] round trip.
        logits = (x @ w).astype(jnp.float32)
        p = jnp.exp(logits - lse)                   # lse: [T, 1]
        p = p.at[jnp.arange(t), targets].add(-1.0)
        p = (p * g.astype(jnp.float32)[:, None]).astype(x.dtype)
        dx = p @ w.T
        dw = x.T @ p
        return dx.astype(x.dtype), dw.astype(w.dtype), None
    # Large vocab: the one-shot fp32 logits alone are 6+ GB at
    # Llama-3's 128k — reuse the chunked backward (same math, [T, C]
    # live at a time).
    c = _auto_block(v, 8192, floor=128)
    return _ce_bwd(v // c, (x, w, targets, lse[:, 0]), g)


_fused_cross_entropy.defvjp(_fused_fwd_rule, _fused_bwd_rule)
