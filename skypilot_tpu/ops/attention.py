"""Attention: dense reference + Pallas TPU flash-attention kernel.

The hot op of every model in the framework. Two implementations with one
numerically-identical contract (inputs [batch, heads, seq, head_dim], GQA
via fewer KV heads):

- ``dense_attention``: O(seq^2)-memory einsum+softmax. XLA fuses this well;
  it is the differentiable training fallback and the ground truth in tests.
- ``flash_attention``: Pallas kernel, online-softmax over KV blocks, causal
  block skipping, fp32 accumulators, O(seq) memory. Forward only; its
  custom VJP recomputes through the dense path (a dedicated backward
  kernel is the planned next step — see ROADMAP).

Kernel design notes (per /opt/skills/guides/pallas_guide.md):
- grid (batch, q_heads, seq/block_q); K/V blocks for the mapped KV head are
  resident in VMEM; the inner fori_loop walks KV blocks with an early upper
  bound under causality (skips fully-masked blocks, ~2x for causal).
- GQA is folded into the BlockSpec index_map (head -> head // group), so no
  KV replication is materialized in HBM.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
_NEG_INF = -1e30


def dense_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    *, causal: bool = True,
                    sm_scale: Optional[float] = None) -> jnp.ndarray:
    """Reference attention. q: [b, hq, s, d]; k/v: [b, hkv, s, d]."""
    b, hq, s, d = q.shape
    hkv = k.shape[1]
    if sm_scale is None:
        sm_scale = d ** -0.5
    if hkv != hq:
        assert hq % hkv == 0
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    logits = jnp.einsum('bhqd,bhkd->bhqk', q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if causal:
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum('bhqk,bhkd->bhqd', probs, v)


# ---------------------------------------------------------------------------
# Pallas flash attention (forward)
# ---------------------------------------------------------------------------
def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *,
                      sm_scale: float, causal: bool,
                      block_q: int, block_k: int, seq_len: int):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale    # [block_q, d]
    head_dim = q.shape[-1]

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        # Last KV block that any row of this Q block can see.
        upper = jax.lax.div((qi + 1) * block_q + block_k - 1, block_k)
        upper = jnp.minimum(upper, num_k_blocks)
    else:
        upper = num_k_blocks

    def body(j, carry):
        acc, m, l = carry
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32)                                   # [block_k, d]
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    init = (
        jnp.zeros((block_q, head_dim), jnp.float32),
        jnp.full((block_q, 1), _NEG_INF, jnp.float32),
        jnp.zeros((block_q, 1), jnp.float32),
    )
    acc, _, l = jax.lax.fori_loop(0, upper, body, init)
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   causal: bool, sm_scale: float,
                   block_q: int, block_k: int,
                   interpret: bool) -> jnp.ndarray:
    b, hq, s, d = q.shape
    hkv = k.shape[1]
    group = hq // hkv
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    assert s % block_q == 0 and s % block_k == 0, (
        f'seq_len {s} must be a multiple of block sizes '
        f'({block_q}, {block_k})')
    grid = (b, hq, s // block_q)
    kernel = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_len=s)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s, d),
                         lambda bi, hi, qi, g=group: (bi, hi // g, 0, 0)),
            pl.BlockSpec((1, 1, s, d),
                         lambda bi, hi, qi, g=group: (bi, hi // g, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, causal, sm_scale, block_q, block_k,
                     interpret):
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret)


def _flash_fwd_rule(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                         interpret)
    return out, (q, k, v)


def _flash_bwd_rule(causal, sm_scale, block_q, block_k, interpret,
                    residuals, g):
    # Recompute-through-dense backward: correct, O(s^2) transient memory.
    # A blocked Pallas backward kernel replaces this (ROADMAP).
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: dense_attention(q_, k_, v_, causal=causal,
                                           sm_scale=sm_scale), q, k, v)
    return vjp(g)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Flash attention. q: [b, hq, s, d]; k/v: [b, hkv, s, d] (GQA).

    `interpret` defaults to True off-TPU so tests run on CPU.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    return _flash_attention(q, k, v, causal, sm_scale, block_q, block_k,
                            interpret)


def attention(q, k, v, *, causal: bool = True,
              sm_scale: Optional[float] = None,
              impl: str = 'auto') -> jnp.ndarray:
    """Dispatch: 'dense', 'flash', or 'auto' (flash on TPU when shapes
    allow, else dense)."""
    if impl == 'dense':
        return dense_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    if impl == 'flash':
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    s = q.shape[2]
    on_tpu = jax.default_backend() == 'tpu'
    if on_tpu and s % 128 == 0 and s >= 256:
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                               block_q=min(DEFAULT_BLOCK_Q, s),
                               block_k=min(DEFAULT_BLOCK_K, s))
    return dense_attention(q, k, v, causal=causal, sm_scale=sm_scale)
