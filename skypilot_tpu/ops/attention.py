"""Attention: dense reference + Pallas TPU flash-attention kernel.

The hot op of every model in the framework. Two implementations with one
numerically-identical contract (inputs [batch, heads, seq, head_dim], GQA
via fewer KV heads):

- ``dense_attention``: O(seq^2)-memory einsum+softmax. XLA fuses this well;
  it is the differentiable training fallback and the ground truth in tests.
- ``flash_attention``: Pallas kernels, online-softmax over KV blocks, causal
  block skipping, fp32 accumulators, O(seq) memory — forward AND backward
  (FlashAttention-2 style: forward saves the per-row logsumexp; backward
  runs a dq kernel gridded over Q blocks and a dk/dv kernel gridded over
  KV blocks, each recomputing P from the saved statistics instead of
  materializing the O(s^2) probability matrix).

Kernel design notes (per /opt/skills/guides/pallas_guide.md):
- grid (batch, q_heads, seq/block_q); K/V blocks for the mapped KV head are
  resident in VMEM; the inner fori_loop walks KV blocks with an early upper
  bound under causality (skips fully-masked blocks, ~2x for causal).
- GQA is folded into the BlockSpec index_map (head -> head // group), so no
  KV replication is materialized in HBM.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
_NEG_INF = -1e30


def dense_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    *, causal: bool = True,
                    sm_scale: Optional[float] = None) -> jnp.ndarray:
    """Reference attention. q: [b, hq, s, d]; k/v: [b, hkv, s, d]."""
    b, hq, s, d = q.shape
    hkv = k.shape[1]
    if sm_scale is None:
        sm_scale = d ** -0.5
    if hkv != hq:
        assert hq % hkv == 0
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    logits = jnp.einsum('bhqd,bhkd->bhqk', q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if causal:
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum('bhqk,bhkd->bhqd', probs, v)


# ---------------------------------------------------------------------------
# Pallas flash attention (forward)
# ---------------------------------------------------------------------------
def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      sm_scale: float, causal: bool,
                      block_q: int, block_k: int, seq_len: int):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale    # [block_q, d]
    head_dim = q.shape[-1]

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        # Last KV block that any row of this Q block can see.
        upper = jax.lax.div((qi + 1) * block_q + block_k - 1, block_k)
        upper = jnp.minimum(upper, num_k_blocks)
    else:
        upper = num_k_blocks

    def body(j, carry):
        acc, m, l = carry
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32)                                   # [block_k, d]
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    init = (
        jnp.zeros((block_q, head_dim), jnp.float32),
        jnp.full((block_q, 1), _NEG_INF, jnp.float32),
        jnp.zeros((block_q, 1), jnp.float32),
    )
    acc, m, l = jax.lax.fori_loop(0, upper, body, init)
    l = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    # Per-row softmax statistic for the backward pass: lse = m + log(l)
    # lets both bwd kernels rebuild P = exp(S - lse) blockwise. Stored as
    # [b, hq, 1, s]: TPU blocks need their last two dims (8,128)-divisible
    # or equal to the array dims, which (1, block_q) satisfies.
    lse_ref[0, 0, 0] = (m + jnp.log(l))[:, 0]


def _flash_forward(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   causal: bool, sm_scale: float,
                   block_q: int, block_k: int,
                   interpret: bool):
    b, hq, s, d = q.shape
    hkv = k.shape[1]
    group = hq // hkv
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    assert s % block_q == 0 and s % block_k == 0, (
        f'seq_len {s} must be a multiple of block sizes '
        f'({block_q}, {block_k})')
    grid = (b, hq, s // block_q)
    kernel = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_len=s)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s, d),
                         lambda bi, hi, qi, g=group: (bi, hi // g, 0, 0)),
            pl.BlockSpec((1, 1, s, d),
                         lambda bi, hi, qi, g=group: (bi, hi // g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, 1, block_q),
                         lambda bi, hi, qi: (bi, hi, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, hq, 1, s), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# Pallas flash attention (backward) — FlashAttention-2 decomposition:
#   delta_i = rowsum(dO_i * O_i)                  (precomputed, fused by XLA)
#   P_ij    = exp(S_ij - lse_i)
#   dV_j    = sum_i P_ij^T @ dO_i
#   dS_ij   = P_ij * (dO_i @ V_j^T - delta_i)
#   dQ_i    = sum_j dS_ij @ K_j * sm_scale
#   dK_j    = sum_i dS_ij^T @ Q_i * sm_scale
# dQ is gridded over Q blocks (rows), dK/dV over KV blocks (columns), so
# every accumulator lives in registers/VMEM and nothing O(s^2) hits HBM.
# ---------------------------------------------------------------------------
def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, sm_scale: float, causal: bool,
                         block_q: int, block_k: int, seq_len: int):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)               # [bq, d]
    do = do_ref[0, 0].astype(jnp.float32)             # [bq, d]
    lse = lse_ref[0, 0, 0][:, None]                   # [bq, 1]
    delta = delta_ref[0, 0, 0][:, None]               # [bq, 1]

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        upper = jax.lax.div((qi + 1) * block_q + block_k - 1, block_k)
        upper = jnp.minimum(upper, num_k_blocks)
    else:
        upper = num_k_blocks

    def body(j, dq):
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32)                               # [bk, d]
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)                           # [bq, bk]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, upper, body, jnp.zeros((block_q, q.shape[-1]), jnp.float32))
    dq_ref[0, 0] = (dq * sm_scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, sm_scale: float, causal: bool,
                          block_q: int, block_k: int, seq_len: int):
    kj = pl.program_id(2)
    k_blk = k_ref[0, 0].astype(jnp.float32)           # [bk, d]
    v_blk = v_ref[0, 0].astype(jnp.float32)           # [bk, d]
    head_dim = k_blk.shape[-1]

    num_q_blocks = pl.cdiv(seq_len, block_q)
    # First Q block whose rows can see any column of this KV block.
    lower = jax.lax.div(kj * block_k, block_q) if causal else 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(
            jnp.float32)                               # [bq, d]
        do = do_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(
            jnp.float32)
        lse = lse_ref[0, 0, 0, pl.ds(i * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, 0, pl.ds(i * block_q, block_q)][:, None]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # [bq, bk]
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)                           # [bq, bk]
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, d]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        ds = p * (dp - delta)
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, d]
        return dk_new, dv_new

    dk, dv = jax.lax.fori_loop(
        lower, num_q_blocks, body,
        (jnp.zeros((block_k, head_dim), jnp.float32),
         jnp.zeros((block_k, head_dim), jnp.float32)))
    dk_ref[0, 0] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, sm_scale,
                    block_q, block_k, interpret):
    b, hq, s, d = q.shape
    hkv = k.shape[1]
    group = hq // hkv
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    # delta = rowsum(dO * O): one fused elementwise+reduce, O(s) memory.
    # Shaped [b, hq, 1, s] to match lse's TPU-friendly block layout.
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, :, None, :]

    kw = dict(sm_scale=sm_scale, causal=causal, block_q=block_q,
              block_k=block_k, seq_len=s)
    q_spec_blk = pl.BlockSpec((1, 1, block_q, d),
                              lambda bi, hi, qi: (bi, hi, qi, 0))
    kv_spec_full = pl.BlockSpec(
        (1, 1, s, d), lambda bi, hi, qi, g_=group: (bi, hi // g_, 0, 0))
    row_spec_blk = pl.BlockSpec((1, 1, 1, block_q),
                                lambda bi, hi, qi: (bi, hi, 0, qi))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **kw),
        grid=(b, hq, s // block_q),
        in_specs=[q_spec_blk, kv_spec_full, kv_spec_full, q_spec_blk,
                  row_spec_blk, row_spec_blk],
        out_specs=q_spec_blk,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    q_spec_full = pl.BlockSpec((1, 1, s, d),
                               lambda bi, hi, kj: (bi, hi, 0, 0))
    kv_spec_blk = pl.BlockSpec(
        (1, 1, block_k, d), lambda bi, hi, kj, g_=group: (bi, hi // g_,
                                                          kj, 0))
    row_spec_full = pl.BlockSpec((1, 1, 1, s),
                                 lambda bi, hi, kj: (bi, hi, 0, 0))
    dkv_out_spec = pl.BlockSpec((1, 1, block_k, d),
                                lambda bi, hi, kj: (bi, hi, kj, 0))
    # dK/dV are produced per Q head ([b, hq, s, d]) and group-summed below:
    # keeping the kernel gridded over Q heads avoids cross-program
    # accumulation; the sum is one XLA reduce over a transient no larger
    # than dQ itself.
    dk_q, dv_q = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **kw),
        grid=(b, hq, s // block_k),
        in_specs=[q_spec_full, kv_spec_blk, kv_spec_blk, q_spec_full,
                  row_spec_full, row_spec_full],
        out_specs=[dkv_out_spec, dkv_out_spec],
        out_shape=[jax.ShapeDtypeStruct((b, hq, s, d), k.dtype),
                   jax.ShapeDtypeStruct((b, hq, s, d), v.dtype)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    if group > 1:
        dk = dk_q.reshape(b, hkv, group, s, d).sum(axis=2)
        dv = dv_q.reshape(b, hkv, group, s, d).sum(axis=2)
    else:
        dk, dv = dk_q, dv_q
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, causal, sm_scale, block_q, block_k,
                     interpret):
    out, _ = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                            interpret)
    return out


def _flash_fwd_rule(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                              interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, sm_scale, block_q, block_k, interpret,
                    residuals, g):
    q, k, v, out, lse = residuals
    return _flash_backward(q, k, v, out, lse, g, causal, sm_scale,
                           block_q, block_k, interpret)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Flash attention. q: [b, hq, s, d]; k/v: [b, hkv, s, d] (GQA).

    `interpret` defaults to True off-TPU so tests run on CPU.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != 'tpu'
    return _flash_attention(q, k, v, causal, sm_scale, block_q, block_k,
                            interpret)


def _fit_block(want: int, seq_len: int) -> int:
    """Largest tile <= `want` that DIVIDES seq_len (the kernels require
    it), preferring lane-aligned multiples of 128. seq 768 with a 512
    request fits 384; non-multiple-of-128 seqs fall back to the gcd."""
    import math
    b = min(want, seq_len)
    while b > 128 and seq_len % b:
        b -= 128
    if seq_len % b:
        b = math.gcd(b, seq_len)
    return max(b, 1)


def attention(q, k, v, *, causal: bool = True,
              sm_scale: Optional[float] = None,
              impl: str = 'auto',
              block_q: Optional[int] = None,
              block_k: Optional[int] = None) -> jnp.ndarray:
    """Dispatch: 'dense', 'flash', or 'auto' (flash on TPU when shapes
    allow, else dense). block_q/block_k override the flash tile sizes
    (clamped to seq; None → defaults)."""
    if impl == 'dense':
        return dense_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    s = q.shape[2]
    bq = _fit_block(block_q or DEFAULT_BLOCK_Q, s)
    bk = _fit_block(block_k or DEFAULT_BLOCK_K, s)
    if impl == 'flash':
        if min(bq, bk) < 128 and s >= 128:
            # The gcd fallback would hand the kernel sub-lane tiles (a
            # pathological grid); explicit flash on such a seq is a
            # user error, not something to quietly degrade.
            raise ValueError(
                f'flash attention needs seq_len divisible by a >=128 '
                f'tile; got seq_len={s} (fitted tiles {bq}x{bk}). Pad '
                f'the sequence or use impl="dense"/"auto".')
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                               block_q=bq, block_k=bk)
    on_tpu = jax.default_backend() == 'tpu'
    if on_tpu and s % 128 == 0 and s >= 256:
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                               block_q=bq, block_k=bk)
    return dense_attention(q, k, v, causal=causal, sm_scale=sm_scale)
