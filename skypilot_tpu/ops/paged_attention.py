"""Paged attention: Pallas TPU kernels over a block-table KV cache.

The mechanism behind the serving engines the reference delegates to
(reference ``llm/vllm`` example YAMLs): the KV cache is a pool of
fixed-size **pages** shared by all slots, each slot owning a list of
page ids (its *block table*). HBM then scales with tokens-in-flight,
not slots x max_seq_len, and one engine serves mixed 2k/16k prompts
without pricing every slot at 16k.

Layout (per layer):

    k_pages, v_pages: [n_kv_heads, n_pages, page_size, head_dim]
    block_tables:     [n_slots, max_pages] int32  (page ids)
    lengths:          [n_slots] int32             (tokens per slot)

Kernel design (per /opt/skills/guides/pallas_guide.md):

- The block table and lengths ride **scalar prefetch**
  (``PrefetchScalarGridSpec``): they land in SMEM before the pipeline
  starts, so the K/V BlockSpec ``index_map`` can translate (slot, page
  step) -> physical page id. The pages a slot touches are
  non-contiguous in HBM; the pipeline gathers them page by page.
- Grid = (slots, kv_heads, max_pages) — but a slot only pays DMA for
  the pages it OWNS: for steps past the slot's last page the index_map
  re-maps to the previous step's page, and Pallas skips the fetch when
  consecutive steps map the same block (the revisiting-block rule the
  pipeline already implements). The kernel body masks those steps out.
  Decode bandwidth is therefore sum(ceil(len_i/page)) pages, the whole
  point of paging.
- Online softmax across the page axis (sequential innermost grid dim on
  TPU), fp32 accumulators in VMEM scratch that persist across the page
  steps of one (slot, head) and reinitialize at page 0.

Two entry points, one numerically-identical reference each:

- ``paged_decode_attention``: one query token per slot (the decode hot
  path; HBM-bandwidth-bound).
- ``paged_prefill_attention``: a C-token chunk of one slot's prompt
  attending to the slot's cached prefix + itself (causal) — the tiled
  replacement for the dense [C, S] einsum, O(C*len) instead of O(C*S).

GQA is native: q carries [group] query heads per KV head and the
kernels never replicate K/V.

int8 KV pages (``kv_dtype=int8``): pages hold int8 values plus one
fp32 absmax scale per cached token row per KV head
(``k_scales/v_scales: [hkv, P, page]``), pool-aligned with the pages.
Quantization happens ON WRITE (each row is quantized independently, so
appending never rescales earlier rows) and dequantization happens IN
KERNEL (one multiply per page row before the matmul) — the HBM stream
is int8, roughly doubling the resident pages per chip. Every entry
point takes optional ``k_scales``/``v_scales``; None means the bf16
path, which is bit-for-bit the pre-quantization code.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _interpret_default(interpret: Optional[bool]) -> bool:
    if interpret is None:
        return jax.default_backend() != 'tpu'
    return interpret


# ---------------------------------------------------------------------------
# int8 row quantization (quant-on-write / dequant-in-kernel)
# ---------------------------------------------------------------------------
def quantize_rows(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-row int8 quantization over the trailing head_dim
    axis: returns ``(values int8[...], scales f32[...[:-1]])`` with
    ``x ≈ values * scales[..., None]``. Deterministic round-to-nearest
    (NOT stochastic): the same K/V row must quantize identically on
    every host and every re-prefill, or preemption-resume and multihost
    lockstep would diverge. An all-zero row gets scale 1.0 so the
    dequant never divides by (or multiplies garbage into) zero."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def _deq(pages: jnp.ndarray, scales: Optional[jnp.ndarray]
         ) -> jnp.ndarray:
    """Reference-path dequant: fp32 values, scale applied per row."""
    out = pages.astype(jnp.float32)
    if scales is not None:
        out = out * scales.astype(jnp.float32)[..., None]
    return out


# ---------------------------------------------------------------------------
# Reference implementations (ground truth in tests; CPU-friendly)
# ---------------------------------------------------------------------------
def paged_decode_attention_reference(
        q: jnp.ndarray, k_pages: jnp.ndarray, v_pages: jnp.ndarray,
        block_tables: jnp.ndarray, lengths: jnp.ndarray,
        *, sm_scale: Optional[float] = None,
        k_scales: Optional[jnp.ndarray] = None,
        v_scales: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """q: [slots, hkv, group, hd]; pages: [hkv, P, page, hd];
    block_tables: [slots, maxp]; lengths: [slots]. Attends to positions
    < lengths[slot]. Returns [slots, hkv, group, hd] fp32."""
    slots, hkv, group, hd = q.shape
    page = k_pages.shape[2]
    maxp = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = hd ** -0.5
    # Gather each slot's pages: [slots, hkv, maxp*page, hd].
    k = _deq(k_pages, k_scales)[:, block_tables]
    v = _deq(v_pages, v_scales)[:, block_tables]
    k = k.transpose(1, 0, 2, 3, 4).reshape(slots, hkv, maxp * page, hd)
    v = v.transpose(1, 0, 2, 3, 4).reshape(slots, hkv, maxp * page, hd)
    s = jnp.einsum('bkgd,bksd->bkgs', q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    pos = jnp.arange(maxp * page)[None, None, None, :]
    s = jnp.where(pos < lengths[:, None, None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bkgs,bksd->bkgd', p, v.astype(jnp.float32))


def paged_prefill_attention_reference(
        q: jnp.ndarray, k_pages: jnp.ndarray, v_pages: jnp.ndarray,
        table_row: jnp.ndarray, offset: jnp.ndarray,
        true_len: jnp.ndarray, *,
        sm_scale: Optional[float] = None,
        k_scales: Optional[jnp.ndarray] = None,
        v_scales: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """q: [C, hkv, group, hd] (chunk queries of ONE slot, global
    positions offset..offset+C); pages: [hkv, P, page, hd]; table_row:
    [maxp]. Causal over prefix+chunk: query at global position i attends
    to cached positions <= i. Returns [C, hkv, group, hd] fp32."""
    C, hkv, group, hd = q.shape
    page = k_pages.shape[2]
    maxp = table_row.shape[0]
    if sm_scale is None:
        sm_scale = hd ** -0.5
    k = _deq(k_pages, k_scales)[:, table_row].reshape(
        hkv, maxp * page, hd)
    v = _deq(v_pages, v_scales)[:, table_row].reshape(
        hkv, maxp * page, hd)
    s = jnp.einsum('ckgd,ksd->ckgs', q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    qpos = offset + jnp.arange(C)
    kpos = jnp.arange(maxp * page)
    mask = kpos[None, :] <= qpos[:, None]       # [C, S]
    s = jnp.where(mask[:, None, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('ckgs,ksd->ckgd', p, v.astype(jnp.float32))


# ---------------------------------------------------------------------------
# Decode kernel
# ---------------------------------------------------------------------------
def _decode_kernel(tables_ref, lengths_ref, q_ref, k_ref, v_ref, *refs,
                   page_size: int, sm_scale: float, max_pages: int,
                   hkv: int, quantized: bool):
    if quantized:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = refs
    else:
        o_ref, acc_ref, m_ref, l_ref = refs
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    p = pl.program_id(1)
    del tables_ref  # consumed by the index_maps
    length = lengths_ref[b]
    n_pages = pl.cdiv(length, page_size)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(p < n_pages)
    def _accumulate():
        pos = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        valid = pos < length
        # All KV heads of the page in one grid step (an unrolled loop of
        # hkv small MXU matmuls): 8x fewer grid steps and 8x larger
        # DMAs than a per-head grid — the fixed per-step cost, not the
        # bytes, dominates paged decode.
        for h in range(hkv):
            q = q_ref[0, h].astype(jnp.float32) * sm_scale  # [group, hd]
            k = k_ref[h, 0].astype(jnp.float32)             # [page, hd]
            v = v_ref[h, 0].astype(jnp.float32)
            if quantized:
                # Dequant in kernel: the HBM stream stays int8; the
                # per-row fp32 scale multiplies once in VMEM.
                k = k * ks_ref[h, 0][:, None]
                v = v * vs_ref[h, 0][:, None]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)         # [group, page]
            s = jnp.where(valid, s, _NEG_INF)
            m_prev = m_ref[h]
            m_new = jnp.maximum(m_prev,
                                jnp.max(s, axis=-1, keepdims=True))
            pr = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_ref[h] = l_ref[h] * alpha + jnp.sum(pr, axis=-1,
                                                  keepdims=True)
            acc_ref[h] = acc_ref[h] * alpha + jax.lax.dot_general(
                pr, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[h] = m_new

    @pl.when(p == max_pages - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def paged_decode_attention(q: jnp.ndarray, k_pages: jnp.ndarray,
                           v_pages: jnp.ndarray,
                           block_tables: jnp.ndarray,
                           lengths: jnp.ndarray, *,
                           sm_scale: Optional[float] = None,
                           interpret: Optional[bool] = None,
                           impl: str = 'auto',
                           k_scales: Optional[jnp.ndarray] = None,
                           v_scales: Optional[jnp.ndarray] = None
                           ) -> jnp.ndarray:
    """One decode token for every slot over the paged cache.

    q: [slots, hkv, group, hd]; k_pages/v_pages: [hkv, P, page, hd];
    block_tables: [slots, maxp] int32; lengths: [slots] int32 (the
    kernel attends to positions < length — callers that write the new
    token's K/V first pass the already-bumped length, mirroring the
    dense decode path's write-then-attend contract).
    k_scales/v_scales: [hkv, P, page] f32 row scales on the int8
    flavor (forces the native kernel — the library kernel has no
    dequant hook); None = bf16 pages, the pre-quantization path.

    impl: 'native' runs this module's grid kernel everywhere; 'jax'
    runs jax's tuned JetStream decode kernel (same page layout —
    convergent design — but an internal double-buffered DMA loop
    instead of grid steps, measured ~1.6x faster on v5e); 'auto' picks
    'jax' on real TPU and 'native' in interpret mode. The native kernel
    is always the ground truth in tests.
    """
    slots, hkv, group, hd = q.shape
    quantized = k_scales is not None
    interpret_resolved = _interpret_default(interpret)
    if impl == 'auto':
        # The library kernel needs lane-aligned blocks (hd multiple of
        # 128; its output block carries `group` in the sublane dim, so
        # tiny test models fall back to the native kernel).
        jax_ok = (hd % 128 == 0 and k_pages.shape[2] % 8 == 0
                  and not quantized)
        impl = ('jax' if jax_ok and not interpret_resolved
                else 'native')
    if impl == 'jax' and quantized:
        raise ValueError("impl='jax' has no int8 dequant hook; use "
                         "the native kernel for kv_dtype=int8")
    if impl == 'jax' and not interpret_resolved:
        from jax.experimental.pallas.ops.tpu.paged_attention import (
            paged_attention as jax_paged_attention)
        if sm_scale is not None and sm_scale != hd ** -0.5:
            raise ValueError(
                "impl='jax' supports only the default 1/sqrt(hd) scale")
        # The library kernel computes raw q·k (no internal softmax
        # scale), so fold 1/sqrt(hd) into q first.
        qf = q.reshape(slots, hkv * group, hd)
        maxp = block_tables.shape[1]
        ppcb = next(f for f in (8, 4, 2, 1) if maxp % f == 0)
        out = jax_paged_attention(
            (qf * (hd ** -0.5)).astype(k_pages.dtype),
            k_pages, v_pages, lengths, block_tables,
            pages_per_compute_block=ppcb)
        return out.reshape(slots, hkv, group, hd).astype(jnp.float32)
    page_size = k_pages.shape[2]
    max_pages = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = hd ** -0.5
    interpret = _interpret_default(interpret)

    def _page_index(b, p, tables, lengths_):
        # Pages past the slot's frontier re-map to the slot's LAST real
        # page: consecutive grid steps then address the same block and
        # the pipeline skips the fetch (the "revisiting block" rule) —
        # dead steps cost neither DMA nor bandwidth.
        n_pages = jax.lax.div(lengths_[b] + page_size - 1, page_size)
        j = jnp.minimum(p, jnp.maximum(n_pages - 1, 0))
        return (0, tables[b, j], 0, 0)

    def _scale_index(*args):
        # Scales live beside their pages: same index map minus the
        # head_dim axis, DERIVED so a clamp-rule fix can never land on
        # the value DMA and miss the scale DMA.
        return _page_index(*args)[:-1]

    in_specs = [
        pl.BlockSpec((1, hkv, group, hd),
                     lambda b, p, *_: (b, 0, 0, 0)),
        pl.BlockSpec((hkv, 1, page_size, hd), _page_index),
        pl.BlockSpec((hkv, 1, page_size, hd), _page_index),
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        in_specs += [pl.BlockSpec((hkv, 1, page_size), _scale_index),
                     pl.BlockSpec((hkv, 1, page_size), _scale_index)]
        operands += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, max_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, hkv, group, hd),
                               lambda b, p, *_: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hkv, group, hd), jnp.float32),
            pltpu.VMEM((hkv, group, 1), jnp.float32),
            pltpu.VMEM((hkv, group, 1), jnp.float32),
        ],
    )
    kernel = functools.partial(_decode_kernel, page_size=page_size,
                               sm_scale=sm_scale, max_pages=max_pages,
                               hkv=hkv, quantized=quantized)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, hkv, group, hd),
                                       jnp.float32),
        interpret=interpret,
    )(block_tables, lengths, *operands)


# ---------------------------------------------------------------------------
# Prefill-chunk kernel
# ---------------------------------------------------------------------------
def _prefill_kernel(table_ref, meta_ref, q_ref, *refs,
                    page_size: int, sm_scale: float, n_groups: int,
                    chunk: int, fan: int, quantized: bool):
    """One grid step processes `fan` pages (each its own scalar-
    prefetched in_spec/DMA): the fixed per-grid-step cost — not the
    bytes — dominates a one-page-per-step kernel, so fanning pages into
    a step amortizes it `fan`-fold."""
    k_refs = refs[:fan]
    v_refs = refs[fan:2 * fan]
    refs = refs[2 * fan:]
    if quantized:
        ks_refs = refs[:fan]
        vs_refs = refs[fan:2 * fan]
        refs = refs[2 * fan:]
    else:
        ks_refs = vs_refs = None
    o_ref = refs[0]
    acc_ref, m_ref, l_ref = refs[1:]
    g = pl.program_id(1)
    del table_ref
    offset = meta_ref[0]
    true_len = meta_ref[1]
    total = offset + true_len                   # slot frontier
    n_pages = pl.cdiv(total, page_size)

    @pl.when(g == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # q: [chunk*group, hd] (queries x group heads flattened so the MXU
    # sees one [C*g, page] matmul per page).
    q = q_ref[0].astype(jnp.float32) * sm_scale

    def _accumulate_page(f: int):
        p = g * fan + f

        @pl.when(p < n_pages)
        def _do():
            k = k_refs[f][0, 0].astype(jnp.float32)   # [page, hd]
            v = v_refs[f][0, 0].astype(jnp.float32)
            if quantized:
                k = k * ks_refs[f][0, 0][:, None]
                v = v * vs_refs[f][0, 0][:, None]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)   # [C*g, page]
            # Causality in GLOBAL positions: row r is query
            # offset + r//g; column c is cached position p*page + c.
            qpos = offset + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0) // (s.shape[0] // chunk)
            kpos = p * page_size + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(kpos <= qpos, s, _NEG_INF)
            m_prev = m_ref[...]
            m_new = jnp.maximum(m_prev,
                                jnp.max(s, axis=-1, keepdims=True))
            pr = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_ref[...] = l_ref[...] * alpha + jnp.sum(
                pr, axis=-1, keepdims=True)
            acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
                pr, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[...] = m_new

    for f in range(fan):
        _accumulate_page(f)

    @pl.when(g == n_groups - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def paged_prefill_attention(q: jnp.ndarray, k_pages: jnp.ndarray,
                            v_pages: jnp.ndarray,
                            table_row: jnp.ndarray,
                            offset: jnp.ndarray,
                            true_len: jnp.ndarray, *,
                            sm_scale: Optional[float] = None,
                            interpret: Optional[bool] = None,
                            pages_per_step: int = 8,
                            k_scales: Optional[jnp.ndarray] = None,
                            v_scales: Optional[jnp.ndarray] = None
                            ) -> jnp.ndarray:
    """One prompt chunk of ONE slot attending over its paged prefix.

    q: [C, hkv, group, hd] (global positions offset..offset+C-1, the
    chunk's K/V already written into the pages); table_row: [maxp]
    int32; offset/true_len: scalars. Tokens beyond true_len are pad —
    their rows compute garbage the caller discards. Returns
    [C, hkv, group, hd] fp32, O(C * len) bandwidth via the
    skip-dead-pages index_maps, with `pages_per_step` pages fanned into
    each grid step to amortize the fixed step cost.
    """
    C, hkv, group, hd = q.shape
    page_size = k_pages.shape[2]
    max_pages = table_row.shape[0]
    fan = max(1, min(pages_per_step, max_pages))
    n_groups = -(-max_pages // fan)
    if sm_scale is None:
        sm_scale = hd ** -0.5
    interpret = _interpret_default(interpret)
    # [hkv, C*group, hd]: queries x group flattened per KV head, group
    # fastest so row r maps to query r // group (contiguous rows share
    # a query position -> the causal iota stays a cheap div).
    qf = q.transpose(1, 0, 2, 3).reshape(hkv, C * group, hd)
    # meta in SMEM: [offset, true_len].
    meta = jnp.stack([jnp.asarray(offset, jnp.int32),
                      jnp.asarray(true_len, jnp.int32)])

    quantized = k_scales is not None

    def _page_index(f):
        def index(h, g, table, meta_):
            total = meta_[0] + meta_[1]
            n_pages = jax.lax.div(total + page_size - 1, page_size)
            j = jnp.minimum(g * fan + f, jnp.maximum(n_pages - 1, 0))
            return (h, table[j], 0, 0)
        return index

    def _scale_index(f):
        # Derived from the page map (minus the head_dim axis): value
        # and scale DMA targets cannot desynchronize.
        page_f = _page_index(f)

        def index(*args):
            return page_f(*args)[:-1]
        return index

    page_spec = [pl.BlockSpec((1, 1, page_size, hd), _page_index(f))
                 for f in range(fan)]
    in_specs = [
        pl.BlockSpec((1, C * group, hd),
                     lambda h, g, *_: (h, 0, 0)),
        *page_spec,          # k pages, fan of them
        *page_spec,          # v pages
    ]
    operands = [qf, *([k_pages] * fan), *([v_pages] * fan)]
    if quantized:
        scale_spec = [pl.BlockSpec((1, 1, page_size), _scale_index(f))
                      for f in range(fan)]
        in_specs += [*scale_spec, *scale_spec]
        operands += [*([k_scales] * fan), *([v_scales] * fan)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(hkv, n_groups),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, C * group, hd),
                               lambda h, g, *_: (h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((C * group, hd), jnp.float32),
            pltpu.VMEM((C * group, 1), jnp.float32),
            pltpu.VMEM((C * group, 1), jnp.float32),
        ],
    )
    kernel = functools.partial(_prefill_kernel, page_size=page_size,
                               sm_scale=sm_scale, n_groups=n_groups,
                               chunk=C, fan=fan, quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((hkv, C * group, hd),
                                       jnp.float32),
        interpret=interpret,
    )(table_row, meta, *operands)
    return out.reshape(hkv, C, group, hd).transpose(1, 0, 2, 3)


# ---------------------------------------------------------------------------
# Verify kernel (speculative decoding): R query tokens per slot
# ---------------------------------------------------------------------------
def paged_verify_attention_reference(
        q: jnp.ndarray, k_pages: jnp.ndarray, v_pages: jnp.ndarray,
        block_tables: jnp.ndarray, lengths: jnp.ndarray,
        *, sm_scale: Optional[float] = None,
        k_scales: Optional[jnp.ndarray] = None,
        v_scales: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """q: [slots, R, hkv, group, hd] — R = spec_k+1 verify queries per
    slot at positions lengths[slot]..lengths[slot]+R-1 (their K/V
    already written, the decode write-then-attend contract). Query i
    attends to positions < lengths[slot] + i + 1 (causal within the
    draft run). Returns [slots, R, hkv, group, hd] fp32."""
    slots, R, hkv, group, hd = q.shape
    page = k_pages.shape[2]
    maxp = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = hd ** -0.5
    k = _deq(k_pages, k_scales)[:, block_tables]
    v = _deq(v_pages, v_scales)[:, block_tables]
    k = k.transpose(1, 0, 2, 3, 4).reshape(slots, hkv, maxp * page, hd)
    v = v.transpose(1, 0, 2, 3, 4).reshape(slots, hkv, maxp * page, hd)
    s = jnp.einsum('brkgd,bksd->brkgs', q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    pos = jnp.arange(maxp * page)
    horizon = (lengths[:, None] + jnp.arange(R)[None, :] + 1)
    valid = pos[None, None, :] < horizon[:, :, None]   # [slots, R, S]
    s = jnp.where(valid[:, :, None, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('brkgs,bksd->brkgd', p, v.astype(jnp.float32))


def _verify_kernel(tables_ref, lengths_ref, q_ref, k_ref, v_ref, *refs,
                   page_size: int, sm_scale: float, max_pages: int,
                   hkv: int, group: int, r_queries: int,
                   quantized: bool):
    """The decode kernel with R queries per (slot, head): rows are
    queries x group flattened (group fastest), each row's causal
    horizon is its query's position — one extra iota/div over the
    decode kernel, the same online-softmax accumulation per page."""
    if quantized:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = refs
    else:
        o_ref, acc_ref, m_ref, l_ref = refs
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    p = pl.program_id(1)
    del tables_ref  # consumed by the index_maps
    length = lengths_ref[b]
    # Pages holding ANY attendable position: the furthest query
    # (r_queries-1) sees positions < length + r_queries.
    n_pages = pl.cdiv(length + r_queries, page_size)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(p < n_pages)
    def _accumulate():
        for h in range(hkv):
            q = q_ref[0, h].astype(jnp.float32) * sm_scale  # [R*g, hd]
            k = k_ref[h, 0].astype(jnp.float32)             # [page, hd]
            v = v_ref[h, 0].astype(jnp.float32)
            if quantized:
                k = k * ks_ref[h, 0][:, None]
                v = v * vs_ref[h, 0][:, None]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)         # [R*g, page]
            kpos = p * page_size + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            qi = jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0) // group
            s = jnp.where(kpos < length + qi + 1, s, _NEG_INF)
            m_prev = m_ref[h]
            m_new = jnp.maximum(m_prev,
                                jnp.max(s, axis=-1, keepdims=True))
            pr = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_ref[h] = l_ref[h] * alpha + jnp.sum(pr, axis=-1,
                                                  keepdims=True)
            acc_ref[h] = acc_ref[h] * alpha + jax.lax.dot_general(
                pr, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[h] = m_new

    @pl.when(p == max_pages - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def paged_verify_attention(q: jnp.ndarray, k_pages: jnp.ndarray,
                           v_pages: jnp.ndarray,
                           block_tables: jnp.ndarray,
                           lengths: jnp.ndarray, *,
                           sm_scale: Optional[float] = None,
                           interpret: Optional[bool] = None,
                           k_scales: Optional[jnp.ndarray] = None,
                           v_scales: Optional[jnp.ndarray] = None
                           ) -> jnp.ndarray:
    """Speculative verify: R = spec_k+1 query tokens for EVERY slot in
    one kernel launch over the paged cache.

    q: [slots, R, hkv, group, hd]; lengths: [slots] int32 — the
    PRE-RUN length (query i sits at position lengths[slot]+i and
    attends to positions < lengths[slot]+i+1; the run's K/V must
    already be written, see ``append_run_pages``). The whole point:
    scoring R candidates streams each owned page through the chip
    ONCE — the same HBM traffic as a single decode step — so accepted
    drafts are nearly free bandwidth-wise. Fully-masked trailing pages
    accumulate exact zeros, so each query's result is bitwise the
    result the decode kernel produces for that position (the
    exact-greedy acceptance rule depends on this).

    Returns [slots, R, hkv, group, hd] fp32.
    """
    slots, R, hkv, group, hd = q.shape
    page_size = k_pages.shape[2]
    max_pages = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = hd ** -0.5
    interpret = _interpret_default(interpret)
    # [slots, hkv, R*group, hd], group fastest: row r is query
    # r // group — same flattening rule as the prefill kernel.
    qf = q.transpose(0, 2, 1, 3, 4).reshape(slots, hkv, R * group, hd)

    quantized = k_scales is not None

    def _page_index(b, p, tables, lengths_):
        # Same revisiting-block rule as decode: steps past the slot's
        # attendable pages re-map to its last real page (no DMA).
        n_pages = jax.lax.div(lengths_[b] + R + page_size - 1,
                              page_size)
        j = jnp.minimum(p, jnp.maximum(n_pages - 1, 0))
        j = jnp.minimum(j, max_pages - 1)
        return (0, tables[b, j], 0, 0)

    def _scale_index(*args):
        # Derived from the page map (minus the head_dim axis): the
        # lengths+R horizon rule can never change on one and not the
        # other.
        return _page_index(*args)[:-1]

    in_specs = [
        pl.BlockSpec((1, hkv, R * group, hd),
                     lambda b, p, *_: (b, 0, 0, 0)),
        pl.BlockSpec((hkv, 1, page_size, hd), _page_index),
        pl.BlockSpec((hkv, 1, page_size, hd), _page_index),
    ]
    operands = [qf, k_pages, v_pages]
    if quantized:
        in_specs += [pl.BlockSpec((hkv, 1, page_size), _scale_index),
                     pl.BlockSpec((hkv, 1, page_size), _scale_index)]
        operands += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, max_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, hkv, R * group, hd),
                               lambda b, p, *_: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hkv, R * group, hd), jnp.float32),
            pltpu.VMEM((hkv, R * group, 1), jnp.float32),
            pltpu.VMEM((hkv, R * group, 1), jnp.float32),
        ],
    )
    kernel = functools.partial(_verify_kernel, page_size=page_size,
                               sm_scale=sm_scale, max_pages=max_pages,
                               hkv=hkv, group=group, r_queries=R,
                               quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, hkv, R * group, hd),
                                       jnp.float32),
        interpret=interpret,
    )(block_tables, lengths, *operands)
    return out.reshape(slots, hkv, R, group, hd).transpose(0, 2, 1, 3, 4)


# ---------------------------------------------------------------------------
# Paged cache writes (pure JAX; XLA lowers to scatters)
# ---------------------------------------------------------------------------
def write_chunk_pages(k_pages: jnp.ndarray, v_pages: jnp.ndarray,
                      k_new: jnp.ndarray, v_new: jnp.ndarray,
                      table_row: jnp.ndarray, offset: jnp.ndarray,
                      k_scales: Optional[jnp.ndarray] = None,
                      v_scales: Optional[jnp.ndarray] = None):
    """Write a C-token chunk's K/V into a slot's pages.

    k_new/v_new: [C, hkv, hd] with C a multiple of page_size and offset
    page-aligned (the engine's chunk cap guarantees both), so the chunk
    covers whole pages: C/page dynamic_update_slice ops at table-looked-
    up page ids, no read-modify-write.

    With ``k_scales``/``v_scales`` (the int8 flavor) the chunk rows are
    quantized on write and the per-row scales land in the pool-aligned
    scale pages; returns ``(k_pages, v_pages, k_scales, v_scales)``
    then, the plain pair otherwise.
    """
    C, hkv, hd = k_new.shape
    page = k_pages.shape[2]
    assert C % page == 0, (C, page)
    quantized = k_scales is not None
    if quantized:
        kc, ksc = quantize_rows(k_new.transpose(1, 0, 2))  # [hkv, C, *]
        vc, vsc = quantize_rows(v_new.transpose(1, 0, 2))
    else:
        kc = k_new.transpose(1, 0, 2).astype(k_pages.dtype)
        vc = v_new.transpose(1, 0, 2).astype(v_pages.dtype)
    first = jax.lax.div(offset, page)
    for i in range(C // page):
        pid = table_row[first + i]
        k_pages = jax.lax.dynamic_update_slice(
            k_pages, kc[:, i * page:(i + 1) * page][:, None],
            (0, pid, 0, 0))
        v_pages = jax.lax.dynamic_update_slice(
            v_pages, vc[:, i * page:(i + 1) * page][:, None],
            (0, pid, 0, 0))
        if quantized:
            k_scales = jax.lax.dynamic_update_slice(
                k_scales, ksc[:, i * page:(i + 1) * page][:, None],
                (0, pid, 0))
            v_scales = jax.lax.dynamic_update_slice(
                v_scales, vsc[:, i * page:(i + 1) * page][:, None],
                (0, pid, 0))
    if quantized:
        return k_pages, v_pages, k_scales, v_scales
    return k_pages, v_pages


def append_run_pages(k_pages: jnp.ndarray, v_pages: jnp.ndarray,
                     k_new: jnp.ndarray, v_new: jnp.ndarray,
                     block_tables: jnp.ndarray, lengths: jnp.ndarray,
                     k_scales: Optional[jnp.ndarray] = None,
                     v_scales: Optional[jnp.ndarray] = None):
    """Append a RUN of R tokens' K/V per slot at positions
    ``lengths[slot] + i`` — the speculative-verify write (input token
    plus padded draft candidates in one step).

    k_new/v_new: [slots, R, hkv, hd]. One scatter per run position,
    chained sequentially. Positions past the slot's block-table
    coverage (padded drafts of a slot the engine capped, inactive
    slots' garbage lanes) redirect to the SINK page 0 — the table
    lookup is clamped and overridden, never allowed to alias a live
    page the way a clamped index would. With scales (int8 flavor) each
    run row is quantized on write and returns a 4-tuple.
    """
    page = k_pages.shape[2]
    maxp = block_tables.shape[1]
    R = k_new.shape[1]
    quantized = k_scales is not None
    for i in range(R):
        pos = lengths + i
        col = pos // page
        valid = col < maxp
        pids = jnp.take_along_axis(
            block_tables, jnp.minimum(col, maxp - 1)[:, None],
            axis=1)[:, 0]
        pids = jnp.where(valid, pids, 0)
        rows = pos % page
        if quantized:
            kq, ks = quantize_rows(k_new[:, i].transpose(1, 0, 2))
            vq, vs = quantize_rows(v_new[:, i].transpose(1, 0, 2))
            k_pages = k_pages.at[:, pids, rows].set(kq)
            v_pages = v_pages.at[:, pids, rows].set(vq)
            k_scales = k_scales.at[:, pids, rows].set(ks)
            v_scales = v_scales.at[:, pids, rows].set(vs)
        else:
            k_pages = k_pages.at[:, pids, rows].set(
                k_new[:, i].transpose(1, 0, 2).astype(k_pages.dtype))
            v_pages = v_pages.at[:, pids, rows].set(
                v_new[:, i].transpose(1, 0, 2).astype(v_pages.dtype))
    if quantized:
        return k_pages, v_pages, k_scales, v_scales
    return k_pages, v_pages


def append_token_pages(k_pages: jnp.ndarray, v_pages: jnp.ndarray,
                       k_new: jnp.ndarray, v_new: jnp.ndarray,
                       block_tables: jnp.ndarray, lengths: jnp.ndarray,
                       k_scales: Optional[jnp.ndarray] = None,
                       v_scales: Optional[jnp.ndarray] = None):
    """Append one token's K/V per slot at position lengths[slot].

    k_new/v_new: [slots, hkv, hd]. One vectorized scatter per array:
    slot i's row lands in page table[i, len//page] at row len%page.
    Distinct slots own distinct pages, so the scatter indices never
    collide (XLA may apply them in any order). With scales (int8
    flavor) the row quantizes on write and returns a 4-tuple.
    """
    page = k_pages.shape[2]
    pids = jnp.take_along_axis(
        block_tables, (lengths // page)[:, None], axis=1)[:, 0]
    rows = lengths % page
    if k_scales is not None:
        kq, ks = quantize_rows(k_new.transpose(1, 0, 2))
        vq, vs = quantize_rows(v_new.transpose(1, 0, 2))
        k_pages = k_pages.at[:, pids, rows].set(kq)
        v_pages = v_pages.at[:, pids, rows].set(vq)
        k_scales = k_scales.at[:, pids, rows].set(ks)
        v_scales = v_scales.at[:, pids, rows].set(vs)
        return k_pages, v_pages, k_scales, v_scales
    k_pages = k_pages.at[:, pids, rows].set(
        k_new.transpose(1, 0, 2).astype(k_pages.dtype))
    v_pages = v_pages.at[:, pids, rows].set(
        v_new.transpose(1, 0, 2).astype(v_pages.dtype))
    return k_pages, v_pages
