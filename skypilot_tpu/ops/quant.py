"""Int8 weight-only quantization for inference.

The reference serves 8B-70B models by delegating to vLLM/TGI, which
ship weight-only int8/int4 paths (reference llm/vllm example YAMLs).
TPU-native equivalent: per-output-channel symmetric int8 weights with
bf16 scales, consumed by the same prefill/decode programs.

Why per-OUTPUT-channel: scales then commute with the matmul —
``x @ (q * s_col) == (x @ q) * s_col`` — so the contraction runs on the
int8->bf16 converted weight (XLA keeps the bytes int8 in HBM and fuses
the convert into the dot's operand read) and one cheap [out]-vector
multiply finishes the job. Decode is HBM-bandwidth-bound on weight
streaming, so halving weight bytes is a throughput win, not just a
memory one; an 8B model drops from ~16 GB to ~8.5 GB and fits a single
v5e chip.

``qdot``/``qembed`` are transparent: plain jnp arrays pass through, so
the shared model code serves both full-precision and quantized params.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

Params = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QuantArray:
    """int8 weight + per-output-channel scale.

    Matmul weights [..., in, out]: scale [..., out] (reduce over in).
    Embedding tables [vocab, d]: scale [vocab] (per row — rows are
    gathered individually, per-row dynamic range is what matters).
    """
    q: jnp.ndarray
    scale: jnp.ndarray

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.scale.dtype


def quantize_weight(w: jnp.ndarray,
                    scale_dtype=jnp.bfloat16) -> QuantArray:
    """Symmetric per-output-channel int8 over the contraction axis
    (axis -2 of [..., in, out])."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2)
    scale = jnp.maximum(absmax / 127.0, 1e-8)
    q = jnp.round(w.astype(jnp.float32) / scale[..., None, :])
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return QuantArray(q=q, scale=scale.astype(scale_dtype))


def quantize_embed(w: jnp.ndarray,
                   scale_dtype=jnp.bfloat16) -> QuantArray:
    """Per-row int8 for embedding tables [vocab, d]: scale [vocab]."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(absmax / 127.0, 1e-8)
    q = jnp.round(w.astype(jnp.float32) / scale[..., None])
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return QuantArray(q=q, scale=scale.astype(scale_dtype))


def qdot(x: jnp.ndarray, w) -> jnp.ndarray:
    """``x @ w`` for plain arrays; dequantizing matmul for QuantArray.

    The int8->x.dtype convert happens inside the dot's operand read
    (XLA fusion); scales apply to the [..., out] result."""
    if isinstance(w, QuantArray):
        y = x @ w.q.astype(x.dtype)
        return y * w.scale.astype(x.dtype)
    return x @ w


def qembed(embed, tokens: jnp.ndarray) -> jnp.ndarray:
    """Row gather for plain or quantized embedding tables."""
    if isinstance(embed, QuantArray):
        rows = embed.q[tokens]
        scale = embed.scale[tokens]
        return rows.astype(scale.dtype) * scale[..., None]
    return embed[tokens]


_MATMUL_KEYS = frozenset({'wq', 'wk', 'wv', 'wo',
                          'w_gate', 'w_up', 'w_down', 'lm_head'})


def quantize_params(params: Params) -> Params:
    """Quantize a Llama param tree: matmul weights per-output-channel,
    the embedding per-row; norms stay in their compute dtype (tiny).

    Leaf-by-leaf with buffer donation: the bf16 source of each weight is
    freed as its int8 replacement materializes, so peak HBM is
    params + one leaf — not params + quantized params (which would OOM
    an 8B model on the 16 GB chip it is being quantized to fit)."""
    qw = jax.jit(quantize_weight, donate_argnums=0)
    qe = jax.jit(quantize_embed, donate_argnums=0)
    layers = dict(params['layers'])
    for key in list(layers):
        if key in _MATMUL_KEYS:
            layers[key] = qw(layers[key])
    return {
        'embed': qe(params['embed']),
        'layers': layers,
        'final_norm': params['final_norm'],
        'lm_head': qw(params['lm_head']),
    }


def init_params_quantized(config, key: jax.Array,
                          tp: int = 1) -> Params:
    """Random-init DIRECTLY into int8: each weight is generated in the
    compute dtype, quantized, and freed before the next — an 8B model
    (16 GB bf16) never exists whole on the chip, only its ~8.5 GB int8
    form plus one transient leaf. Mirrors llama.init_params's tree
    shape and scaling exactly (structure asserted by
    test_infer.test_quantized_init_matches_structure).

    ``tp > 1``: each leaf is produced ALREADY SHARDED over the tp mesh
    (jit with quant-aware out_shardings, parallel/sharding.py) — a 70B
    int8 leaf never materializes on one chip either. Partitionable
    threefry keeps the values identical to the unsharded init."""
    dtype = jnp.dtype(config.dtype)
    d, hd = config.dim, config.head_dim
    L = config.n_layers
    k_embed, k_layers, k_head = jax.random.split(key, 3)

    leaf_shardings = {}
    if tp > 1:
        from skypilot_tpu.infer.engine import tp_mesh
        from skypilot_tpu.models import llama as llama_lib
        from skypilot_tpu.parallel import sharding as sharding_lib
        mesh = tp_mesh(tp)
        abstract = jax.eval_shape(lambda: quantize_params(
            llama_lib.init_params(config, jax.random.PRNGKey(0))))
        shard_tree = sharding_lib.param_shardings(mesh, abstract)
        leaf_shardings = {
            'embed': shard_tree['embed'],
            'lm_head': shard_tree['lm_head'],
            **{k: v for k, v in shard_tree['layers'].items()
               if k in _MATMUL_KEYS},
        }

    def qnormal(k, shape, scale, quant_fn=quantize_weight, name=None):
        def build():
            w = (jax.random.normal(k, shape, dtype) *
                 jnp.asarray(scale, dtype))
            return quant_fn(w)
        sh = leaf_shardings.get(name)
        kw = {'out_shardings': sh} if sh is not None else {}
        return jax.jit(build, **kw)()

    ks = jax.random.split(k_layers, 7)
    scale = d ** -0.5
    out_scale = scale / (2 * L) ** 0.5
    layers = {
        'attn_norm': jnp.ones((L, d), dtype),
        'wq': qnormal(ks[0], (L, d, config.n_heads * hd), scale,
                      name='wq'),
        'wk': qnormal(ks[1], (L, d, config.n_kv_heads * hd), scale,
                      name='wk'),
        'wv': qnormal(ks[2], (L, d, config.n_kv_heads * hd), scale,
                      name='wv'),
        'wo': qnormal(ks[3], (L, config.n_heads * hd, d), out_scale,
                      name='wo'),
        'mlp_norm': jnp.ones((L, d), dtype),
        'w_gate': qnormal(ks[4], (L, d, config.ffn_dim), scale,
                          name='w_gate'),
        'w_up': qnormal(ks[5], (L, d, config.ffn_dim), scale,
                        name='w_up'),
        'w_down': qnormal(ks[6], (L, config.ffn_dim, d), out_scale,
                          name='w_down'),
    }
    return {
        'embed': qnormal(k_embed, (config.vocab_size, d), 1.0,
                         quantize_embed, name='embed'),
        'layers': layers,
        'final_norm': jnp.ones((d,), dtype),
        'lm_head': qnormal(k_head, (d, config.vocab_size), scale,
                           name='lm_head'),
    }


def quantize_params_transfer(params: Params) -> Params:
    """quantize_params for HOST-resident trees (checkpoint restored to
    RAM via CheckpointManager.restore_to_host): each leaf transfers to
    the default device, quantizes, and frees its bf16 form before the
    next — peak device memory is the int8 tree plus one bf16 leaf."""
    # EXPLICIT target device: device_put(x) with no device is the
    # identity for already-committed arrays, and restore_to_host
    # commits leaves to the cpu backend — without the target the whole
    # "quantized" tree would silently stay in host RAM.
    target = jax.local_devices()[0]

    def q(fn):
        def run(leaf):
            dev = jax.device_put(jnp.asarray(leaf), target)
            return jax.jit(fn, donate_argnums=0)(dev)
        return run
    qw, qe = q(quantize_weight), q(quantize_embed)
    layers = dict(params['layers'])
    for key in list(layers):
        if key in _MATMUL_KEYS:
            layers[key] = qw(layers[key])
        else:
            layers[key] = jax.device_put(jnp.asarray(layers[key]),
                                         target)
    return {
        'embed': qe(params['embed']),
        'layers': layers,
        'final_norm': jax.device_put(jnp.asarray(params['final_norm']),
                                     target),
        'lm_head': qw(params['lm_head']),
    }


def is_quantized(params: Params) -> bool:
    return any(isinstance(leaf, QuantArray)
               for leaf in jax.tree_util.tree_leaves(
                   params,
                   is_leaf=lambda x: isinstance(x, QuantArray)))


def param_bytes(params: Params) -> int:
    """HBM footprint of a (possibly quantized) param tree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total
