"""Ring attention: exact attention over sequence-sharded inputs.

Long-context is first-class (SURVEY.md §2.8: the reference has *no*
sequence parallelism — greenfield here). Each device holds a sequence shard
of Q/K/V; K/V blocks rotate around the mesh axis ring via ``ppermute``
(ICI-neighbor exchange) while a blockwise online softmax accumulates exact
results — attention memory stays O(seq/N) per device and compute overlaps
with the rotation.

Usage: inside ``shard_map`` with q/k/v sharded on the sequence axis::

    out = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name='sp'),
        mesh=mesh,
        in_specs=P(None, None, 'sp', None), out_specs=P(None, None, 'sp',
        None))(q, k, v)

(Blockwise formulation after Liu et al., "Ring Attention with Blockwise
Transformers" — public technique; implementation is original.)
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str, *, causal: bool = True,
                   sm_scale: Optional[float] = None) -> jnp.ndarray:
    """q/k/v: local shards [b, h, s_local, d] on a ring of `axis_name`.

    GQA: pass k/v with fewer heads; they are expanded locally (head count
    is small relative to seq shards, so this is cheap).
    """
    b, hq, s_local, d = q.shape
    hkv = k.shape[1]
    if hkv != hq:
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    if sm_scale is None:
        sm_scale = d ** -0.5

    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    q32 = q.astype(jnp.float32) * sm_scale
    q_pos = my_idx * s_local + jnp.arange(s_local)

    def step(i, carry):
        k_blk, v_blk, acc, m, l = carry
        # The block we hold at ring step i originated at device (idx - i).
        src = (my_idx - i) % n
        k_pos = src * s_local + jnp.arange(s_local)
        s = jnp.einsum('bhqd,bhkd->bhqk', q32, k_blk.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # Fully-masked rows keep m = -inf; guard the exp.
        m_safe = jnp.where(jnp.isfinite(m_new) | (m_new > _NEG_INF / 2),
                           m_new, 0.0)
        p = jnp.exp(s - m_safe)
        p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
        alpha = jnp.where(m > _NEG_INF / 2, jnp.exp(m - m_safe), 0.0)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            'bhqk,bhkd->bhqd', p, v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        # Rotate K/V to the next device (ICI neighbor exchange). XLA
        # overlaps this ppermute with the next step's compute.
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return k_next, v_next, acc_new, m_new, l_new

    # Accumulator inits must be tagged as device-varying over the ring axis
    # (the loop writes axis-dependent values into them).
    init = (
        k, v,
        jax.lax.pvary(jnp.zeros((b, hq, s_local, d), jnp.float32),
                      (axis_name,)),
        jax.lax.pvary(jnp.full((b, hq, s_local, 1), _NEG_INF, jnp.float32),
                      (axis_name,)),
        jax.lax.pvary(jnp.zeros((b, hq, s_local, 1), jnp.float32),
                      (axis_name,)),
    )
    _, _, acc, _, l = jax.lax.fori_loop(0, n, step, init)
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)
