"""Rotary position embeddings (RoPE), Llama-3 style."""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_seq_len: int,
                     theta: float = 500_000.0) -> Tuple[jnp.ndarray,
                                                        jnp.ndarray]:
    """Precomputed (cos, sin) tables, shape [max_seq_len, head_dim//2],
    fp32 (precision matters at long context)."""
    inv_freq = 1.0 / (theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray,
               positions: jnp.ndarray = None) -> jnp.ndarray:
    """Rotate pairs of channels. x: [..., seq, heads, head_dim].

    `positions`: optional [..., seq] absolute positions (used by
    sequence-parallel shards and decode caches); defaults to arange.
    """
    seq = x.shape[-3]
    if positions is None:
        c = cos[:seq][..., None, :]   # [seq, 1, hd/2]
        s = sin[:seq][..., None, :]
    else:
        c = cos[positions][..., None, :]
        s = sin[positions][..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)
