"""Cost/time-minimizing placement optimizer.

Counterpart of the reference's ``sky/optimizer.py`` (``Optimizer.optimize``
at :109, ``_optimize_by_dp`` at :429 for chain DAGs, ``_optimize_by_ilp``
at :490 via pulp for general DAGs, ``_fill_in_launchable_resources``
at :1664). pulp is not available in this environment, so general DAGs use an
exact exhaustive search over per-task top-K candidates (small DAGs — the
reference's own ILP instances are tiny) with a greedy fallback beyond that.

Time estimates for TPU candidates are FLOPs-aware: if a task carries
``estimated_runtime_hours`` it is assumed to be measured on the *requested*
slice; candidate slices of other sizes in `any_of` requests scale runtime by
relative total bf16 TFLOPs — a TPU-first touch the GPU reference lacks.
"""
from __future__ import annotations

import dataclasses
import enum
import itertools
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import task as task_lib

_DEFAULT_RUNTIME_HOURS = 1.0
# Exhaustive product cap for general DAGs; beyond this fall back to greedy.
_EXHAUSTIVE_LIMIT = 200_000
_TOP_K = 8


class OptimizeTarget(enum.Enum):
    COST = 'cost'
    TIME = 'time'


@dataclasses.dataclass
class TaskPlan:
    task: task_lib.Task
    candidate: catalog.Candidate
    run_hours: float
    run_cost: float
    egress_cost: float = 0.0
    # The Resources alternative this candidate satisfies — best_resources is
    # derived from it so non-placement fields (disk, image, ports, ...)
    # survive optimization.
    req: Optional[resources_lib.Resources] = None

    @property
    def total_cost(self) -> float:
        return self.run_cost + self.egress_cost


@dataclasses.dataclass
class Plan:
    per_task: List[TaskPlan]
    # Wall-clock = longest path through the DAG (parallel branches overlap),
    # filled by Optimizer.optimize.
    critical_path_hours: float = 0.0

    @property
    def total_cost(self) -> float:
        return sum(p.total_cost for p in self.per_task)

    @property
    def total_hours(self) -> float:
        return self.critical_path_hours


def _candidate_resources(t: task_lib.Task) -> List[resources_lib.Resources]:
    """Expand `any_of` alternatives (multi-resource failover requests)."""
    base = t.resources
    if base.any_of:
        return [base.copy(any_of=None, **alt) for alt in base.any_of]
    return [base]


def _run_hours(t: task_lib.Task, ref_tpu, cand: catalog.Candidate) -> float:
    hours = t.estimated_runtime_hours or _DEFAULT_RUNTIME_HOURS
    # FLOPs-aware rescale across TPU slice sizes. `ref_tpu` is the slice the
    # estimate was made on (the task's base request / first alternative).
    if ref_tpu is not None and cand.tpu is not None:
        cand_flops = cand.tpu.total_bf16_tflops
        if cand_flops > 0:
            hours = hours * ref_tpu.total_bf16_tflops / cand_flops
    return hours


def _fill_candidates(t: task_lib.Task,
                     target: OptimizeTarget,
                     blocked: Optional[List[catalog.Candidate]] = None
                     ) -> List[TaskPlan]:
    """Feasible, priced, sorted placements for one task
    (reference _fill_in_launchable_resources, sky/optimizer.py:1664)."""
    plans: List[TaskPlan] = []
    blocked_keys = {(b.cloud, b.region, b.zone, b.instance_type)
                    for b in (blocked or [])}
    alternatives = _candidate_resources(t)
    # Declarative (cloud, feature) gating (reference
    # CloudImplementationFeatures): a task needing spot/multislice/
    # ports/... only considers clouds implementing them. Derived PER
    # alternative — any_of entries may flip spot/ports/num_slices.
    from skypilot_tpu import cloud_capabilities as caps
    # Runtime estimates are anchored to the first alternative's slice.
    ref_tpu = next((r.tpu for r in alternatives if r.tpu is not None), None)
    feature_notes: List[str] = []
    for req in alternatives:
        required = caps.required_features(t, req)
        try:
            cands = catalog.get_candidates(req, required=required)
        except exceptions.ResourcesMismatchError as e:
            # A pinned-cloud alternative lacking a feature is skipped,
            # not fatal — other any_of alternatives may be feasible.
            feature_notes.append(str(e))
            continue
        for cand in cands:
            if (cand.cloud, cand.region, cand.zone,
                    cand.instance_type) in blocked_keys:
                continue
            hours = _run_hours(t, ref_tpu, cand)
            plans.append(TaskPlan(task=t, candidate=cand, run_hours=hours,
                                  run_cost=hours * cand.cost_per_hour,
                                  req=req))
    if not plans:
        # Name the blocking features (the cloud_capabilities contract):
        # pinned mismatches were collected above; for unpinned requests
        # explain which enabled clouds lost on which feature.
        if not feature_notes:
            from skypilot_tpu import state
            for cloud in state.get_enabled_clouds() or ['gcp']:
                for req in alternatives:
                    missing = caps.unsupported(
                        cloud, caps.required_features(t, req))
                    if missing:
                        feature_notes.append(
                            f'cloud {cloud!r} lacks '
                            f'{[f.value for f in missing]}')
        hint = ('; '.join(sorted(set(feature_notes)))
                if feature_notes else 'Check the catalog/regions.')
        raise exceptions.ResourcesUnavailableError(
            f'No feasible placement for task {t.name or "<unnamed>"} '
            f'with resources {t.resources!r}. {hint}')
    key = ((lambda p: (p.run_cost, p.run_hours))
           if target is OptimizeTarget.COST
           else (lambda p: (p.run_hours, p.run_cost)))
    plans.sort(key=key)
    return plans


def _egress(src: TaskPlan, dst: TaskPlan) -> float:
    gib = src.task.estimated_output_gib or 0.0
    return gib * catalog.egress_cost_per_gib(src.candidate, dst.candidate)


def _optimize_chain(order: List[task_lib.Task],
                    cands: Dict[int, List[TaskPlan]],
                    target: OptimizeTarget) -> List[TaskPlan]:
    """DP over a chain (reference _optimize_by_dp, sky/optimizer.py:429)."""
    # dp[j] = best objective ending with candidate j of current task.
    def obj(p: TaskPlan) -> float:
        return p.total_cost if target is OptimizeTarget.COST else p.run_hours

    prev_plans = cands[0]
    dp: List[Tuple[float, List[TaskPlan]]] = [
        (obj(p), [p]) for p in prev_plans]
    for i in range(1, len(order)):
        new_dp: List[Tuple[float, List[TaskPlan]]] = []
        for p in cands[i]:
            best: Optional[Tuple[float, List[TaskPlan]]] = None
            for (score, path) in dp:
                e = _egress(path[-1], p)
                cand_plan = dataclasses.replace(p, egress_cost=e)
                s = score + obj(cand_plan)
                if best is None or s < best[0]:
                    best = (s, path + [cand_plan])
            assert best is not None
            new_dp.append(best)
        dp = new_dp
    return min(dp, key=lambda sp: sp[0])[1]


def _optimize_general(dag: dag_lib.Dag,
                      order: List[task_lib.Task],
                      cands: Dict[int, List[TaskPlan]],
                      target: OptimizeTarget) -> List[TaskPlan]:
    """Exact search over top-K candidates per task; greedy fallback.

    Replaces the reference's pulp ILP (sky/optimizer.py:490) — exact for the
    DAG sizes the reference itself solves (tens of tasks would exceed its
    ILP too).
    """
    idx_of = {id(t): i for i, t in enumerate(order)}
    parents: Dict[int, List[int]] = {
        i: [idx_of[id(p)] for p in dag.parents(t)]
        for i, t in enumerate(order)}

    def obj(p: TaskPlan) -> float:
        return p.total_cost if target is OptimizeTarget.COST else p.run_hours

    tops = {i: cands[i][:_TOP_K] for i in range(len(order))}
    space = 1
    for i in tops:
        space *= len(tops[i])
    if space <= _EXHAUSTIVE_LIMIT:
        best_score, best_sel = float('inf'), None
        for sel in itertools.product(*[tops[i] for i in range(len(order))]):
            score = 0.0
            sel_list = list(sel)
            for i, p in enumerate(sel_list):
                e = sum(_egress(sel_list[pi], p) for pi in parents[i])
                score += obj(dataclasses.replace(p, egress_cost=e))
            if score < best_score:
                best_score, best_sel = score, sel_list
        assert best_sel is not None
        return [
            dataclasses.replace(
                p, egress_cost=sum(_egress(best_sel[pi], p)
                                   for pi in parents[i]))
            for i, p in enumerate(best_sel)
        ]
    # Greedy: pick each task's best given already-placed parents.
    chosen: List[TaskPlan] = []
    for i in range(len(order)):
        best = None
        for p in tops[i]:
            e = sum(_egress(chosen[pi], p) for pi in parents[i])
            scored = dataclasses.replace(p, egress_cost=e)
            if best is None or obj(scored) < obj(best):
                best = scored
        chosen.append(best)
    return chosen


def _set_best_resources(p: TaskPlan) -> None:
    """Write the chosen placement back onto the task."""
    c = p.candidate
    base = p.req if p.req is not None else p.task.resources
    override = {
        'cloud': c.cloud,
        'region': c.region,
        'zone': c.zone,
        'use_spot': c.use_spot,
        'any_of': None,
    }
    if c.tpu is not None:
        override['accelerators'] = c.tpu.name
    elif c.accelerator_name:
        override['accelerators'] = (
            f'{c.accelerator_name}:{c.accelerator_count}')
    else:
        override['instance_type'] = c.instance_type
    p.task.best_resources = base.copy(**override)


class Optimizer:
    """Reference sky/optimizer.py:109 ``Optimizer.optimize``."""

    @staticmethod
    def optimize_job_group(dag: dag_lib.Dag,
                           target: OptimizeTarget = OptimizeTarget.COST,
                           blocked: Optional[List[catalog.Candidate]] = None,
                           quiet: bool = False) -> Plan:
        """Gang-place a PARALLEL job group on common infra (reference
        ``Optimizer.optimize_job_group`` + ``_optimize_same_infra``,
        sky/optimizer.py:1037). All tasks must land in one (cloud, region)
        so inter-job traffic stays on local DCN, not cross-region WAN.
        """
        if not dag.is_job_group():
            return Optimizer.optimize(dag, target, blocked, quiet)
        order = dag.tasks
        cands = {i: _fill_candidates(t, target, blocked)
                 for i, t in enumerate(order)}
        # Group each task's candidates by (cloud, region); a region is
        # feasible only if EVERY task has a candidate there.
        by_region: Dict[Tuple[str, str], List[Optional[TaskPlan]]] = {}
        for i in range(len(order)):
            for p in cands[i]:
                key = (p.candidate.cloud, p.candidate.region)
                slot = by_region.setdefault(key, [None] * len(order))
                if slot[i] is None:   # cands are sorted best-first
                    slot[i] = p

        def obj(p: TaskPlan) -> float:
            return p.total_cost if target is OptimizeTarget.COST \
                else p.run_hours

        best_key, best_sel, best_score = None, None, float('inf')
        for key, sel in by_region.items():
            if any(s is None for s in sel):
                continue
            score = sum(obj(s) for s in sel)
            if score < best_score:
                best_key, best_sel, best_score = key, sel, score
        if best_sel is None:
            raise exceptions.ResourcesUnavailableError(
                f'No common (cloud, region) can satisfy all '
                f'{len(order)} jobs of job group '
                f'{dag.name or "<unnamed>"}.')
        for p in best_sel:
            _set_best_resources(p)
        # Gang: wall-clock is the slowest member, all run simultaneously.
        plan = Plan(per_task=list(best_sel),
                    critical_path_hours=max(p.run_hours for p in best_sel))
        if not quiet:
            print(f'Job group placed in {best_key[0]}/{best_key[1]}')
            print(format_plan(plan))
        return plan

    @staticmethod
    def optimize(dag: dag_lib.Dag,
                 target: OptimizeTarget = OptimizeTarget.COST,
                 blocked: Optional[List[catalog.Candidate]] = None,
                 quiet: bool = False) -> Plan:
        if dag.is_job_group():
            return Optimizer.optimize_job_group(dag, target, blocked, quiet)
        order = dag.topological_order()
        cands = {i: _fill_candidates(t, target, blocked)
                 for i, t in enumerate(order)}
        if dag.is_chain() or len(order) == 1:
            chosen = _optimize_chain(order, cands, target)
        else:
            chosen = _optimize_general(dag, order, cands, target)
        for p in chosen:
            _set_best_resources(p)
        # Critical path over the DAG (longest run_hours chain).
        hours_of = {id(p.task): p.run_hours for p in chosen}
        finish: Dict[int, float] = {}
        for t in order:
            start = max((finish[id(p)] for p in dag.parents(t)), default=0.0)
            finish[id(t)] = start + hours_of[id(t)]
        plan = Plan(per_task=chosen,
                    critical_path_hours=max(finish.values(), default=0.0))
        if not quiet:
            print(format_plan(plan))
        return plan


def format_plan(plan: Plan) -> str:
    lines = ['Optimizer plan:']
    for p in plan.per_task:
        lines.append(
            f'  {p.task.name or "<task>"}: {p.candidate} '
            f'~{p.run_hours:.2f}h  run ${p.run_cost:.2f}'
            + (f'  egress ${p.egress_cost:.2f}' if p.egress_cost else ''))
    lines.append(f'  total: ${plan.total_cost:.2f} '
                 f'(~{plan.total_hours:.2f}h)')
    return '\n'.join(lines)


def optimize(dag_or_task, target: OptimizeTarget = OptimizeTarget.COST,
             quiet: bool = False) -> Plan:
    """Convenience wrapper accepting a Task or a Dag."""
    if isinstance(dag_or_task, task_lib.Task):
        d = dag_lib.Dag()
        d.add(dag_or_task)
        dag_or_task = d
    return Optimizer.optimize(dag_or_task, target, quiet=quiet)
