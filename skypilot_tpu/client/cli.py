"""`sky-tpu` command-line interface.

Counterpart of the reference's click CLI (reference sky/client/cli/
command.py, 7,856 LoC). Commands call the engine directly when no API
server is configured, or go through the SDK/API server when
``SKY_TPU_API_SERVER`` is set (reference architecture: CLI → SDK → server;
the direct path matches the reference's early engine-only mode that
SURVEY.md §7 stage 4 recommends building first).
"""
from __future__ import annotations

import os
import sys
import time
from typing import Optional

import click

import skypilot_tpu as sky
from skypilot_tpu.utils import common


def _engine():
    """Engine facade: direct or via SDK depending on config."""
    if os.environ.get('SKY_TPU_API_SERVER'):
        try:
            from skypilot_tpu.client import sdk
        except ImportError as e:
            raise click.ClickException(
                f'SKY_TPU_API_SERVER is set but the SDK is unavailable: '
                f'{e}') from e
        sdk.ensure_server_compatibility()
        return sdk
    from skypilot_tpu import core
    return core


@click.group()
@click.version_option(sky.__version__)
def cli() -> None:
    """sky-tpu: TPU-native workload orchestrator."""


def _env_overrides(env: tuple) -> Optional[dict]:
    overrides = {}
    for e in env:
        k, _, v = e.partition('=')
        overrides[k] = v
    return overrides or None


def _load_task(yaml_path: str, env: tuple) -> 'sky.Task':
    return sky.Task.from_yaml(yaml_path, env_overrides=_env_overrides(env))


@cli.command()
@click.argument('task_yaml')
@click.option('--cluster', '-c', default=None, help='Cluster name.')
@click.option('--cloud', default=None, help='Override cloud.')
@click.option('--env', multiple=True, help='KEY=VALUE env override.')
@click.option('--detach-run', '-d', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
@click.option('--down', 'autodown', is_flag=True, default=False,
              help='Autodown the cluster when the job finishes.')
def launch(task_yaml: str, cluster: Optional[str], cloud: Optional[str],
           env: tuple, detach_run: bool, yes: bool, autodown: bool) -> None:
    """Launch a task from a YAML spec (provision + run).

    Multi-document YAMLs describe a pipeline (serial chain) or a job
    group (``execution: parallel``) and run through the DAG path.
    """
    import yaml as yaml_lib
    with open(os.path.expanduser(task_yaml), encoding='utf-8') as f:
        docs = [d for d in yaml_lib.safe_load_all(f) if d is not None]
    if len(docs) > 1:
        from skypilot_tpu import execution
        from skypilot_tpu.utils import dag_utils
        overrides = dict(e.partition('=')[::2] for e in env)
        dag = dag_utils.load_dag_from_yaml(task_yaml,
                                           overrides or None)
        if cloud:
            for t in dag.tasks:
                t.set_resources(t.resources.copy(cloud=cloud))
        if cluster:
            click.echo('Warning: --cluster is ignored for multi-task '
                       'YAMLs (each task gets its own cluster).')
        if detach_run and not dag.is_job_group():
            click.echo('Warning: --detach-run is ignored for serial '
                       'pipelines (stages must run in order).')
        if not yes:
            mode = 'job group' if dag.is_job_group() else 'pipeline'
            click.confirm(
                f'Launching {mode} {dag.name or task_yaml} '
                f'({len(dag)} tasks). Proceed?', abort=True)
        results = execution.launch_dag(dag, quiet=False, down=autodown,
                                       detach_run=detach_run)
        for name, job_id, _ in results:
            click.echo(f'Cluster: {name}  job: {job_id}')
        return
    task = _load_task(task_yaml, env)
    if cloud:
        task.set_resources(task.resources.copy(cloud=cloud))
    if not yes:
        click.confirm(
            f'Launching {task.name or task_yaml} '
            f'({task.resources!r}, {task.num_nodes} host(s)). Proceed?',
            abort=True)
    engine = _engine()
    job_id, info = engine.launch(task, cluster_name=cluster, quiet=False)
    name = info.cluster_name
    click.echo(f'Cluster: {name}  job: {job_id}')
    if autodown:
        # Server-side: the agent downs the cluster once its queue idles —
        # works detached and survives a client crash mid-tail.
        engine.autostop(name, 0, True)
        click.echo(f'{name}: will autodown when idle.')
    if job_id >= 0 and not detach_run:
        for chunk in engine.tail_logs(name, job_id, follow=True):
            sys.stdout.buffer.write(chunk)
            sys.stdout.buffer.flush()
        st = engine.job_status(name, job_id)
        click.echo(f'Job {job_id}: {st.value}')
        if st != common.JobStatus.SUCCEEDED:
            sys.exit(100)


@cli.command('exec')
@click.argument('cluster')
@click.argument('task_yaml')
@click.option('--env', multiple=True)
@click.option('--detach-run', '-d', is_flag=True, default=False)
def exec_cmd(cluster: str, task_yaml: str, env: tuple,
             detach_run: bool) -> None:
    """Run a task on an existing cluster (skips provision/setup)."""
    task = _load_task(task_yaml, env)
    engine = _engine()
    job_id, _ = engine.exec(task, cluster)
    click.echo(f'Job: {job_id}')
    if not detach_run:
        for chunk in engine.tail_logs(cluster, job_id, follow=True):
            sys.stdout.buffer.write(chunk)
            sys.stdout.buffer.flush()


@cli.command()
@click.option('--refresh', '-r', is_flag=True, default=False)
@click.option('--all-workspaces', '-u', is_flag=True, default=False,
              help='Include clusters from every workspace.')
def status(refresh: bool, all_workspaces: bool) -> None:
    """Show clusters (scoped to the active workspace by default)."""
    records = _engine().status(refresh=refresh,
                               all_workspaces=all_workspaces)
    if not records:
        click.echo('No clusters.')
        return
    fmt = '{:<18} {:<10} {:<26} {:<8} {:<14}'
    click.echo(fmt.format('NAME', 'STATUS', 'RESOURCES', 'HOSTS',
                          'AUTOSTOP'))
    for r in records:
        res = r['resources']
        acc = res.get('accelerators') or res.get('instance_type', '-')
        hosts = len((r['cluster_info'] or {}).get('hosts', [])) or 1
        astop = (f"{r['autostop_minutes']}m"
                 f"{' (down)' if r['autostop_down'] else ''}"
                 if r['autostop_minutes'] >= 0 else '-')
        click.echo(fmt.format(r['name'], r['status'].value,
                              f"{res.get('cloud', '?')}:{acc}", hosts,
                              astop))


@cli.command()
@click.argument('cluster')
@click.argument('job_id', type=int)
@click.option('--no-follow', is_flag=True, default=False)
@click.option('--rank', type=int, default=0,
              help='Which host rank log to stream.')
def logs(cluster: str, job_id: int, no_follow: bool, rank: int) -> None:
    """Stream a job's logs."""
    for chunk in _engine().tail_logs(cluster, job_id,
                                     follow=not no_follow, rank=rank):
        sys.stdout.buffer.write(chunk)
        sys.stdout.buffer.flush()


@cli.command()
@click.argument('cluster')
def queue(cluster: str) -> None:
    """Show a cluster's job queue."""
    jobs = _engine().queue(cluster)
    fmt = '{:<6} {:<16} {:<12} {:<8}'
    click.echo(fmt.format('ID', 'NAME', 'STATUS', 'HOSTS'))
    for j in jobs:
        click.echo(fmt.format(j['job_id'], j['name'], j['status'],
                              j['num_hosts']))


@cli.command()
@click.argument('cluster')
@click.argument('job_id', type=int)
def cancel(cluster: str, job_id: int) -> None:
    """Cancel a job."""
    _engine().cancel(cluster, job_id)
    click.echo(f'Cancelled job {job_id} on {cluster}.')


@cli.command()
@click.argument('cluster')
@click.option('--yes', '-y', is_flag=True, default=False)
def stop(cluster: str, yes: bool) -> None:
    """Stop a cluster (keep disk)."""
    if not yes:
        click.confirm(f'Stop cluster {cluster}?', abort=True)
    _engine().stop(cluster)
    click.echo(f'Cluster {cluster} stopped.')


@cli.command()
@click.argument('cluster')
def start(cluster: str) -> None:
    """Restart a stopped cluster."""
    _engine().start(cluster)
    click.echo(f'Cluster {cluster} started.')


@cli.command()
@click.argument('cluster')
@click.option('--yes', '-y', is_flag=True, default=False)
def down(cluster: str, yes: bool) -> None:
    """Terminate a cluster."""
    if not yes:
        click.confirm(f'Terminate cluster {cluster}?', abort=True)
    _engine().down(cluster)
    click.echo(f'Cluster {cluster} terminated.')


@cli.command()
@click.argument('cluster')
@click.option('--idle-minutes', '-i', type=int, required=True)
@click.option('--down', 'down_', is_flag=True, default=False)
def autostop(cluster: str, idle_minutes: int, down_: bool) -> None:
    """Set autostop/autodown after idleness."""
    _engine().autostop(cluster, idle_minutes, down_)
    click.echo(f'{cluster}: autostop {idle_minutes}m'
               f'{" then down" if down_ else ""}.')


@cli.command()
def check() -> None:
    """Probe cloud credentials and capabilities."""
    engine = _engine()
    if not hasattr(engine, 'check_detailed'):
        # Remote SDK path: the API server probes ITS credentials and
        # records enabled clouds in its own state DB.
        for cloud, ok in engine.check().items():
            click.echo(f'  {"✓" if ok else "✗"} {cloud}: '
                       f'{"enabled" if ok else "disabled"}')
        return
    results = engine.check_detailed()
    for r in results:
        mark = '✓' if r.ok else '✗'
        line = f'  {mark} {r.cloud}: {"enabled" if r.ok else "disabled"}'
        if r.ok and r.storage_ok:
            line += ' [compute, storage]'
        elif r.ok:
            line += ' [compute]'
        click.echo(line)
        if r.reason:
            click.echo(f'      {r.reason}')
        for k, v in r.details.items():
            click.echo(f'      {k}: {v}')
    enabled = [r.cloud for r in results if r.ok]
    click.echo(f'\nEnabled clouds: {", ".join(enabled) or "none"}')


@cli.command('trace')
@click.argument('request_id', required=False)
@click.option('--perfetto', 'perfetto_path', default=None,
              help='Also write Perfetto/Chrome-trace JSON here '
                   '(open in ui.perfetto.dev or chrome://tracing).')
def trace_cmd(request_id: Optional[str],
              perfetto_path: Optional[str]) -> None:
    """Render the distributed trace of one API request.

    REQUEST_ID is the id `sky-tpu` ops return (also accepts a raw
    trace id). With no argument, lists recent traces. Requires the
    request to have run with SKY_TPU_TRACE=1 on the client and server
    (see docs/observability.md).
    """
    import json as json_lib

    from skypilot_tpu.observability import render as render_lib
    from skypilot_tpu.observability import store as store_lib
    from skypilot_tpu.observability import trace as trace_mod

    def _local_store():
        return store_lib.SpanStore()

    # Query wherever spans actually shipped: the same resolution chain
    # the shipper uses (env → config endpoint → local api_server.json),
    # falling back to the client-local store. The resolved URL is
    # pinned into the env so the SDK talks to the SAME server (a local
    # server found via api_server.json may sit on a non-default port).
    server = trace_mod._resolve_collector()  # noqa: SLF001
    use_server = server is not None
    if use_server:
        os.environ['SKY_TPU_API_SERVER'] = server
    if request_id is None:
        traces = None
        if use_server:
            from skypilot_tpu import exceptions as exc
            from skypilot_tpu.client import sdk
            try:
                traces = sdk.api_traces()
            except exc.SkyTpuError:
                traces = None   # stale/dead server: fall back to local
        if traces is None:
            traces = _local_store().list_traces()
        if not traces:
            click.echo('No traces recorded. Run with SKY_TPU_TRACE=1.')
            return
        fmt = '{:34} {:>8} {:24} {}'
        click.echo(fmt.format('TRACE', 'SPANS', 'ROOT', 'REQUEST'))
        for t in traces:
            click.echo(fmt.format(t['trace_id'], t['n_spans'],
                                  t.get('root') or '-',
                                  t.get('request_id') or '-'))
        return
    spans = []
    if use_server:
        from skypilot_tpu import exceptions as exc
        from skypilot_tpu.client import sdk
        try:
            spans = sdk.api_trace(request_id)
        except exc.SkyTpuError:
            spans = []
    if not spans:
        # Engine mode / server unreachable: the local span store holds
        # whatever this host's processes shipped.
        store = _local_store()
        spans = store.trace_for_request(request_id)
        if not spans:
            spans = store.get_trace(request_id)
    if not spans:
        raise click.ClickException(
            f'no trace recorded for {request_id!r} — run the request '
            f'with SKY_TPU_TRACE=1 (client and server), or check '
            f'`sky-tpu trace` for the trace list.')
    click.echo(render_lib.render_tree(spans))
    if perfetto_path:
        with open(perfetto_path, 'w', encoding='utf-8') as f:
            json_lib.dump(render_lib.to_perfetto(spans), f)
        click.echo(f'wrote {perfetto_path}')


def _fetch_json(url: str, timeout: float = 10.0):
    """GET + parse a control endpoint's JSON, converting transport
    and parse errors into one friendly ClickException (ValueError
    covers a non-JSON body, HTTPException a non-HTTP peer — wrong
    port, a reverse proxy's HTML error page)."""
    import http.client
    import json as json_lib
    import urllib.request
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json_lib.loads(r.read())
    except (OSError, ValueError, http.client.HTTPException) as e:
        raise click.ClickException(f'could not fetch {url}: {e}')


@cli.command('profile')
@click.argument('target', required=False)
@click.option('--perfetto', 'perfetto_path', default=None,
              help='Write a Perfetto/Chrome-trace JSON of the '
                   'timeline (open in ui.perfetto.dev).')
@click.option('--steps', 'n_steps', default=20, show_default=True,
              help='Step records shown in the text summary.')
def profile_cmd(target: Optional[str], perfetto_path: Optional[str],
                n_steps: int) -> None:
    """Read the engine flight recorder (docs/observability.md
    "Flight recorder").

    TARGET is a replica URL (``http://host:port`` — fetches the live
    ``/debug/stepline`` ring) or a request id / dump trace id (reads
    the anomaly dumps the recorder snapshotted into the span store).
    With no argument, lists recorded dumps.
    """
    import json as json_lib

    from skypilot_tpu.observability import render as render_lib
    from skypilot_tpu.observability import stepline as stepline_lib
    from skypilot_tpu.observability import store as store_lib

    def _write_perfetto(make_doc) -> None:
        """``make_doc`` is a thunk: a full ring renders to tens of
        thousands of trace events — built only when --perfetto
        actually asked for them."""
        if not perfetto_path:
            return
        doc = make_doc()
        errs = stepline_lib.validate_perfetto(doc)
        if errs:
            raise click.ClickException(
                f'exported trace failed validation: {errs[:3]}')
        with open(perfetto_path, 'w', encoding='utf-8') as f:
            json_lib.dump(doc, f)
        click.echo(f'wrote {perfetto_path}')

    if target and target.startswith(('http://', 'https://')):
        snap = _fetch_json(target.rstrip('/') + '/debug/stepline')
        if not snap.get('enabled', True):
            click.echo('flight recorder disabled on this replica '
                       '(--no-stepline).')
            return
        # Tolerate a replica on an older build whose records miss a
        # newer field — a version skew must degrade to zeros, not a
        # KeyError traceback.
        _defaults = {'kind': '?', 'tenant_depths': None}
        summ = stepline_lib.summarize([
            stepline_lib.StepRecord(**{
                k: rec.get(k, _defaults.get(k, 0))
                for k in stepline_lib.StepRecord.__slots__})
            for rec in snap.get('steps', ())])
        click.echo(f"steps recorded: {snap.get('steps_total', 0)} "
                   f"(ring keeps {len(snap.get('steps', []))}); "
                   f"anomaly dumps: {snap.get('dumps', 0)}")
        if summ['steps']:
            click.echo(
                'step time: mean {:.3f} ms — dispatch {:.0%}, drain '
                '{:.0%}, readback {:.0%}, host {:.0%}'.format(
                    summ['step_mean_ms'],
                    summ['dispatch_share'] or 0,
                    summ['drain_share'] or 0,
                    summ['readback_share'] or 0,
                    summ['host_share'] or 0))
            click.echo(f"step kinds: {summ['step_kinds']}")
            fmt = '{:>8} {:>8} {:>9} {:>6} {:>7} {:>7} {:>7}'
            click.echo(fmt.format('STEP', 'KIND', 'DUR_MS', 'BATCH',
                                  'CHUNK', 'QUEUE', 'FREEPG'))
            for rec in snap.get('steps', [])[-max(1, n_steps):]:
                click.echo(fmt.format(
                    rec.get('idx', 0), rec.get('kind', '?'),
                    f"{rec.get('dur_s', 0) * 1e3:.2f}",
                    rec.get('batch', 0), rec.get('chunk_tokens', 0),
                    rec.get('queue_depth', 0),
                    rec.get('pages_free', -1)))
        _write_perfetto(lambda: stepline_lib.to_perfetto(snap))
        return

    store = store_lib.SpanStore()
    if not target:
        dumps = store.list_traces(limit=200,
                                  trace_id_prefix='stepline-')
        if not dumps:
            click.echo(
                'No flight-recorder dumps. Dumps appear after an '
                'anomaly (TTFT-SLO breach, preemption, cache_full, '
                'admission shed, breaker open); profile a live '
                'replica with `sky-tpu profile <url>`.')
            return
        fmt = '{:36} {:>8} {}'
        click.echo(fmt.format('DUMP', 'SPANS', 'REQUEST'))
        for t in dumps:
            click.echo(fmt.format(t['trace_id'], t['n_spans'],
                                  t.get('request_id') or '-'))
        return
    # A request id can live in both its ordinary PR-1 span trace and
    # a recorder dump; `profile` reads the black box, so prefer the
    # newest stepline-* trace and never silently render the plain
    # request trace (`sky-tpu trace` is the command for that).
    spans: list = []
    for tid in store.trace_ids_for_request(target):
        if str(tid).startswith('stepline-'):
            spans = store.get_trace(tid)
            break
    if not spans:
        spans = store.get_trace(target)
    spans = [s for s in spans or []]
    if not spans:
        raise click.ClickException(
            f'no flight-recorder dump for {target!r} — run '
            f'`sky-tpu profile` for the dump list, or profile a '
            f'live replica with its URL.')
    trigger = next((s for s in spans
                    if s['name'] == 'stepline.trigger'), None)
    if trigger is not None:
        click.echo(f"trigger: {trigger['status']} "
                   f"{trigger.get('attrs') or {}}")
    click.echo(render_lib.render_tree(spans))
    _write_perfetto(lambda: render_lib.to_perfetto(spans))


@cli.command('slo')
@click.argument('lb_url')
@click.option('--json', 'as_json', is_flag=True,
              help='Raw /-/alerts JSON instead of the table.')
def slo_cmd(lb_url: str, as_json: bool) -> None:
    """Show a live LB's SLO objectives, error budgets, and firing
    alerts (docs/observability.md "SLOs and alerting").

    LB_URL is the service endpoint (``http://host:port``); this reads
    its ``/-/alerts`` view: per-objective burn rates on the page
    (5m/1h) and ticket (30m/6h) windows, the error budget remaining,
    and the live firing set with recent transitions.
    """
    import json as json_lib

    doc = _fetch_json(lb_url.rstrip('/') + '/-/alerts')
    if as_json:
        click.echo(json_lib.dumps(doc, indent=1))
        return
    if not doc.get('enabled', False):
        click.echo('No SLO objectives declared for this service — '
                   'add an `slo:` section to the service spec '
                   '(docs/observability.md "SLOs and alerting").')
        return
    fmt = ('{:<24} {:<20} {:>7} {:>8} {:>9} {:>9} {:>8}')
    click.echo(fmt.format('OBJECTIVE', 'METRIC', 'TARGET', 'BUDGET',
                          'PAGE_5M', 'PAGE_1H', 'STATE'))
    for key, row in sorted(doc.get('objectives', {}).items()):
        state = ('PAGE' if row.get('page_firing')
                 else 'ticket' if row.get('ticket_firing') else 'ok')
        metric = row.get('metric', '?')
        if row.get('threshold_s') is not None:
            metric += f"<={row['threshold_s']:g}s"
        if row.get('tenant'):
            metric += f" [{row['tenant']}]"
        click.echo(fmt.format(
            key, metric, f"{row.get('target', 0):g}",
            f"{row.get('error_budget_remaining', 0):.2%}",
            f"{row.get('page_burn_short', 0):g}",
            f"{row.get('page_burn_long', 0):g}", state))
    firing = doc.get('firing') or []
    if firing:
        click.echo('\nFIRING:')
        for f in firing:
            click.echo(f"  [{f['tier']}] {f['objective']} "
                       f"since t={f.get('since_t')}")
    tail = (doc.get('transitions') or [])[-5:]
    if tail:
        click.echo('\nrecent transitions:')
        for t in tail:
            click.echo(f"  t={t['t']} {t['tier']} {t['objective']} "
                       f"-> {t['state']} (burn {t['burn_short']}/"
                       f"{t['burn_long']})")


@cli.command('cost')
@click.argument('lb_url')
@click.option('--json', 'as_json', is_flag=True,
              help='Raw cost keys of /-/metrics instead of the '
                   'report.')
def cost_cmd(lb_url: str, as_json: bool) -> None:
    """Show a live service's fleet cost report (docs/cost.md
    "Reading a cost report").

    LB_URL is the service endpoint (``http://host:port``); this reads
    the cost-plane keys of its ``/-/metrics`` view: the fleet's
    current $/hour and spot fraction (from the controller's catalog
    snapshot), the efficiency rate in $ per 1k good tokens, and the
    scale-to-zero counters (parked requests, cold starts).
    """
    import json as json_lib

    m = _fetch_json(lb_url.rstrip('/') + '/-/metrics')
    keys = ('fleet_cost_per_hour', 'cost_per_1k_good_tokens',
            'spot_fraction', 'cost_catalog_stale', 'parked_requests',
            'cold_starts_total', 'cold_start_p50_s')
    if as_json:
        click.echo(json_lib.dumps({k: m.get(k) for k in keys},
                                  indent=1))
        return
    rate = m.get('fleet_cost_per_hour') or 0.0
    per_1k = m.get('cost_per_1k_good_tokens')
    click.echo(f'fleet cost:      ${rate:.4f}/hour '
               f'(${rate * 24 * 30:.2f}/month at this rate)')
    click.echo('cost efficiency: '
               + (f'${per_1k:.6f} per 1k good tokens'
                  if per_1k is not None else
                  'n/a (no recent token throughput)'))
    click.echo(f"spot fraction:   {m.get('spot_fraction', 0.0):.0%} "
               f"of {m.get('ready_replicas', 0)} ready replica(s)")
    if m.get('cost_catalog_stale'):
        click.echo('WARNING: price catalog is STALE — placement is '
                   'running on last-known prices (the fetcher is '
                   'failing; see serve.costplane.catalog_stale).')
    cold = m.get('cold_starts_total') or 0
    if cold or m.get('parked_requests'):
        p50 = m.get('cold_start_p50_s')
        click.echo(f"scale-to-zero:   {m.get('parked_requests', 0)} "
                   f'parked request(s), {cold} cold start(s)'
                   + (f', p50 wake {p50:.1f}s'
                      if p50 is not None else ''))


@cli.group('incident')
def incident() -> None:
    """Incident replay plane (docs/simulation.md): convert
    flight-recorder anomaly dumps into replayable twin scenarios."""


@incident.command('list')
def incident_list() -> None:
    """List exportable flight-recorder dumps in the span store."""
    from skypilot_tpu.observability import incident as incident_lib
    from skypilot_tpu.observability import store as store_lib

    dumps = incident_lib.list_dumps(store_lib.SpanStore())
    if not dumps:
        click.echo('No flight-recorder dumps. Dumps appear after an '
                   'anomaly (slo_page, breaker_open, quarantine, '
                   'engine stepline triggers).')
        return
    fmt = '{:36} {:14} {:>8}'
    click.echo(fmt.format('DUMP', 'TRIGGER', 'SPANS'))
    for d in dumps:
        click.echo(fmt.format(d['dump_id'], d['trigger'] or '-',
                              d['n_spans']))


@incident.command('export')
@click.argument('dump_id')
@click.option('--output', '-o', default=None,
              help='Incident trace path (default '
                   '<dump-id>.incident.jsonl).')
def incident_export(dump_id: str, output: Optional[str]) -> None:
    """Export a flight-recorder dump as a versioned incident trace.

    DUMP_ID is a span-store dump trace id (or unique prefix) from
    `sky-tpu incident list` / `sky-tpu profile`. The exported JSONL
    carries the reconstructed arrival process and inferred fault
    timeline, scrubbed to lengths + cohort hashes — no prompt
    content. Replay it with `sky-tpu incident replay` or commit it
    under tests/sim/incidents/ as a permanent regression gate.
    """
    from skypilot_tpu.observability import incident as incident_lib
    from skypilot_tpu.observability import store as store_lib

    try:
        trace = incident_lib.trace_from_spans(
            incident_lib.find_dump(store_lib.SpanStore(), dump_id))
    except ValueError as e:
        raise click.ClickException(str(e))
    path = output or f"{trace.meta.get('dump_id', dump_id)}" \
                     f'.incident.jsonl'
    from skypilot_tpu.sim import tracefmt
    tracefmt.save(trace, path)
    click.echo(f'wrote {path}: trigger='
               f"{trace.meta.get('trigger')}, "
               f'{len(trace.requests)} request(s), '
               f'{len(trace.faults)} fault(s), '
               f'{len(trace.kills)} kill(s)')
    if trace.truncated:
        # No-silent-caps: a wrapped evidence ring makes a PARTIAL
        # incident — say exactly how much history fell off.
        click.echo(
            f'WARNING: evidence rings wrapped before the dump — '
            f"{trace.meta.get('dropped_request_events', 0)} request "
            f'event(s) and '
            f"{trace.meta.get('dropped_fleet_events', 0)} fleet "
            f'event(s) fell off; the trace is marked '
            f'truncated: true')


@incident.command('replay')
@click.argument('trace_file')
@click.option('--seed', default=0, show_default=True)
@click.option('--json', 'as_json', is_flag=True,
              help='Machine-readable verdict JSON.')
def incident_replay(trace_file: str, seed: int,
                    as_json: bool) -> None:
    """Replay an exported incident in the digital twin and verify the
    recorded anomaly class reproduces (same page-alert sequence)."""
    import json as json_lib

    from skypilot_tpu.observability import incident as incident_lib
    from skypilot_tpu.sim import tracefmt

    try:
        trace = tracefmt.load(trace_file)
    except ValueError as e:
        raise click.ClickException(str(e))
    report = incident_lib.replay(trace, seed=seed)
    problems = incident_lib.verify_replay(trace, report)
    if as_json:
        click.echo(json_lib.dumps({
            'reproduced': not problems, 'problems': problems,
            'recorded_page_firing':
                trace.meta.get('expected_page_firing') or [],
            'summary': report.summary()}, indent=1, sort_keys=True))
    else:
        click.echo(f'replayed {len(report.records)} request(s), '
                   f'{len(report.slo_alerts)} alert transition(s)')
        for p in problems:
            click.echo(f'PROBLEM: {p}')
        click.echo('reproduced: ' + ('yes' if not problems else 'NO'))
    if problems:
        sys.exit(1)


@cli.command('simulate')
@click.option('--spec', 'spec_path', default=None,
              help='Service YAML whose replica_policy/'
                   'load_balancing_policy/slo sections override the '
                   "trace's recorded config (optional `sim:` section "
                   'for twin-only knobs).')
@click.option('--trace', 'trace_path', required=True,
              help='Trace file: a loadgen trace (replayed verbatim) '
                   'or an exported incident (arrival process + fault '
                   'timeline reconstruction).')
@click.option('--seed', default=0, show_default=True)
@click.option('--sweep', 'sweep_arg', default=None,
              help='One-knob sweep key=v1,v2,... over Scenario '
                   'fields (e.g. slots=4,8 or lb_sync_s=5,15); '
                   'emits a ranked table with per-run decision-log '
                   'digests.')
@click.option('--json', 'as_json', is_flag=True,
              help='Raw summary JSON instead of the report.')
def simulate_cmd(spec_path: Optional[str], trace_path: str,
                 seed: int, sweep_arg: Optional[str],
                 as_json: bool) -> None:
    """What-if simulation (docs/simulation.md): run a recorded trace
    through the digital twin headless and report SLO burn, shed/
    resume/quarantine counts, autoscaler churn, and metered cost —
    deterministically per seed."""
    import json as json_lib

    from skypilot_tpu.sim import tracefmt
    from skypilot_tpu.sim import whatif

    try:
        trace = tracefmt.load(trace_path)
    except ValueError as e:
        raise click.ClickException(str(e))
    spec: dict = {}
    if spec_path:
        import yaml as yaml_lib
        with open(os.path.expanduser(spec_path),
                  encoding='utf-8') as f:
            doc = yaml_lib.safe_load(f) or {}
        spec = doc.get('service') or doc
    try:
        scenario = whatif.scenario_from_spec(spec, trace)
        if sweep_arg:
            key, values = whatif.parse_sweep(sweep_arg)
            rows = whatif.run_sweep(scenario, key, values, seed=seed)
            if as_json:
                click.echo(json_lib.dumps(rows, indent=1,
                                          sort_keys=True))
            else:
                click.echo(whatif.sweep_table(rows))
            return
        summary = whatif.run_simulate(scenario, seed=seed)
    except ValueError as e:
        raise click.ClickException(str(e))
    if as_json:
        click.echo(json_lib.dumps(summary, indent=1, sort_keys=True))
        return
    click.echo(f"scenario {summary['scenario']} @ seed {seed}: "
               f"{summary['requests']} request(s), "
               f"{summary['completed']} completed, "
               f"{summary['shed']} shed, "
               f"{summary['client_errors']} client error(s), "
               f"{summary['resumed']} resumed, "
               f"{summary['quarantines']} quarantine(s)")
    slo = summary['slo']
    click.echo(f"SLO: page firing {slo['page_firing'] or 'none'}; "
               f"alerts by tier {slo['alerts_by_tier'] or '{}'}")
    auto = summary['autoscaler']
    click.echo(f"autoscaler: {auto['launches']} launch(es), "
               f"{auto['drains']} drain(s), churn {auto['churn']} "
               f"over targets {auto['targets'] or '[]'}")
    if summary['cost']:
        click.echo(f"cost: {summary['cost']}")
    click.echo(f"ttft: p50 {summary['ttft_p50_s']} "
               f"p99 {summary['ttft_p99_s']}")
    click.echo(f"decision log sha256: "
               f"{summary['decision_log_sha256']}")


@cli.command('show-accelerators')
@click.option('--filter', 'name_filter', default=None)
def show_accelerators(name_filter: Optional[str]) -> None:
    """List accelerators with pricing."""
    from skypilot_tpu import catalog
    accs = catalog.list_accelerators(name_filter=name_filter)
    fmt = '{:<12} {:<8} {:<6} {:<10} {:>10} {:>10}'
    click.echo(fmt.format('ACCELERATOR', 'CLOUD', 'HOSTS', 'TOPOLOGY',
                          '$/HR', 'SPOT $/HR'))
    for name in sorted(accs):
        for o in accs[name]:
            click.echo(fmt.format(
                name, o['cloud'], o.get('num_hosts', 1),
                o.get('topology', '-'),
                f"{o['price']:.2f}", f"{o['spot_price']:.2f}"))


@cli.command('cost-report')
def cost_report() -> None:
    """Cost of terminated clusters."""
    rows = _engine().cost_report()
    fmt = '{:<18} {:>10} {:>10}'
    click.echo(fmt.format('CLUSTER', 'HOURS', 'COST $'))
    for r in rows:
        click.echo(fmt.format(r['name'], f"{r['duration_hours']:.2f}",
                              f"{r['cost']:.2f}"))


def _changed_lint_paths() -> frozenset:
    """Package-relative paths of files changed vs git (worktree diff
    against HEAD + untracked), for `sky-tpu lint --changed`."""
    import subprocess

    import skypilot_tpu
    pkg_root = os.path.dirname(os.path.abspath(skypilot_tpu.__file__))
    repo_root = os.path.dirname(pkg_root)
    pkg_name = os.path.basename(pkg_root)
    try:
        diff = subprocess.run(
            ['git', '-C', repo_root, 'diff', '--name-only', 'HEAD'],
            capture_output=True, text=True, check=True)
        untracked = subprocess.run(
            ['git', '-C', repo_root, 'ls-files', '--others',
             '--exclude-standard'],
            capture_output=True, text=True, check=True)
    except (OSError, subprocess.CalledProcessError) as e:
        detail = getattr(e, 'stderr', '') or str(e)
        raise click.ClickException(
            f'--changed needs a git worktree: {detail.strip()}') from e
    out = set()
    for line in (diff.stdout + untracked.stdout).splitlines():
        line = line.strip()
        if line.startswith(f'{pkg_name}/') and line.endswith('.py'):
            out.add(line[len(pkg_name) + 1:])
        elif line.startswith('docs/') and line.endswith('.md'):
            out.add(line)
    return frozenset(out)


@cli.command('lint')
@click.argument('path', required=False)
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='Machine-readable report (findings, offenders, '
                   'stale allowlist entries).')
@click.option('--verbose', '-v', is_flag=True, default=False,
              help='Also list allowlisted findings.')
@click.option('--no-allowlist', is_flag=True, default=False,
              help='Ignore the audited allowlist: report, and fail '
                   'on, every finding.')
@click.option('--changed', is_flag=True, default=False,
              help='Report only findings in files changed vs git '
                   '(diff against HEAD + untracked). The whole '
                   'package is still parsed — the interprocedural '
                   'passes need the full call graph — but the '
                   'parsed-module cache makes the re-scan cheap.')
def lint_cmd(path: Optional[str], as_json: bool, verbose: bool,
             no_allowlist: bool, changed: bool) -> None:
    """Run the AST-based invariant checkers over the package.

    Checkers (docs/static-analysis.md): SKY-LOCK (guarded-field lock
    discipline, interprocedural: `# holds:` annotations verified
    against real callers), SKY-ORDER (global lock-acquisition-order
    cycles + re-entrant non-reentrant acquisition), SKY-HOLD (no
    blocking operations — await/sleep/net/subprocess/device readback —
    while a lock is held), SKY-ASYNC (no blocking calls / sleep-polls
    in async and hot paths), SKY-EXCEPT (no swallowed reset/
    cancellation in serve/infer network paths), SKY-TRACE (no
    concretization or data-dependent branching in jit-reachable
    code), SKY-REGISTRY (failpoint sites + serving-metric keys in
    sync with the docs catalogs). PATH narrows the scan to one file
    or subtree (default: the whole installed package);
    ``--changed`` scopes the REPORT to git-changed files instead.
    Exits non-zero on any error-severity finding beyond the audited
    allowlist, or on a stale allowlist entry.
    """
    from skypilot_tpu import analysis
    report_paths = None
    if changed:
        if path:
            raise click.ClickException(
                'PATH and --changed are mutually exclusive')
        report_paths = _changed_lint_paths()
        if not report_paths:
            click.echo('lint --changed: no changed package files.')
            return
    try:
        report = analysis.run(
            root=path, allowlist={} if no_allowlist else None,
            report_paths=report_paths)
    except FileNotFoundError as e:
        raise click.ClickException(str(e)) from e
    if as_json:
        click.echo(report.to_json())
    else:
        click.echo(report.render_text(verbose=verbose))
    if not report.ok:
        sys.exit(1)


@cli.group()
def jobs() -> None:
    """Managed jobs: auto-recovering (spot) task execution."""


def _jobs_engine():
    """jobs facade: direct engine or SDK (mirrors _engine())."""
    if os.environ.get('SKY_TPU_API_SERVER'):
        from skypilot_tpu.client import sdk

        class _SdkJobs:
            launch = staticmethod(
                lambda task, name=None, pool=None:
                sdk.jobs_launch(task, name, pool=pool))
            queue = staticmethod(sdk.jobs_queue)
            cancel = staticmethod(sdk.jobs_cancel)
            pool_apply = staticmethod(sdk.jobs_pool_apply)
            pool_status = staticmethod(sdk.jobs_pool_status)
            pool_down = staticmethod(sdk.jobs_pool_down)
        return _SdkJobs
    from skypilot_tpu import jobs as jobs_lib
    return jobs_lib


@jobs.command('launch')
@click.argument('task_yaml', required=False)
@click.option('--recipe', default=None,
              help='Launch a stored recipe instead of a YAML file '
                   '(pipelines supported).')
@click.option('--name', '-n', default=None, help='Job name.')
@click.option('--pool', '-p', default=None,
              help='Run on a claimed worker from this pre-provisioned '
                   'pool instead of provisioning a cluster '
                   '(sky-tpu jobs pool apply).')
@click.option('--env', multiple=True, help='KEY=VALUE env override.')
@click.option('--yes', '-y', is_flag=True, default=False)
def jobs_launch(task_yaml: Optional[str], recipe: Optional[str],
                name: Optional[str], pool: Optional[str], env: tuple,
                yes: bool) -> None:
    """Submit a managed job (auto-recovers on preemption).

    A multi-document YAML submits a managed PIPELINE: stages run
    sequentially, each with its own cluster and per-stage recovery.
    --recipe NAME launches a stored template (sky-tpu recipe ls).
    --pool NAME runs on an idle worker of a pre-provisioned pool.
    """
    from skypilot_tpu.utils import dag_utils
    if (task_yaml is None) == (recipe is None):
        raise click.UsageError('pass exactly one of TASK_YAML or '
                               '--recipe NAME')
    if recipe:
        if _remote():
            from skypilot_tpu.client import sdk
            rec = sdk.call('recipes.get', {'name': recipe})
        else:
            from skypilot_tpu import recipes as recipes_lib
            rec = recipes_lib.get(recipe)
        dag = dag_utils.load_dag_from_yaml_str(
            rec['yaml'], env_overrides=_env_overrides(env))
        name = name or recipe
    else:
        dag = dag_utils.load_dag_from_yaml(
            task_yaml, env_overrides=_env_overrides(env))
    if len(dag) > 1:
        stages = ', '.join(t.name or f'stage-{i}'
                           for i, t in enumerate(dag.tasks))
        if not yes:
            click.confirm(
                f'Submitting managed pipeline '
                f'{name or dag.name or task_yaml} '
                f'({len(dag)} stages: {stages}). Proceed?', abort=True)
        job_id = _jobs_engine().launch(dag, name=name, pool=pool)
    else:
        task = dag.tasks[0]
        if not yes:
            where = (f'pool {pool}' if pool
                     else repr(task.resources))
            click.confirm(
                f'Submitting managed job {name or task.name or task_yaml} '
                f'({where}). Proceed?', abort=True)
        job_id = _jobs_engine().launch(task, name=name, pool=pool)
    click.echo(f'Managed job: {job_id}')
    click.echo(f'Watch: sky-tpu jobs queue   '
               f'logs: sky-tpu jobs logs {job_id}')


@jobs.group('pool')
def jobs_pool() -> None:
    """Worker pools: pre-provisioned clusters that managed jobs reuse."""


@jobs_pool.command('apply')
@click.argument('pool_yaml', required=False)
@click.option('--pool', '-p', 'pool_name', default=None,
              help='Pool name (defaults to the task name).')
@click.option('--workers', type=int, default=None,
              help='Override (or, without YAML, resize to) this many '
                   'workers.')
@click.option('--yes', '-y', is_flag=True, default=False)
def jobs_pool_apply_cmd(pool_yaml: Optional[str],
                        pool_name: Optional[str],
                        workers: Optional[int], yes: bool) -> None:
    """Create/update a worker pool from YAML, or resize with --workers.

    The YAML needs a `pool:` section (pool: {workers: N}) instead of
    `service:`; `setup:` pre-bakes each worker once, and jobs launched
    with `--pool NAME` bring their own `run` command.
    """
    task = None
    if pool_yaml is not None:
        from skypilot_tpu import task as task_lib
        task = task_lib.Task.from_yaml(pool_yaml)
    elif workers is None or pool_name is None:
        raise click.UsageError('pass POOL_YAML, or both --pool NAME and '
                               '--workers N to resize')
    if not yes:
        what = (f'apply {pool_yaml}' if task is not None
                else f'resize to {workers} workers')
        click.confirm(f'Pool {pool_name or (task and task.name)}: '
                      f'{what}. Proceed?', abort=True)
    out = _jobs_engine().pool_apply(task, pool_name=pool_name,
                                    workers=workers)
    click.echo(f'Pool {out["name"]}: {out["workers"]} workers '
               f'(version {out["version"]})')
    click.echo(f'Watch: sky-tpu jobs pool status {out["name"]}   '
               f'launch onto it: sky-tpu jobs launch --pool '
               f'{out["name"]} task.yaml')


@jobs_pool.command('status')
@click.argument('pool_names', nargs=-1)
def jobs_pool_status_cmd(pool_names: tuple) -> None:
    """Show pool(s) and their workers' job assignments."""
    snaps = _jobs_engine().pool_status(list(pool_names) or None)
    if not snaps:
        click.echo('No pools.')
        return
    for s in snaps:
        click.echo(f'{s["name"]}: {s["status"]}  '
                   f'ready {s["ready_replicas"]}/{s["target_workers"]}  '
                   f'idle {s["idle_workers"]}')
        fmt = '  {:<4} {:<24} {:<14} {:<10}'
        click.echo(fmt.format('ID', 'CLUSTER', 'STATUS', 'JOB'))
        for r in s['replicas']:
            click.echo(fmt.format(
                r['replica_id'], (r['cluster_name'] or '')[:24],
                r['status'],
                r['assigned_job'] if r['assigned_job'] else 'idle'))


@jobs_pool.command('down')
@click.argument('pool_name')
@click.option('--purge', is_flag=True, default=False,
              help='Force-clean a pool whose controller died.')
@click.option('--yes', '-y', is_flag=True, default=False)
def jobs_pool_down_cmd(pool_name: str, purge: bool, yes: bool) -> None:
    """Tear down a pool and all its workers."""
    if not yes:
        click.confirm(f'Tear down pool {pool_name} and all its workers?',
                      abort=True)
    _jobs_engine().pool_down(pool_name, purge=purge)
    click.echo(f'Pool {pool_name}: down.')


@jobs.command('queue')
def jobs_queue() -> None:
    """List managed jobs."""
    rows = _jobs_engine().queue()
    fmt = '{:<6} {:<18} {:<16} {:>4} {:<20}'
    click.echo(fmt.format('ID', 'NAME', 'STATUS', 'REC', 'CLUSTER'))
    for j in rows:
        click.echo(fmt.format(j['job_id'], (j['name'] or '')[:18],
                              j['status'], j['recovery_count'],
                              j['cluster_name'] or '-'))
        for t in j.get('tasks') or []:
            click.echo(fmt.format(
                f' ↳{t["task_id"]}', (t['name'] or '')[:18],
                t['status'], t['recovery_count'],
                t['cluster_name'] or '-'))


@jobs.command('cancel')
@click.argument('job_id', type=int)
@click.option('--yes', '-y', is_flag=True, default=False)
def jobs_cancel(job_id: int, yes: bool) -> None:
    """Cancel a managed job (tears its cluster down)."""
    if not yes:
        click.confirm(f'Cancel managed job {job_id}?', abort=True)
    _jobs_engine().cancel(job_id)
    click.echo(f'Cancellation requested for job {job_id}.')


@jobs.command('logs')
@click.argument('job_id', type=int)
@click.option('--follow/--no-follow', default=True)
@click.option('--controller', is_flag=True, default=False,
              help='Show the controller log instead of the job output.')
def jobs_logs(job_id: int, follow: bool, controller: bool) -> None:
    """Tail a managed job's output (or its controller's log)."""
    server_mode = bool(os.environ.get('SKY_TPU_API_SERVER'))
    if controller:
        if server_mode:
            raise click.ClickException(
                '--controller logs live on the API-server host; run there '
                'without SKY_TPU_API_SERVER set.')
        from skypilot_tpu import jobs as jobs_lib
        for chunk in jobs_lib.tail_controller_logs(job_id, follow=follow):
            sys.stdout.buffer.write(chunk)
            sys.stdout.buffer.flush()
        return
    if server_mode:
        # The server's DB owns managed jobs; resolve the cluster through
        # it and stream via the server's log proxy.
        from skypilot_tpu.client import sdk
        records = [j for j in sdk.jobs_queue() if j['job_id'] == job_id]
        if not records:
            raise click.ClickException(f'No managed job {job_id}.')
        record, tail = records[0], sdk.tail_logs
    else:
        from skypilot_tpu import core as core_lib
        from skypilot_tpu import jobs as jobs_lib
        record, tail = jobs_lib.get(job_id), core_lib.tail_logs
    cluster, cjid = record['cluster_name'], record['cluster_job_id']
    if not cluster or cjid < 0:
        raise click.ClickException(
            f'Job {job_id} has no cluster yet ({record["status"]}); try '
            f'--controller for the launch narration.')
    for chunk in tail(cluster, cjid, follow=follow):
        sys.stdout.buffer.write(chunk)
        sys.stdout.buffer.flush()


@cli.group()
def serve() -> None:
    """Serving: replicated, auto-scaled services behind a load balancer."""


def _serve_engine():
    """serve facade: direct engine or SDK (mirrors _engine())."""
    if os.environ.get('SKY_TPU_API_SERVER'):
        from skypilot_tpu.client import sdk

        class _SdkServe:
            up = staticmethod(
                lambda task, service_name=None: sdk.serve_up(
                    task, service_name))
            update = staticmethod(sdk.serve_update)
            down = staticmethod(lambda name: sdk.serve_down(name))
            status = staticmethod(sdk.serve_status)
            restart_replica = staticmethod(sdk.serve_restart_replica)
        return _SdkServe
    from skypilot_tpu import serve as serve_lib
    return serve_lib


@serve.command('up')
@click.argument('task_yaml')
@click.option('--service-name', '-n', default=None)
@click.option('--env', multiple=True, help='KEY=VALUE env override.')
@click.option('--yes', '-y', is_flag=True, default=False)
def serve_up(task_yaml: str, service_name: Optional[str], env: tuple,
             yes: bool) -> None:
    """Start a service from a YAML with a `service:` section."""
    task = _load_task(task_yaml, env)
    if not yes:
        click.confirm(
            f'Starting service {service_name or task.name or task_yaml} '
            f'({task.resources!r} per replica). Proceed?', abort=True)
    out = _serve_engine().up(task, service_name)
    if out.get('respawned'):
        click.echo(f'Re-attached a controller to existing service '
                   f'{out["name"]} (crash recovery).')
    if out.get('warning'):
        click.echo(f'WARNING: {out["warning"]}')
    click.echo(f'Service: {out["name"]}  endpoint: {out["endpoint"]}')
    click.echo(f'Watch replicas: sky-tpu serve status {out["name"]}')


@serve.command('update')
@click.argument('service_name')
@click.argument('task_yaml')
@click.option('--env', multiple=True)
def serve_update(service_name: str, task_yaml: str, env: tuple) -> None:
    """Roll a service to a new task version (zero-downtime)."""
    task = _load_task(task_yaml, env)
    version = _serve_engine().update(task, service_name)
    click.echo(f'Service {service_name} rolling to version {version}.')


@serve.command('down')
@click.argument('service_name')
@click.option('--yes', '-y', is_flag=True, default=False)
def serve_down(service_name: str, yes: bool) -> None:
    """Tear down a service and all its replicas."""
    if not yes:
        click.confirm(f'Tear down service {service_name}?', abort=True)
    _serve_engine().down(service_name)
    click.echo(f'Service {service_name} torn down.')


@serve.command('restart-replica')
@click.argument('service_name')
@click.argument('replica_id', type=int)
@click.option('--yes', '-y', is_flag=True, default=False)
def serve_restart_replica(service_name: str, replica_id: int,
                          yes: bool) -> None:
    """Replace one replica: terminate it; the autoscaler launches a
    substitute to hold the target count."""
    if not yes:
        click.confirm(f'Restart replica {replica_id} of '
                      f'{service_name}?', abort=True)
    _serve_engine().restart_replica(service_name, replica_id)
    click.echo(f'Replica {replica_id} flagged for replacement.')


@serve.command('status')
@click.argument('service_name', required=False)
def serve_status(service_name: Optional[str]) -> None:
    """Show services and their replicas."""
    snaps = _serve_engine().status(service_name)
    if not snaps:
        click.echo('No services.')
        return
    for s in snaps:
        click.echo(f'{s["name"]}: {s["status"]} v{s["version"]} '
                   f'endpoint={s["endpoint"]} policy={s["policy"]}')
        if s.get('degraded_reason'):
            # Stale-pid detection (docs/robustness.md "Crash safety"):
            # the controller process is dead — say how to recover.
            click.echo(f'  !! {s["degraded_reason"]}')
            # Open intents are a normal in-flight journal when the
            # controller lives (every launch holds one while
            # provisioning); they are only an ALARM when nothing is
            # left alive to finish them.
            if s.get('intents_open'):
                click.echo(f'  !! {s["intents_open"]} lifecycle '
                           f'intent(s) open — recovery owed to the '
                           f'respawned controller')
        fmt = '  {:<4} {:<22} {:<14} {:<4} {:<24}'
        click.echo(fmt.format('ID', 'CLUSTER', 'STATUS', 'VER', 'URL'))
        for r in s['replicas']:
            click.echo(fmt.format(r['replica_id'], r['cluster_name'],
                                  r['status'], r['version'],
                                  r['url'] or '-'))
            # Integrity quarantine (docs/robustness.md "Data
            # integrity"): say WHY and for how long — the reason
            # column survives the drain-and-replace transitions.
            if r.get('quarantine_reason'):
                age = ''
                if r.get('quarantined_at'):
                    age = (f', {time.time() - r["quarantined_at"]:.0f}s'
                           f' ago')
                click.echo(f'       !! quarantined: '
                           f'{r["quarantine_reason"]}{age}')


@cli.group()
def api() -> None:
    """Manage the local API server."""


@api.command('start')
@click.option('--host', default='127.0.0.1')
@click.option('--port', type=int, default=common.DEFAULT_API_PORT)
@click.option('--foreground', is_flag=True, default=False)
def api_start(host: str, port: int, foreground: bool) -> None:
    """Start the API server (background daemon by default)."""
    import subprocess
    import time as time_lib

    from skypilot_tpu.utils import common as common_lib
    if foreground:
        from skypilot_tpu.server import app as server_app
        sys.argv = ['app', '--host', host, '--port', str(port)]
        server_app.main()
        return
    log = open(os.path.join(common_lib.base_dir(), 'api_server.log'), 'ab')
    subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.server.app',
         '--host', host, '--port', str(port)],
        stdout=log, stderr=subprocess.STDOUT, start_new_session=True)
    url = f'http://{host}:{port}'
    deadline = time_lib.time() + 15
    import requests as requests_lib
    while time_lib.time() < deadline:
        try:
            if requests_lib.get(f'{url}/api/health', timeout=1).ok:
                click.echo(f'API server running at {url}')
                click.echo(f'Point clients at it: '
                           f'export SKY_TPU_API_SERVER={url}')
                return
        except requests_lib.RequestException:
            time_lib.sleep(0.3)
    raise click.ClickException('API server failed to start (see '
                               '~/.sky_tpu/api_server.log)')


@api.command('stop')
def api_stop() -> None:
    """Stop the background API server."""
    import json as json_lib
    import signal

    from skypilot_tpu.utils import common as common_lib
    meta_path = os.path.join(common_lib.base_dir(), 'api_server.json')
    if not os.path.exists(meta_path):
        click.echo('No API server metadata found.')
        return
    with open(meta_path, encoding='utf-8') as f:
        meta = json_lib.load(f)
    try:
        os.kill(meta['pid'], signal.SIGTERM)
        click.echo(f'Stopped API server (pid {meta["pid"]}).')
    except ProcessLookupError:
        click.echo('API server not running.')
    os.unlink(meta_path)


@api.command('status')
def api_status() -> None:
    """Probe the API server's health."""
    from skypilot_tpu.client import sdk
    health = sdk.api_health()
    click.echo(f'{sdk.server_url()}: {health["status"]} '
               f'(v{health["version"]}, api {health["api_version"]})')


@api.command('login')
@click.option('--timeout', type=float, default=300.0,
              help='Seconds to wait for the browser authorization.')
def api_login(timeout: float) -> None:
    """Log in to a remote API server (PKCE browser flow).

    Opens the server's /auth/authorize page; once the (SSO-
    authenticated) browser confirms, the CLI receives a bearer token
    and persists it for subsequent commands.
    """
    import secrets as pysecrets
    import time as time_lib
    import webbrowser

    import requests as requests_lib

    from skypilot_tpu.client import sdk
    from skypilot_tpu.server.auth import sessions
    url = sdk.server_url()
    verifier = pysecrets.token_urlsafe(32)
    challenge = sessions.compute_code_challenge(verifier)
    authorize = f'{url}/auth/authorize?code_challenge={challenge}'
    click.echo(f'Authorize this CLI in your browser:\n  {authorize}')
    click.echo(f'Verification code: {sessions.user_code(challenge)} '
               '— the browser page must show the SAME code before you '
               'click Authorize.')
    try:
        webbrowser.open(authorize)
    except Exception:  # noqa: BLE001 — headless host; URL printed above
        pass
    deadline = time_lib.time() + timeout
    while time_lib.time() < deadline:
        try:
            r = requests_lib.post(f'{url}/auth/token',
                                  json={'code_verifier': verifier},
                                  timeout=10)
        except requests_lib.RequestException as e:
            raise click.ClickException(f'API server unreachable: {e}')
        if r.status_code == 200:
            token = r.json()['token']
            token_path = os.path.join(
                os.path.expanduser('~/.sky_tpu'), 'token')
            os.makedirs(os.path.dirname(token_path), exist_ok=True)
            fd = os.open(token_path, os.O_WRONLY | os.O_CREAT |
                         os.O_TRUNC, 0o600)
            with os.fdopen(fd, 'w') as f:
                f.write(token)
            click.echo(f'Logged in. Token saved to {token_path}; '
                       f'export SKY_TPU_API_TOKEN=$(cat {token_path})')
            return
        time_lib.sleep(2.0)
    raise click.ClickException('Login timed out (browser authorization '
                               'never arrived).')


def _remote() -> bool:
    """True when ops should go through the API server (its RBAC applies;
    acting on the local DB would mint tokens the server rejects)."""
    return bool(os.environ.get('SKY_TPU_API_SERVER'))


@cli.command('dump')
@click.option('--output', '-o', default=None)
@click.option('--no-logs', is_flag=True, default=False)
def dump(output, no_logs) -> None:
    """Bundle state + logs into a diagnostics tarball (server-side
    state when an API server is configured, then downloaded)."""
    if _remote():
        from skypilot_tpu.client import sdk
        sdk.ensure_server_compatibility()
        remote_path = sdk.call('debug_dump',
                               {'include_logs': not no_logs})
        filename = os.path.basename(remote_path)
        local = output or filename
        sdk.download_dump(filename, local)
        click.echo(local)
        return
    from skypilot_tpu import core as core_lib
    path = core_lib.debug_dump(output, include_logs=not no_logs)
    click.echo(path)


@cli.group()
def users() -> None:
    """User management + service-account tokens (RBAC)."""


@users.command('ls')
def users_ls() -> None:
    if _remote():
        from skypilot_tpu.client import sdk
        rows = sdk.call('users.list')
    else:
        from skypilot_tpu import users as users_lib
        users_lib.core.ensure_user()
        rows = users_lib.list_users()
    fmt = '{:<10} {:<16} {:<8}'
    click.echo(fmt.format('ID', 'NAME', 'ROLE'))
    for u in rows:
        click.echo(fmt.format(u['id'], u['name'], u['role']))


@users.command('role')
@click.argument('user_id')
@click.argument('role')
def users_role(user_id: str, role: str) -> None:
    if _remote():
        from skypilot_tpu.client import sdk
        sdk.call('users.role', {'user_id': user_id, 'role': role})
    else:
        from skypilot_tpu import users as users_lib
        users_lib.update_role(user_id, role)
    click.echo(f'{user_id}: role={role}')


@users.command('token-create')
@click.argument('name')
@click.option('--expires-days', type=float, default=None)
def users_token_create(name: str, expires_days: Optional[float]) -> None:
    """Mint a service-account token (shown once; store it safely)."""
    expires = expires_days * 86400 if expires_days else None
    if _remote():
        from skypilot_tpu.client import sdk
        token = sdk.call('users.token_create',
                         {'name': name, 'expires_in_s': expires})
    else:
        from skypilot_tpu import users as users_lib
        token = users_lib.create_token(name, expires_in_s=expires)
    click.echo(token)


@users.command('tokens')
def users_tokens() -> None:
    if _remote():
        from skypilot_tpu.client import sdk
        rows = sdk.call('users.token_list')
    else:
        from skypilot_tpu import users as users_lib
        rows = users_lib.list_tokens()
    fmt = '{:<18} {:<14} {:<10} {:<8}'
    click.echo(fmt.format('TOKEN_ID', 'NAME', 'USER', 'REVOKED'))
    for t in rows:
        click.echo(fmt.format(t['token_id'], t['name'], t['user_id'],
                              'yes' if t['revoked'] else 'no'))


@users.command('token-revoke')
@click.argument('token_id')
def users_token_revoke(token_id: str) -> None:
    if _remote():
        from skypilot_tpu.client import sdk
        sdk.call('users.token_revoke', {'token_id': token_id})
    else:
        from skypilot_tpu import users as users_lib
        users_lib.revoke_token(token_id)
    click.echo(f'{token_id}: revoked')


@cli.group()
def workspaces() -> None:
    """Workspaces: scoped cluster/config namespaces."""


@workspaces.command('ls')
def workspaces_ls() -> None:
    from skypilot_tpu import workspaces as ws_lib
    if _remote():
        from skypilot_tpu.client import sdk
        all_ws = sdk.call('workspaces.list')
    else:
        all_ws = ws_lib.get_workspaces()
    active = ws_lib.active_workspace()
    for name, cfg in all_ws.items():
        mark = '*' if name == active else ' '
        priv = ' (private)' if (cfg or {}).get('private') else ''
        click.echo(f'{mark} {name}{priv}')


@workspaces.command('create')
@click.argument('name')
@click.option('--private', is_flag=True, default=False)
@click.option('--allowed-user', 'allowed_users', multiple=True)
def workspaces_create(name: str, private: bool,
                      allowed_users: tuple) -> None:
    cfg = {}
    if private:
        cfg['private'] = True
        cfg['allowed_users'] = list(allowed_users)
    if _remote():
        from skypilot_tpu.client import sdk
        sdk.call('workspaces.create', {'name': name, 'config': cfg})
    else:
        from skypilot_tpu import workspaces as ws_lib
        ws_lib.create_workspace(name, cfg)
    click.echo(f'Workspace {name} created.')


@workspaces.command('delete')
@click.argument('name')
def workspaces_delete(name: str) -> None:
    if _remote():
        from skypilot_tpu.client import sdk
        sdk.call('workspaces.delete', {'name': name})
    else:
        from skypilot_tpu import workspaces as ws_lib
        ws_lib.delete_workspace(name)
    click.echo(f'Workspace {name} deleted.')


@workspaces.command('switch')
@click.argument('name')
def workspaces_switch(name: str) -> None:
    """Set the active workspace in the global config."""
    from skypilot_tpu import config as config_lib
    from skypilot_tpu import users as users_lib
    from skypilot_tpu import workspaces as ws_lib
    # Raises for unknown workspaces and for private ones that exclude
    # the local identity.
    ws_lib.check_workspace_permission(users_lib.core.ensure_user(), name)
    config_lib.update_global({'active_workspace': name})
    click.echo(f'Active workspace: {name}')


@cli.group()
def pools() -> None:
    """Bare-metal SSH node pools (reference `sky ssh`)."""


@pools.command('ls')
def pools_ls() -> None:
    if _remote():
        from skypilot_tpu.client import sdk
        all_pools = sdk.call('pools.list')
    else:
        from skypilot_tpu.ssh_node_pools import SSHNodePoolManager
        all_pools = SSHNodePoolManager().get_all_pools()
    fmt = '{:<16} {:<7} {:<14} {:<6} {}'
    click.echo(fmt.format('POOL', 'HOSTS', 'ACCELERATOR', 'MODE',
                          'FIRST_HOST'))
    for name, cfg in all_pools.items():
        click.echo(fmt.format(name, len(cfg['hosts']),
                              cfg.get('accelerator', '-'),
                              cfg.get('mode', 'ssh'), cfg['hosts'][0]))


@pools.command('apply')
@click.argument('spec_yaml')
def pools_apply(spec_yaml: str) -> None:
    """Add/update pools from a YAML mapping of pool-name -> config.

    Pools live on the API server when one is configured — launches
    resolve pools server-side.
    """
    import yaml as yaml_lib
    with open(os.path.expanduser(spec_yaml), encoding='utf-8') as f:
        cfg = yaml_lib.safe_load(f) or {}
    if _remote():
        from skypilot_tpu.client import sdk
        sdk.call('pools.apply', {'pools': cfg})
    else:
        from skypilot_tpu.ssh_node_pools import SSHNodePoolManager
        SSHNodePoolManager().update_pools(cfg)
    click.echo(f'Pools updated: {", ".join(cfg)}')


@pools.command('delete')
@click.argument('name')
def pools_delete(name: str) -> None:
    if _remote():
        from skypilot_tpu.client import sdk
        ok = sdk.call('pools.delete', {'name': name})
    else:
        from skypilot_tpu.ssh_node_pools import SSHNodePoolManager
        ok = SSHNodePoolManager().delete_pool(name)
    if ok:
        click.echo(f'Pool {name} deleted.')
    else:
        raise click.ClickException(f'No such pool: {name}')


@cli.group()
def volumes() -> None:
    """Persistent volumes (gcp-pd, gcsfuse, hostpath)."""


@volumes.command('apply')
@click.argument('spec_yaml')
def volumes_apply(spec_yaml: str) -> None:
    """Create/register a volume from a YAML spec."""
    import yaml as yaml_lib
    with open(os.path.expanduser(spec_yaml), encoding='utf-8') as f:
        cfg = yaml_lib.safe_load(f) or {}
    if _remote():
        from skypilot_tpu.client import sdk
        rec = sdk.call('volumes.apply', {'spec': cfg})
    else:
        from skypilot_tpu import volumes as volumes_lib
        rec = volumes_lib.volume_apply(cfg)
    click.echo(f'Volume {rec["name"]} ({rec["type"]}): {rec["status"]}')


@volumes.command('ls')
def volumes_ls() -> None:
    if _remote():
        from skypilot_tpu.client import sdk
        rows = sdk.call('volumes.list')
    else:
        from skypilot_tpu import volumes as volumes_lib
        rows = volumes_lib.volume_list()
    fmt = '{:<16} {:<10} {:<8} {:<14} {:>8} {:<10} {:<16}'
    click.echo(fmt.format('NAME', 'TYPE', 'CLOUD', 'ZONE', 'SIZE_GB',
                          'STATUS', 'ATTACHED_TO'))
    for v in rows:
        click.echo(fmt.format(v['name'], v['type'], v['cloud'],
                              v['zone'] or '-', v['size_gb'] or '-',
                              v['status'], v['attached_to'] or '-'))


@volumes.command('delete')
@click.argument('names', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True, default=False)
def volumes_delete(names: tuple, yes: bool) -> None:
    if not yes:
        click.confirm(f'Delete volume(s) {", ".join(names)}?', abort=True)
    if _remote():
        from skypilot_tpu.client import sdk
        sdk.call('volumes.delete', {'names': list(names)})
    else:
        from skypilot_tpu import volumes as volumes_lib
        volumes_lib.volume_delete(list(names))
    click.echo('Deleted.')


@cli.group()
def recipe() -> None:
    """Recipe hub: shareable, validated task templates
    (reference sky/recipes)."""


@recipe.command('add')
@click.argument('name')
@click.argument('task_yaml')
@click.option('--description', '-d', default='')
def recipe_add(name: str, task_yaml: str, description: str) -> None:
    """Validate + store TASK_YAML as recipe NAME."""
    with open(task_yaml, encoding='utf-8') as f:
        yaml_str = f.read()
    if _remote():
        from skypilot_tpu.client import sdk
        sdk.call('recipes.add', {'name': name, 'yaml': yaml_str,
                                 'description': description})
    else:
        from skypilot_tpu import recipes as recipes_lib
        recipes_lib.add(name, yaml_str, description=description)
    click.echo(f'Recipe {name!r} saved.')


@recipe.command('ls')
def recipe_ls() -> None:
    if _remote():
        from skypilot_tpu.client import sdk
        rows = sdk.call('recipes.list')
    else:
        from skypilot_tpu import recipes as recipes_lib
        rows = recipes_lib.list_recipes()
    fmt = '{:<24} {:<4} {:<16} {}'
    click.echo(fmt.format('NAME', 'VER', 'BY', 'DESCRIPTION'))
    for r in rows:
        click.echo(fmt.format(r['name'], 'v' + str(r['version']),
                              (r.get('created_by') or '-')[:15],
                              r.get('description') or '-'))


@recipe.command('show')
@click.argument('name')
def recipe_show(name: str) -> None:
    """Print a recipe's YAML."""
    if _remote():
        from skypilot_tpu.client import sdk
        rec = sdk.call('recipes.get', {'name': name})
    else:
        from skypilot_tpu import recipes as recipes_lib
        rec = recipes_lib.get(name)
    click.echo(rec['yaml'])


@recipe.command('rm')
@click.argument('name')
@click.option('--yes', '-y', is_flag=True, default=False)
def recipe_rm(name: str, yes: bool) -> None:
    if not yes:
        click.confirm(f'Delete recipe {name}?', abort=True)
    if _remote():
        from skypilot_tpu.client import sdk
        sdk.call('recipes.delete', {'name': name})
    else:
        from skypilot_tpu import recipes as recipes_lib
        recipes_lib.delete(name)
    click.echo(f'Recipe {name!r} deleted.')


@recipe.command('launch')
@click.argument('name')
@click.option('--cluster', '-c', default=None)
@click.option('--env', multiple=True, help='KEY=VALUE env override.')
@click.option('--yes', '-y', is_flag=True, default=False)
def recipe_launch(name: str, cluster: Optional[str], env: tuple,
                  yes: bool) -> None:
    """Launch a stored recipe (single-task recipes)."""
    bad = [e for e in env if '=' not in e]
    if bad:
        raise click.UsageError(
            f'--env must be KEY=VALUE, got {bad[0]!r}')
    envs = dict(e.split('=', 1) for e in env)
    if not yes:
        click.confirm(f'Launch recipe {name}?', abort=True)
    if _remote():
        from skypilot_tpu.client import sdk
        out = sdk.call('recipes.launch', {'name': name,
                                          'cluster_name': cluster,
                                          'env_overrides': envs})
        click.echo(f'Launched: {out}')
    else:
        from skypilot_tpu import recipes as recipes_lib
        job_id, info = recipes_lib.launch(name, cluster,
                                          env_overrides=envs)
        click.echo(f'Cluster: {info.cluster_name}  job: {job_id}')


def main() -> None:
    try:
        cli(standalone_mode=False)
    except click.Abort:
        click.echo('Aborted.')
        sys.exit(1)
    except click.ClickException as e:
        e.show()
        sys.exit(e.exit_code)
    except sky.exceptions.SkyTpuError as e:
        click.echo(f'Error: {e}', err=True)
        sys.exit(1)


if __name__ == '__main__':
    main()
