"""Async Python SDK over the REST API server (aiohttp).

Counterpart of the reference's ``sky/client/sdk_async.py``: the same
surface as the sync SDK (``client/sdk.py``) with every call awaitable and
log tails exposed as async iterators — for agents, notebooks and servers
that multiplex many control-plane calls on one event loop.

Implementation notes: the wire protocol is identical to the sync SDK
(POST op → request_id → poll ``/api/get``); URL/auth/compat logic is
imported from the sync module so the two cannot drift. CPU-bound work
(zipping a workdir for upload) runs in a thread via asyncio.to_thread.
"""
from __future__ import annotations

import asyncio
import json
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

import aiohttp

from skypilot_tpu import exceptions
from skypilot_tpu import task as task_lib
from skypilot_tpu.client import sdk as _sync
from skypilot_tpu.provision.common import ClusterInfo
from skypilot_tpu.utils import common

server_url = _sync.server_url

_POLL_S = 0.3


def _headers() -> Dict[str, str]:
    return _sync._auth_headers()  # noqa: SLF001 — shared by design


async def _post_raw(op: str, payload: Dict[str, Any]) -> Dict[str, Any]:
    url = server_url()
    try:
        async with aiohttp.ClientSession() as sess:
            async with sess.post(f'{url}/{op}', json=payload,
                                 headers=_headers(),
                                 timeout=aiohttp.ClientTimeout(
                                     total=30)) as r:
                if r.status in (400, 401, 403, 404, 426, 501):
                    try:
                        body = await r.json()
                        detail = body.get('error', '')
                    except (aiohttp.ContentTypeError,
                            json.JSONDecodeError):
                        detail = await r.text()
                    raise exceptions.SkyTpuError(detail)
                r.raise_for_status()
                return await r.json()
    except aiohttp.ClientError as e:
        raise exceptions.ApiServerConnectionError(url) from e


async def _post(op: str, payload: Dict[str, Any]) -> str:
    return (await _post_raw(op, payload))['request_id']


async def call(op: str, payload: Optional[Dict[str, Any]] = None) -> Any:
    """POST an op and await its result (sync ops answer inline)."""
    resp = await _post_raw(op, payload or {})
    if 'result' in resp:
        return resp['result']
    return await get(resp['request_id'])


async def get(request_id: str) -> Any:
    """Await a request's result (server-side async request pattern)."""
    url = server_url()
    while True:
        try:
            async with aiohttp.ClientSession() as sess:
                async with sess.get(f'{url}/api/get/{request_id}',
                                    headers=_headers(),
                                    timeout=aiohttp.ClientTimeout(
                                        total=30)) as r:
                    r.raise_for_status()
                    body = await r.json()
        except aiohttp.ClientError as e:
            raise exceptions.ApiServerConnectionError(url) from e
        status = body['status']
        if status == 'SUCCEEDED':
            return body['result']
        if status in ('FAILED', 'CANCELLED'):
            raise exceptions.SkyTpuError(
                body.get('error') or f'request {request_id} {status}')
        await asyncio.sleep(_POLL_S)


async def stream_and_get(request_id: str, *, quiet: bool = True) -> Any:
    """Stream the request's server log, then return its result. A dropped
    stream is non-fatal (the request keeps running server-side)."""
    url = server_url()
    try:
        async with aiohttp.ClientSession() as sess:
            async with sess.get(f'{url}/api/stream/{request_id}',
                                headers=_headers(),
                                timeout=aiohttp.ClientTimeout(
                                    total=None)) as r:
                async for chunk in r.content.iter_any():
                    if not quiet and chunk:
                        import sys
                        sys.stdout.buffer.write(chunk)
                        sys.stdout.buffer.flush()
    except aiohttp.ClientError:
        pass   # fall back to polling
    return await get(request_id)


async def api_health() -> Dict[str, Any]:
    url = server_url()
    try:
        async with aiohttp.ClientSession() as sess:
            async with sess.get(f'{url}/api/health',
                                timeout=aiohttp.ClientTimeout(
                                    total=5)) as r:
                r.raise_for_status()
                return await r.json()
    except aiohttp.ClientError as e:
        raise exceptions.ApiServerConnectionError(url) from e


async def api_cancel(request_id: str) -> str:
    url = server_url()
    try:
        async with aiohttp.ClientSession() as sess:
            async with sess.post(f'{url}/api/cancel/{request_id}',
                                 headers=_headers(),
                                 timeout=aiohttp.ClientTimeout(
                                     total=30)) as r:
                if r.status == 404:
                    raise exceptions.SkyTpuError(
                        f'unknown request {request_id}')
                r.raise_for_status()
                return (await r.json())['status']
    except aiohttp.ClientError as e:
        raise exceptions.ApiServerConnectionError(url) from e


# ---- cluster ops ---------------------------------------------------------
async def launch(task: task_lib.Task,
                 cluster_name: Optional[str] = None,
                 *, quiet: bool = True,
                 **_kw) -> Tuple[int, ClusterInfo]:
    task_cfg = task.to_yaml_config()
    if task.workdir:
        # Zip+upload is blocking (file IO + requests); keep the loop free.
        task_cfg['workdir'] = await asyncio.to_thread(
            _sync._upload_workdir, task.workdir)  # noqa: SLF001
    rid = await _post('launch', {'task': task_cfg,
                                 'cluster_name': cluster_name})
    result = await stream_and_get(rid, quiet=quiet)
    return result['job_id'], ClusterInfo.from_dict(result['cluster_info'])


async def exec(task: task_lib.Task, cluster_name: str,  # noqa: A001
               **_kw) -> Tuple[int, ClusterInfo]:
    task_cfg = task.to_yaml_config()
    if task.workdir:
        task_cfg['workdir'] = await asyncio.to_thread(
            _sync._upload_workdir, task.workdir)  # noqa: SLF001
    rid = await _post('exec', {'task': task_cfg,
                               'cluster_name': cluster_name})
    result = await get(rid)
    return result['job_id'], ClusterInfo.from_dict(result['cluster_info'])


async def status(cluster_names: Optional[List[str]] = None,
                 refresh: bool = False,
                 all_workspaces: bool = False) -> List[Dict[str, Any]]:
    rid = await _post('status', {'cluster_names': cluster_names,
                                 'refresh': refresh,
                                 'all_workspaces': all_workspaces})
    records = await get(rid)
    for r in records:
        r['status'] = common.ClusterStatus(r['status'])
    return records


async def down(cluster_name: str) -> None:
    await get(await _post('down', {'cluster_name': cluster_name}))


async def stop(cluster_name: str) -> None:
    await get(await _post('stop', {'cluster_name': cluster_name}))


async def start(cluster_name: str) -> None:
    await get(await _post('start', {'cluster_name': cluster_name}))


async def autostop(cluster_name: str, idle_minutes: int,
                   down_: bool = False) -> None:
    await get(await _post('autostop', {'cluster_name': cluster_name,
                                       'idle_minutes': idle_minutes,
                                       'down': down_}))


async def queue(cluster_name: str) -> List[Dict[str, Any]]:
    return await get(await _post('queue', {'cluster_name': cluster_name}))


async def cancel(cluster_name: str, job_id: int) -> None:
    await get(await _post('cancel', {'cluster_name': cluster_name,
                                     'job_id': job_id}))


async def job_status(cluster_name: str, job_id: int) -> common.JobStatus:
    return common.JobStatus(await get(await _post('job_status', {
        'cluster_name': cluster_name, 'job_id': job_id})))


async def wait_job(cluster_name: str, job_id: int,
                   timeout: float = 3600.0) -> common.JobStatus:
    loop = asyncio.get_event_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        st = await job_status(cluster_name, job_id)
        if st.is_terminal():
            return st
        await asyncio.sleep(0.5)
    raise TimeoutError(f'job {job_id} still running after {timeout}s')


async def tail_logs(cluster_name: str, job_id: int, *,
                    follow: bool = True,
                    rank: int = 0) -> AsyncIterator[bytes]:
    url = server_url()
    follow_q = '1' if follow else '0'
    try:
        async with aiohttp.ClientSession() as sess:
            async with sess.get(
                    f'{url}/logs/{cluster_name}/{job_id}'
                    f'?follow={follow_q}&rank={rank}',
                    headers=_headers(),
                    timeout=aiohttp.ClientTimeout(total=None)) as r:
                if r.status != 200:
                    detail = (await r.json()).get('error', '')
                    raise exceptions.SkyTpuError(
                        f'log tail failed: {detail}')
                async for chunk in r.content.iter_any():
                    yield chunk
    except aiohttp.ClientError as e:
        raise exceptions.ApiServerConnectionError(url) from e


async def check(clouds: Optional[List[str]] = None) -> Dict[str, bool]:
    return await get(await _post('check', {'clouds': clouds}))


async def cost_report() -> List[Dict[str, Any]]:
    return await get(await _post('cost_report', {}))


# ---- managed jobs --------------------------------------------------------
async def jobs_launch(task: task_lib.Task,
                      name: Optional[str] = None) -> int:
    return await get(await _post('jobs.launch',
                                 {'task': task.to_yaml_config(),
                                  'name': name}))


async def jobs_queue() -> List[Dict[str, Any]]:
    return await get(await _post('jobs.queue', {}))


async def jobs_cancel(job_id: int) -> bool:
    return await get(await _post('jobs.cancel', {'job_id': job_id}))


# ---- serve ---------------------------------------------------------------
async def serve_up(task: task_lib.Task,
                   service_name: Optional[str] = None) -> Dict[str, Any]:
    return await get(await _post('serve.up',
                                 {'task': task.to_yaml_config(),
                                  'service_name': service_name}))


async def serve_update(task: task_lib.Task, service_name: str) -> int:
    return await get(await _post('serve.update',
                                 {'task': task.to_yaml_config(),
                                  'service_name': service_name}))


async def serve_down(service_name: str) -> None:
    await get(await _post('serve.down', {'service_name': service_name}))


async def serve_status(service_name: Optional[str] = None
                       ) -> List[Dict[str, Any]]:
    return await get(await _post('serve.status',
                                 {'service_name': service_name}))
