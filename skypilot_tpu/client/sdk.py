"""Python SDK over the REST API server.

Counterpart of the reference's ``sky/client/sdk.py`` (3,210 LoC): the same
async request pattern — every call POSTs, gets a ``request_id``, then
``stream_and_get``/``get`` resolve it (reference sdk.py:2150/:2226). The
function surface mirrors ``skypilot_tpu.core`` so the CLI can swap between
direct-engine and server mode transparently.

Server discovery: ``SKY_TPU_API_SERVER`` env var, or ``api_server.endpoint``
in the layered config, else http://127.0.0.1:46580.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

import requests as requests_lib

from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions
from skypilot_tpu import task as task_lib
from skypilot_tpu.observability import trace as trace_lib
from skypilot_tpu.provision.common import ClusterInfo
from skypilot_tpu.utils import common
from skypilot_tpu.utils import retry as retry_lib


def server_url() -> str:
    url = os.environ.get('SKY_TPU_API_SERVER')
    if not url:
        url = config_lib.get_nested(('api_server', 'endpoint'))
    return (url or
            f'http://127.0.0.1:{common.DEFAULT_API_PORT}').rstrip('/')


CLIENT_API_VERSION = 1


def _auth_headers() -> Dict[str, str]:
    """Bearer token from env/config (reference service-account auth) +
    the client's API version for the server's compatibility gate."""
    headers = {'X-Sky-Tpu-Api-Version': str(CLIENT_API_VERSION)}
    token = (os.environ.get('SKY_TPU_API_TOKEN') or
             config_lib.get_nested(('api_server', 'token')))
    if not token:
        # `sky-tpu api login` persists its PKCE-minted token here.
        token_path = os.path.expanduser('~/.sky_tpu/token')
        if os.path.exists(token_path):
            with open(token_path, encoding='utf-8') as f:
                token = f.read().strip()
    if token:
        headers['Authorization'] = f'Bearer {token}'
    return headers


def _post_raw(op: str, payload: Dict[str, Any]) -> Dict[str, Any]:
    url = server_url()
    # Root span of the distributed trace: the op submission as the
    # client observed it. The traceparent header carries the context to
    # the server; the span ships immediately after (ops are rare and
    # opt-in traced, so the extra POST is fine) so `sky-tpu trace`
    # shows the client hop without waiting for process exit.
    with trace_lib.span(f'sdk.{op}') as tspan:
        try:
            r = requests_lib.post(
                f'{url}/{op}', json=payload, timeout=30,
                headers=trace_lib.inject_headers(_auth_headers()))
        except requests_lib.RequestException as e:
            raise exceptions.ApiServerConnectionError(url) from e
        if r.status_code in (400, 401, 403, 426):
            raise exceptions.SkyTpuError(r.json().get('error', r.text))
        r.raise_for_status()
        body = r.json()
        if tspan is not None and 'request_id' in body:
            tspan.set_attr('request_id', body['request_id'])
    if trace_lib.enabled():
        trace_lib.flush()
    return body


def _post(op: str, payload: Dict[str, Any]) -> str:
    return _post_raw(op, payload)['request_id']


def call(op: str, payload: Optional[Dict[str, Any]] = None) -> Any:
    """POST an op and block for its result (async ops poll /api/get;
    sync ops like users.token_create answer inline)."""
    resp = _post_raw(op, payload or {})
    if 'result' in resp:
        return resp['result']
    return get(resp['request_id'])


def _http_transient(exc: BaseException) -> bool:
    """SDK GET retry classification: connection trouble is transient;
    HTTP status errors are the server answering — NOT transient, with
    one exception: 429/503 are the server saying "come back later"
    (admission shed / draining), and a GET is idempotent, so they
    retry honoring the server's Retry-After as the backoff floor."""
    if isinstance(exc, requests_lib.HTTPError):
        resp = exc.response
        return resp is not None and resp.status_code in (429, 503)
    return isinstance(exc, requests_lib.RequestException)


def _http_retry_after(exc: BaseException) -> Optional[float]:
    """Server-supplied backoff floor: the Retry-After header the serve
    stack computes as a queue-drain estimate (PR 7) — emitted on every
    429/503 and, until now, ignored by this retry path."""
    resp = getattr(exc, 'response', None)
    if resp is None:
        return None
    ra = resp.headers.get('Retry-After')
    try:
        return float(ra) if ra is not None else None
    except (TypeError, ValueError):
        return None   # HTTP-date form (or garbage): no floor


def _http_get(path: str, *, timeout=30, stream: bool = False,
              retries: int = 3):
    """GET with the same error contract as _post: connection trouble and
    HTTP errors surface as SkyTpuError subclasses, never raw requests
    exceptions (clients catch SkyTpuError only).

    GETs are idempotent — transient connection failures (server restart,
    flaky proxy; the chaos suite injects exactly this) and 429/503
    sheds retry through the shared Retrier (utils/retry.py) before
    surfacing, honoring a server-supplied Retry-After as the backoff
    floor.
    """
    url = server_url()

    def _once():
        r = requests_lib.get(f'{url}{path}', timeout=timeout,
                             stream=stream, headers=_auth_headers())
        r.raise_for_status()
        return r

    try:
        return retry_lib.Retrier(
            'sdk.get', max_attempts=retries + 1, base_delay_s=0.4,
            max_delay_s=5.0, transient=(),
            retry_on=_http_transient,
            retry_after=_http_retry_after).call(_once)
    except requests_lib.HTTPError as e:
        detail = ''
        try:
            detail = e.response.json().get('error', '')
        except Exception:  # noqa: BLE001 — non-JSON error body
            pass
        raise exceptions.SkyTpuError(
            f'API server error for GET {path}: '
            f'{detail or e}') from e
    except requests_lib.RequestException as e:
        raise exceptions.ApiServerConnectionError(url) from e


def get(request_id: str) -> Any:
    """Resolve a finished request's result (blocks by polling)."""
    while True:
        body = _http_get(f'/api/get/{request_id}').json()
        status = body['status']
        if status == 'SUCCEEDED':
            return body['result']
        if status in ('FAILED', 'CANCELLED'):
            raise exceptions.SkyTpuError(
                body.get('error') or f'request {request_id} {status}')
        time.sleep(0.3)


def stream_and_get(request_id: str, *, quiet: bool = False) -> Any:
    """Stream the request's server-side log, then return its result.

    A dropped stream is non-fatal: the request keeps running server-side
    (async-request design), so fall back to polling for the result.
    """
    try:
        with _http_get(f'/api/stream/{request_id}', stream=True,
                       timeout=None) as r:
            for chunk in r.iter_content(chunk_size=None):
                if not quiet and chunk:
                    import sys
                    sys.stdout.buffer.write(chunk)
                    sys.stdout.buffer.flush()
    except (exceptions.ApiServerConnectionError,
            requests_lib.RequestException):
        pass   # reconnect via the poll below
    return get(request_id)


def api_cancel(request_id: str) -> str:
    """Cancel a queued/running API request; returns the final status.

    Running requests execute in isolated worker processes server-side, so
    cancellation kills the worker's whole process group."""
    url = server_url()
    try:
        r = requests_lib.post(f'{url}/api/cancel/{request_id}',
                              timeout=30, headers=_auth_headers())
    except requests_lib.RequestException as e:
        raise exceptions.ApiServerConnectionError(url) from e
    if r.status_code == 404:
        raise exceptions.SkyTpuError(f'unknown request {request_id}')
    r.raise_for_status()
    return r.json()['status']


def api_health() -> Dict[str, Any]:
    url = server_url()
    try:
        r = requests_lib.get(f'{url}/api/health', timeout=5)
        r.raise_for_status()
        return r.json()
    except requests_lib.RequestException as e:
        raise exceptions.ApiServerConnectionError(url) from e


_compat_checked_url: Optional[str] = None


def ensure_server_compatibility() -> None:
    """check_server_compatibility, once per server URL per process —
    every CLI invocation in server mode goes through this."""
    global _compat_checked_url
    url = server_url()
    if _compat_checked_url == url:
        return
    check_server_compatibility()
    _compat_checked_url = url


def download_dump(filename: str, local_path: str) -> str:
    """Fetch a server-side debug dump (reference /debug/dump_download).

    A dropped connection mid-body surfaces as SkyTpuError (module
    contract) and removes the truncated local file rather than leaving
    it around looking like a valid dump."""
    try:
        with _http_get(f'/api/dump_download/{filename}', stream=True,
                       timeout=120) as r:
            with open(local_path, 'wb') as f:
                for chunk in r.iter_content(chunk_size=1 << 16):
                    f.write(chunk)
    except requests_lib.RequestException as e:
        try:
            os.unlink(local_path)
        except OSError:
            pass
        raise exceptions.SkyTpuError(
            f'dump download interrupted: {e}') from e
    return local_path


def check_server_compatibility() -> None:
    """New-client/old-server direction of the version gate: the server
    only rejects clients NEWER than itself via the request header; a
    newer client must itself refuse servers older than it understands
    (reference backward-compat middleware covers both directions)."""
    server_v = api_health().get('api_version', 0)
    if server_v < CLIENT_API_VERSION:
        raise exceptions.SkyTpuError(
            f'API server at {server_url()} speaks api v{server_v} but '
            f'this client requires >= v{CLIENT_API_VERSION}; upgrade '
            f'the server or downgrade the client.')


def api_requests() -> List[Dict[str, Any]]:
    return _http_get('/api/requests').json()['requests']


def api_trace(key: str) -> List[Dict[str, Any]]:
    """Spans of one trace, by request id or raw trace id. Empty list
    when nothing was recorded (tracing off, or spans GC'd)."""
    try:
        return _http_get(f'/api/traces/{key}').json()['spans']
    except exceptions.SkyTpuError as e:
        if 'no trace recorded' in str(e):
            return []
        raise


def api_traces() -> List[Dict[str, Any]]:
    """Recent trace summaries from the server's span store."""
    return _http_get('/api/traces').json()['traces']


# ---- core-mirroring surface ---------------------------------------------
def _upload_workdir(workdir: str) -> str:
    """Zip + POST the local workdir; returns the server-side path
    (reference client-side workdir upload feeding server.py:1463)."""
    import tempfile
    import zipfile
    root = os.path.expanduser(workdir)
    if not os.path.isdir(root):
        raise exceptions.SkyTpuError(
            f'workdir {workdir!r} does not exist (an empty upload '
            f'would launch a job with no files)')
    # Spool to disk and stream the POST: a large workdir must not be
    # held in client RAM (twice) as a BytesIO.
    spool = tempfile.NamedTemporaryFile(suffix='.zip', delete=False)
    try:
        n_files = 0
        with zipfile.ZipFile(spool, 'w', zipfile.ZIP_DEFLATED) as zf:
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = [d for d in dirnames
                               if d not in ('.git', '__pycache__')]
                for fn in filenames:
                    full = os.path.join(dirpath, fn)
                    # Dangling symlinks / files deleted mid-walk must
                    # not crash the launch.
                    if not os.path.isfile(full):
                        continue
                    zf.write(full, os.path.relpath(full, root))
                    n_files += 1
        if n_files == 0:
            raise exceptions.SkyTpuError(
                f'workdir {workdir!r} contains no files — refusing to '
                f'launch a job with an empty workdir')
        spool.close()
        url = server_url()
        try:
            with open(spool.name, 'rb') as f:
                r = requests_lib.post(f'{url}/api/upload', data=f,
                                      timeout=300,
                                      headers=_auth_headers())
        except requests_lib.RequestException as e:
            raise exceptions.ApiServerConnectionError(url) from e
    finally:
        try:
            os.unlink(spool.name)
        except OSError:
            pass
    if r.status_code != 200:
        try:
            detail = r.json().get('error', r.text)
        except ValueError:
            detail = r.text
        raise exceptions.SkyTpuError(f'workdir upload failed: {detail}')
    return r.json()['workdir']


def launch(task: task_lib.Task, cluster_name: Optional[str] = None,
           *, quiet: bool = True, **_kw) -> Tuple[int, ClusterInfo]:
    task_cfg = task.to_yaml_config()
    if task.workdir:
        # The server launches from ITS filesystem: ship the client's
        # workdir up first and point the task at the server-side copy.
        task_cfg['workdir'] = _upload_workdir(task.workdir)
    rid = _post('launch', {'task': task_cfg,
                           'cluster_name': cluster_name})
    result = stream_and_get(rid, quiet=quiet)
    return result['job_id'], ClusterInfo.from_dict(result['cluster_info'])


def exec(task: task_lib.Task, cluster_name: str,  # noqa: A001
         **_kw) -> Tuple[int, ClusterInfo]:
    task_cfg = task.to_yaml_config()
    if task.workdir:
        # Same as launch(): the server syncs from ITS filesystem, so the
        # client's workdir must be shipped up first — otherwise exec would
        # silently rsync whatever happens to live at that path server-side.
        task_cfg['workdir'] = _upload_workdir(task.workdir)
    rid = _post('exec', {'task': task_cfg,
                         'cluster_name': cluster_name})
    result = get(rid)
    return result['job_id'], ClusterInfo.from_dict(result['cluster_info'])


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = False,
           all_workspaces: bool = False) -> List[Dict[str, Any]]:
    rid = _post('status', {'cluster_names': cluster_names,
                           'refresh': refresh,
                           'all_workspaces': all_workspaces})
    records = get(rid)
    for r in records:
        r['status'] = common.ClusterStatus(r['status'])
    return records


def down(cluster_name: str) -> None:
    get(_post('down', {'cluster_name': cluster_name}))


def stop(cluster_name: str) -> None:
    get(_post('stop', {'cluster_name': cluster_name}))


def start(cluster_name: str) -> None:
    get(_post('start', {'cluster_name': cluster_name}))


def autostop(cluster_name: str, idle_minutes: int,
             down_: bool = False) -> None:
    get(_post('autostop', {'cluster_name': cluster_name,
                           'idle_minutes': idle_minutes, 'down': down_}))


def queue(cluster_name: str) -> List[Dict[str, Any]]:
    return get(_post('queue', {'cluster_name': cluster_name}))


def cancel(cluster_name: str, job_id: int) -> None:
    get(_post('cancel', {'cluster_name': cluster_name, 'job_id': job_id}))


def job_status(cluster_name: str, job_id: int) -> common.JobStatus:
    return common.JobStatus(get(_post('job_status', {
        'cluster_name': cluster_name, 'job_id': job_id})))


def wait_job(cluster_name: str, job_id: int,
             timeout: float = 3600.0) -> common.JobStatus:
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = job_status(cluster_name, job_id)
        if st.is_terminal():
            return st
        time.sleep(0.5)
    raise TimeoutError(f'job {job_id} still running after {timeout}s')


def tail_logs(cluster_name: str, job_id: int, *, follow: bool = True,
              rank: int = 0) -> Iterator[bytes]:
    follow_q = '1' if follow else '0'
    with _http_get(f'/logs/{cluster_name}/{job_id}'
                   f'?follow={follow_q}&rank={rank}',
                   stream=True, timeout=None) as r:
        yield from r.iter_content(chunk_size=None)


def check(clouds: Optional[List[str]] = None) -> Dict[str, bool]:
    return get(_post('check', {'clouds': clouds}))


def cost_report() -> List[Dict[str, Any]]:
    return get(_post('cost_report', {}))


# ---- managed jobs (reference sky/jobs/client/sdk.py) ---------------------
def jobs_launch(task, name: Optional[str] = None,
                pool: Optional[str] = None) -> int:
    """Submit a managed job (Task) or pipeline (Dag)."""
    from skypilot_tpu import dag as dag_lib
    if isinstance(task, dag_lib.Dag):
        from skypilot_tpu.utils import dag_utils
        return get(_post('jobs.launch', {
            'dag_yaml': dag_utils.dump_dag_to_yaml_str(task),
            'name': name, 'pool': pool}))
    return get(_post('jobs.launch', {'task': task.to_yaml_config(),
                                     'name': name, 'pool': pool}))


def jobs_queue() -> List[Dict[str, Any]]:
    return get(_post('jobs.queue', {}))


def jobs_cancel(job_id: int) -> bool:
    return get(_post('jobs.cancel', {'job_id': job_id}))


# ---- jobs worker pools (reference `sky jobs pool ...`) -------------------
def jobs_pool_apply(task=None, pool_name: Optional[str] = None,
                    workers: Optional[int] = None) -> Dict[str, Any]:
    payload: Dict[str, Any] = {'pool_name': pool_name, 'workers': workers}
    if task is not None:
        payload['task'] = task.to_yaml_config()
    return get(_post('jobs.pool_apply', payload))


def jobs_pool_status(pool_names: Optional[List[str]] = None
                     ) -> List[Dict[str, Any]]:
    return get(_post('jobs.pool_status', {'pool_names': pool_names}))


def jobs_pool_down(pool_name: str, purge: bool = False) -> None:
    return get(_post('jobs.pool_down', {'pool_name': pool_name,
                                        'purge': purge}))


# ---- serve (reference sky/serve/client/sdk.py) ---------------------------
def serve_up(task: task_lib.Task,
             service_name: Optional[str] = None) -> Dict[str, Any]:
    return get(_post('serve.up', {'task': task.to_yaml_config(),
                                  'service_name': service_name}))


def serve_update(task: task_lib.Task, service_name: str) -> int:
    return get(_post('serve.update', {'task': task.to_yaml_config(),
                                      'service_name': service_name}))


def serve_down(service_name: str) -> None:
    get(_post('serve.down', {'service_name': service_name}))


def serve_status(service_name: Optional[str] = None
                 ) -> List[Dict[str, Any]]:
    return get(_post('serve.status', {'service_name': service_name}))


def serve_restart_replica(service_name: str, replica_id: int) -> None:
    get(_post('serve.restart_replica',
              {'service_name': service_name,
               'replica_id': replica_id}))
