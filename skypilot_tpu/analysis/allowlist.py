"""The audited allowlist for `sky-tpu lint`.

Entries: ``'<package-relative path>:<CODE>': (count, justification)``.
Counts are exact caps per path+checker: MORE findings than the cap
fails (a new violation crept in), FEWER fails too (the site was fixed
— ratchet the entry down so it stops granting headroom). Every entry
carries the one-line justification the audit produced; the detailed
reasoning lives next to the code site.

Populated during this checker suite's bring-up audit; edit only with
a justification in the diff.

The SKY-ASYNC caps migrate the grep-based pins of the pre-lint
``tests/unit_tests/test_retry_lint.py`` one for one: client/sdk.py 2,
runtime/agent_client.py 1, serve/controller.py 2, serve/__init__.py
2, serve/load_balancer.py 3, infer/multihost.py 1 — no pinned site
was lost in the migration, and the AST checker additionally covers
blocking I/O in async defs (the open() entries) which the grep never
saw.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

# The canonical global lock-acquisition order (SKY-ORDER). A thread
# may only acquire locks left-to-right along this list; an edge that
# contradicts it fails lint even before a full cycle closes. Entries
# are lockflow ids (``Class.attr`` / ``module.attr``); bare names
# match any class. Populated during the PR 10 bring-up audit — every
# entry carries the reasoning for its position. The audit found ZERO
# cross-lock nestings in shipped code (every critical section is
# leaf-level by design); this list exists so the first nesting anyone
# adds must conform to a reviewed order instead of inventing one.
LOCK_ORDER: List[str] = [
    # Outermost: the lockstep driver serializes submissions BEFORE any
    # engine state is touched (tick drains _pending under it, then
    # calls engine.submit after release — if they ever nest, driver
    # first).
    'MultihostEngineDriver._lock',
    # The engine lock is the serving hot path's hub: submit/cancel/
    # metrics threads vs the step loop. Anything engine code calls out
    # to (scheduler — same lock by contract — allocator, prefix tree)
    # must be lock-free or leaf-level below it.
    'InferenceEngine._lock',
    # LB-side leaf locks: policy bookkeeping and breaker state are
    # touched from the event loop in O(replicas) critical sections and
    # never call back into the engine or driver.
    'LoadBalancingPolicy._lock',
    'CircuitBreaker._lock',
]

ALLOWLIST: Dict[str, Tuple[int, str]] = {
    # ---- SKY-ASYNC: audited status-poll cadences (waiting for a
    # state change is not an error retry; Retrier is for retries) ----
    'client/sdk.py:SKY-ASYNC': (
        2, 'get() result poll + wait_job status poll — state-change '
           'cadences in a sync client, not retry loops'),
    'runtime/agent_client.py:SKY-ASYNC': (
        1, 'wait_job status poll cadence (sync client thread)'),
    # (serve/controller.py dropped to zero sleep sites: the tick loop
    # waits on the shutdown Event now — prompt teardown, no cadence
    # sleep left to pin.)
    'serve/__init__.py:SKY-ASYNC': (
        2, 'serve up/down status polls (sync CLI-facing helpers)'),
    'infer/multihost.py:SKY-ASYNC': (
        1, 'lockstep watchdog heartbeat — a monitoring cadence on its '
           'own thread, never a token-delivery poll'),
    'serve/load_balancer.py:SKY-ASYNC': (
        2, 'replica-set sync + stats-flush cadences — background '
           'maintenance ticks, none on the request path (token '
           'forwarding wakes on upstream chunks; the run() idle loop '
           'is event-driven now)'),
    # ---- SKY-ASYNC: blocking file I/O on non-serving event loops ---
    'runtime/agent.py:SKY-ASYNC': (
        6, 'local log/config file opens in agent handlers — small '
           'bounded disk I/O on the per-host agent daemon; no token '
           'stream rides this loop'),
    'server/app.py:SKY-ASYNC': (
        3, 'dashboard/static file serving + startup TLS reads on the '
           'API-server loop — local files, request rate is human-'
           'scale, not the serving hot path'),
    # ---- SKY-LOCK: the digital twin's single-thread carve-out ------
    # The sim kernel (docs/robustness.md "Digital twin") is ONE thread
    # by construction — determinism is the whole point, so the real
    # schedulers' `# holds: _lock` calling contracts are vacuously
    # satisfied (single-thread confinement is stronger than any lock;
    # taking real locks in the hot replay loop would only buy wall
    # clock). Counts pinned exactly so NEW lock-annotated calls from
    # sim code still get audited here.
    'sim/replica.py:SKY-LOCK': (
        20, 'ModelReplica drives a REAL scheduler instance from the '
            'kernel thread only (admit/enqueue/pop_next/pending/'
            'note_*); no other thread can exist during a replay'),
    'sim/cloud.py:SKY-LOCK': (
        2, 'VirtualCloud.drain reads scheduler pending() on the '
           'kernel thread'),
    'sim/twin.py:SKY-LOCK': (
        1, 'DigitalTwin.run reads lb_metrics() after kernel.run() '
           'returns — the trampoline (the twin\'s "event loop") has '
           'drained; nothing else runs'),
    # ---- SKY-EXCEPT: audited broad handlers in the LB --------------
    'serve/load_balancer.py:SKY-EXCEPT': (
        8, '2 fail-open maintenance loops (replica sync / stats '
           'flush: DB hiccups must not stop serving; no client '
           'connection in scope, CancelledError passes as '
           'BaseException) + 6 suppress(Exception) on teardown '
           'paths (trace-setup is fail-open by contract; write_eof/'
           'aclose/final error-report run on already-failed streams '
           'where any error has nobody left to report to)'),
}
