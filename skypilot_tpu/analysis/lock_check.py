"""SKY-LOCK: lock discipline over declared guarded fields.

A class declares its concurrency contract in a ``_GUARDED_BY`` class
attribute (a dict literal of field name → guard spec); the checker
then enforces, lexically and module-wide, that every access to a
guarded field satisfies the spec. Guard specs:

``'<lock>'``
    Every access (read or write) must be inside ``with <x>.<lock>:``
    or in a method annotated ``# holds: <lock>`` (a documented calling
    contract — every caller holds the lock; the engine's
    ``_sweep_dead_requests`` is the canonical example).

``'<lock>:mut'``
    Only MUTATIONS need the lock — the single-writer discipline:
    one thread owns the field and mutates it under the lock so other
    threads' readers (who do take the lock) never see a torn update;
    the owning thread's own reads stay lock-free. Covers the engine's
    ``_slots``/``_inflight_tok``.

``'owner'``
    Confinement: the field may only be touched from the declaring
    class's own methods. External code must use the accessors — this
    is what keeps ``PageAllocator``'s refcount bookkeeping atomic
    under the engine lock without the allocator growing a lock of its
    own.

``'event-loop'``
    Single-threaded asyncio state (the LB's counters): accesses only
    from ``async def`` bodies (which run on the loop) or sync methods
    annotated ``# holds: event-loop`` (callers are coroutines).

``__init__`` is exempt everywhere: construction precedes sharing.

Scope: accesses are checked across the whole MODULE that declares the
registry (so a sibling class reaching into another class's guarded
field — the EnginePool-reads-``engine._ttfts`` bug this checker was
built on — is caught), but not across modules; cross-module reach-ins
are already 'owner'-style API violations in review.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Sequence, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import walker

REGISTRY_ATTR = '_GUARDED_BY'


def _registries(src: core.SourceFile) -> Dict[str, List[Tuple[str, str]]]:
    """field name -> [(class name, guard spec)] for this module."""
    out: Dict[str, List[Tuple[str, str]]] = {}
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
                value = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                targets = [stmt.target]
                value = stmt.value
            else:
                continue
            if not any(isinstance(t, ast.Name)
                       and t.id == REGISTRY_ATTR for t in targets):
                continue
            if not isinstance(value, ast.Dict):
                continue
            for k, v in zip(value.keys, value.values):
                if (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)):
                    out.setdefault(k.value, []).append(
                        (node.name, v.value))
    return out


class LockChecker(core.Checker):
    code = 'SKY-LOCK'
    title = ('guarded fields accessed only under their lock / '
             'declared context')

    def check(self, files: Sequence[core.SourceFile],
              ctx: core.RunContext) -> Iterable[core.Finding]:
        for src in files:
            regs = _registries(src)
            if not regs:
                continue
            yield from self._check_module(src, regs)

    def _check_module(self, src: core.SourceFile,
                      regs: Dict[str, List[Tuple[str, str]]]
                      ) -> Iterable[core.Finding]:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Attribute):
                continue
            specs = regs.get(node.attr)
            if not specs:
                continue
            func = walker.enclosing_function(node)
            fname = getattr(func, 'name', '')
            if fname in ('__init__', '__new__'):
                continue
            cls = walker.enclosing_class(node)
            cls_name = cls.name if cls is not None else ''
            holds = (walker.holds_annotations(src, func)
                     if func is not None else set())
            for decl_cls, spec in specs:
                bad = self._violates(node, spec, decl_cls, cls_name,
                                     holds, func)
                if bad:
                    yield core.Finding(
                        self.code, src.rel, node.lineno,
                        f'{decl_cls}.{node.attr} (guarded by '
                        f'{spec!r}) {bad}')
                    break   # one finding per access site

    @staticmethod
    def _violates(node: ast.Attribute, spec: str, decl_cls: str,
                  cls_name: str, holds, func) -> str:
        """Return a message when the access violates ``spec``, else
        ''."""
        if spec == 'owner':
            if cls_name != decl_cls:
                return (f'touched outside {decl_cls} — use the '
                        f'accessor methods (confinement keeps its '
                        f'bookkeeping atomic under the owner\'s '
                        f'lock)')
            return ''
        if spec == 'event-loop':
            if (isinstance(func, ast.AsyncFunctionDef)
                    or 'event-loop' in holds):
                return ''
            return ('accessed from a sync def — event-loop state is '
                    'only safe on the loop; annotate the method '
                    '"# holds: event-loop" if every caller is a '
                    'coroutine')
        lock, _, mode = spec.partition(':')
        if mode == 'mut' and not walker.is_mutating_access(node):
            return ''
        if lock in walker.held_locks(node) or lock in holds:
            return ''
        kind = 'mutated' if walker.is_mutating_access(node) else 'read'
        return (f'{kind} outside "with self.{lock}" (annotate the '
                f'method "# holds: {lock}" only if every caller '
                f'holds it)')
