"""SKY-LOCK: lock discipline over declared guarded fields.

A class declares its concurrency contract in a ``_GUARDED_BY`` class
attribute (a dict literal of field name → guard spec); the checker
then enforces, lexically and module-wide, that every access to a
guarded field satisfies the spec. Guard specs:

``'<lock>'``
    Every access (read or write) must be inside ``with <x>.<lock>:``
    or in a method annotated ``# holds: <lock>`` (a documented calling
    contract — every caller holds the lock; the engine's
    ``_sweep_dead_requests`` is the canonical example).

``'<lock>:mut'``
    Only MUTATIONS need the lock — the single-writer discipline:
    one thread owns the field and mutates it under the lock so other
    threads' readers (who do take the lock) never see a torn update;
    the owning thread's own reads stay lock-free. Covers the engine's
    ``_slots``/``_inflight_tok``.

``'owner'``
    Confinement: the field may only be touched from the declaring
    class's own methods. External code must use the accessors — this
    is what keeps ``PageAllocator``'s refcount bookkeeping atomic
    under the engine lock without the allocator growing a lock of its
    own.

``'event-loop'``
    Single-threaded asyncio state (the LB's counters): accesses only
    from ``async def`` bodies (which run on the loop) or sync methods
    annotated ``# holds: event-loop`` (callers are coroutines).

``__init__`` is exempt everywhere: construction precedes sharing.

Scope: accesses are checked across the whole MODULE that declares the
registry (so a sibling class reaching into another class's guarded
field — the EnginePool-reads-``engine._ttfts`` bug this checker was
built on — is caught), but not across modules; cross-module reach-ins
are already 'owner'-style API violations in review.

v2 — interprocedural (PR 10): on top of the lexical rules, the
lock-flow dataflow (lockflow.py) makes two upgrades:

1. A helper that touches a guarded field WITHOUT taking the lock or
   carrying a ``# holds:`` annotation is now legal if the lock is
   provably held at **all** resolved call sites reaching it (the
   MUST-entry set). When it is not, the finding reports the unlocked
   call chain (``h_metrics -> EnginePool.metrics ->
   _merge_tenants``) instead of just the access line.
2. Every ``# holds: <lock>`` annotation is **verified** against its
   real callers instead of being trusted: a resolved call site that
   does not hold the lock is its own finding, at the call site. An
   annotation with no resolved callers stays trusted (entry points
   and dispatch the resolver cannot see).

``# holds: event-loop`` verifies the same way — callers must be
coroutines or provably on-loop themselves.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import lockflow
from skypilot_tpu.analysis import walker

REGISTRY_ATTR = '_GUARDED_BY'


def _registries(src: core.SourceFile) -> Dict[str, List[Tuple[str, str]]]:
    """field name -> [(class name, guard spec)] for this module."""
    out: Dict[str, List[Tuple[str, str]]] = {}
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
                value = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                targets = [stmt.target]
                value = stmt.value
            else:
                continue
            if not any(isinstance(t, ast.Name)
                       and t.id == REGISTRY_ATTR for t in targets):
                continue
            if not isinstance(value, ast.Dict):
                continue
            for k, v in zip(value.keys, value.values):
                if (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)):
                    out.setdefault(k.value, []).append(
                        (node.name, v.value))
    return out


class LockChecker(core.Checker):
    code = 'SKY-LOCK'
    title = ('guarded fields accessed only under their lock / '
             'declared context')

    def check(self, files: Sequence[core.SourceFile],
              ctx: core.RunContext) -> Iterable[core.Finding]:
        flow = lockflow.analyze(files)
        for src in files:
            regs = _registries(src)
            if regs:
                yield from self._check_module(src, regs, flow)
        yield from self._verify_annotations(flow)

    def _check_module(self, src: core.SourceFile,
                      regs: Dict[str, List[Tuple[str, str]]],
                      flow: 'lockflow.LockFlow'
                      ) -> Iterable[core.Finding]:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Attribute):
                continue
            specs = regs.get(node.attr)
            if not specs:
                continue
            func = walker.enclosing_function(node)
            fname = getattr(func, 'name', '')
            if fname in ('__init__', '__new__'):
                continue
            cls = walker.enclosing_class(node)
            cls_name = cls.name if cls is not None else ''
            holds = (walker.holds_annotations(src, func)
                     if func is not None else set())
            key = None
            if func is not None:
                prefix = walker.enclosing_qualname(func)
                key = (src.rel, f'{prefix}.{func.name}'
                       if prefix else func.name)
            must = (flow.must_entry.get(key, frozenset())
                    if key is not None else frozenset())
            for decl_cls, spec in specs:
                bad = self._violates(node, spec, decl_cls, cls_name,
                                     holds, func, must)
                if bad:
                    chain: Optional[Tuple[str, ...]] = None
                    lock = spec.partition(':')[0]
                    if (key is not None
                            and flow.in_edges.get(key)
                            and spec not in ('owner',)):
                        chain = tuple(flow.unlocked_chain(
                            key,
                            lock if spec != 'event-loop'
                            else lockflow.EVENT_LOOP))
                    via = (f'; unlocked call chain: '
                           f'{" -> ".join(chain)}'
                           if chain and len(chain) > 1 else '')
                    yield core.Finding(
                        self.code, src.rel, node.lineno,
                        f'{decl_cls}.{node.attr} (guarded by '
                        f'{spec!r}) {bad}{via}',
                        chain=chain)
                    break   # one finding per access site

    @staticmethod
    def _violates(node: ast.Attribute, spec: str, decl_cls: str,
                  cls_name: str, holds, func, must) -> str:
        """Return a message when the access violates ``spec``, else
        ''. ``must`` is the lock-flow MUST-entry set of the enclosing
        function — locks provably held at entry on every resolved
        call chain."""
        if spec == 'owner':
            if cls_name != decl_cls:
                return (f'touched outside {decl_cls} — use the '
                        f'accessor methods (confinement keeps its '
                        f'bookkeeping atomic under the owner\'s '
                        f'lock)')
            return ''
        if spec == 'event-loop':
            if (isinstance(func, ast.AsyncFunctionDef)
                    or 'event-loop' in holds
                    or lockflow.EVENT_LOOP in must):
                return ''
            return ('accessed from a sync def — event-loop state is '
                    'only safe on the loop; annotate the method '
                    '"# holds: event-loop" if every caller is a '
                    'coroutine')
        lock, _, mode = spec.partition(':')
        if mode == 'mut' and not walker.is_mutating_access(node):
            return ''
        if lock in walker.held_locks(node) or lock in holds:
            return ''
        if lockflow.has_base(must, lock):
            # Interprocedurally proven: the lock is held at every
            # resolved call site reaching this helper.
            return ''
        kind = 'mutated' if walker.is_mutating_access(node) else 'read'
        return (f'{kind} outside "with self.{lock}" and not provably '
                f'locked at every call site (annotate the method '
                f'"# holds: {lock}" only if every caller holds it)')

    # -- `# holds:` verification ------------------------------------------
    def _verify_annotations(self, flow: 'lockflow.LockFlow'
                            ) -> Iterable[core.Finding]:
        """An annotation is a claim about CALLERS; check it against
        every resolved call site instead of trusting it. Chains in the
        findings name the unlocked path (the PR 10 contract: a lie in
        an annotation must fail lint, not deadlock in production)."""
        for key in sorted(flow.summaries):
            summ = flow.summaries[key]
            for ann in sorted(summ.annotations):
                seen: set = set()
                for e in flow.in_edges.get(key, []):
                    caller_summ = flow.summaries.get(e.caller)
                    if caller_summ is None:
                        continue
                    if len(e.targets) > 1 and not all(
                            lockflow.has_base(
                                flow.summaries[t].annotations, ann)
                            for t in e.targets
                            if t in flow.summaries):
                        # Ambiguous (duck) dispatch where some
                        # candidates do NOT carry the contract: the
                        # call is presumably to one of those
                        # (EnginePool calling each ENGINE's
                        # set_tenant_weights, not the scheduler's).
                        # Only same-contract candidate sets verify.
                        continue
                    caller_locks = set(e.held)
                    if not e.deferred:
                        # A deferred reference to an annotated helper
                        # runs outside the caller's lock context — the
                        # caller's own holds say nothing about it.
                        caller_locks |= set(flow.must_entry.get(
                            e.caller, frozenset()))
                        caller_locks |= set(caller_summ.annotations)
                    if lockflow.has_base(caller_locks, ann):
                        continue
                    info = flow.funcs[e.caller]
                    site = (info.src.rel, e.line)
                    if site in seen:
                        continue
                    seen.add(site)
                    chain = tuple(flow.unlocked_chain(e.caller, ann)
                                  + [flow.qualname(key)])
                    yield core.Finding(
                        self.code, info.src.rel, e.line,
                        f'call to {flow.qualname(key)} (annotated '
                        f'"# holds: {ann}") without {ann} held in '
                        f'{info.qualname} — the annotation is a '
                        f'calling contract, and this chain breaks '
                        f'it: {" -> ".join(chain)}',
                        chain=chain)
