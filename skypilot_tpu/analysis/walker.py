"""Shared AST-navigation helpers for the lint checkers.

Every checker needs the same structural questions answered about a
node: which function/class encloses it, is that function async, which
locks are lexically held (``with self._lock:``), what does a call
resolve to, is an attribute access a mutation. They live here once;
checkers stay declarative.

Parent links (``_sky_parent``) are attached by
:class:`core.SourceFile` at parse time.
"""
from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from skypilot_tpu.analysis import core

_FUNC_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef)

# Method names that mutate their receiver in place — an access like
# ``self._waiting.append(x)`` is a WRITE to ``_waiting`` for lock
# discipline even though the attribute itself is only loaded.
MUTATOR_METHODS = frozenset((
    'append', 'appendleft', 'add', 'clear', 'discard', 'extend',
    'insert', 'pop', 'popleft', 'popitem', 'remove', 'setdefault',
    'sort', 'update'))


def parents(node: ast.AST) -> Iterator[ast.AST]:
    cur = getattr(node, '_sky_parent', None)
    while cur is not None:
        yield cur
        cur = getattr(cur, '_sky_parent', None)


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    """Nearest enclosing (async) function def, or None at module
    scope."""
    for p in parents(node):
        if isinstance(p, _FUNC_TYPES):
            return p
    return None


def enclosing_class(node: ast.AST) -> Optional[ast.ClassDef]:
    """Nearest enclosing class whose body (directly or through
    functions) contains ``node``."""
    for p in parents(node):
        if isinstance(p, ast.ClassDef):
            return p
    return None


def in_async_function(node: ast.AST) -> bool:
    """Whether the NEAREST enclosing function is ``async def`` (a sync
    helper nested inside an async def is not event-loop context)."""
    return isinstance(enclosing_function(node), ast.AsyncFunctionDef)


def dotted_name(expr: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = dotted_name(expr.value)
        return f'{base}.{expr.attr}' if base else None
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def held_locks(node: ast.AST) -> Set[str]:
    """Attribute names of every context manager lexically held at
    ``node`` within its own function: ``with self._lock:`` (or any
    ``with <expr>.<name>:``) contributes ``<name>``. Stops at the
    function boundary — a ``with`` in an outer function does not
    cover a nested def's body."""
    held: Set[str] = set()
    for p in parents(node):
        if isinstance(p, _FUNC_TYPES):
            break
        if isinstance(p, (ast.With, ast.AsyncWith)):
            for item in p.items:
                expr = item.context_expr
                if isinstance(expr, ast.Attribute):
                    held.add(expr.attr)
                elif isinstance(expr, ast.Name):
                    held.add(expr.id)
    return held


def holds_annotations(src: 'core.SourceFile',
                      func: ast.AST) -> Set[str]:
    """Lock names asserted by a ``# holds: <name>[, <name>]`` comment
    in the function header (the ``def`` line through the line of the
    first body statement). The annotation documents a calling
    contract — "every caller already holds this" — for helpers that
    mutate guarded state without taking the lock themselves."""
    names: Set[str] = set()
    if not isinstance(func, _FUNC_TYPES) or not func.body:
        return names
    for lineno in range(func.lineno, func.body[0].lineno + 1):
        line = src.line(lineno)
        marker = '# holds:'
        idx = line.find(marker)
        if idx < 0:
            continue
        for tok in line[idx + len(marker):].split(','):
            tok = tok.strip()
            if tok:
                names.add(tok)
    return names


def is_mutating_access(attr: ast.Attribute) -> bool:
    """Whether this attribute access WRITES the attribute: direct
    store/delete (incl. aug-assign), subscript store/delete on it, or
    an in-place mutator method call (``.append`` & co)."""
    if isinstance(attr.ctx, (ast.Store, ast.Del)):
        return True
    parent = getattr(attr, '_sky_parent', None)
    if (isinstance(parent, ast.Subscript) and parent.value is attr
            and isinstance(parent.ctx, (ast.Store, ast.Del))):
        return True
    if (isinstance(parent, ast.Attribute)
            and parent.value is attr
            and parent.attr in MUTATOR_METHODS):
        grand = getattr(parent, '_sky_parent', None)
        if isinstance(grand, ast.Call) and grand.func is parent:
            return True
    return False


def walk_function_body(func: ast.AST,
                       skip_nested: bool = True) -> Iterator[ast.AST]:
    """Walk a function's body; by default nested function defs are not
    descended into (they have their own scope/context)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        if skip_nested and isinstance(node, _FUNC_TYPES):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}
