"""Shared AST-navigation helpers for the lint checkers.

Every checker needs the same structural questions answered about a
node: which function/class encloses it, is that function async, which
locks are lexically held (``with self._lock:``), what does a call
resolve to, is an attribute access a mutation. They live here once;
checkers stay declarative.

Lock-holding detection covers three idioms, each of which burned a
real checker blind spot (the PR 10 walker bugfix sweep):

- aliasing: ``lock = self._lock`` followed by ``with lock:`` counts
  as holding ``_lock`` (the alias map is per-function);
- manual ``try/finally`` pairs: ``self._lock.acquire()`` …
  ``self._lock.release()`` hold the lock for every statement between
  the acquire and the first matching release (line-interval
  approximation — sound for the straight-line try/finally idiom);
- parenthesized multi-item ``with (a, b):``, which parses as ONE
  withitem whose context expression is a Tuple on 3.9/3.10 grammars.

Parent links (``_sky_parent``) are attached by
:class:`core.SourceFile` at parse time.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from skypilot_tpu.analysis import core

_FUNC_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef)

# Method names that mutate their receiver in place — an access like
# ``self._waiting.append(x)`` is a WRITE to ``_waiting`` for lock
# discipline even though the attribute itself is only loaded.
MUTATOR_METHODS = frozenset((
    'append', 'appendleft', 'add', 'clear', 'discard', 'extend',
    'insert', 'pop', 'popleft', 'popitem', 'remove', 'setdefault',
    'sort', 'update'))


def parents(node: ast.AST) -> Iterator[ast.AST]:
    cur = getattr(node, '_sky_parent', None)
    while cur is not None:
        yield cur
        cur = getattr(cur, '_sky_parent', None)


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    """Nearest enclosing (async) function def, or None at module
    scope."""
    for p in parents(node):
        if isinstance(p, _FUNC_TYPES):
            return p
    return None


def enclosing_class(node: ast.AST) -> Optional[ast.ClassDef]:
    """Nearest enclosing class whose body (directly or through
    functions) contains ``node``."""
    for p in parents(node):
        if isinstance(p, ast.ClassDef):
            return p
    return None


def in_async_function(node: ast.AST) -> bool:
    """Whether the NEAREST enclosing function is ``async def`` (a sync
    helper nested inside an async def is not event-loop context)."""
    return isinstance(enclosing_function(node), ast.AsyncFunctionDef)


def dotted_name(expr: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = dotted_name(expr.value)
        return f'{base}.{expr.attr}' if base else None
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def lock_aliases(func: Optional[ast.AST]) -> Dict[str, str]:
    """Per-function map of local alias -> dotted source expression for
    simple rebinding assignments (``lock = self._lock``). Chains
    resolve through up to three hops (``a = self._lock; b = a``).
    Memoized on the function node (checkers ask per-node; the scan is
    per-function)."""
    out: Dict[str, str] = {}
    if func is None or not isinstance(func, _FUNC_TYPES):
        return out
    cached = getattr(func, '_sky_lock_aliases', None)
    if cached is not None:
        return cached
    for node in walk_function_body(func):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        value = dotted_name(node.value)
        if value is not None and value != target.id:
            out[target.id] = value
    for _ in range(3):
        changed = False
        for alias, expr in list(out.items()):
            head, _, rest = expr.partition('.')
            if head in out and head != alias:
                out[alias] = out[head] + (f'.{rest}' if rest else '')
                changed = True
        if not changed:
            break
    func._sky_lock_aliases = out   # type: ignore[attr-defined]
    return out


def _with_item_exprs(item: ast.withitem) -> List[ast.AST]:
    """Expressions a withitem holds — the context expr itself, or each
    element of a parenthesized ``with (a, b):`` Tuple (which the
    3.9/3.10 grammar parses as a single Tuple-valued item)."""
    expr = item.context_expr
    if isinstance(expr, ast.Tuple):
        return list(expr.elts)
    return [expr]


def held_lock_sites(node: ast.AST) -> List[Tuple[str, int]]:
    """``(dotted lock expr, acquisition line)`` for every context
    manager lexically held at ``node`` within its own function, in
    acquisition (line) order. Covers ``with``/``async with`` blocks
    (including tuple items), alias-resolved names (``lock =
    self._lock; with lock:``), and manual ``.acquire()`` calls whose
    first subsequent ``.release()`` (or the function end) lies beyond
    ``node``. Stops at the function boundary."""
    func = enclosing_function(node)
    aliases = lock_aliases(func)

    def resolve(expr: ast.AST) -> Optional[str]:
        dotted = dotted_name(expr)
        if dotted is None:
            return None
        head, _, rest = dotted.partition('.')
        if head in aliases:
            return aliases[head] + (f'.{rest}' if rest else '')
        return dotted

    held: List[Tuple[str, int]] = []
    for p in parents(node):
        if isinstance(p, _FUNC_TYPES):
            break
        if isinstance(p, (ast.With, ast.AsyncWith)):
            for item in p.items:
                for expr in _with_item_exprs(item):
                    dotted = resolve(expr)
                    if dotted is not None:
                        held.append((dotted, p.lineno))
    lineno = getattr(node, 'lineno', None)
    if func is not None and lineno is not None:
        cached = getattr(func, '_sky_acqrel', None)
        if cached is None:
            acquires: List[Tuple[int, str]] = []
            releases: List[Tuple[int, str]] = []
            for sub in walk_function_body(func):
                if not (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)):
                    continue
                if sub.func.attr not in ('acquire', 'release'):
                    continue
                base = resolve(sub.func.value)
                if base is None:
                    continue
                (acquires if sub.func.attr == 'acquire'
                 else releases).append((sub.lineno, base))
            func._sky_acqrel = (   # type: ignore[attr-defined]
                acquires, releases)
        else:
            acquires, releases = cached
        for acq_line, base in acquires:
            if acq_line >= lineno:
                continue
            rel_line = min((ln for ln, b in releases
                            if b == base and ln > acq_line),
                           default=None)
            if rel_line is None or lineno <= rel_line:
                if not any(b == base for b, _ in held):
                    held.append((base, acq_line))
    return sorted(set(held), key=lambda pair: pair[1])


def held_locks(node: ast.AST) -> Set[str]:
    """Attribute names of every context manager lexically held at
    ``node`` within its own function: ``with self._lock:`` (or any
    ``with <expr>.<name>:``, an aliased ``with lock:``, a manual
    ``acquire()/release()`` interval, or a tuple item of
    ``with (a, b):``) contributes ``<name>``. Stops at the function
    boundary — a ``with`` in an outer function does not cover a
    nested def's body."""
    return {dotted.rsplit('.', 1)[-1]
            for dotted, _ in held_lock_sites(node)}


_HOLDS_NAME = re.compile(r'^[A-Za-z_][A-Za-z0-9_\-]*$')


def holds_annotations(src: 'core.SourceFile',
                      func: ast.AST) -> Set[str]:
    """Lock names asserted by a ``# holds: <name>[, <name>]`` comment
    in the function header (the ``def`` line through the line of the
    first body statement). The annotation documents a calling
    contract — "every caller already holds this" — for helpers that
    mutate guarded state without taking the lock themselves.

    Tokens must be identifiers (or ``event-loop``): a docstring that
    *mentions* the annotation syntax (``# holds: <name>``) must not
    read as a real annotation now that annotations are verified."""
    names: Set[str] = set()
    if not isinstance(func, _FUNC_TYPES) or not func.body:
        return names
    for lineno in range(func.lineno, func.body[0].lineno + 1):
        line = src.line(lineno)
        marker = '# holds:'
        idx = line.find(marker)
        if idx < 0:
            continue
        for tok in line[idx + len(marker):].split(','):
            tok = tok.strip()
            if tok and _HOLDS_NAME.match(tok):
                names.add(tok)
    return names


def is_mutating_access(attr: ast.Attribute) -> bool:
    """Whether this attribute access WRITES the attribute: direct
    store/delete (incl. aug-assign), subscript store/delete on it, or
    an in-place mutator method call (``.append`` & co)."""
    if isinstance(attr.ctx, (ast.Store, ast.Del)):
        return True
    parent = getattr(attr, '_sky_parent', None)
    if (isinstance(parent, ast.Subscript) and parent.value is attr
            and isinstance(parent.ctx, (ast.Store, ast.Del))):
        return True
    if (isinstance(parent, ast.Attribute)
            and parent.value is attr
            and parent.attr in MUTATOR_METHODS):
        grand = getattr(parent, '_sky_parent', None)
        if isinstance(grand, ast.Call) and grand.func is parent:
            return True
    return False


def walk_function_body(func: ast.AST,
                       skip_nested: bool = True) -> Iterator[ast.AST]:
    """Walk a function's body; by default nested function defs are not
    descended into (they have their own scope/context)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        if skip_nested and isinstance(node, _FUNC_TYPES):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


# ---------------------------------------------------------------------------
# Whole-package call-graph machinery (shared by SKY-TRACE and the
# interprocedural lock-flow pass)
# ---------------------------------------------------------------------------

# (module rel path, function qualname) — qualname is dotted nesting,
# e.g. 'InferenceEngine.__init__._decode_paged'.
FuncKey = Tuple[str, str]


class FuncInfo:
    """One (possibly nested) function def: its module, AST node,
    dotted qualname, and the name of its directly-enclosing class (for
    ``self.`` resolution), if any."""

    def __init__(self, src: 'core.SourceFile', node: ast.AST,
                 qualname: str, cls: Optional[str] = None) -> None:
        self.src = src
        self.node = node
        self.qualname = qualname
        self.cls = cls

    @property
    def key(self) -> FuncKey:
        return (self.src.rel, self.qualname)


def index_functions(files) -> Dict[str, Dict[str, FuncInfo]]:
    """module rel -> {qualname -> FuncInfo} for every (nested) def."""
    out: Dict[str, Dict[str, FuncInfo]] = {}
    for src in files:
        funcs: Dict[str, FuncInfo] = {}

        def visit(node: ast.AST, prefix: str,
                  cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FUNC_TYPES):
                    qn = (f'{prefix}.{child.name}' if prefix
                          else child.name)
                    funcs[qn] = FuncInfo(src, child, qn, cls)
                    visit(child, qn, None)
                elif isinstance(child, ast.ClassDef):
                    visit(child, (f'{prefix}.{child.name}' if prefix
                                  else child.name), child.name)
                else:
                    visit(child, prefix, cls)

        visit(src.tree, '', None)
        out[src.rel] = funcs
    return out


def module_imports(src: 'core.SourceFile') -> Dict[str, str]:
    """alias -> candidate module rel path. The leading dotted
    component is the package name (whatever the scanned root is
    called), so it is stripped; aliases that do not resolve to a
    scanned file simply yield no callees (jnp, np, ...). Memoized on
    the SourceFile (callers ask per-function; the walk is
    per-module)."""
    cached = getattr(src, '_sky_imports', None)
    if cached is not None:
        return cached
    out: Dict[str, str] = {}
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ImportFrom):
            if not node.module or node.level:
                continue
            parts = node.module.split('.')
            base = '/'.join(parts[1:])
            for alias in node.names:
                target = (f'{base}/{alias.name}.py' if base
                          else f'{alias.name}.py')
                out[alias.asname or alias.name] = target
        elif isinstance(node, ast.Import):
            for alias in node.names:
                parts = alias.name.split('.')
                if len(parts) < 2:
                    continue
                rel = '/'.join(parts[1:]) + '.py'
                out[alias.asname or parts[0]] = rel
    src._sky_imports = out   # type: ignore[attr-defined]
    return out


def import_bound_names(src: 'core.SourceFile') -> Set[str]:
    """EVERY name bound by an import statement in the module —
    including externals (`os`, `np`, `requests`) that
    :func:`module_imports` cannot resolve to a scanned file. Call
    resolution uses this to refuse duck dispatch on
    ``os.path.exists()``-style calls (the receiver is a module, not
    one of our objects). Memoized on the SourceFile."""
    cached = getattr(src, '_sky_ext_names', None)
    if cached is not None:
        return cached
    out: Set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                out.add(alias.asname or alias.name.split('.')[0])
    src._sky_ext_names = out   # type: ignore[attr-defined]
    return out


def enclosing_qualname(node: ast.AST) -> str:
    parts: List[str] = []
    for p in parents(node):
        if isinstance(p, (_FUNC_TYPES + (ast.ClassDef,))):
            parts.append(p.name)
    return '.'.join(reversed(parts))
